#!/bin/sh
# Install sbt-agent on a Slurm login node as a systemd service.
#
# Reference parity: manifests/deploy/install_slurm_agent.sh (systemd unit
# with Restart=always; SURVEY.md §5 failure-detection inventory). The agent
# needs the Slurm CLI (sbatch/scancel/scontrol/sacct/sinfo) on PATH and a
# writable state directory for the submit-dedupe ledger — the ledger is
# what keeps SubmitJob idempotent across agent restarts (the reference's
# in-memory map loses that, api/slurm.go:91-112).
set -eu

PREFIX=${PREFIX:-/usr/local}
STATE_DIR=${STATE_DIR:-/var/lib/sbt-agent}
SOCK_DIR=${SOCK_DIR:-/var/run/slurm-bridge}
LISTEN=${LISTEN:-0.0.0.0:9999}

command -v sbatch >/dev/null || { echo "sbatch not on PATH" >&2; exit 1; }
command -v sbt-agent >/dev/null || pip install "$(dirname "$0")/../.."

mkdir -p "$STATE_DIR" "$SOCK_DIR"

cat > /etc/systemd/system/sbt-agent.service <<UNIT
[Unit]
Description=slurm-bridge-tpu agent (WorkloadManager gRPC server)
After=network.target

[Service]
ExecStart=$(command -v sbt-agent) \\
    --listen ${LISTEN} \\
    --socket ${SOCK_DIR}/sbt-agent.sock \\
    --ledger ${STATE_DIR}/submit-ledger.json
Restart=always
RestartSec=2
User=slurm
Group=slurm

[Install]
WantedBy=multi-user.target
UNIT

systemctl daemon-reload
systemctl enable --now sbt-agent
echo "sbt-agent listening on ${LISTEN} and ${SOCK_DIR}/sbt-agent.sock"
