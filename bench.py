#!/usr/bin/env python
"""Headline benchmark: place 50k pending pods against a 10k-node snapshot.

Prints ONE JSON line:
  {"metric": "pods_placed_per_sec_50kx10k", "value": N, "unit": "pods/s",
   "vs_baseline": X}

where ``vs_baseline`` is the speedup of the JAX auction solver (on the
available accelerator) over the native C++ greedy packer — the stand-in for
the reference's in-process Go-side placement path (BASELINE.md: the
reference publishes no numbers, so the greedy packer we built at parity IS
the measured baseline).

The solve runs through :class:`DeviceSolver`: the node snapshot stays
device-resident across ticks (as the production reconcile loop holds it)
and only the assignment vector is fetched back — on a tunneled accelerator
the result fetch costs ~140 ms flat, an order of magnitude over the actual
kernel time, so what is measured is the tick loop's real steady state.

Extra per-scenario detail goes to stderr; stdout carries only the one line.
The full five-scenario table lives in ``benchmarks/scenarios.py``.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _steady_state_ms(fn, *, warmup: int = 1, iters: int = 5) -> float:
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def main() -> None:
    from slurm_bridge_tpu.solver import AuctionConfig
    from slurm_bridge_tpu.solver.greedy_native import greedy_place_native
    from slurm_bridge_tpu.solver.session import DeviceSolver
    from slurm_bridge_tpu.solver.snapshot import random_scenario

    import jax

    backend = jax.default_backend()
    n_dev = len(jax.devices())
    print(f"# backend={backend} devices={n_dev}", file=sys.stderr)

    # BASELINE.md scenario #3-shaped: 50k pods, 10k nodes, gres + gangs
    snap, batch = random_scenario(
        10_000, 50_000, seed=42, load=0.7, gpu_fraction=0.15, gang_fraction=0.05
    )
    p = batch.num_shards
    print(f"# scenario: {p} shards x {snap.num_nodes} nodes", file=sys.stderr)

    # --- baseline: native greedy (CPU) ---
    t_greedy = _steady_state_ms(
        lambda: greedy_place_native(snap, batch), warmup=0, iters=3
    )
    g = greedy_place_native(snap, batch)
    print(
        f"# greedy_native: {t_greedy:.1f} ms, placed {int(g.placed.sum())}",
        file=sys.stderr,
    )

    # --- JAX auction (sharded across every device when more than one) ---
    cfg = AuctionConfig(rounds=12)
    if n_dev > 1:
        from slurm_bridge_tpu.solver.sharded import sharded_place

        solve = lambda: sharded_place(snap, batch, cfg)  # noqa: E731
    else:
        # snapshot is device-resident; the per-tick upload is the queue only
        solver = DeviceSolver(snap, cfg)
        solve = lambda: solver.solve(batch)  # noqa: E731

    t_auction = _steady_state_ms(solve, iters=5)
    a = solve()
    # denominate in JOBS (pods), not gang shards — gangs are all-or-nothing
    # so a job appears in by_job iff fully placed
    placed = len(a.by_job(batch))
    print(
        f"# auction[{backend}x{n_dev}]: {t_auction:.1f} ms, placed {placed} jobs "
        f"/ {int(a.placed.sum())} shards (greedy placed {len(g.by_job(batch))} jobs)",
        file=sys.stderr,
    )

    pods_per_sec = placed / (t_auction / 1e3)
    print(
        json.dumps(
            {
                "metric": "pods_placed_per_sec_50kx10k",
                "value": round(pods_per_sec, 1),
                "unit": "pods/s",
                "vs_baseline": round(t_greedy / t_auction, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
