#!/usr/bin/env python
"""Headline benchmark: place 50k pending pods against a 10k-node snapshot.

Prints ONE JSON line:
  {"metric": "pods_placed_per_sec_50kx10k", "value": N, "unit": "pods/s",
   "vs_baseline": X, "backend": "tpu|cpu"}

where ``vs_baseline`` is the speedup of the JAX auction solver (on the
available accelerator) over the native C++ greedy packer — the stand-in for
the reference's in-process Go-side placement path (BASELINE.md: the
reference publishes no numbers, so the greedy packer we built at parity IS
the measured baseline).

Robustness contract (round-1 failure: the TPU backend init wedged and the
bench recorded *nothing*; round-2: one short retry gave up and fell back
to CPU): TPU init is treated as a hostile dependency. Backend acquisition
runs FIRST, in a worker thread under a long single-shot budget
(SBT_BENCH_TPU_BUDGET seconds for attempt 1, default 600, HALVED on each
retry: 600 → 300 → 150), progress-logged every 30 s, with a faulthandler
stack dump into diagnostics/ at half-budget and at expiry. A wedged
attempt poisons the process's init lock, so retries happen across process
re-execs — SBT_BENCH_TPU_ATTEMPTS of them (default 3), each a fresh
process — before the final re-exec pins CPU. Every path still emits
the one JSON line with an honest "backend" field, and failure paths exit
nonzero (ADVICE r2) so a harness keying off rc sees them.

The solve runs through :class:`DeviceSolver`: the node snapshot stays
device-resident across ticks (as the production reconcile loop holds it)
and only the assignment vector is fetched back.

Extra per-scenario detail goes to stderr; stdout carries only the one line.
The full five-scenario table lives in ``benchmarks/scenarios.py``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

_FORCED_CPU_ENV = "SBT_BENCH_CPU"
_ATTEMPT_ENV = "SBT_BENCH_TPU_ATTEMPT"  # 1-based, bumped on each re-exec
_METRIC = "pods_placed_per_sec_50kx10k"
_DIAG_DIR = os.environ.get("SBT_BENCH_DIAG_DIR") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "diagnostics"
)

# Filled in as the run progresses so the watchdog can emit a partial line.
_PARTIAL: dict = {"metric": _METRIC, "value": 0.0, "unit": "pods/s",
                  "vs_baseline": 0.0, "backend": "none"}
_EMITTED = threading.Event()


def _emit(payload: dict) -> None:
    if _EMITTED.is_set():
        return
    _EMITTED.set()
    print(json.dumps(payload), flush=True)


def _start_watchdog(timeout_s: float) -> threading.Timer:
    """If the bench wedges, emit the partial JSON line instead of nothing."""

    def _fire() -> None:
        print(f"# WATCHDOG: bench exceeded {timeout_s:.0f}s — emitting partial",
              file=sys.stderr, flush=True)
        _emit(dict(_PARTIAL, note="watchdog-partial"))
        sys.stdout.flush()
        os._exit(3)  # partial data ≠ success (ADVICE r2)

    timer = threading.Timer(timeout_s, _fire)
    timer.daemon = True
    timer.start()
    return timer


def _reexec(extra_env: dict) -> None:
    """Replace the process — the only escape from a poisoned init lock."""
    env = dict(os.environ, **extra_env)
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def _reexec_forced_cpu() -> None:
    print("# giving up on the accelerator — re-exec with forced CPU",
          file=sys.stderr, flush=True)
    _reexec({_FORCED_CPU_ENV: "1"})


def _dump_stacks(attempt: int, tag: str, elapsed: float) -> str:
    """faulthandler dump of every thread into diagnostics/ — captures WHERE
    backend init is stuck (VERDICT r2 #1: dump on every timeout, not just
    the last). Returns the path (best-effort; never raises)."""
    import faulthandler

    try:
        os.makedirs(_DIAG_DIR, exist_ok=True)
        path = os.path.join(
            _DIAG_DIR, f"tpu_probe_bench_attempt{attempt}_{tag}.log"
        )
        with open(path, "a") as f:
            f.write(
                f"# bench TPU probe attempt {attempt} [{tag}] after "
                f"{elapsed:.0f}s — {time.strftime('%Y-%m-%dT%H:%M:%S')}\n"
                f"# JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '')!r} "
                f"SBT_BACKEND={os.environ.get('SBT_BACKEND', '')!r}\n"
            )
            faulthandler.dump_traceback(file=f)
        print(f"# stack dump → {path}", file=sys.stderr, flush=True)
        return path
    except Exception as exc:  # noqa: BLE001 — diagnostics must not kill us
        print(f"# stack dump failed: {exc!r}", file=sys.stderr, flush=True)
        return ""


def _record_chip(ok: bool, detail: str) -> None:
    """Feed this bench run's probe outcome into the shared chip state so
    the watcher and later bench runs see it. Best-effort, never raises."""
    try:
        from slurm_bridge_tpu.utils import chipstate

        chipstate.record(ok, detail, dir_override=_DIAG_DIR)
    except Exception as exc:  # noqa: BLE001 — diagnostics must not kill us
        print(f"# chip-state record failed: {exc!r}", file=sys.stderr,
              flush=True)


def _force_cpu() -> str:
    import jax

    # Config beats both the env and the image's sitecustomize platform pin.
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        import jax.extend.backend

        jax.extend.backend.clear_backends()
        jax.config.update("jax_platforms", "cpu")
    jax.devices()
    return "cpu"


def _acquire_backend() -> str:
    """Initialize a JAX backend, preferring the accelerator, never hanging.

    VERDICT r2 #1 contract — TPU init is a hostile dependency:
    - one LONG single-shot budget for the first attempt
      (SBT_BENCH_TPU_BUDGET, default 600 s), halved on each retry — a
      wedge that survived a full window rarely clears, and the total must
      leave room for the forced-CPU solve; progress-logged every 30 s;
    - a wedged attempt poisons this process's init lock, so the retry is a
      process re-exec (SBT_BENCH_TPU_ATTEMPTS total, default 3) — each
      attempt gets a genuinely fresh PJRT client;
    - faulthandler stack dumps into diagnostics/ at half-budget and at
      expiry, every attempt, so where init sticks is on the record;
    - only after the last attempt does the re-exec pin CPU.
    On probe *error* (exception, lock free) an in-process CPU fallback
    suffices and no re-exec is spent.
    """
    if os.environ.get(_FORCED_CPU_ENV) == "1":
        return _force_cpu()

    import jax

    attempt = int(os.environ.get(_ATTEMPT_ENV, "1"))
    max_attempts = int(os.environ.get("SBT_BENCH_TPU_ATTEMPTS", "3"))
    # halve the budget per attempt (600 → 300 → 150 by default): the first
    # window is generous, but a wedge that survived it rarely clears, and
    # the total must leave room for the forced-CPU solve inside whatever
    # patience the outer harness has
    # empty string means UNSET (ADVICE r5 #4): `SBT_BENCH_TPU_BUDGET= python
    # bench.py` must keep the known-dead-chip short-circuit AND the default
    # budget, not disable the former while silently using the latter
    budget_env = os.environ.get("SBT_BENCH_TPU_BUDGET") or None
    budget = float(budget_env or "600") / (2 ** (attempt - 1))
    # VERDICT r4 #3: when the availability watcher (hack/chip-watch.sh →
    # utils/chipstate.py) has the chip on record as dead — ≥2 consecutive
    # failed probes, newest recent enough to still be evidence — don't
    # burn ~17.5 min re-discovering the wedge: one short probe (the state
    # could be stale-optimistic the other way), no re-exec retries, then
    # CPU. An explicit SBT_BENCH_TPU_BUDGET overrides the short-circuit.
    if budget_env is None:
        try:
            from slurm_bridge_tpu.utils import chipstate

            if chipstate.chip_known_dead(dir_override=_DIAG_DIR):
                budget = min(
                    budget,
                    float(os.environ.get("SBT_BENCH_TPU_SHORT_BUDGET", "60")),
                )
                max_attempts = 1
                print(
                    "# chip watcher records the chip DEAD — short probe only "
                    "(override with SBT_BENCH_TPU_BUDGET)",
                    file=sys.stderr, flush=True,
                )
        except Exception as exc:  # noqa: BLE001 — state is advisory
            print(f"# chip-state check failed: {exc!r}",
                  file=sys.stderr, flush=True)
    result: dict = {}

    def _probe() -> None:
        try:
            result["backend"] = jax.default_backend()
        except Exception as exc:  # noqa: BLE001 — report and fall back
            result["error"] = exc

    print(
        f"# TPU probe attempt {attempt}/{max_attempts}, budget {budget:.0f}s",
        file=sys.stderr, flush=True,
    )
    t = threading.Thread(target=_probe, daemon=True)
    t0 = time.perf_counter()
    t.start()
    dumped_half = False
    while True:
        # bounded by the remaining budget (a sub-30s budget must not sit
        # out a full 30s progress interval) AND by the half-budget
        # checkpoint while it is still pending — the two dumps exist to
        # show whether the wedge moved between them, so they must not
        # collapse into one instant
        now = time.perf_counter() - t0
        bound = min(30.0, max(budget - now, 0.1))
        if not dumped_half:
            bound = min(bound, max(budget / 2 - now, 0.1))
        t.join(bound)
        elapsed = time.perf_counter() - t0
        if result:
            break
        print(f"# ... backend init still running ({elapsed:.0f}s)",
              file=sys.stderr, flush=True)
        if not dumped_half and elapsed >= budget / 2:
            dumped_half = True
            _dump_stacks(attempt, "halfbudget", elapsed)
        if elapsed >= budget:
            break

    if result.get("backend"):
        print(f"# backend up after {time.perf_counter() - t0:.0f}s",
              file=sys.stderr, flush=True)
        if result["backend"] != "cpu":
            _record_chip(True, f"bench acquired {result['backend']}")
        return result["backend"]
    if "error" in result:
        print(f"# backend probe failed cleanly: {result['error']!r}",
              file=sys.stderr, flush=True)
        try:
            return _force_cpu()
        except Exception as exc:  # noqa: BLE001
            print(f"# in-process CPU fallback failed: {exc!r}",
                  file=sys.stderr, flush=True)
            _reexec_forced_cpu()
            raise AssertionError("unreachable")

    # Wedged inside backend init: dump, then retry in a FRESH process (the
    # init lock here is poisoned) or give up to CPU after the last attempt.
    _dump_stacks(attempt, "expired", time.perf_counter() - t0)
    _record_chip(False, f"bench probe attempt {attempt} wedged >{budget:.0f}s")
    if attempt < max_attempts:
        print(f"# attempt {attempt} wedged — re-exec for attempt {attempt + 1}",
              file=sys.stderr, flush=True)
        _reexec({_ATTEMPT_ENV: str(attempt + 1)})
    _reexec_forced_cpu()
    raise AssertionError("unreachable")


def _steady_state_ms(fn, *, warmup: int = 1, iters: int = 5) -> float:
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def main() -> None:
    # the watchdog must outlive one full probe budget plus the solve —
    # a fixed constant would silently cut SBT_BENCH_TPU_BUDGET short,
    # skipping the promised stack dump / re-exec attempts
    budget = float(os.environ.get("SBT_BENCH_TPU_BUDGET") or "600")
    _start_watchdog(budget + 900.0)
    backend = _acquire_backend()

    import jax

    from slurm_bridge_tpu.solver import AuctionConfig
    from slurm_bridge_tpu.solver.greedy_native import greedy_place_native
    from slurm_bridge_tpu.solver.session import DeviceSolver
    from slurm_bridge_tpu.solver.snapshot import random_scenario

    n_dev = len(jax.devices())
    _PARTIAL["backend"] = backend
    print(f"# backend={backend} devices={n_dev}", file=sys.stderr, flush=True)

    # BASELINE.md scenario #3-shaped: 50k pods, 10k nodes, gres + gangs.
    # SBT_BENCH_SHAPE="pods,nodes" shrinks it for the contract test
    # (tests/test_bench.py) — the emitted line's SCHEMA is what the driver
    # depends on, and that must be testable in seconds, not minutes.
    shape = os.environ.get("SBT_BENCH_SHAPE", "50000,10000")
    n_pods, n_nodes = (int(x) for x in shape.split(","))
    if (n_pods, n_nodes) != (50_000, 10_000):
        # a non-default shape must never masquerade as the headline metric
        # (a stray env var in a driver run would record an incomparable
        # number under the standard label)
        globals()["_METRIC"] = f"pods_placed_per_sec_{n_pods}x{n_nodes}"
        _PARTIAL["metric"] = _METRIC
        print(f"# NON-DEFAULT shape {shape}: metric relabeled {_METRIC}",
              file=sys.stderr, flush=True)
    snap, batch = random_scenario(
        n_nodes, n_pods, seed=42, load=0.7, gpu_fraction=0.15, gang_fraction=0.05
    )
    p = batch.num_shards
    print(f"# scenario: {p} shards x {snap.num_nodes} nodes", file=sys.stderr,
          flush=True)

    # --- baseline: native greedy (CPU); warmup absorbs any g++ rebuild ---
    t_greedy = _steady_state_ms(
        lambda: greedy_place_native(snap, batch), warmup=1, iters=3
    )
    g = greedy_place_native(snap, batch)
    print(
        f"# greedy_native: {t_greedy:.1f} ms, placed {int(g.placed.sum())}",
        file=sys.stderr, flush=True,
    )

    # --- the solver, through the production routing rule ---
    # (solver/routing.py, same decision the scheduler's backend="auto"
    # makes): with an accelerator and a solve above the dispatch floor,
    # the JAX auction kernel — rounds=8 is the measured knee on the chip
    # (vs rounds=12 it gives up 19 of 45,405 placed jobs, -0.04%, still
    # ~500 above the greedy baseline, for a 27% lower p50); without one,
    # the indexed native packer (greedy-parity quality, no JAX-CPU
    # auction: 1-core hosts can't amortise its round loop — VERDICT r3 #1)
    from slurm_bridge_tpu.solver.routing import choose_path, gang_shard_fraction

    cfg = AuctionConfig(rounds=8)
    route = choose_path(
        p, snap.num_nodes, backend_name=backend,
        gang_fraction=gang_shard_fraction(batch.gang_id),
    )
    if route == "native":
        from slurm_bridge_tpu.solver.indexed_native import indexed_place_native
        from slurm_bridge_tpu.solver.routing import native_fit_policy

        # same fit policy the production scheduler routes with (worst-fit:
        # the measured quality winner at this shape — BASELINE.md round 5)
        pol = native_fit_policy()
        solve = lambda: indexed_place_native(snap, batch, policy=pol)  # noqa: E731
    elif n_dev > 1:
        from slurm_bridge_tpu.solver.sharded import sharded_place

        solve = lambda: sharded_place(snap, batch, cfg)  # noqa: E731
    else:
        # snapshot is device-resident; the per-tick upload is the queue only
        solver = DeviceSolver(snap, cfg)
        solve = lambda: solver.solve(batch)  # noqa: E731

    t_auction = _steady_state_ms(solve, iters=5)
    a = solve()
    # denominate in JOBS (pods), not gang shards — gangs are all-or-nothing
    # so a job appears in by_job iff fully placed
    placed = len(a.by_job(batch))
    engine = "indexed-native" if route == "native" else "auction"
    print(
        f"# {engine}[{backend}x{n_dev}]: {t_auction:.1f} ms, placed {placed} jobs "
        f"/ {int(a.placed.sum())} shards (greedy placed {len(g.by_job(batch))} jobs)",
        file=sys.stderr, flush=True,
    )

    pods_per_sec = placed / (t_auction / 1e3)
    _PARTIAL.update(value=round(pods_per_sec, 1),
                    vs_baseline=round(t_greedy / t_auction, 2))

    # --- end-to-end tick: proto decode → encode (cached) → solve ---
    # The solve above starts from an already-encoded snapshot; production
    # ticks start from agent RPC protos and pay the lowering every tick.
    # This stage measures that whole pipeline with the cross-tick encode
    # caches warm (solver/encoder.py), plus the kept-as-oracle loop
    # encoder for the speedup the caches buy (ISSUE 1 acceptance: ≥10×).
    tick_label = (
        "tick_p50_ms_50kx10k"
        if (n_pods, n_nodes) == (50_000, 10_000)
        else f"tick_p50_ms_{n_pods}x{n_nodes}"
    )
    tick = _tick_pipeline(n_pods, n_nodes, backend, n_dev, cfg)
    for k, v in tick.items():
        print(f"# tick: {k}={v}", file=sys.stderr, flush=True)

    _emit(
        {
            "metric": _METRIC,
            "value": round(pods_per_sec, 1),
            "unit": "pods/s",
            "vs_baseline": round(t_greedy / t_auction, 2),
            "backend": backend,
            # which engine the routing rule picked (solver/routing.py) —
            # "auction" on the chip, "indexed-native" on a CPU-only host
            "engine": engine,
            # BASELINE.md's other headline: <200 ms p50 solve latency —
            # measured, not implied (VERDICT r2 weak #6)
            "p50_ms": round(t_auction, 1),
            "p50_target_ms": 200,
            # the end-to-end tick metric + its phase breakdown and the
            # encode speedup over the loop oracle (solver/snapshot.py)
            tick_label: tick["tick_p50_ms"],
            "tick_decode_ms": tick["decode_ms"],
            "tick_encode_ms": tick["encode_ms"],
            "tick_solve_ms": tick["solve_ms"],
            "encode_loop_ms": tick["encode_loop_ms"],
            "encode_speedup_vs_loop": tick["encode_speedup_vs_loop"],
        }
    )


def _tick_pipeline(
    n_pods: int, n_nodes: int, backend: str, n_dev: int, cfg
) -> dict:
    """benchmarks.stages.profile_tick (the ONE tick-pipeline measurement,
    shared with the `make bench-smoke` CI gate) on this bench's routed
    solve engine — same decision the headline solve above made."""
    from benchmarks.stages import profile_tick
    from slurm_bridge_tpu.solver.routing import choose_path

    # routing by shape only (shard count ≈ pods: the pipeline re-derives
    # the exact batch internally; the decision thresholds are coarse)
    route = choose_path(n_pods, n_nodes, backend_name=backend)
    if route == "native":
        solve = None  # profile_tick's default IS the routed native packer
    elif n_dev > 1:
        from slurm_bridge_tpu.solver.sharded import sharded_place

        solve = lambda s, b: sharded_place(s, b, cfg)  # noqa: E731
    else:
        from slurm_bridge_tpu.solver.session import DeviceSolver

        session: list = []

        def solve(s, b):
            if not session:
                session.append(DeviceSolver(s, cfg))
            else:
                session[0].update_snapshot(s)
            return session[0].solve(b)

    return profile_tick(n_nodes, n_pods, seed=42, solve=solve)


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # noqa: BLE001 — the one line must still appear
        import traceback

        traceback.print_exc()
        _emit(dict(_PARTIAL, note=f"error: {type(exc).__name__}: {exc}"))
        sys.exit(2)  # the JSON line is out, but this run is NOT a success
