#!/usr/bin/env python
"""Headline benchmark: place 50k pending pods against a 10k-node snapshot.

Prints ONE JSON line:
  {"metric": "pods_placed_per_sec_50kx10k", "value": N, "unit": "pods/s",
   "vs_baseline": X, "backend": "tpu|cpu"}

where ``vs_baseline`` is the speedup of the JAX auction solver (on the
available accelerator) over the native C++ greedy packer — the stand-in for
the reference's in-process Go-side placement path (BASELINE.md: the
reference publishes no numbers, so the greedy packer we built at parity IS
the measured baseline).

Robustness contract (round-1 failure: the TPU backend init wedged and the
bench recorded *nothing*): backend acquisition runs in a worker thread
under a bounded timeout with one retry; on failure or hang the bench falls
back to CPU (config-update first, process re-exec if the init lock is
wedged) and STILL emits the one JSON line, with an honest "backend" field.
A global watchdog emits whatever partial numbers exist rather than dying
silently.

The solve runs through :class:`DeviceSolver`: the node snapshot stays
device-resident across ticks (as the production reconcile loop holds it)
and only the assignment vector is fetched back.

Extra per-scenario detail goes to stderr; stdout carries only the one line.
The full five-scenario table lives in ``benchmarks/scenarios.py``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

_FORCED_CPU_ENV = "SBT_BENCH_CPU"
_METRIC = "pods_placed_per_sec_50kx10k"

# Filled in as the run progresses so the watchdog can emit a partial line.
_PARTIAL: dict = {"metric": _METRIC, "value": 0.0, "unit": "pods/s",
                  "vs_baseline": 0.0, "backend": "none"}
_EMITTED = threading.Event()


def _emit(payload: dict) -> None:
    if _EMITTED.is_set():
        return
    _EMITTED.set()
    print(json.dumps(payload), flush=True)


def _start_watchdog(timeout_s: float) -> threading.Timer:
    """If the bench wedges, emit the partial JSON line instead of nothing."""

    def _fire() -> None:
        print(f"# WATCHDOG: bench exceeded {timeout_s:.0f}s — emitting partial",
              file=sys.stderr, flush=True)
        _emit(dict(_PARTIAL, note="watchdog-partial"))
        sys.stdout.flush()
        os._exit(0)

    timer = threading.Timer(timeout_s, _fire)
    timer.daemon = True
    timer.start()
    return timer


def _reexec_forced_cpu() -> None:
    """Escape a wedged backend-init lock: replace the whole process."""
    print("# backend init wedged — re-exec with forced CPU", file=sys.stderr,
          flush=True)
    env = dict(os.environ, **{_FORCED_CPU_ENV: "1"})
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def _force_cpu() -> str:
    import jax

    # Config beats both the env and the image's sitecustomize platform pin.
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        import jax.extend.backend

        jax.extend.backend.clear_backends()
        jax.config.update("jax_platforms", "cpu")
    jax.devices()
    return "cpu"


def _acquire_backend(probe_timeouts=(150.0, 60.0)) -> str:
    """Initialize a JAX backend, preferring the accelerator, never hanging.

    Returns the backend name actually live. On probe timeout the init lock
    may be held by the dead probe thread, so recovery is by re-exec with a
    marker env var; on probe *error* the lock is free and an in-process
    CPU fallback suffices.
    """
    if os.environ.get(_FORCED_CPU_ENV) == "1":
        return _force_cpu()

    import jax

    for attempt, timeout_s in enumerate(probe_timeouts, 1):
        result: dict = {}

        def _probe() -> None:
            try:
                result["backend"] = jax.default_backend()
            except Exception as exc:  # noqa: BLE001 — report and fall back
                result["error"] = exc

        t = threading.Thread(target=_probe, daemon=True)
        t.start()
        t.join(timeout_s)
        if result.get("backend"):
            return result["backend"]
        if "error" in result:
            print(f"# backend probe {attempt} failed: {result['error']!r}",
                  file=sys.stderr, flush=True)
            continue
        # Probe thread is wedged inside backend init; the init lock is
        # poisoned for this process. Re-exec (does not return).
        _reexec_forced_cpu()

    # All probes errored cleanly — fall back in-process.
    try:
        return _force_cpu()
    except Exception as exc:  # noqa: BLE001
        print(f"# in-process CPU fallback failed: {exc!r}", file=sys.stderr,
              flush=True)
        _reexec_forced_cpu()
        raise AssertionError("unreachable")


def _steady_state_ms(fn, *, warmup: int = 1, iters: int = 5) -> float:
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def main() -> None:
    _start_watchdog(1500.0)
    backend = _acquire_backend()

    import jax

    from slurm_bridge_tpu.solver import AuctionConfig
    from slurm_bridge_tpu.solver.greedy_native import greedy_place_native
    from slurm_bridge_tpu.solver.session import DeviceSolver
    from slurm_bridge_tpu.solver.snapshot import random_scenario

    n_dev = len(jax.devices())
    _PARTIAL["backend"] = backend
    print(f"# backend={backend} devices={n_dev}", file=sys.stderr, flush=True)

    # BASELINE.md scenario #3-shaped: 50k pods, 10k nodes, gres + gangs
    snap, batch = random_scenario(
        10_000, 50_000, seed=42, load=0.7, gpu_fraction=0.15, gang_fraction=0.05
    )
    p = batch.num_shards
    print(f"# scenario: {p} shards x {snap.num_nodes} nodes", file=sys.stderr,
          flush=True)

    # --- baseline: native greedy (CPU); warmup absorbs any g++ rebuild ---
    t_greedy = _steady_state_ms(
        lambda: greedy_place_native(snap, batch), warmup=1, iters=3
    )
    g = greedy_place_native(snap, batch)
    print(
        f"# greedy_native: {t_greedy:.1f} ms, placed {int(g.placed.sum())}",
        file=sys.stderr, flush=True,
    )

    # --- JAX auction (sharded across every device when more than one) ---
    cfg = AuctionConfig(rounds=12)
    if n_dev > 1:
        from slurm_bridge_tpu.solver.sharded import sharded_place

        solve = lambda: sharded_place(snap, batch, cfg)  # noqa: E731
    else:
        # snapshot is device-resident; the per-tick upload is the queue only
        solver = DeviceSolver(snap, cfg)
        solve = lambda: solver.solve(batch)  # noqa: E731

    t_auction = _steady_state_ms(solve, iters=5)
    a = solve()
    # denominate in JOBS (pods), not gang shards — gangs are all-or-nothing
    # so a job appears in by_job iff fully placed
    placed = len(a.by_job(batch))
    print(
        f"# auction[{backend}x{n_dev}]: {t_auction:.1f} ms, placed {placed} jobs "
        f"/ {int(a.placed.sum())} shards (greedy placed {len(g.by_job(batch))} jobs)",
        file=sys.stderr, flush=True,
    )

    pods_per_sec = placed / (t_auction / 1e3)
    _PARTIAL.update(value=round(pods_per_sec, 1),
                    vs_baseline=round(t_greedy / t_auction, 2))
    _emit(
        {
            "metric": _METRIC,
            "value": round(pods_per_sec, 1),
            "unit": "pods/s",
            "vs_baseline": round(t_greedy / t_auction, 2),
            "backend": backend,
        }
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # noqa: BLE001 — the one line must still appear
        import traceback

        traceback.print_exc()
        _emit(dict(_PARTIAL, note=f"error: {type(exc).__name__}: {exc}"))
        sys.exit(0)
