#!/bin/sh
# Regenerate docs/api.md from the wire descriptor + CLI surfaces.
# Reference parity: hack/generate-apidoc.sh.
set -eu
cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu python hack/gen_apidoc.py > docs/api.md
echo "regenerated docs/api.md"
