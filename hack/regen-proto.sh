#!/bin/sh
# Regenerate the workload_pb2 module from wire/workload.proto.
#
# Reference parity: pkg/workload/generate.go:20 (go:generate protoc). The
# image has protoc but not the grpc python plugin, so only the message
# module is generated; service stubs are derived from the descriptor at
# runtime (wire/rpc.py — exactly what generated stubs do, minus codegen).
#
# The .protoc-version stamp records the generating toolchain so the
# hygiene check (hack/run-checks.sh) can tell real drift from version skew.
set -eu
cd "$(dirname "$0")/.."
protoc \
  --proto_path=slurm_bridge_tpu/wire \
  --python_out=slurm_bridge_tpu/wire \
  slurm_bridge_tpu/wire/workload.proto
protoc --version > slurm_bridge_tpu/wire/.protoc-version
echo "regenerated slurm_bridge_tpu/wire/workload_pb2.py"
