#!/bin/sh
# The on-chip measurement ritual (run FIRST whenever the TPU is alive —
# availability is intermittent on a multi-hour scale, so front-load):
#   1. compiled-pallas parity (Mosaic, not interpret mode)
#   2. headline bench (the driver-contract JSON line)
#   3. the five BASELINE scenarios
#   4. the per-stage auction round profile
# Each step runs even when an earlier one fails (a dropped tunnel RPC must
# not forfeit the rest of the availability window); the script exits
# nonzero if ANY step did. Redirect stdout into diagnostics/ and fold the
# numbers into BASELINE.md.
set -u
cd "$(dirname "$0")/.."
rc=0
echo "== compiled-pallas parity (SBT_TEST_TPU=1 tests/test_ops.py) =="
SBT_TEST_TPU=1 python -m pytest tests/test_ops.py -q || rc=1
echo "== headline (bench.py) =="
python bench.py || rc=1
echo "== five scenarios =="
python -m benchmarks.scenarios --json || rc=1
echo "== per-stage profile =="
python -m benchmarks.scenarios --stages --json || rc=1
exit $rc
