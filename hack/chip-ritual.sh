#!/bin/sh
# The on-chip measurement ritual (run FIRST whenever the TPU is alive —
# availability is intermittent on a multi-hour scale, so front-load):
#   1. compiled-pallas parity (Mosaic, not interpret mode)
#   2. headline bench (the driver-contract JSON line)
#   3. the five BASELINE scenarios
#   4. the per-stage auction round profile
# Results land on stdout; redirect into diagnostics/ and fold the numbers
# into BASELINE.md.
set -eu
cd "$(dirname "$0")/.."
echo "== compiled-pallas parity (SBT_TEST_TPU=1 tests/test_ops.py) =="
SBT_TEST_TPU=1 python -m pytest tests/test_ops.py -q
echo "== headline (bench.py) =="
python bench.py
echo "== five scenarios =="
python -m benchmarks.scenarios --json
echo "== per-stage profile =="
python -m benchmarks.scenarios --stages --json
