#!/bin/sh
# CI entry point: tests + the driver's compile contracts.
#
# Reference parity: .github/workflows/unittest.yaml (make test) and
# test-go.yml (hygiene). The CPU mesh env mirrors tests/conftest.py.
set -eu
cd "$(dirname "$0")/.."
python -m pytest tests/ -q
python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn)(*args)
g.dryrun_multichip(8)
print("graft contracts OK")
EOF
# hygiene: generated artifacts must match their sources (no-diff check,
# mirroring the reference's test-go.yml workflow). Regenerates into a temp
# dir and compares — never mutates the working tree, and names the
# toolchain in the error so a protoc/python version skew isn't mistaken
# for real drift.
tmp=$(mktemp -d)
trap 'rm -rf "$tmp"' EXIT
protoc --proto_path=slurm_bridge_tpu/wire --python_out="$tmp" \
  slurm_bridge_tpu/wire/workload.proto
cmp -s "$tmp/workload_pb2.py" slurm_bridge_tpu/wire/workload_pb2.py || {
  echo "workload_pb2.py out of sync with workload.proto" \
       "(or toolchain skew: $(protoc --version)) — run hack/regen-proto.sh"
  exit 1
}
pyver=$(python -c 'import sys; print(f"{sys.version_info.major}.{sys.version_info.minor}")')
if head -1 docs/api.md | grep -q "on python $pyver "; then
  JAX_PLATFORMS=cpu python hack/gen_apidoc.py > "$tmp/api.md"
  cmp -s "$tmp/api.md" docs/api.md || {
    echo "docs/api.md stale — run hack/generate-apidoc.sh"; exit 1
  }
else
  echo "# docs/api.md generated under a different python minor — skipping compare"
fi
echo "hygiene OK"
