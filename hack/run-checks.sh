#!/bin/sh
# CI entry point: tests + the driver's compile contracts.
#
# Reference parity: .github/workflows/unittest.yaml (make test) and
# test-go.yml (hygiene). The CPU mesh env mirrors tests/conftest.py.
set -eu
cd "$(dirname "$0")/.."
python -m pytest tests/ -q
python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn)(*args)
g.dryrun_multichip(8)
print("graft contracts OK")
EOF
