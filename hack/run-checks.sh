#!/bin/sh
# CI entry point: tests + the driver's compile contracts.
#
# Reference parity: .github/workflows/unittest.yaml (make test) and
# test-go.yml (hygiene). The CPU mesh env mirrors tests/conftest.py.
#
# Two lanes (VERDICT r4 #7): `python -m pytest -m "not slow"` is the
# ~2-min signal for iteration (heavyweight e2e/subprocess/fuzz suites are
# marked slow); this script always runs EVERYTHING — total coverage is
# unchanged, the split only orders feedback.
set -eu
cd "$(dirname "$0")/.."
python -m pytest tests/ -q
python - <<'EOF'
import os
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older JAX: XLA_FLAGS above governs the device count
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn)(*args)
g.dryrun_multichip(8)
print("graft contracts OK")
EOF
exec "$(dirname "$0")/check-hygiene.sh"
