#!/bin/sh
# CI entry point: tests + the driver's compile contracts.
#
# Reference parity: .github/workflows/unittest.yaml (make test) and
# test-go.yml (hygiene). The CPU mesh env mirrors tests/conftest.py.
#
# Two lanes (VERDICT r4 #7): `python -m pytest -m "not slow"` is the
# ~2-min signal for iteration (heavyweight e2e/subprocess/fuzz suites are
# marked slow); this script always runs EVERYTHING — total coverage is
# unchanged, the split only orders feedback.
set -eu
cd "$(dirname "$0")/.."
python -m pytest tests/ -q
python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn)(*args)
g.dryrun_multichip(8)
print("graft contracts OK")
EOF
exec "$(dirname "$0")/check-hygiene.sh"
