#!/bin/sh
# Background TPU availability watcher. The tunneled chip is intermittent on
# a multi-DAY scale (wedged for all of round 4), so a long-running probe
# loop is the only way to catch a window. Each probe appends one JSON line
# to diagnostics/chip_watch.jsonl and rewrites diagnostics/chip_state.json
# (the last-probe summary bench.py consults to short-circuit its ladder —
# VERDICT r4 #3). Run it from minute zero:
#
#   nohup hack/chip-watch.sh >/dev/null 2>&1 &
#
# SBT_CHIP_WATCH_INTERVAL (seconds, default 1500) tunes the cadence;
# SBT_CHIP_WATCH_ONCE=1 runs a single probe and exits (used by tests and
# by the ritual's pre-check).
set -u
cd "$(dirname "$0")/.."
mkdir -p diagnostics
interval="${SBT_CHIP_WATCH_INTERVAL:-1500}"
while :; do
  python -m slurm_bridge_tpu.utils.chipstate probe || true
  [ "${SBT_CHIP_WATCH_ONCE:-}" = "1" ] && exit 0
  sleep "$interval"
done
