"""Sharded placement — partition/island fan-out for the 10×-scale tick.

Public surface:

- :class:`ShardConfig` — declarative knobs a Scenario (or the bridge
  CLI) carries; attach to :class:`~slurm_bridge_tpu.bridge.scheduler.
  PlacementScheduler` via ``shard=``. ``shard=None`` (the default) is
  the monolithic tick byte-for-byte.
- :class:`ShardExecutor` — per-shard encode+solve fan-out + merge.
- :func:`build_plan` / :class:`ShardPlan` — the partition/island shard
  layout (planner.py).
- :func:`reconcile_gangs` — the cross-shard all-or-nothing second
  chance for gangs no single shard could place (reconcile.py).

See docs/sharding.md for the full design walkthrough.
"""

from slurm_bridge_tpu.shard.executor import ShardExecutor
from slurm_bridge_tpu.shard.planner import (
    Island,
    Shard,
    ShardConfig,
    ShardPlan,
    build_plan,
    route_jobs,
)
from slurm_bridge_tpu.shard.reconcile import reconcile_gangs

__all__ = [
    "Island",
    "Shard",
    "ShardConfig",
    "ShardExecutor",
    "ShardPlan",
    "build_plan",
    "reconcile_gangs",
    "route_jobs",
]
