"""Shard planning — partition/island decomposition of one placement tick.

The 10×-scale tick (500k pods × 100k nodes) cannot stay monolithic: one
encode, one solve and one bind over the whole cluster serializes work
that is naturally independent, because a Slurm job can only ever place
inside its own partition. The planner exploits exactly that boundary:

- every **island** is a partition-local group of interchangeable nodes —
  the partition's GPU nodes form one island, its CPU nodes another, and
  an island bigger than ``max_nodes_per_shard`` splits into contiguous
  chunks (the trace generator's GPU islands map 1:1 onto these);
- islands are packed into **shards** (first-fit-decreasing, stable
  order), so a shard is a self-contained sub-cluster: a small partition
  rides whole inside one shard, a huge partition spans several;
- **demand is routed** to shards along the same boundary: a job's
  partition names its candidate shards. Gangs are routed WHOLE — all
  shards of a gang go to the one shard holding its best island (the
  rank-aware locality score below) — so gang atomicity never crosses a
  shard boundary inside the fan-out; gangs the chosen shard still could
  not place get a cross-shard second chance in
  :mod:`slurm_bridge_tpu.shard.reconcile`.

Rank-aware locality (arxiv 2603.22691's quality bar — tightly-coupled
MPI gangs keep topology locality when the cluster is split): demand is
routed in descending effective-priority order, so a production gang
claims its best island before best-effort work dilutes it, and the score
prefers (1) a shard that can host the whole gang, (2) a shard where one
single island can host it (ICI-local placement), (3) the least-loaded
shard — ties break on shard id, keeping the whole pass deterministic.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import numpy as np

from slurm_bridge_tpu.core.types import JobDemand, NodeInfo, PartitionInfo


@dataclass(frozen=True)
class ShardConfig:
    """Declarative sharding knobs — frozen + scalar-valued so a
    :class:`~slurm_bridge_tpu.sim.harness.Scenario` can carry one."""

    #: islands bigger than this split into contiguous chunks, and a
    #: shard never grows past it — the per-shard solve stays small
    #: enough that encode+solve cost is O(cluster/shards)
    max_nodes_per_shard: int = 4096
    #: per-shard solve fan-out width (1 = serial). Encodes always run
    #: serially — the shared feature-code table must grow in a
    #: deterministic order — and merges are keyed by shard id, so the
    #: result is byte-identical at any width.
    workers: int = 1
    #: cross-shard gang reconciliation pass (shard/reconcile.py)
    reconcile: bool = True
    #: reconcile candidates examined per tick (rank-major order)
    reconcile_limit: int = 512
    #: multi-device shard_map solve for big shards: None = the routing
    #: auto rule (≥2 devices AND P×N ≥ sharded_threshold), False = never
    #: (CPU-only fallback), True = force-try whenever ≥2 devices exist
    device_solve: bool | None = None
    #: P×N floor for the device shard_map sweep (routing.use_sharded)
    sharded_threshold: int = 1 << 20
    #: drift re-key threshold (ISSUE 17): when > 0 and any shard's
    #: drained-node fraction exceeds it, the plan re-keys with drained
    #: nodes quarantined into their own islands instead of keeping stale
    #: boundaries (a half-drained shard solves at half capacity but
    #: still pays full encode). 0 disables the probe — every pinned
    #: digest is preserved because the plan key never changes shape.
    drift_rekey_fraction: float = 0.0


@dataclass(frozen=True)
class Island:
    """One partition-local group of interchangeable nodes."""

    key: tuple  # (partition, "gpu"|"cpu", chunk index)
    nodes: tuple[int, ...]  # positions into the tick's global node list


@dataclass
class Shard:
    sid: int
    node_idx: np.ndarray  # global node positions (island-contiguous)
    partitions: tuple[str, ...]
    island_keys: tuple[tuple, ...]


@dataclass
class ShardPlan:
    """The tick's shard layout + routing indexes (all deterministic)."""

    shards: list[Shard]
    islands: list[Island]
    #: partition name → shard ids holding its nodes (ascending)
    part_shards: dict[str, tuple[int, ...]]
    #: node name → global position
    name_pos: dict[str, int]
    #: global position → node name (immutable for the plan's lifetime —
    #: built once; an O(N) inversion per tick was real cost at 100k)
    pos_name: tuple[str, ...]
    #: global node position → owning shard id
    node_shard: np.ndarray
    #: global node position → global island index (-1 = unowned)
    node_island: np.ndarray
    #: (shard id, partition) → member global positions
    members: dict[tuple[int, str], np.ndarray]
    #: partition name → ALL member global positions (reconcile scans)
    part_nodes: dict[str, np.ndarray]
    #: layout key the executor caches the plan on
    token: tuple

    @property
    def num_shards(self) -> int:
        return len(self.shards)


def plan_token(
    partitions: list[PartitionInfo],
    nodes: list[NodeInfo],
    config: ShardConfig,
) -> tuple:
    """Identity of everything a cached plan indexes. The NODE list is
    part of the key, not just the partition layout: a node can vanish
    from the Nodes response while the partition still lists it, and a
    stale plan's positional indexes over the shorter list would shift
    every node after the gap (the monolithic encoder re-derives from
    the passed list every tick; the plan cache must re-key instead)."""
    return (
        tuple((p.name, p.nodes) for p in partitions),
        tuple(nd.name for nd in nodes),
        config.max_nodes_per_shard,
    )


def drained_positions(nodes: list[NodeInfo]) -> frozenset[int]:
    """Global positions of drained/down nodes (sim agent's drain rule)."""
    return frozenset(
        i
        for i, nd in enumerate(nodes)
        if "DRAIN" in nd.state.upper() or "DOWN" in nd.state.upper()
    )


def build_plan(
    partitions: list[PartitionInfo],
    nodes: list[NodeInfo],
    config: ShardConfig,
    drained: frozenset[int] = frozenset(),
) -> ShardPlan:
    """Decompose the inventory into islands and pack them into shards.

    ``drained`` (global node positions) quarantines those nodes into
    dedicated ``<kind>-drained`` islands — the drift re-key path: live
    nodes re-pack densely while the drained remainder stays routable (a
    node can un-drain next tick) without diluting live shards.
    """
    cap = max(1, config.max_nodes_per_shard)
    name_pos = {nd.name: i for i, nd in enumerate(nodes)}
    owned: set[int] = set()
    islands: list[Island] = []
    for p in partitions:
        # first-claim ownership: a node listed by two partitions solves
        # in the first one's shard (cluster_state dedupes the same way)
        mine = [
            name_pos[n]
            for n in p.nodes
            if n in name_pos and name_pos[n] not in owned
        ]
        owned.update(mine)
        gpu = [i for i in mine if nodes[i].gpus > 0]
        cpu = [i for i in mine if nodes[i].gpus <= 0]
        groups: list[tuple[str, list[int]]] = []
        for kind, group in (("gpu", gpu), ("cpu", cpu)):
            if not drained:
                groups.append((kind, group))
                continue
            groups.append((kind, [i for i in group if i not in drained]))
            groups.append(
                (kind + "-drained", [i for i in group if i in drained])
            )
        for kind, group in groups:
            if not group:
                continue
            nchunks = (len(group) + cap - 1) // cap
            for c, chunk in enumerate(np.array_split(np.asarray(group), nchunks)):
                islands.append(
                    Island(key=(p.name, kind, c), nodes=tuple(chunk.tolist()))
                )

    # first-fit-decreasing island packing, stable on the island key
    order = sorted(
        range(len(islands)), key=lambda i: (-len(islands[i].nodes), islands[i].key)
    )
    bins: list[list[int]] = []  # island indices per shard
    room: list[int] = []
    for i in order:
        size = len(islands[i].nodes)
        placed = False
        for b, r in enumerate(room):
            if r >= size:
                bins[b].append(i)
                room[b] = r - size
                placed = True
                break
        if not placed:
            bins.append([i])
            room.append(cap - size)

    shards: list[Shard] = []
    node_shard = np.full(len(nodes), -1, np.int32)
    node_island = np.full(len(nodes), -1, np.int32)
    members: dict[tuple[int, str], list[int]] = {}
    part_shards: dict[str, set[int]] = {}
    for sid, isl_ids in enumerate(bins):
        isl_ids = sorted(isl_ids, key=lambda i: islands[i].key)
        idx: list[int] = []
        parts: set[str] = set()
        for i in isl_ids:
            isl = islands[i]
            idx.extend(isl.nodes)
            parts.add(isl.key[0])
            members.setdefault((sid, isl.key[0]), []).extend(isl.nodes)
            part_shards.setdefault(isl.key[0], set()).add(sid)
            for pos in isl.nodes:
                node_island[pos] = i
        node_arr = np.asarray(idx, np.int64)
        node_shard[node_arr] = sid
        shards.append(
            Shard(
                sid=sid,
                node_idx=node_arr,
                partitions=tuple(sorted(parts)),
                island_keys=tuple(islands[i].key for i in isl_ids),
            )
        )
    part_nodes = {
        p: np.concatenate(
            [np.asarray(members[(s, p)], np.int64) for s in sorted(sids)]
        )
        for p, sids in part_shards.items()
    }
    return ShardPlan(
        shards=shards,
        islands=islands,
        part_shards={p: tuple(sorted(s)) for p, s in part_shards.items()},
        name_pos=name_pos,
        pos_name=tuple(nd.name for nd in nodes),
        node_shard=node_shard,
        node_island=node_island,
        members={k: np.asarray(v, np.int64) for k, v in members.items()},
        part_nodes=part_nodes,
        token=(),
    )


def sub_partitions(
    plan: ShardPlan, partitions: list[PartitionInfo], sid: int
) -> list[PartitionInfo]:
    """Per-shard PartitionInfo list: each partition restricted to the
    nodes this shard owns (structural share of every other field)."""
    by_name = {p.name: p for p in partitions}
    out = []
    for pname in plan.shards[sid].partitions:
        p = by_name[pname]
        mine = plan.members[(sid, pname)]
        out.append(
            dataclasses.replace(
                p, nodes=tuple(plan.pos_name[int(i)] for i in mine)
            )
        )
    return out


def route_demand_vec(d: JobDemand | None) -> tuple[np.ndarray, int]:
    """(per-shard [cpu, mem, gpu] ask, shard count) for routing — the
    same totals-divided-across-shards rule the encoder lowers with."""
    if d is None:
        return np.asarray([1.0, 0.0, 0.0], np.float32), 1
    from slurm_bridge_tpu.core.arrays import array_len

    arr = array_len(d.array) if d.array else 1
    nsh = max(1, d.nodes)
    cpus = float(d.total_cpus(arr)) / nsh
    mem = float(d.total_mem_mb(arr)) / nsh
    gpu = 0.0
    if d.gres:
        parts = d.gres.split(":")
        try:
            gpu = float(int(parts[-1].split("(")[0]))
        except ValueError:
            gpu = 0.0
    return np.asarray([cpus, mem, gpu], np.float32), nsh


def route_jobs(
    plan: ShardPlan,
    free: np.ndarray,
    demands: list[JobDemand],
    all_pods: list,
    n_pending: int,
    priorities: list[float] | None = None,
) -> dict[int, list[int]]:
    """Assign every job index to one shard; returns shard id → global
    job indices (each list: pending ascending, then incumbents
    ascending — the per-shard ``all_pods`` order the executor encodes).

    Incumbents go to the shard owning their first hinted node (their
    allocation is already there). Pending jobs route in descending
    effective-priority order so high-rank gangs claim their best island
    first; the locality score is documented in the module docstring.
    """
    num_shards = plan.num_shards
    all_sids = tuple(range(num_shards))
    est_load = np.zeros(num_shards, np.float64)
    cap = np.asarray(
        [max(1.0, float(free[s.node_idx, 0].sum())) for s in plan.shards],
        np.float64,
    )
    out: dict[int, list[int]] = {}

    def assign(j: int, sid: int, load: float) -> None:
        out.setdefault(sid, []).append(j)
        est_load[sid] += load

    # incumbents first: pinned by their existing allocation
    for j in range(n_pending, len(all_pods)):
        pod = all_pods[j]
        hints = getattr(pod, "hint", None) or getattr(
            getattr(pod, "spec", None), "placement_hint", ()
        )
        sid = -1
        for h in hints:
            pos = plan.name_pos.get(h)
            if pos is not None and plan.node_shard[pos] >= 0:
                sid = int(plan.node_shard[pos])
                break
        if sid < 0:
            cands = plan.part_shards.get(demands[j].partition, all_sids)
            sid = cands[0]
        d, nsh = route_demand_vec(demands[j])
        assign(j, sid, float(d[0]) * nsh)

    if priorities is not None:
        prio = [float(priorities[j]) for j in range(n_pending)]
    else:
        prio = [
            float(demands[j].priority if demands[j] else 0.0)
            for j in range(n_pending)
        ]
    # feasibility memo: jobs draw from a handful of demand shapes, so
    # (shard, partition, demand) → (feasible count, best-island count)
    # turns 500k per-job vector scans into a few thousand — routing
    # stays O(jobs + shapes × shards), not O(jobs × nodes)
    feas_memo: dict[tuple, tuple[int, int]] = {}

    def feas_of(sid: int, part: str, d: np.ndarray) -> tuple[int, int]:
        key = (sid, part, d.tobytes())
        hit = feas_memo.get(key)
        if hit is None:
            m = plan.members.get((sid, part))
            if m is None:
                # "any partition" job: score the whole shard
                m = plan.shards[sid].node_idx
            ok = (free[m] >= d).all(axis=1)
            feas = int(ok.sum())
            isl_best = 0
            if feas:
                isl = plan.node_island[m[ok]]
                isl = isl[isl >= 0]
                isl_best = int(np.bincount(isl).max()) if isl.size else 0
            hit = feas_memo[key] = (feas, isl_best)
        return hit

    for j in sorted(range(n_pending), key=lambda j: (-prio[j], j)):
        part = demands[j].partition
        cands = plan.part_shards.get(part) if part else None
        if cands is None:
            cands = all_sids
        d, need = route_demand_vec(demands[j])
        load = float(d[0]) * need
        if len(cands) == 1:
            assign(j, cands[0], load)
            continue
        best = None
        for sid in cands:
            feas, isl_best = feas_of(sid, part, d)
            score = (
                feas >= need,
                isl_best >= need,
                -est_load[sid] / cap[sid],
                -sid,
            )
            if best is None or score > best[0]:
                best = (score, sid)
        assign(j, best[1], load)

    # per-shard order: pending ascending then incumbents ascending — the
    # JobRowCache key lists stay stable across steady-state ticks
    for sid, js in out.items():
        out[sid] = sorted(
            js, key=lambda j: (0 if j < n_pending else 1, j)
        )
    return out
