"""Cross-shard gang reconciliation — the second chance for gangs no
single shard could place.

A gang is routed whole to one shard (planner docstring), but a split
partition's free capacity may be scattered: shard A holds 3 feasible
nodes, shard B holds 2, and a 4-node gang fails in both even though the
PARTITION can host it. After every shard has solved, this pass re-solves
exactly those gangs against the merged residual-capacity view — the
per-shard ``free_after`` arrays scattered back onto the global node
axis — under the same rules as the policy backfill pass:

- **all-or-nothing**: a gang places completely or not at all (tentative
  takes roll back);
- **tightest-fit** node choice (least cpu headroom after placement), so
  reconciled gangs consume fragmentation instead of creating it;
- the **no-delay guard**: an assignment may not shrink the feasible node
  set of another still-unplaced equal-or-higher-rank gang below its
  size — reconciliation never trades a higher-priority gang's feasible
  start for a lower one's.

Candidates are processed rank-major (class rank desc, effective priority
desc, job index asc) and capped at ``limit`` per tick, mirroring the
backfill bounds. Everything is NumPy over per-partition member arrays —
the pass scales with failed gangs × partition size, not cluster size.
"""

from __future__ import annotations

import numpy as np


def reconcile_gangs(
    candidates: list[dict],
    free: np.ndarray,
    features: np.ndarray,
    part_nodes: dict[str, np.ndarray],
    *,
    limit: int = 512,
    node_tries: int = 8,
) -> list[tuple[int, list[int]]]:
    """Place failed gangs against the merged residual view.

    ``candidates``: one dict per fully-unplaced gang —
    ``{"j": global job index, "d": per-shard demand [3], "need": shard
    count, "part": partition name, "req": feature mask, "rank": class
    rank, "prio": effective priority}``. ``free`` is the global [N, 3]
    residual (mutated in place for every accepted gang); ``features``
    the global uint32 feature-mask column; ``part_nodes`` the planner's
    partition → member-position arrays.

    Returns ``(job index, chosen global node positions)`` per placed
    gang.
    """
    cands = sorted(
        candidates, key=lambda c: (-c["rank"], -c["prio"], c["j"])
    )[: max(0, limit)]

    def feas_mask(c, m):
        return ((free[m] >= c["d"]).all(axis=1)) & (
            (np.uint32(c["req"]) & ~features[m]) == 0
        )

    # protected set: gangs feasible NOW — their start must survive the
    # pass (the no-delay guard, same as policy/engine.py backfill)
    for c in cands:
        m = part_nodes.get(c["part"])
        if m is None or m.size < c["need"]:
            c["mask"] = None
            continue
        c["m"] = m
        c["mask"] = feas_mask(c, m)
        c["count"] = int(c["mask"].sum())
    protected = [c for c in cands if c["mask"] is not None and c["count"] >= c["need"]]

    out: list[tuple[int, list[int]]] = []
    for c in cands:
        if c["mask"] is None:
            continue
        m, d, need, rank = c["m"], c["d"], c["need"], c["rank"]
        fit = feas_mask(c, m)
        slots = np.nonzero(fit)[0]
        if slots.size < need:
            continue
        # tightest fit first: least cpu headroom after placement
        slots = slots[np.argsort(free[m[slots], 0] - d[0], kind="stable")]
        chosen: list[int] = []  # member-local positions
        hits: list = []  # (protected gang, member-local pos) reductions
        rolled = False
        for s in slots[: max(need, node_tries)].tolist():
            n = int(m[s])
            bad = False
            n_hits = []
            for g in protected:
                if g is c or g["rank"] < rank:
                    continue
                # g's mask is over ITS member array; same partition ⇒
                # same array, so the local index transfers directly
                if g["part"] != c["part"] or not g["mask"][s]:
                    continue
                if not (free[n] - d >= g["d"]).all():
                    if g["count"] - 1 < g["need"]:
                        bad = True
                        break
                    n_hits.append((g, s))
            if bad:
                continue
            free[n] -= d
            for g, gs in n_hits:
                g["mask"] = g["mask"].copy()
                g["mask"][gs] = False
                g["count"] -= 1
            hits.extend(n_hits)
            chosen.append(s)
            if len(chosen) == need:
                break
        if len(chosen) < need:
            # all-or-nothing: roll the tentative takes back (restoring
            # free restores exactly the feasibility each hit recorded)
            for s in chosen:
                free[int(m[s])] += d
            for g, gs in hits:
                g["mask"] = g["mask"].copy()
                g["mask"][gs] = True
                g["count"] += 1
            rolled = True
        if rolled:
            continue
        if c in protected:
            protected.remove(c)  # it started; nothing left to guard
        out.append((c["j"], [int(m[s]) for s in chosen]))
    return out
