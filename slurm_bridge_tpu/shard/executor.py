"""Per-shard encode+solve fan-out + merge — the sharded tick engine.

One :class:`ShardExecutor` replaces the scheduler's monolithic
``_solve_local`` when sharding is on (``PlacementScheduler(shard=...)``):

1. **plan** — the partition/island shard layout (planner.py), cached
   while the partition layout is unchanged;
2. **route** — every pending job and incumbent to one shard (gangs
   whole, rank-aware locality score);
3. **encode** — per shard, against per-shard :class:`EncodedInventory` /
   :class:`JobRowCache` instances that carry across ticks exactly like
   the monolithic caches (identity window, column-diff delta, row
   reuse). The feature-code table is SHARED across shards — one bit
   space, assigned in serial shard order — so feature masks stay
   comparable when the reconcile pass mixes rows from different shards;
4. **solve** — per shard, fanned across a lazily-built worker pool (the
   same reuse-across-ticks / ``with_current_span`` discipline as the
   provider pod-sync pool). The per-shard router mirrors the monolithic
   one (greedy pin-through, indexed native below the dispatch floor)
   and PROMOTES big shards to the multi-device shard_map sweep
   (``solver/sharded.py`` — the MULTICHIP_r05 dp4×mp2 parity dryrun,
   now on the routed path) with a CPU fallback to the native packer if
   the device solve is unavailable or raises;
5. **merge + reconcile** — per-shard placements map back to global job
   indices; per-shard residuals scatter onto the global node axis and
   gangs no shard could place get the cross-shard all-or-nothing pass
   (reconcile.py).

Determinism: routing, encode order, merge order and reconciliation are
all keyed on shard/job ids — the worker pool only changes WHEN a shard
solves, never what it returns, so any ``workers`` width produces the
same tick byte-for-byte (shard-smoke double-runs it).
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from slurm_bridge_tpu.obs import explain as explain_mod
from slurm_bridge_tpu.obs.metrics import REGISTRY, Histogram
from slurm_bridge_tpu.obs.tracing import TRACER, with_current_span
from slurm_bridge_tpu.shard.planner import (
    ShardConfig,
    ShardPlan,
    build_plan,
    plan_token,
    route_jobs,
    sub_partitions,
)
from slurm_bridge_tpu.shard.reconcile import reconcile_gangs
from slurm_bridge_tpu.solver import greedy_place
from slurm_bridge_tpu.solver.encoder import EncodedInventory, JobRowCache
from slurm_bridge_tpu.solver.snapshot import PAD_PARTITION, Placement, pad_batch

log = logging.getLogger("sbt.shard")

_shard_solve_seconds = REGISTRY.histogram(
    "sbt_shard_solve_seconds",
    "per-shard encode+solve wall time",
    buckets=Histogram.FAST_BUCKETS,
)
_shard_ticks = REGISTRY.counter(
    "sbt_shard_ticks_total", "sharded solve ticks executed"
)
_shard_count = REGISTRY.gauge(
    "sbt_shard_count", "shards in the current plan"
)
_shard_route = REGISTRY.counter(
    "sbt_shard_route_total", "per-shard solves by engine chosen"
)
_shard_jobs = REGISTRY.counter(
    "sbt_shard_jobs_routed_total", "jobs routed into shards"
)
_shard_reconcile = REGISTRY.counter(
    "sbt_shard_reconcile_gangs_total",
    "cross-shard reconcile outcomes, labeled placed|unplaced",
)


class _ShardState:
    """Cross-tick caches for one shard (mirrors the monolithic pair)."""

    __slots__ = ("inv", "rows", "solver")

    def __init__(self, feature_codes: dict):
        self.inv = EncodedInventory()
        # ONE feature-bit space across every shard: _rebuild grows this
        # dict in place and never replaces it, so sharing the object is
        # enough to keep masks comparable cross-shard
        self.inv.feature_codes = feature_codes
        self.rows = JobRowCache()
        self.solver = None  # DeviceSolver, built on first device route


class ShardExecutor:
    def __init__(
        self,
        config: ShardConfig | None = None,
        *,
        backend: str = "auto",
        auction_config=None,
        bucket: int = 1024,
    ):
        from slurm_bridge_tpu.solver import AuctionConfig

        self.config = config or ShardConfig()
        self.backend = backend
        self.auction_config = auction_config or AuctionConfig()
        self.bucket = bucket
        self._plan: ShardPlan | None = None
        self._plan_key: tuple | None = None
        self._states: dict[int, _ShardState] = {}
        self._feature_codes: dict[str, int] = {}
        #: per-tick sub-list cache: same global (nodes, partitions) lists
        #: (the scheduler's inventory_ttl window) reuse the same sub-list
        #: objects, so per-shard EncodedInventory identity hits fire.
        #: Holds the list objects themselves (identity-compared) — a bare
        #: id() key could false-hit when a freed list's address is
        #: recycled and silently serve last tick's inventory
        self._sub_cache: tuple[object, object, dict] | None = None
        self._pool = None
        self._pool_lock = threading.Lock()
        #: features-tuple → folded uint32 mask, invalidated when the
        #: shared code table grows (reconcile's idle-shard fold)
        self._feat_memo: dict[tuple, int] = {}
        self._feat_memo_token = -1
        #: device solves serialize — one accelerator, many shards
        self._device_lock = threading.Lock()
        # ---- per-tick observability (the scheduler/harness read these)
        self.last_encode_ms = 0.0
        self.last_shards_used = 0
        self.last_reconcile_attempts = 0
        self.last_reconcile_placed = 0
        self.last_routes: dict[str, int] = {}
        #: streaming-admission seam (ISSUE 12): when the scheduler asks
        #: (``capture_residual=True``), the merged post-backfill residual
        #: is packaged as (snapshot-like, residual, plan) — the window
        #: the fast path admits against between ticks. None otherwise:
        #: admission-off ticks pay nothing for the seam.
        self.last_window: tuple | None = None
        self._capture_residual = False
        #: explainability seam (ISSUE 15): the merged residual + one
        #: record per unplaced pending job (shard id, spill flag), what
        #: the scheduler's attribution pass reads. None when explain is
        #: off — explain-off ticks pay nothing for the seam.
        self.last_explain_inputs = None
        self._explain = False
        self._trail = None
        self._trail_job = -1
        #: (nodes list ref) → [N, 3] capacity columns memo (identity-
        #: stable node lists make steady generations rebuild nothing)
        self._explain_cap_memo: tuple | None = None
        #: (partitions ref, plan ref) → (partition_codes, partition_of)
        #: memo for the window snapshot build
        self._window_parts: tuple | None = None
        # ---- run aggregates (determinism/quality sections) ----
        self.ticks_total = 0
        self.reconcile_attempts_total = 0
        self.reconcile_placed_total = 0
        self.locality_sum = 0.0
        self.locality_count = 0
        #: fleet seam (ISSUE 17): when a FleetRuntime is attached, the
        #: greedy/native per-shard solves dispatch to the shard owner's
        #: solver sidecar over gRPC (byte-parity by construction); None
        #: keeps every solve in-process with zero overhead
        self.remote = None
        #: drift re-key probe cache: (base plan key, base plan) so the
        #: drained-fraction check doesn't rebuild the base plan per tick
        self._drift_probe: tuple | None = None

    # ---- plan + sub-inventory caching ----

    def _ensure_plan(self, partitions, nodes) -> ShardPlan:
        key = plan_token(partitions, nodes, self.config)
        drained = frozenset()
        if self.config.drift_rekey_fraction > 0:
            key, drained = self._drift_key(key, partitions, nodes)
        if self._plan is None or key != self._plan_key:
            self._plan = build_plan(partitions, nodes, self.config, drained)
            self._plan_key = key
            # a re-plan re-keys every shard's node set: drop shard states
            # whose ids fall away; survivors keep their caches (their
            # EncodedInventory rebuilds itself on the first refresh)
            self._states = {
                sid: st
                for sid, st in self._states.items()
                if sid < self._plan.num_shards
            }
            self._sub_cache = None
            _shard_count.set(self._plan.num_shards)
        return self._plan

    def _drift_key(self, base_key, partitions, nodes):
        """Drift re-key probe (ISSUE 17): when any BASE-plan shard's
        drained fraction exceeds ``drift_rekey_fraction``, the effective
        plan key grows the drained set — deterministic (a pure function
        of node states) and cheap (the base plan is cached on its own
        key; the per-shard check is one membership scan)."""
        from slurm_bridge_tpu.shard.planner import drained_positions

        drained = drained_positions(nodes)
        if not drained:
            return base_key, frozenset()
        if self._drift_probe is None or self._drift_probe[0] != base_key:
            self._drift_probe = (
                base_key, build_plan(partitions, nodes, self.config)
            )
        base_plan = self._drift_probe[1]
        thresh = self.config.drift_rekey_fraction
        for shard in base_plan.shards:
            hit = sum(1 for pos in shard.node_idx if int(pos) in drained)
            if hit and hit / len(shard.node_idx) > thresh:
                return (base_key, tuple(sorted(drained))), drained
        return base_key, frozenset()

    def _sub_lists(self, plan, partitions, nodes, sid):
        if (
            self._sub_cache is None
            or self._sub_cache[0] is not nodes
            or self._sub_cache[1] is not partitions
        ):
            self._sub_cache = (nodes, partitions, {})
        cache = self._sub_cache[2]
        ent = cache.get(sid)
        if ent is None:
            shard = plan.shards[sid]
            ent = (
                [nodes[int(i)] for i in shard.node_idx],
                sub_partitions(plan, partitions, sid),
            )
            cache[sid] = ent
        return ent

    def _state(self, sid: int) -> _ShardState:
        st = self._states.get(sid)
        if st is None:
            st = self._states[sid] = _ShardState(self._feature_codes)
        return st

    # ---- per-shard mirror ownership (ISSUE 16) ----

    def mirror_groups(self, partitions: list[str]) -> list[list[str]]:
        """Partition names grouped by OWNING shard — the mirror-ownership
        split of the tentpole: each group is one shard's slice of the
        cluster, so the harness can classify/sweep/repair one shard's
        pods as a unit (and pipeline one group's status fetch under the
        next group's classification) instead of running a single global
        provider pass.

        Grouping is pure plan lookup: a partition belongs to the
        LOWEST shard id that holds any of its nodes (``part_shards`` is
        ascending); partitions the current plan does not know (mid-tick
        additions before a re-plan) own themselves as a pseudo-shard.
        Groups are the maximal CONTIGUOUS runs of the sorted input that
        share an owner, so the flattened output is byte-for-byte the
        sorted input — the digest-critical invariant: every side effect
        of the mirror (vnode registration uids, submit batches, status
        writes) happens in exactly the order the global pass produced,
        no matter how ownership fragments the name ordering. A shard
        whose partitions interleave with another's in name order simply
        owns several runs. With no plan yet — or sharding off — every
        partition lands in one group, which is exactly the global
        mirror pass."""
        ordered = sorted(partitions)
        if self._plan is None or not self._plan.part_shards:
            return [ordered] if ordered else []
        groups: list[list[str]] = []
        prev_owner: object = None
        for name in ordered:
            sids = self._plan.part_shards.get(name)
            owner: object = int(sids[0]) if sids else None
            if not groups or owner is None or owner != prev_owner:
                groups.append([name])
            else:
                groups[-1].append(name)
            prev_owner = owner
        return groups

    # ---- the sharded solve ----

    def solve(
        self,
        partitions,
        nodes,
        demands,
        all_pods,
        n_pending,
        *,
        priorities=None,
        demand_key=None,
        policy=None,
        deductions=None,
        capture_residual: bool = False,
        explain: bool = False,
        trail=None,
        trail_job: int = -1,
    ) -> tuple[dict[int, list[str]], list[int]]:
        """The sharded equivalent of ``PlacementScheduler._solve_local``:
        returns (global job index → assigned node names, global
        incumbent indices that lost their nodes).

        ``deductions`` (streaming admission) — in-flight fast-path
        binds, ``name → (hint node names, per-shard demand vec)`` —
        are subtracted from both the routing free view and each
        per-shard snapshot, so the fan-out can never double-claim
        fast-claimed capacity."""
        self._capture_residual = capture_residual
        self._explain = explain
        self._trail = trail
        self._trail_job = trail_job
        self.last_explain_inputs = None
        plan = self._ensure_plan(partitions, nodes)
        _shard_ticks.inc()
        self.ticks_total += 1
        # demand routing gets its own span (ISSUE 11 satellite): at the
        # 500k shape the free-array build + rank-aware routing is most of
        # the solve time the shard.encode/solve children did not explain
        with TRACER.span("scheduler.shard.route") as route_span:
            free = np.asarray(
                [
                    (nd.free_cpus, nd.free_memory_mb, nd.free_gpus)
                    if nd.schedulable
                    else (0.0, 0.0, 0.0)
                    for nd in nodes
                ],
                np.float32,
            )
            if deductions:
                for _nm, (hint, dvec) in sorted(deductions.items()):
                    for h in hint:
                        pos = plan.name_pos.get(h)
                        if pos is not None:
                            free[pos] -= dvec
            routed = route_jobs(
                plan, free, demands, all_pods, n_pending, priorities
            )
            route_span.count("jobs", len(all_pods))
            route_span.count("shards", len(routed))
            route_span.count("nodes", len(nodes))
            if trail is not None and trail_job >= 0:
                for sid in sorted(routed):
                    if trail_job in routed[sid]:
                        shard = plan.shards[sid]
                        trail.add(
                            "route",
                            f"routed whole to shard {sid} (partitions "
                            f"{','.join(shard.partitions)}, "
                            f"{len(shard.node_idx)} nodes)",
                        )
                        break
        _shard_jobs.inc(len(all_pods))
        self.last_shards_used = len(routed)
        if demand_key is None:
            demand_key = lambda pod: id(pod)  # noqa: E731 - test seam

        # ---- encode (serial: the shared feature table must grow in
        # deterministic shard order) ----
        t0 = time.perf_counter()
        work: list[tuple] = []
        for sid in sorted(routed):
            jobs_s = routed[sid]
            with TRACER.span("scheduler.shard.encode") as enc_span:
                enc_span.set_tag("shard", str(sid))
                st = self._state(sid)
                sub_nodes, sub_parts = self._sub_lists(
                    plan, partitions, nodes, sid
                )
                snapshot = st.inv.refresh(sub_nodes, sub_parts)
                if deductions:
                    name_idx_s = st.inv.name_idx
                    for _nm, (hint, dvec) in sorted(deductions.items()):
                        for h in hint:
                            spos = name_idx_s.get(h)
                            if spos is not None:
                                snapshot.free[spos] -= dvec
                demands_s = [demands[j] for j in jobs_s]
                prio_s = (
                    [priorities[j] for j in jobs_s]
                    if priorities is not None
                    else None
                )
                batch = st.rows.encode(
                    [demand_key(all_pods[j]) for j in jobs_s],
                    demands_s,
                    snapshot,
                    codes_token=st.inv.codes_token(),
                    priorities=prio_s,
                )
                enc_span.count("rows", int(batch.num_shards))
                enc_span.count("jobs", len(jobs_s))
                n_pend_local = sum(1 for j in jobs_s if j < n_pending)
                incumbent, shard_rows = self._pin_incumbents(
                    st, snapshot, batch, all_pods, jobs_s, n_pend_local
                )
            work.append(
                (sid, st, snapshot, batch, incumbent, shard_rows, jobs_s,
                 n_pend_local)
            )
        self.last_encode_ms = (time.perf_counter() - t0) * 1e3

        # ---- solve (fanned; results keyed by shard id) ----
        self.last_routes = {}
        results: dict[int, Placement] = {}

        def run_one(item):
            sid, st, snapshot, batch, incumbent = item[:5]
            t1 = time.perf_counter()
            with TRACER.span("scheduler.shard.solve") as span:
                span.set_tag("shard", str(sid))
                placement, engine = self._solve_shard(
                    st, snapshot, batch, incumbent, sid=sid
                )
                span.set_tag("engine", engine)
                span.count("shards", int(batch.num_shards))
                span.count("nodes", snapshot.num_nodes)
            _shard_solve_seconds.observe(time.perf_counter() - t1)
            _shard_route.inc(engine=engine)
            return sid, placement, engine

        workers = max(1, self.config.workers)
        if workers > 1 and len(work) > 1:
            parent = TRACER.current()
            pool = self._get_pool(workers)

            def run_traced(item):
                with with_current_span(parent):
                    return run_one(item)

            outs = list(pool.map(run_traced, work))
        else:
            outs = [run_one(item) for item in work]
        for sid, placement, engine in outs:
            results[sid] = placement
            self.last_routes[engine] = self.last_routes.get(engine, 0) + 1

        return self._merge(
            plan, free, work, results, demands, all_pods, n_pending, policy,
            nodes,
        )

    def _get_pool(self, workers: int):
        with self._pool_lock:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="sbt-shard"
                )
            return self._pool

    def close(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    # ---- per-shard internals (mirror _solve_local / _solve) ----

    def _pin_incumbents(
        self, st, snapshot, batch, all_pods, jobs_s, n_pend_local
    ):
        """Streaming-incumbent pinning, per shard: release usage, pin
        rows to held nodes, drop shards whose hint vanished, +0.5
        tie-break — exactly the monolithic semantics."""
        name_idx = st.inv.name_idx
        incumbent = np.full(batch.num_shards, -1, np.int32)
        shard_rows: dict[int, list[int]] = {}
        for row in range(batch.num_shards):
            shard_rows.setdefault(int(batch.job_of[row]), []).append(row)
        for lj in range(n_pend_local, len(jobs_s)):
            pod = all_pods[jobs_s[lj]]
            hints = getattr(pod, "hint", None) or getattr(
                getattr(pod, "spec", None), "placement_hint", ()
            )
            for k, row in enumerate(shard_rows.get(lj, [])):
                node = name_idx.get(hints[k]) if k < len(hints) else None
                if node is not None:
                    incumbent[row] = node
                    snapshot.free[node] += batch.demand[row]
                else:
                    batch.partition_of[row] = PAD_PARTITION
                    batch.demand[row] = 0.0
        if n_pend_local < len(jobs_s):
            batch.priority[batch.job_of >= n_pend_local] += 0.5
        return incumbent, shard_rows

    def _remote_solve(self, sid, engine, policy, snapshot, batch, incumbent):
        """Fleet dispatch (ISSUE 17): ship this shard's columns to its
        owning replica's solver sidecar. None -> solve inline (no fleet
        attached, shard unkeyed, or the remembered-fallback path after a
        sidecar death — the tick always completes)."""
        remote = self.remote
        if remote is None or sid < 0:
            return None
        return remote.try_solve(sid, engine, policy, snapshot, batch, incumbent)

    def _solve_shard(self, st, snapshot, batch, incumbent, sid=-1):
        """Route ONE shard's solve; returns (placement, engine name).

        The remote engine names ("greedy-remote"/"native-remote") surface
        in ``last_routes`` and metrics only — placements are byte-parity
        with inline (fleet/columnar.py), so digests never see the split.
        """
        if self.backend == "greedy":
            placement = self._remote_solve(
                sid, "greedy", "", snapshot, batch, incumbent
            )
            if placement is not None:
                return placement, "greedy-remote"
            return (
                greedy_place(snapshot, batch, incumbent=incumbent),
                "greedy",
            )
        # promoted device path: a shard big enough to amortize the mesh
        # collectives rides the shard_map sweep whenever ≥2 devices
        # exist (MULTICHIP_r05: dp4×mp2 parity ≥90% vs single-device);
        # anything that goes wrong degrades to the native packer — the
        # CPU fallback that keeps a device-less (or wedged-chip) host
        # solving every tick
        cells = batch.num_shards * snapshot.num_nodes
        if self.config.device_solve is not False:
            forced = self.config.device_solve is True
            if forced or cells >= self.config.sharded_threshold:
                placement = self._try_device_sharded(
                    snapshot, batch, incumbent, forced
                )
                if placement is not None:
                    return placement, "auction-sharded"
        from slurm_bridge_tpu.solver.routing import (
            choose_path,
            gang_shard_fraction,
            incumbent_fraction,
            native_fit_policy,
        )

        if self.backend == "auto":
            route = choose_path(
                batch.num_shards,
                snapshot.num_nodes,
                gang_fraction=gang_shard_fraction(batch.gang_id),
                inc_fraction=incumbent_fraction(incumbent),
            )
            if route == "native":
                from slurm_bridge_tpu.solver.indexed_native import (
                    indexed_place_native,
                )

                policy = native_fit_policy(bool((incumbent >= 0).any()))
                placement = self._remote_solve(
                    sid, "native", policy, snapshot, batch, incumbent
                )
                if placement is not None:
                    return placement, "native-remote"
                return (
                    indexed_place_native(
                        snapshot,
                        batch,
                        incumbent=incumbent,
                        policy=policy,
                    ),
                    "native",
                )
        # single-device auction (explicit auction pin, or auto-device):
        # serialized — shards share one accelerator
        from slurm_bridge_tpu.solver.session import DeviceSolver

        p_real = batch.num_shards
        if self.bucket:
            batch = pad_batch(batch, self.bucket)
            if batch.num_shards != p_real:
                incumbent = np.concatenate(
                    [incumbent, np.full(batch.num_shards - p_real, -1, np.int32)]
                )
        with self._device_lock:
            if st.solver is None:
                st.solver = DeviceSolver(snapshot, self.auction_config)
            else:
                st.solver.update_snapshot(snapshot)
            placement = st.solver.solve(batch, incumbent=incumbent)
        if placement.node_of.shape[0] != p_real:
            placement = Placement(
                node_of=placement.node_of[:p_real],
                placed=placement.placed[:p_real],
                free_after=placement.free_after,
            )
        return placement, "auction"

    def _try_device_sharded(
        self, snapshot, batch, incumbent, forced: bool
    ) -> Placement | None:
        """The shard_map sweep, or None (→ native fallback). Import and
        device probing both live inside the guard: a host without JAX
        devices must not pay (or crash on) backend init per tick."""
        try:
            from slurm_bridge_tpu.parallel.backend import ensure_backend

            ensure_backend()
            import jax

            if len(jax.devices()) < 2:
                return None
            from slurm_bridge_tpu.solver.sharded import sharded_place

            p_real = batch.num_shards
            inc = incumbent
            if self.bucket:
                batch = pad_batch(batch, self.bucket)
                if batch.num_shards != p_real:
                    inc = np.concatenate(
                        [inc, np.full(batch.num_shards - p_real, -1, np.int32)]
                    )
            with self._device_lock:
                placement = sharded_place(
                    snapshot, batch, self.auction_config, incumbent=inc
                )
            if placement.node_of.shape[0] != p_real:
                placement = Placement(
                    node_of=placement.node_of[:p_real],
                    placed=placement.placed[:p_real],
                    free_after=placement.free_after,
                )
            return placement
        except Exception:
            # wedged chip / missing mesh / OOM: the tick must still
            # solve — log once per occurrence and take the CPU path
            log.warning(
                "device shard_map solve failed%s; falling back to the "
                "native packer for this shard",
                " (forced)" if forced else "",
                exc_info=True,
            )
            return None

    # ---- merge + reconcile ----

    def _merge(
        self, plan, free, work, results, demands, all_pods, n_pending,
        policy, nodes,
    ):
        with TRACER.span("scheduler.shard.merge") as merge_span:
            out = self._merge_traced(
                plan, free, work, results, demands, all_pods, n_pending,
                policy, nodes,
            )
            merge_span.count("jobs_placed", len(out[0]))
            merge_span.count("lost", len(out[1]))
            return out

    def _merge_traced(
        self, plan, free, work, results, demands, all_pods, n_pending,
        policy, nodes,
    ):
        by_job_names: dict[int, list[str]] = {}
        lost_jobs: list[int] = []
        residual = free.copy()
        #: integral-granularity correction for the ADMISSION window only
        #: (reconcile keeps the float residual byte-for-byte): this
        #: tick's pending binds re-subtracted at ceil — see the
        #: monolithic seam in bridge/scheduler.py
        win_adj = np.zeros_like(residual) if self._capture_residual else None
        failed_gangs: list[dict] = []
        names_of = plan.pos_name
        for item in work:
            (sid, st, snapshot, batch, incumbent, shard_rows, jobs_s,
             n_pend_local) = item
            placement = results[sid]
            node_idx = plan.shards[sid].node_idx
            residual[node_idx] = placement.free_after
            if win_adj is not None:
                pr = np.nonzero(
                    placement.placed & (batch.job_of < n_pend_local)
                )[0]
                if pr.size:
                    adj = np.ceil(batch.demand[pr]) - batch.demand[pr]
                    np.add.at(
                        win_adj, node_idx[placement.node_of[pr]], adj
                    )
            by_local = placement.by_job(batch)
            if policy is not None and policy.config.backfill:
                for row, node in policy.backfill(
                    snapshot, batch, placement, n_pend_local,
                    rank_of=lambda lj, js=jobs_s: policy.class_rank_of_job(js[lj]),
                ):
                    by_local.setdefault(int(batch.job_of[row]), []).append(node)
                    residual[int(node_idx[node])] -= batch.demand[row]
                    if win_adj is not None:
                        win_adj[int(node_idx[node])] += (
                            np.ceil(batch.demand[row]) - batch.demand[row]
                        )
            for lj, idxs in by_local.items():
                by_job_names[jobs_s[lj]] = [
                    snapshot.node_names[i] for i in idxs
                ]
            for lj in range(n_pend_local, len(jobs_s)):
                if any(
                    incumbent[r] >= 0 and placement.node_of[r] != incumbent[r]
                    for r in shard_rows.get(lj, [])
                ):
                    lost_jobs.append(jobs_s[lj])
            # fully-unplaced pending gangs → reconcile candidates
            if self.config.reconcile:
                for lj in range(n_pend_local):
                    rows = shard_rows.get(lj, [])
                    if len(rows) <= 1 or lj in by_local:
                        continue
                    if any(placement.placed[r] for r in rows):
                        continue  # partial remnants are dead this tick
                    r0 = rows[0]
                    j = jobs_s[lj]
                    failed_gangs.append({
                        "j": j,
                        "d": batch.demand[r0].copy(),
                        "need": len(rows),
                        "part": demands[j].partition,
                        "req": int(batch.req_features[r0]),
                        "rank": (
                            policy.class_rank_of_job(j)
                            if policy is not None
                            else 0
                        ),
                        "prio": float(batch.priority[r0]),
                    })
        lost_jobs.sort()

        self.last_reconcile_attempts = len(failed_gangs)
        self.last_reconcile_placed = 0
        #: global feature masks, shared by reconcile and the explain
        #: capture below — built at most once per tick, and ONLY when
        #: something actually needs them (spilled gangs here; unplaced
        #: jobs in the capture's own fallback)
        gfeats = (
            self._global_features(plan, work, nodes) if failed_gangs else None
        )
        #: gangs that reached the reconcile pass and STILL failed — the
        #: SHARD_SPILL population the attribution pass marks
        spilled: set[int] = set()
        if failed_gangs:
            # the cross-shard pass runs ONLY when some shard reported
            # spill — zero failed gangs means zero reconcile cost (and no
            # span: absence in the tree IS the attribution)
            with TRACER.span("scheduler.shard.reconcile") as rec_span:
                placed = reconcile_gangs(
                    failed_gangs,
                    residual,
                    gfeats,
                    plan.part_nodes,
                    limit=self.config.reconcile_limit,
                )
                rec_span.count("attempts", len(failed_gangs))
                rec_span.count("placed", len(placed))
            self.last_reconcile_placed = len(placed)
            spilled = {c["j"] for c in failed_gangs} - {j for j, _ in placed}
            if self._trail is not None and self._trail_job >= 0:
                tj = self._trail_job
                if any(c["j"] == tj for c in failed_gangs):
                    took = next((ns for j, ns in placed if j == tj), None)
                    self._trail.add(
                        "reconcile",
                        "cross-shard pass placed the gang on the merged "
                        "residual"
                        if took is not None
                        else "cross-shard pass attempted the gang against "
                        "the merged residual and could not place it",
                    )
            if win_adj is not None and placed:
                # reconcile debits `residual` at the float model (that
                # residual is reconcile's own byte-pinned contract);
                # the ADMISSION window needs the integral-granularity
                # correction for these placements too, or it would
                # overstate free capacity on exactly the nodes the
                # reconciled gangs are about to allocate
                d_of = {c["j"]: c["d"] for c in failed_gangs}
                for j, positions in placed:
                    dv = d_of[j]
                    adj = np.ceil(dv) - dv
                    for p in positions:
                        win_adj[p] += adj
            for j, positions in placed:
                by_job_names[j] = [names_of[p] for p in positions]
            _shard_reconcile.inc(len(placed), outcome="placed")
            _shard_reconcile.inc(
                len(failed_gangs) - len(placed), outcome="unplaced"
            )
        self.reconcile_attempts_total += self.last_reconcile_attempts
        self.reconcile_placed_total += self.last_reconcile_placed
        self._note_locality(plan, by_job_names, demands, n_pending)
        if self._capture_residual:
            self.last_window = (
                self._window_snapshot(plan, work, nodes, demands),
                residual - win_adj,
                plan,
            )
        if self._explain:
            # explainability capture (ISSUE 15): one record per unplaced
            # pending job, read straight from the per-shard batch rows —
            # the residual is the float-model merged free AFTER backfill
            # and reconcile (the window above keeps its own ceil-adjusted
            # copy, so sharing `residual` here is safe)
            jobs_x: list[explain_mod.UnplacedJob] = []
            for item in work:
                (sid, _st, _snap, batch, _inc, shard_rows, jobs_s,
                 n_pend_local) = item
                for lj in range(n_pend_local):
                    j = jobs_s[lj]
                    if j in by_job_names:
                        continue
                    rows = shard_rows.get(lj)
                    if not rows:
                        continue
                    r0 = rows[0]
                    jobs_x.append(
                        explain_mod.UnplacedJob(
                            j=j,
                            partition=demands[j].partition,
                            d=batch.demand[r0].copy(),
                            need=len(rows),
                            req=int(batch.req_features[r0]),
                            shard=sid,
                            spilled=j in spilled,
                        )
                    )
            if jobs_x:
                self.last_explain_inputs = explain_mod.ExplainInputs(
                    free=residual,
                    capacity=self._capacity_cols(nodes),
                    features=(
                        gfeats
                        if gfeats is not None
                        else self._global_features(plan, work, nodes)
                    ),
                    part_members=plan.part_nodes,
                    jobs=jobs_x,
                )
            # a fully-placed tick keeps last_explain_inputs None: no
            # capacity columns, no global feature scatter — zero
            # explain cost beyond the unplaced scan above
        return by_job_names, lost_jobs

    def _capacity_cols(self, nodes) -> np.ndarray:
        """[N, 3] total-capacity columns on the global node axis,
        memoized on the (identity-stable) node list the decode caches
        replay while the inventory is unchanged."""
        memo = self._explain_cap_memo
        if memo is not None and memo[0] is nodes:
            return memo[1]
        cap = np.asarray(
            [(nd.cpus, nd.memory_mb, nd.gpus) for nd in nodes], np.float32
        )
        self._explain_cap_memo = (nodes, cap)
        return cap

    def _window_snapshot(self, plan, work, nodes, demands):
        """A global-axis ClusterSnapshot for the admission window: the
        per-shard snapshots stitched back onto the plan's node order —
        shared feature-code table, so demand feature masks stay
        comparable, and a partitions-identity memo so the per-tick cost
        is the feature scatter plus one free-array handoff."""
        from slurm_bridge_tpu.solver.snapshot import (
            ClusterSnapshot,
            node_partition_map,
        )

        parts_ref = self._sub_cache[1] if self._sub_cache else None
        memo = self._window_parts
        if memo is None or memo[0] is not parts_ref or memo[1] is not plan:
            # rebuild the partition coding off the CURRENT partitions
            # list (identity-keyed, like every other per-tick memo)
            partitions = parts_ref if parts_ref is not None else []
            partition_codes, node_part = node_partition_map(partitions)
            partition_of = np.fromiter(
                (node_part.get(nm, -1) for nm in plan.pos_name),
                np.int32,
                len(plan.pos_name),
            )
            memo = self._window_parts = (
                parts_ref, plan, partition_codes, partition_of,
            )
        _p, _pl, partition_codes, partition_of = memo
        return ClusterSnapshot(
            node_names=list(plan.pos_name),
            capacity=np.zeros((len(plan.pos_name), 3), np.float32),
            free=np.zeros((0, 3), np.float32),  # the window carries its own
            partition_of=partition_of,
            features=self._global_features(plan, work, nodes),
            partition_codes=partition_codes,
            feature_codes=self._feature_codes,
        )

    def _global_features(self, plan, work, nodes) -> np.ndarray:
        """Per-node uint32 feature masks on the global axis, assembled
        from the per-shard snapshots (one shared code table ⇒ masks are
        directly comparable). Shards NO job routed to this tick have no
        snapshot — their nodes fold masks straight from the shared code
        table, because leaving them 0 would make reconcile reject
        feature-requiring gangs on exactly the idle capacity the pass
        exists to reach."""
        feats = np.zeros(plan.node_shard.shape[0], np.uint32)
        covered: set[int] = set()
        for item in work:
            sid, _st, snapshot = item[0], item[1], item[2]
            feats[plan.shards[sid].node_idx] = snapshot.features
            covered.add(sid)
        codes = self._feature_codes
        if self._feat_memo_token != len(codes):
            # a grown code table re-resolves previously-unknown features
            self._feat_memo = {}
            self._feat_memo_token = len(codes)
        memo = self._feat_memo
        for shard in plan.shards:
            if shard.sid in covered:
                continue
            for pos in shard.node_idx.tolist():
                ft = nodes[pos].features
                m = memo.get(ft)
                if m is None:
                    m = 0
                    for f in ft:
                        bit = codes.get(f)
                        if bit is not None:
                            m |= 1 << bit
                    memo[ft] = m
                feats[pos] = np.uint32(m)
        return feats

    def _note_locality(self, plan, by_job_names, demands, n_pending) -> None:
        """Rank-locality accounting: for every placed pending gang, the
        fraction of its shards inside ONE island (1.0 = fully
        ICI-local). The scorecard reports the run mean."""
        for j, names in by_job_names.items():
            if j >= n_pending or len(names) <= 1:
                continue
            isl = [
                int(plan.node_island[plan.name_pos[n]])
                for n in names
                if n in plan.name_pos
            ]
            if not isl:
                continue
            counts = np.bincount(np.asarray([i for i in isl if i >= 0]))
            best = int(counts.max()) if counts.size else 0
            self.locality_sum += best / len(names)
            self.locality_count += 1

    # ---- observability rollups ----

    def stats(self) -> dict:
        """Deterministic run aggregates (harness determinism/quality)."""
        return {
            "shard_count": self._plan.num_shards if self._plan else 0,
            "shard_ticks": self.ticks_total,
            "reconcile_attempts": self.reconcile_attempts_total,
            "reconcile_placed": self.reconcile_placed_total,
            "gang_rank_locality_mean": (
                round(self.locality_sum / self.locality_count, 4)
                if self.locality_count
                else None
            ),
            "gangs_scored": self.locality_count,
        }
