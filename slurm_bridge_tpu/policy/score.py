"""Placement-quality scorecard — what a sim scenario is scored on
besides speed (ISSUE 9: "placement quality as a first-class metric").

The harness feeds a :class:`QualityTracker` per tick (all virtual-time
data, fully deterministic) and the scorecard lands in the scenario JSON
next to ``tick_p50_ms``:

- **utilization** — allocated / total cpu over the run (mean + p50 of
  per-tick samples, sim ground truth);
- **fragmentation index** — the stranded-capacity measure from the
  constraint-packing literature (arxiv 2511.08373): the fraction of
  total free cpu sitting on nodes too small to host the reference job
  (the trace's median per-node cpu ask). 0 = every free cpu is usable,
  1 = all free capacity is dust;
- **gang wait-time p95** — ticks from arrival to bind, gang jobs
  (``nodes > 1``) tracked separately, never-bound jobs censored at run
  end and counted;
- **preemption churn** — total preempted + the worst single tick;
- **per-tenant fairness** — Jain index over weighted per-tenant service
  (allocated dominant-resource × virtual time, ground truth).
"""

from __future__ import annotations

import numpy as np

from slurm_bridge_tpu.policy.fairshare import jain_index


def _pct(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    return round(float(np.percentile(np.asarray(xs, np.float64), q)), 3)


class QualityTracker:
    """Per-run quality accounting the sim harness drives.

    ``tenant_of`` / ``is_gang`` / ``class_of`` map BridgeJob names to
    trace facts; ``ref_cpu`` is the fragmentation reference demand (the
    trace's median per-node cpu ask). All inputs and samples are
    virtual-time deterministic.
    """

    def __init__(
        self,
        *,
        tenant_of: dict[str, str] | None = None,
        is_gang: dict[str, bool] | None = None,
        class_of: dict[str, str] | None = None,
        tenant_weights: dict[str, float] | None = None,
        ref_cpu: float = 1.0,
        tick_interval_s: float = 1.0,
    ):
        self.tenant_of = tenant_of or {}
        self.is_gang = is_gang or {}
        self.class_of = class_of or {}
        self.tenant_weights = tenant_weights or {}
        self.ref_cpu = max(1.0, float(ref_cpu))
        self.tick_interval_s = tick_interval_s
        self._arrived: dict[str, int] = {}  # job name -> arrival tick
        self._waits: list[tuple[str, int, bool]] = []  # (name, wait, bound)
        self._util: list[float] = []
        self._frag: list[float] = []
        self._preempts: list[int] = []
        self._service: dict[str, float] = {}
        self.resizes = 0
        # ---- streaming-admission latency axis (ISSUE 12) ----
        # Interactive (fast-path-eligible) arrivals tracked for the
        # arrival→bind latency scorecard. A fast-path bind's latency is
        # the measured wall time of the admission attempt (the path runs
        # event-driven at arrival — processing IS the wait); a batch
        # bind's latency is its virtual wait, (wait_ticks + 0.5) ×
        # tick_interval — the +0.5 models the expected wait for the
        # next periodic solve to even start, which the sim's synchronous
        # arrive-then-solve tick otherwise hides.
        self._interactive: set[str] = set()
        self._fastpath_ms: dict[str, float] = {}
        # ---- placement explainability (ISSUE 15) ----
        # Per-tick pressure ledgers from the scheduler's attribution
        # pass; the scorecard rolls them into ``wait_reasons`` —
        # job-ticks spent pending, by structured reason code — and the
        # top reason × partition × class × tenant cells.
        self._pressure: list[dict] = []

    # ---- per-event hooks ----

    def note_arrival(self, job_name: str, tick: int) -> None:
        self._arrived.setdefault(job_name, tick)

    def note_rearrival(self, job_name: str, tick: int) -> None:
        """A resize/requeue re-enters the queue: wait restarts (the
        re-placement latency is the interesting number)."""
        self._arrived[job_name] = tick

    def note_bound(self, job_name: str, tick: int) -> None:
        at = self._arrived.pop(job_name, None)
        if at is not None:
            self._waits.append((job_name, tick - at, True))

    def note_interactive(self, job_name: str) -> None:
        """One fast-path-ELIGIBLE arrival (admission on, class +
        gang-size eligible, past the cold-start warmup)."""
        self._interactive.add(job_name)

    def note_fastpath_bind(self, job_name: str, latency_ms: float) -> None:
        """The arrival bound via the fast path in ``latency_ms`` wall ms."""
        self._fastpath_ms[job_name] = float(latency_ms)

    def note_preempts(self, count: int) -> None:
        self._preempts.append(count)

    def note_pressure(self, ledger: dict) -> None:
        """One solve tick's pressure ledger (obs/explain.py schema)."""
        self._pressure.append(ledger)

    def note_resize(self) -> None:
        self.resizes += 1

    # ---- per-tick sampling (sim ground truth) ----

    def sample(self, cluster) -> None:
        """One tick's utilization/fragmentation/tenant-service sample
        from the sim cluster (duck-typed: ``nodes`` of SimNode,
        ``jobs`` of SimJob)."""
        total = alloc = free_total = stranded = 0.0
        for node in cluster.nodes.values():
            total += node.cpus
            a = min(node.cpus, node.alloc_cpus)
            alloc += a
            if not node.drained:
                f = node.cpus - a
                free_total += f
                if 0.0 < f < self.ref_cpu:
                    stranded += f
        self._util.append(alloc / total if total else 0.0)
        self._frag.append(stranded / free_total if free_total else 0.0)
        from slurm_bridge_tpu.core.types import JobStatus

        dt = self.tick_interval_s
        for job in cluster.jobs.values():
            if job.state != JobStatus.RUNNING:
                continue
            tenant = self.tenant_of.get(job.name, "")
            self._service[tenant] = (
                self._service.get(tenant, 0.0)
                + job.cpus_per_node * job.num_nodes * dt
            )

    # ---- the scorecard ----

    def scorecard(self, final_tick: int, *, extra: dict | None = None) -> dict:
        # censor never-bound jobs at run end so an unbound gang shows up
        # as a LONG wait, not a missing sample
        waits = list(self._waits)
        unbound = 0
        for name, at in sorted(self._arrived.items()):
            waits.append((name, final_tick - at, False))
            unbound += 1
        all_w = [float(w) for _, w, _ in waits]
        gang_w = [float(w) for n, w, _ in waits if self.is_gang.get(n)]
        by_class: dict[str, list[float]] = {}
        for n, w, _ in waits:
            by_class.setdefault(self.class_of.get(n, ""), []).append(float(w))
        weighted = [
            s / max(self.tenant_weights.get(t, 1.0), 1e-9)
            for t, s in sorted(self._service.items())
        ]
        out = {
            "utilization_mean": round(float(np.mean(self._util)), 4)
            if self._util
            else 0.0,
            "utilization_p50": _pct(self._util, 50),
            "fragmentation_mean": round(float(np.mean(self._frag)), 4)
            if self._frag
            else 0.0,
            "wait_p50_ticks": _pct(all_w, 50),
            "wait_p95_ticks": _pct(all_w, 95),
            "wait_max_ticks": round(max(all_w), 3) if all_w else 0.0,
            "gang_wait_p95_ticks": _pct(gang_w, 95),
            "gang_wait_max_ticks": round(max(gang_w), 3) if gang_w else 0.0,
            "class_wait_p95_ticks": {
                c: _pct(ws, 95) for c, ws in sorted(by_class.items()) if c
            },
            "unbound_final": unbound,
            "preempted_total": int(sum(self._preempts)),
            "preempted_max_per_tick": int(max(self._preempts, default=0)),
            "tenant_service": {
                t: round(s, 3) for t, s in sorted(self._service.items())
            },
            "jain_fairness": round(jain_index(weighted), 4),
            "resizes": self.resizes,
        }
        # ---- interactive arrival→bind latency (ISSUE 12 gate axis) ----
        lat: list[float] = []
        tick_ms = self.tick_interval_s * 1e3
        by_name = {n: (w, b) for n, w, b in waits}
        for name in self._interactive:
            fast = self._fastpath_ms.get(name)
            if fast is not None:
                lat.append(fast)
                continue
            w, _bound = by_name.get(name, (float(final_tick), False))
            lat.append((float(w) + 0.5) * tick_ms)
        out["interactive_arrivals"] = len(self._interactive)
        out["fastpath_binds"] = len(self._fastpath_ms)
        out["interactive_latency_p50_ms"] = _pct(lat, 50)
        out["interactive_latency_p99_ms"] = _pct(lat, 99)
        # ---- wait-reason attribution (ISSUE 15 scorecard axis) ----
        # Job-ticks spent pending, by structured reason code — the
        # "WHY is work waiting" companion to the wait percentiles
        # above. Empty with explain off (or a run that never left
        # anything unplaced).
        from slurm_bridge_tpu.obs.explain import merge_ledgers

        out.update(merge_ledgers(self._pressure))
        if extra:
            out.update(extra)
        return out
