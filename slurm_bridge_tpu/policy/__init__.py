"""Placement-quality policy subsystem (ISSUE 9).

Priority classes, weighted dominant-resource fair share, a bounded
preemption pool, post-solve backfill, and the quality scorecard the sim
scenarios are gated on. Attached to the scheduler via
``PlacementScheduler(policy=PlacementPolicy(PolicyConfig(...)))``;
``policy=None`` (the default) is byte-identical PR-8 behavior.
"""

from slurm_bridge_tpu.policy.classes import (
    CLASS_LABEL,
    DEFAULT_CLASSES,
    TENANT_LABEL,
    ClassTable,
    PriorityClass,
)
from slurm_bridge_tpu.policy.engine import PlacementPolicy, PolicyConfig
from slurm_bridge_tpu.policy.fairshare import (
    FairShare,
    dominant_share,
    jain_index,
)
from slurm_bridge_tpu.policy.score import QualityTracker

__all__ = [
    "CLASS_LABEL",
    "TENANT_LABEL",
    "DEFAULT_CLASSES",
    "ClassTable",
    "PriorityClass",
    "PlacementPolicy",
    "PolicyConfig",
    "FairShare",
    "dominant_share",
    "jain_index",
    "QualityTracker",
]
