"""Priority classes — the small class table placement policy runs on.

A :class:`PriorityClass` is (name → class priority, preemptible flag),
the Kubernetes PriorityClass idea re-expressed for the bridge: the CLASS
decides who wins contention and who may be displaced, while the numeric
``spec.priority`` a user writes only breaks ties *within* a class. That
split is what prevents priority inversion: a production gang with a
modest numeric priority must still displace a best-effort job that
happens to carry ``priority=99``.

Resolution order for a pod (``resolve``):

1. the ``sbt.kubecluster.org/priority-class`` label (set on the
   BridgeJob, mirrored onto the sizecar pod by the operator);
2. the table's default class otherwise.

An unknown label falls back to the default class with a rate-limited
warning — a typo'd class name must degrade, not fail admission.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

log = logging.getLogger("sbt.policy")

#: pod/job label carrying the priority-class name
CLASS_LABEL = "sbt.kubecluster.org/priority-class"
#: pod/job label carrying the tenant name (fair-share accounting key)
TENANT_LABEL = "sbt.kubecluster.org/tenant"


@dataclass(frozen=True)
class PriorityClass:
    """One row of the class table.

    ``priority`` orders classes (higher wins contention); ``preemptible``
    gates the OTHER side: whether running work of this class may be
    displaced by a higher class. A non-preemptible class can still *cause*
    preemption — it just never suffers it.
    """

    name: str
    priority: int
    preemptible: bool = True


#: the default table — deliberately small, mirroring the shapes the
#: papers score against ("Priority Matters", arxiv 2511.08373): scavenger
#: work, the bulk batch tier, latency-sensitive production, and a system
#: tier that nothing may displace
DEFAULT_CLASSES: tuple[PriorityClass, ...] = (
    PriorityClass("best-effort", 0, preemptible=True),
    PriorityClass("batch", 100, preemptible=True),
    PriorityClass("production", 200, preemptible=False),
    PriorityClass("system", 1000, preemptible=False),
)

_WARNED_UNKNOWN: set[str] = set()


class ClassTable:
    """Name → :class:`PriorityClass` lookup with a default fallback.

    ``rank_of`` maps a class to its dense index in ascending class-
    priority order — the small integers the effective-priority encoding
    uses (class priorities themselves can be sparse and large; the dense
    rank keeps solver priorities exactly representable in float32).
    """

    def __init__(
        self,
        classes: tuple[PriorityClass, ...] = DEFAULT_CLASSES,
        *,
        default: str = "batch",
    ):
        if not classes:
            raise ValueError("class table cannot be empty")
        self.classes = tuple(sorted(classes, key=lambda c: (c.priority, c.name)))
        self.by_name = {c.name: c for c in self.classes}
        if default not in self.by_name:
            raise ValueError(
                f"default class {default!r} not in table "
                f"({', '.join(self.by_name)})"
            )
        self.default = self.by_name[default]
        self._rank = {c.name: i for i, c in enumerate(self.classes)}

    def __len__(self) -> int:
        return len(self.classes)

    def resolve(self, labels) -> PriorityClass:
        """The class for a pod given its labels (None-safe)."""
        name = labels.get(CLASS_LABEL, "") if labels else ""
        if not name:
            return self.default
        cls = self.by_name.get(name)
        if cls is None:
            if name not in _WARNED_UNKNOWN:
                _WARNED_UNKNOWN.add(name)
                log.warning(
                    "unknown priority class %r (known: %s); using default %r",
                    name, ", ".join(self.by_name), self.default.name,
                )
            return self.default
        return cls

    def rank_of(self, cls: PriorityClass) -> int:
        """Dense ascending index of ``cls`` (0 = lowest class)."""
        return self._rank[cls.name]
