"""Weighted dominant-resource fair share across tenants.

The admission order *within* a priority class is not FIFO-by-priority:
tenants take turns weighted by quota, ordered by dominant-resource
deficit (DRF — Ghodsi et al., re-used here as the deterministic tick-
local ordering rule). Each tenant's accumulated service is its granted
dominant-resource share (max over resource dims of demand/cluster
capacity), divided by its weight; the tenant with the smallest share
goes next, and the planned grant is charged immediately so one tenant
with a deep queue cannot monopolize a tick.

Everything is deterministic: ties break on tenant name, then job
priority (descending), then job name.
"""

from __future__ import annotations

import heapq

import numpy as np


def jain_index(values) -> float:
    """Jain's fairness index over per-tenant (weighted) service values:
    (Σx)² / (n·Σx²) — 1.0 is perfectly fair, 1/n is one-tenant-takes-all.
    Empty or all-zero input reads as perfectly fair (nothing granted,
    nothing unfair)."""
    xs = np.asarray(list(values), dtype=np.float64)
    if xs.size == 0:
        return 1.0
    total = float(xs.sum())
    if total <= 0.0:
        return 1.0
    return float(total * total / (xs.size * float((xs * xs).sum())))


def dominant_share(demand_vec, totals) -> float:
    """max_r demand_r / capacity_r over the resource dims with nonzero
    cluster capacity — one job's dominant-resource share."""
    share = 0.0
    for d, t in zip(demand_vec, totals):
        if t > 0:
            share = max(share, float(d) / float(t))
    return share


class FairShare:
    """Per-tenant weighted service accumulator + DRF ordering.

    ``usage`` persists across ticks (service granted so far this run);
    :meth:`order` additionally charges planned grants within the tick so
    the produced order interleaves tenants even from a cold start.
    """

    def __init__(self, weights: dict[str, float] | None = None):
        #: tenant → quota weight (missing tenants weigh 1.0)
        self.weights = dict(weights or {})
        #: tenant → accumulated dominant-share service (unweighted)
        self.usage: dict[str, float] = {}

    def weight(self, tenant: str) -> float:
        w = self.weights.get(tenant, 1.0)
        return w if w > 0 else 1.0

    def charge(self, tenant: str, share: float) -> None:
        self.usage[tenant] = self.usage.get(tenant, 0.0) + share

    def order(self, jobs: list[tuple[str, float, float, str]]) -> list[int]:
        """DRF order over one class's jobs.

        ``jobs[i] = (tenant, dominant_share, spec_priority, name)``.
        Returns the indices of ``jobs`` in admission order: repeatedly
        pick the tenant with the smallest planned weighted share and
        admit its best remaining job (priority desc, name asc).
        """
        queues: dict[str, list[int]] = {}
        for i, (tenant, _share, _prio, _name) in enumerate(jobs):
            queues.setdefault(tenant, []).append(i)
        for tenant, idxs in queues.items():
            idxs.sort(key=lambda i: (-jobs[i][2], jobs[i][3]))
            idxs.reverse()  # pop() from the end = best first
        heap = [
            (self.usage.get(t, 0.0) / self.weight(t), t)
            for t in sorted(queues)
        ]
        heapq.heapify(heap)
        out: list[int] = []
        while heap:
            share, tenant = heapq.heappop(heap)
            idxs = queues[tenant]
            i = idxs.pop()
            out.append(i)
            if idxs:
                heapq.heappush(
                    heap,
                    (share + jobs[i][1] / self.weight(tenant), tenant),
                )
        return out
