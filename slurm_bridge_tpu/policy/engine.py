"""The placement policy engine — sits between the pending scan and the
solver (ISSUE 9 tentpole).

Policy OFF (``PlacementScheduler(policy=None)``, the default) is the
PR-8 behavior byte-for-byte: no reordering, no priority rewrite, the
whole incumbent set in the preemption pool, no backfill. Everything in
this module runs only when a :class:`PlacementPolicy` is attached.

Policy ON changes three things about a tick:

1. **Admission order** (:meth:`prepare`): pending pods are grouped by
   priority CLASS (descending) and ordered within a class by weighted
   dominant-resource fair share across tenants (``fairshare.FairShare``)
   — not raw priority-FIFO. The order is lowered into per-job *effective
   priorities* the solver admits by: dense integers
   ``class_rank * count + slot`` (exact in float32), so class dominance
   and the fair order survive the kernel's priority sort unchanged.
2. **Preemption pool** (:meth:`prepare`): only incumbents whose class is
   preemptible AND strictly below the highest pending class in their
   own partition may be displaced, and at most
   ``max_preemptions_per_tick`` of them (weakest first) join the
   re-solve — bounded churn. Everyone else keeps their allocation
   untouched (they are simply not in the batch). Pool incumbents occupy
   the TOP slots of their class band (weakest lowest), so equal-class
   newcomers can never displace them — only a higher class can — and
   the solver prefers displacing the numerically weakest.
3. **Backfill** (:meth:`backfill`): after the main solve, everything
   left unplaced — single-shard jobs AND whole gangs, placed
   all-or-nothing — is packed into the leftover fragmentation holes
   (smallest demand first, tightest-fit), guarded so no placement
   shrinks the feasible node set of any other unplaced
   equal-or-higher-class gang below its size — backfill never delays a
   higher-priority gang's feasible start.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from slurm_bridge_tpu.policy.classes import (
    CLASS_LABEL,
    DEFAULT_CLASSES,
    TENANT_LABEL,
    ClassTable,
    PriorityClass,
)
from slurm_bridge_tpu.policy.fairshare import FairShare, dominant_share

__all__ = [
    "CLASS_LABEL",
    "TENANT_LABEL",
    "PolicyConfig",
    "PlacementPolicy",
    "feasible_nodes",
]


def feasible_nodes(
    free: np.ndarray,
    partition_of: np.ndarray,
    features: np.ndarray,
    d: np.ndarray,
    part: int,
    req: int,
) -> np.ndarray:
    """The ONE node-feasibility rule placement second passes share: a
    node can host one shard of ``(d, part, req)`` iff it is in the
    partition, every resource axis fits ``free``, and it carries every
    required feature bit. Backfill's guard and the streaming-admission
    fast path (slurm_bridge_tpu.admission) both call this, so the
    fast-path ≡ guarded-backfill oracle holds by construction on the
    fit half of the decision."""
    return (
        (partition_of == part)
        & ((free >= d).all(axis=1))
        & ((np.uint32(req) & ~features) == 0)
    )


@dataclass(frozen=True)
class PolicyConfig:
    """Declarative policy knobs — frozen + tuple-valued so a
    :class:`~slurm_bridge_tpu.sim.harness.Scenario` can carry one."""

    classes: tuple[PriorityClass, ...] = DEFAULT_CLASSES
    default_class: str = "batch"
    #: (tenant, weight) quota table; missing tenants weigh 1.0
    tenant_weights: tuple[tuple[str, float], ...] = ()
    #: dominant-resource fair admission within a class (off = priority
    #: FIFO within the class, classes still dominate)
    fair_share: bool = True
    #: second-pass hole filling after the main solve
    backfill: bool = True
    #: churn bound: at most this many incumbents join the preemption
    #: pool per tick, weakest (lowest class, lowest priority) first
    max_preemptions_per_tick: int = 64
    #: backfill candidates examined per tick (smallest demand first)
    backfill_limit: int = 256
    #: distinct nodes tried per backfill candidate before giving up
    backfill_node_tries: int = 8


def _demand_vec(demand) -> tuple[float, float, float]:
    """One job's TOTAL (cpu, mem, gpu) ask — the fair-share charge."""
    if demand is None:
        return (1.0, 0.0, 0.0)
    from slurm_bridge_tpu.core.arrays import array_len

    arr = array_len(demand.array) if demand.array else 1
    cpus = float(demand.total_cpus(arr))
    mem = float(demand.total_mem_mb(arr) or cpus * 1024.0)
    gpu = 0.0
    if demand.gres:
        parts = demand.gres.split(":")
        try:
            gpu = float(int(parts[-1].split("(")[0])) * max(1, demand.nodes)
        except ValueError:
            gpu = 0.0
    return (cpus, mem, gpu)


class PlacementPolicy:
    """One scheduler's policy state (fair-share usage persists across
    ticks; everything else is recomputed per tick)."""

    def __init__(self, config: PolicyConfig | None = None):
        self.config = config or PolicyConfig()
        self.table = ClassTable(
            self.config.classes, default=self.config.default_class
        )
        self.fair = FairShare(dict(self.config.tenant_weights))
        #: cluster capacity totals [cpu, mem, gpu] of the current tick
        self._totals = (1.0, 1.0, 1.0)
        #: per-pending-job (tenant, dominant share, class rank), aligned
        #: with the REORDERED pending list prepare() returned
        self._tick_jobs: list[tuple[str, float, int]] = []
        # ---- observability (the sim scorecard reads these) ----
        self.backfill_binds_total = 0
        self.pool_size_last = 0
        self.pool_excluded_last = 0
        self.backfill_candidates_last = 0
        self.backfill_binds_last = 0
        #: partition → MIN class rank among preemptible incumbents the
        #: bounded pool EXCLUDED this tick (explainability, ISSUE 15):
        #: an unplaced job of a strictly higher rank in that partition
        #: could have been helped by a bigger ``max_preemptions_per_tick``
        #: — the PREEMPTION_CAP attribution reads this
        self.pool_excluded_rank_by_part: dict[str, int] = {}
        #: fair-share usage changed since the last store save (PR-10:
        #: the ledger rides the WAL through a PolicyState singleton)
        self._usage_dirty = False

    # ---- tick lifecycle ----

    def begin_tick(self, nodes) -> None:
        """Capture cluster capacity totals (the DRF denominator)."""
        cpu = mem = gpu = 0.0
        for nd in nodes:
            cpu += nd.cpus
            mem += nd.memory_mb
            gpu += nd.gpus
        self._totals = (max(cpu, 1.0), max(mem, 1.0), max(gpu, 0.0))

    def _pod_meta(self, pod) -> tuple[PriorityClass, str, float, float]:
        """(class, tenant, dominant share, spec priority) for one
        schedulable pod (a scheduler ``_RowPod`` or anything with
        ``labels``/``demand``/``name``)."""
        labels = getattr(pod, "labels", None)
        cls = self.table.resolve(labels)
        tenant = (labels.get(TENANT_LABEL, "") if labels else "") or ""
        share = dominant_share(_demand_vec(pod.demand), self._totals)
        prio = float(pod.demand.priority) if pod.demand is not None else 0.0
        return cls, tenant, share, prio

    def prepare(
        self, pending: list, incumbents: list
    ) -> tuple[list, list, list[float]]:
        """The tick's admission order, preemption pool, and effective
        priorities.

        Returns ``(ordered_pending, pool_incumbents, priorities)`` with
        ``priorities`` aligned to ``ordered_pending + pool_incumbents``
        (the ``all_pods`` list the scheduler encodes).
        """
        cfg = self.config
        metas = [self._pod_meta(p) for p in pending]
        # class buckets, highest class first
        buckets: dict[int, list[int]] = {}
        for i, (cls, _t, _s, _p) in enumerate(metas):
            buckets.setdefault(self.table.rank_of(cls), []).append(i)
        order: list[int] = []
        for rank in sorted(buckets, reverse=True):
            idxs = buckets[rank]
            if cfg.fair_share:
                jobs = [
                    (metas[i][1], metas[i][2], metas[i][3], pending[i].name)
                    for i in idxs
                ]
                order.extend(idxs[k] for k in self.fair.order(jobs))
            else:
                order.extend(
                    sorted(idxs, key=lambda i: (-metas[i][3], pending[i].name))
                )

        # preemption pool: preemptible incumbents of a class strictly
        # below the highest pending class IN THEIR OWN PARTITION —
        # partition-blind eligibility would let a big partition's
        # harmless scavengers fill the churn-bounded pool while the
        # contended partition's displaceable incumbents stay untouchable
        # (deterministic ticks would then starve the gang forever)
        part_max_rank: dict[str, int] = {}
        for i, m in enumerate(metas):
            rank = self.table.rank_of(m[0])
            part = pending[i].partition
            if rank > part_max_rank.get(part, -1):
                part_max_rank[part] = rank
        eligible: list[tuple[tuple, int]] = []
        for i, inc in enumerate(incumbents):
            cls, _tenant, _share, prio = self._pod_meta(inc)
            rank = self.table.rank_of(cls)
            if cls.preemptible and rank < part_max_rank.get(
                inc.partition, -1
            ):
                eligible.append(((rank, prio, inc.name), i))
        eligible.sort(key=lambda e: e[0])
        cap = max(0, cfg.max_preemptions_per_tick)
        pool_idx = [i for _, i in eligible[:cap]]
        self.pool_size_last = len(pool_idx)
        self.pool_excluded_last = len(incumbents) - len(pool_idx)
        # cap-excluded ELIGIBLE incumbents, by partition (min rank) —
        # the PREEMPTION_CAP explainability signal: these could have
        # been displaced if the churn bound were higher
        self.pool_excluded_rank_by_part = {}
        for (rank, _prio, _name), i in eligible[cap:]:
            part = incumbents[i].partition
            cur = self.pool_excluded_rank_by_part.get(part)
            if cur is None or rank < cur:
                self.pool_excluded_rank_by_part[part] = rank
        pool = [incumbents[i] for i in pool_idx]

        # effective priorities: dense per-band integers, exact in float32
        # (band = rank*count + slot; bands never overlap). Pool
        # incumbents occupy the TOP slots of their class band — weakest
        # (highest pool index) lowest — so every same-class pending sits
        # strictly below every same-class incumbent (only a higher CLASS
        # can displace), while within the pool the numerically weakest
        # incumbent is the one the solver prefers to displace. Pending
        # slots start below each band's incumbent block.
        count = len(order) + len(pool) + 2
        pool_ranks = [
            self.table.rank_of(self._pod_meta(inc)[0]) for inc in pool
        ]
        inc_count: dict[int, int] = {}
        for r in pool_ranks:
            inc_count[r] = inc_count.get(r, 0) + 1
        # pool is sorted weakest-first; strongest gets the band top
        inc_eff = [0.0] * len(pool)
        seen: dict[int, int] = {}
        for i in range(len(pool) - 1, -1, -1):
            r = pool_ranks[i]
            inc_eff[i] = float(r * count + (count - 1 - seen.get(r, 0)))
            seen[r] = seen.get(r, 0) + 1
        eff = [0.0] * len(order)
        self._tick_jobs = []
        for pos, i in enumerate(order):
            cls, tenant, share, _prio = metas[i]
            rank = self.table.rank_of(cls)
            slot = count - 2 - inc_count.get(rank, 0) - min(pos, count - 3)
            eff[pos] = float(rank * count + max(slot, 0))
            self._tick_jobs.append((tenant, share, rank))
        return [pending[i] for i in order], pool, eff + inc_eff

    def note_admitted(self, job_indices) -> None:
        """Charge fair-share usage for the pending jobs the solver (or
        backfill) admitted this tick — indices into the REORDERED
        pending list."""
        for j in job_indices:
            if 0 <= j < len(self._tick_jobs):
                tenant, share, _rank = self._tick_jobs[j]
                self.fair.charge(tenant, share)
                self._usage_dirty = True

    def charge_admission(self, labels, demand) -> None:
        """Fair-share charge for ONE pod admitted outside the batch tick
        (the streaming-admission fast path). Uses the capacity totals of
        the last ``begin_tick`` — before any tick has run there is no
        denominator, and charging against the (1,1,1) placeholder would
        wildly overcharge, so the pre-first-tick window charges nothing
        (the batch tick it falls back to would not have admitted yet
        either)."""
        if self._totals == (1.0, 1.0, 1.0):
            return
        tenant = (labels.get(TENANT_LABEL, "") if labels else "") or ""
        share = dominant_share(_demand_vec(demand), self._totals)
        self.fair.charge(tenant, share)
        self._usage_dirty = True

    # ---- durable fair share (PR-10, ROADMAP policy follow-up) ----

    def load_from_store(self, store) -> None:
        """Hydrate the fair-share ledger from the PolicyState singleton
        (restored by WAL replay on a restarted bridge). Missing object =
        fresh start — exactly the pre-PR-10 behavior."""
        from slurm_bridge_tpu.bridge.objects import PolicyState

        obj = store.try_get(PolicyState.KIND, PolicyState.FAIRSHARE_NAME)
        if obj is not None:
            self.fair.usage = {k: float(v) for k, v in obj.usage.items()}

    def save_to_store(self, store) -> None:
        """Persist the ledger when (and only when) an admission charged
        it this tick — a no-admission tick writes NOTHING, keeping the
        steady-state zero-writes discipline intact. The write is an
        ordinary store commit, so WAL persistence picks it up through
        the same ``changes_since`` path as every other kind."""
        if not self._usage_dirty:
            return
        from slurm_bridge_tpu.bridge.objects import Meta, PolicyState
        from slurm_bridge_tpu.bridge.store import AlreadyExists, NotFound

        usage = dict(self.fair.usage)

        def record(obj):
            obj.usage = dict(usage)
            obj.generation += 1

        try:
            store.mutate(
                PolicyState.KIND, PolicyState.FAIRSHARE_NAME, record,
                site="policy.fairshare",
            )
        except NotFound:
            try:
                store.create(
                    PolicyState(
                        meta=Meta(name=PolicyState.FAIRSHARE_NAME),
                        usage=usage,
                        generation=1,
                    ),
                    site="policy.fairshare",
                )
            except AlreadyExists:  # racing writer: its value is newer
                pass
        self._usage_dirty = False

    def class_rank_of_job(self, j: int) -> int:
        """Class rank of reordered pending job ``j`` (default rank when
        unknown — direct solver callers without a prepare pass)."""
        if 0 <= j < len(self._tick_jobs):
            return self._tick_jobs[j][2]
        return self.table.rank_of(self.table.default)

    # ---- backfill ----

    def backfill(
        self, snapshot, batch, placement, n_pending: int, *, rank_of=None
    ) -> list[tuple[int, int]]:
        """Second-pass hole filling after the main solve.

        ``rank_of`` (optional) maps a batch job index to its class rank;
        the default reads the engine's own reordered-pending table. The
        sharded executor passes a shard-local → global translation here
        — per-shard batches index their own job lists, not the tick's.

        Everything the solve left unplaced gets one exact, bounded
        second chance against ``placement.free_after``: smallest total
        demand first, tightest-fit node choice, gangs all-or-nothing
        (the policy-side analogue of the auction's in-engine ``repair``
        — which approximate configs turn off — with the class guard the
        engine cannot have). The guard: no assignment may shrink the
        feasible node set of another unplaced equal-or-higher-class
        gang below its size — backfill never delays a higher-priority
        gang's feasible start. Gangs already infeasible *now* cannot be
        delayed by this pass and are not guarded.

        Returns ``(shard_row, node_index)`` assignments.
        """
        cfg = self.config
        if rank_of is None:
            rank_of = self.class_rank_of_job
        self.backfill_candidates_last = 0
        self.backfill_binds_last = 0
        unplaced = ~placement.placed & (batch.job_of >= 0) & (
            batch.job_of < n_pending
        )
        rows = np.nonzero(unplaced)[0]
        if rows.size == 0:
            return []
        free = placement.free_after.copy()
        feats = snapshot.features
        parts = snapshot.partition_of

        def feas_mask(d, part, req):
            return feasible_nodes(free, parts, feats, d, part, req)

        # one record per FULLY-unplaced gang (a partially-placed gang's
        # stragglers are dead this tick — the engines admit gangs
        # all-or-nothing, so leftovers only exist transiently)
        by_gang: dict[int, list[int]] = {}
        for r in rows.tolist():
            by_gang.setdefault(int(batch.gang_id[r]), []).append(r)
        cands: list[dict] = []
        for g, g_rows in sorted(by_gang.items()):
            r0 = g_rows[0]
            part = int(batch.partition_of[r0])
            if part < 0:
                continue
            cands.append(
                {
                    "rows": g_rows,
                    "need": len(g_rows),
                    "rank": rank_of(int(batch.job_of[r0])),
                    "d": batch.demand[r0],
                    "part": part,
                    "req": int(batch.req_features[r0]),
                }
            )
        # masks only for multi-shard gangs — singles never read theirs,
        # and a full-cluster mask per candidate is real vector work at
        # 10k nodes; the placement loop recomputes candidate fits fresh
        for c in cands:
            if c["need"] > 1:
                c["mask"] = feas_mask(c["d"], c["part"], c["req"])
                c["count"] = int(c["mask"].sum())
        # protected set: gangs feasible NOW (their start must survive)
        protected = [c for c in cands if c["need"] > 1 and c["count"] >= c["need"]]
        cands.sort(
            key=lambda c: (float(c["d"][0]) * c["need"], c["rows"][0])
        )
        cands = cands[: cfg.backfill_limit]
        self.backfill_candidates_last = len(cands)

        out: list[tuple[int, int]] = []
        for c in cands:
            d, part, req, need, rank = (
                c["d"], c["part"], c["req"], c["need"], c["rank"],
            )
            fit = feas_mask(d, part, req)
            nodes = np.nonzero(fit)[0]
            if nodes.size < need:
                continue
            # tightest fit first: least cpu headroom after placement
            nodes = nodes[np.argsort(free[nodes, 0] - d[0], kind="stable")]
            chosen: list[int] = []
            hits: list = []  # (gang record, node) feasibility reductions
            conflict = False
            limit = max(need, cfg.backfill_node_tries)
            for n in nodes[:limit].tolist():
                # guard: does taking n break another protected gang?
                bad = False
                n_hits = []
                for g in protected:
                    if g is c or g["rank"] < rank or not g["mask"][n]:
                        continue
                    if not (free[n] - d >= g["d"]).all():
                        if g["count"] - 1 < g["need"]:
                            bad = True
                            break
                        n_hits.append(g)
                if bad:
                    continue
                free[n] -= d
                for g in n_hits:
                    g["mask"] = g["mask"].copy()
                    g["mask"][n] = False
                    g["count"] -= 1
                hits.extend((g, n) for g in n_hits)
                chosen.append(n)
                if len(chosen) == need:
                    break
            if len(chosen) < need:
                # all-or-nothing: roll the tentative takes back. A hit
                # means (g, n) was feasible BEFORE the take and only the
                # capacity changed — restoring free[n] restores exactly
                # that, so the mask flips back without a cluster rescan.
                for n in chosen:
                    free[n] += d
                for g, n in hits:
                    g["mask"] = g["mask"].copy()
                    g["mask"][n] = True
                    g["count"] += 1
                continue
            if c in protected:
                protected.remove(c)  # it started; nothing left to guard
            out.extend((r, n) for r, n in zip(c["rows"], chosen))
        self.backfill_binds_last = len(out)
        self.backfill_binds_total += len(out)
        return out
