"""Composable fault injection for the simulator.

A :class:`FaultPlan` is a set of :class:`Fault` windows over the tick
axis. Two delivery mechanisms:

- **RPC-level** faults ride a :class:`FaultyClient` wrapper around the
  :class:`SimWorkloadClient`: injected gRPC errors (raised as
  :class:`SimRpcError`, a real ``grpc.RpcError`` so every production
  error path runs), recorded virtual latency, stale snapshots (inventory
  RPCs frozen at window entry) and lost status updates (JobInfo/JobState
  frozen per job) — all seeded, so identical runs inject identically.
- **Cluster-level** faults (node drain/resume churn, partition
  disappearance, preemption storms) are applied by the harness at tick
  boundaries through the :class:`SimCluster` mutators and the arrival
  trace.

Windows are ``[start_tick, end_tick)``; cluster-level faults revert at
``end_tick`` (drained nodes resume, hidden partitions return).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import grpc


class SimRpcError(grpc.RpcError):
    """An injected RPC failure carrying the ``code()``/``details()``
    surface the bridge's handlers read (grpc's own subclasses are not
    constructible outside a live call)."""

    def __init__(self, code: grpc.StatusCode, details: str = "injected fault"):
        super().__init__(details)
        self._code = code
        self._details = details

    def code(self) -> grpc.StatusCode:
        return self._code

    def details(self) -> str:
        return self._details


#: fault kinds delivered via the client wrapper
RPC_KINDS = ("rpc_error", "rpc_latency", "stale_snapshot", "lost_status")
#: fault kinds applied by the harness at tick boundaries
CLUSTER_KINDS = (
    "drain_nodes",
    "partition_vanish",
    "preemption_storm",
    "elastic_resize",
)
#: fault kinds that kill/replace the bridge process itself (PR-7): the
#: harness tears the control plane down at the start tick and recovery
#: rides snapshot+WAL + level-triggered re-convergence
BRIDGE_KINDS = ("crash_restart", "leader_failover")
#: fault kinds that kill/replace the AGENT process (PR-8): the harness
#: drops the fake agent's process state (jobs, ledger, queue, per-node
#: allocation) and rebuilds it from the agent job-state journal
AGENT_KINDS = ("agent_crash",)
#: fault kinds that kill a FLEET replica's sidecar process (ISSUE 17):
#: the harness SIGKILLs the named replica at the start tick; its
#: shard-set re-keys to survivors on the next membership heartbeat and
#: the restart-with-backoff path re-adopts it
FLEET_KINDS = ("kill_replica",)
#: every kind any delivery mechanism understands — plan validation warns
#: on anything else (a typo'd kind silently tests nothing)
ALL_KINDS = RPC_KINDS + CLUSTER_KINDS + BRIDGE_KINDS + AGENT_KINDS + FLEET_KINDS


@dataclass(frozen=True)
class Fault:
    """One fault window. Fields beyond (kind, start, end) are kind-specific:

    - ``rpc_error``: ``methods`` ("" or empty = all), ``rate``, ``code``
    - ``rpc_latency``: ``methods``, ``latency_ms`` (virtual, recorded — the
      simulator never sleeps)
    - ``stale_snapshot``: inventory RPCs serve window-entry state
    - ``lost_status``: JobInfo/JobState serve each job's window-entry state
    - ``drain_nodes``: ``nodes`` explicit names and/or ``node_fraction``
      drawn deterministically from the plan seed; resumed at ``end_tick``
    - ``partition_vanish``: ``partition`` hidden for the window
    - ``preemption_storm``: ``jobs`` arrivals at ``priority`` injected at
      ``start_tick`` (requires the scheduler's preemption mode to
      displace); ``gang_size`` > 1 makes each storm job a gang and
      ``storm_class`` stamps a priority-class label (the
      ``priority_inversion`` shape)
    - ``elastic_resize``: at ``start_tick``, ``jobs`` currently-bound
      sim jobs change shard count mid-flight (VirtualFlow semantics,
      arxiv 2009.09523): singles grow to 2 nodes, gangs halve; the job
      is cancelled, its demand rewritten, and it re-places at the new
      shape under a fresh submit generation
    - ``crash_restart``: at ``start_tick`` the whole bridge stack (store,
      operator, configurator, scheduler) dies WITHOUT a final flush and a
      fresh stack reloads from snapshot+WAL; ``end_tick`` should be
      ``start_tick + 1`` so ``recovery_ticks`` counts from the restart
    - ``leader_failover``: the lease-holding bridge steps down
      (``graceful=True``: flush + release; ``False``: silent crash, the
      standby waits out lease expiry) and a standby elector takes over,
      rebuilding the stack from snapshot+WAL with zero node flap
    - ``agent_crash``: at ``start_tick`` the fake agent's PROCESS state
      (jobs, submit ledger, queue, per-node allocation) is dropped and
      rebuilt from the agent job-state journal replay; node hardware
      state and hidden partitions are cluster-side truth and survive.
      Composes with ``crash_restart`` at the same tick for the
      simultaneous bridge+agent crash.

    Windows of different kinds may overlap freely (PR-8 composed chaos):
    a ``crash_restart`` inside an ``rpc_error``/``rpc_latency`` window
    recovers THROUGH the degraded RPC plane, and one inside a
    ``partition_vanish`` window recovers INTO the shrunken inventory
    (the restored VirtualNode of a vanished partition stays in the store,
    unmanaged, until the partition returns and the provider adopts it).
    """

    kind: str
    start_tick: int
    end_tick: int
    methods: tuple[str, ...] = ()
    rate: float = 1.0
    code: str = "UNAVAILABLE"
    latency_ms: float = 0.0
    nodes: tuple[str, ...] = ()
    node_fraction: float = 0.0
    partition: str = ""
    jobs: int = 0
    priority: int = 1000
    graceful: bool = True
    #: preemption_storm: shard count per storm job (1 = singles)
    gang_size: int = 1
    #: preemption_storm: priority-class label stamped on storm jobs
    storm_class: str = ""
    #: preemption_storm: cpus_per_task draw for storm jobs (() = the
    #: PR-2 default (4, 8, 16)); node-sized asks force real preemption
    storm_cpus: tuple[int, ...] = ()
    #: kill_replica: fleet replica id whose sidecar dies ("" = the
    #: owner of shard 0 at the start tick)
    replica: str = ""

    def active(self, tick: int) -> bool:
        return self.start_tick <= tick < self.end_tick

    def matches(self, method: str) -> bool:
        return not self.methods or method in self.methods

    @property
    def status_code(self) -> grpc.StatusCode:
        return getattr(grpc.StatusCode, self.code)


#: (context, name) pairs already warned about — plan validation is
#: rate-limited to once per process per offending name, so a scenario
#: constructed in a loop (the smoke gate's double-run) warns exactly once
_VALIDATION_WARNED: set[tuple[str, str]] = set()


def _known_rpc_methods() -> frozenset[str]:
    """Every RPC method name the WorkloadManager service actually has —
    derived from the proto descriptor, so the validation can never drift
    from the wire surface."""
    global _KNOWN_RPC_METHODS
    if _KNOWN_RPC_METHODS is None:
        from slurm_bridge_tpu.wire.rpc import service_methods

        _, specs = service_methods("WorkloadManager")
        _KNOWN_RPC_METHODS = frozenset(s.name for s in specs)
    return _KNOWN_RPC_METHODS


_KNOWN_RPC_METHODS: frozenset[str] | None = None

log = logging.getLogger("sbt.sim.faults")


@dataclass(frozen=True)
class FaultPlan:
    faults: tuple[Fault, ...] = ()

    def __post_init__(self) -> None:
        """Validate the plan at construction: a typo'd RPC method in
        ``methods`` (or an unknown ``kind``) silently no-ops — the
        scenario then tests LESS than it claims. Warn once per process
        per offending name (rate-limited: smoke gates construct each
        scenario many times)."""
        for f in self.faults:
            if f.kind not in ALL_KINDS:
                key = ("kind", f.kind)
                if key not in _VALIDATION_WARNED:
                    _VALIDATION_WARNED.add(key)
                    log.warning(
                        "FaultPlan: unknown fault kind %r — no delivery "
                        "mechanism will apply it (known: %s)",
                        f.kind, ", ".join(ALL_KINDS),
                    )
                continue
            if f.kind not in ("rpc_error", "rpc_latency"):
                continue
            for m in f.methods:
                if m in _known_rpc_methods():
                    continue
                key = ("method", m)
                if key not in _VALIDATION_WARNED:
                    _VALIDATION_WARNED.add(key)
                    log.warning(
                        "FaultPlan: %s fault names RPC method %r, which "
                        "matches no WorkloadManager method — the window "
                        "injects nothing for it", f.kind, m,
                    )

    def __bool__(self) -> bool:
        return bool(self.faults)

    def strip(self, kinds: tuple[str, ...]) -> "FaultPlan":
        """The plan with every fault of the given kinds removed — how the
        smoke gate builds a crash-free twin that keeps the REST of the
        chaos (rpc flaps, vanished partitions) intact."""
        return FaultPlan(tuple(f for f in self.faults if f.kind not in kinds))

    def active(self, kind: str, tick: int) -> list[Fault]:
        return [f for f in self.faults if f.kind == kind and f.active(tick)]

    def starting(self, kind: str, tick: int) -> list[Fault]:
        return [f for f in self.faults if f.kind == kind and f.start_tick == tick]

    def ending(self, kind: str, tick: int) -> list[Fault]:
        return [f for f in self.faults if f.kind == kind and f.end_tick == tick]

    @property
    def last_end_tick(self) -> int:
        """Tick by which every fault window has closed (0 = no faults)."""
        return max((f.end_tick for f in self.faults), default=0)

    def describe(self) -> list[dict]:
        out = []
        for f in self.faults:
            d = {"kind": f.kind, "window": [f.start_tick, f.end_tick]}
            if f.kind == "rpc_error":
                d.update(methods=list(f.methods) or ["*"], rate=f.rate, code=f.code)
            elif f.kind == "rpc_latency":
                d.update(methods=list(f.methods) or ["*"], latency_ms=f.latency_ms)
            elif f.kind == "drain_nodes":
                d.update(nodes=len(f.nodes), node_fraction=f.node_fraction)
            elif f.kind == "partition_vanish":
                d.update(partition=f.partition)
            elif f.kind == "preemption_storm":
                d.update(jobs=f.jobs, priority=f.priority)
                if f.gang_size > 1:
                    d.update(gang_size=f.gang_size)
                if f.storm_class:
                    d.update(storm_class=f.storm_class)
                if f.storm_cpus:
                    d.update(storm_cpus=list(f.storm_cpus))
            elif f.kind == "elastic_resize":
                d.update(jobs=f.jobs)
            elif f.kind == "leader_failover":
                d.update(graceful=f.graceful)
            elif f.kind == "kill_replica":
                d.update(replica=f.replica or "shard-0-owner")
            out.append(d)
        return out

    @property
    def composed(self) -> bool:
        """True when windows of different kinds overlap in time — the
        PR-8 chaos-composition shape (crash during a degraded window)."""
        for i, a in enumerate(self.faults):
            for b in self.faults[i + 1 :]:
                if a.kind != b.kind and a.start_tick < b.end_tick and b.start_tick < a.end_tick:
                    return True
        return False


#: inventory RPCs a stale_snapshot window freezes
_SNAPSHOT_METHODS = ("Partitions", "Partition", "Nodes")
#: status RPCs a lost_status window freezes
_STATUS_METHODS = ("JobInfo", "JobState")


class FaultyClient:
    """Client wrapper consulting the plan's RPC-level faults per call.

    The harness advances :attr:`tick` at each tick boundary; injection
    draws come from a dedicated seeded RNG, so runs with identical plans,
    seeds and call sequences inject identically (determinism contract).
    """

    def __init__(self, inner, plan: FaultPlan, *, seed: int = 0):
        import numpy as np

        self._inner = inner
        self._plan = plan
        self._rng = np.random.default_rng(seed)
        self.tick = 0
        self.injected_errors: dict[str, int] = {}
        self.injected_latency_ms = 0.0
        self._stale: dict[tuple, object] = {}
        self._stale_window = False

    def set_tick(self, tick: int) -> None:
        self.tick = tick
        stale_now = bool(self._plan.active("stale_snapshot", tick)) or bool(
            self._plan.active("lost_status", tick)
        )
        if stale_now and not self._stale_window:
            self._stale.clear()  # fresh window: freeze state as of entry
        self._stale_window = stale_now

    def close(self) -> None:
        self._inner.close()

    def _submit_jobs(self, inner_fn, request, timeout):
        """Per-item injection for the batched submit (PR-4).

        A whole-RPC failure must name ``SubmitJobs`` explicitly; every
        other matching fault ("SubmitJob" or empty methods = all) draws
        PER ITEM and turns its victims into ok=false entries — a
        flaky-agent plan written for the unary submit path exercises the
        same failure surface against the batched form, and one injected
        fault no longer takes 2,000 batch-mates down with it.
        """
        from slurm_bridge_tpu.wire import pb

        for f in self._plan.active("rpc_error", self.tick):
            if "SubmitJobs" in f.methods and self._rng.random() < f.rate:
                self.injected_errors["SubmitJobs"] = (
                    self.injected_errors.get("SubmitJobs", 0) + 1
                )
                raise SimRpcError(f.status_code, f"injected {f.code} on SubmitJobs")
        for f in self._plan.active("rpc_latency", self.tick):
            # latency faults naming the batched method explicitly charge
            # once per round-trip (symmetric with the rpc_error handling)
            if "SubmitJobs" in f.methods:
                self.injected_latency_ms += f.latency_ms
        item_faults = [
            f
            for f in self._plan.active("rpc_error", self.tick)
            if f.matches("SubmitJob")
        ]
        latency = [
            f
            for f in self._plan.active("rpc_latency", self.tick)
            if f.matches("SubmitJob")
        ]
        entries: list = [None] * len(request.requests)
        forward: list = []
        fwd_idx: list[int] = []
        for i, req in enumerate(request.requests):
            for f in latency:
                self.injected_latency_ms += f.latency_ms
            hit = None
            for f in item_faults:
                if self._rng.random() < f.rate:
                    hit = f
                    break
            if hit is not None:
                self.injected_errors["SubmitJob"] = (
                    self.injected_errors.get("SubmitJob", 0) + 1
                )
                entries[i] = pb.SubmitJobsEntry(
                    ok=False,
                    error_code=hit.code,
                    error=f"injected {hit.code} on SubmitJob",
                )
                continue
            forward.append(req)
            fwd_idx.append(i)
        if forward:
            resp = inner_fn(
                pb.SubmitJobsRequest(requests=forward), timeout=timeout
            )
            for i, entry in zip(fwd_idx, resp.results):
                entries[i] = entry
        return pb.SubmitJobsResponse(results=entries)

    #: the raw-bytes bulk twins (ISSUE 14) are deliberately MASKED: a
    #: fault window must keep manipulating structured responses (per-job
    #: lost_status freezes, per-item submit injection), and the fault
    #: draw sequence must stay byte-identical to the pre-coldec baseline
    #: — so a faulted provider simply falls back to the pb2 path.
    _MASKED_BYTES_RPCS = ("JobsInfoBytes", "NodesBytes", "SubmitJobsBytes")

    def __getattr__(self, method: str):
        if method in self._MASKED_BYTES_RPCS:
            raise AttributeError(
                f"{method} masked under fault injection (pb2 path only)"
            )
        inner_fn = getattr(self._inner, method)
        if not callable(inner_fn) or method.startswith("_"):
            return inner_fn

        def call(request, timeout=None):
            if method == "SubmitJobs":
                return self._submit_jobs(inner_fn, request, timeout)
            for f in self._plan.active("rpc_error", self.tick):
                if f.matches(method) and self._rng.random() < f.rate:
                    self.injected_errors[method] = (
                        self.injected_errors.get(method, 0) + 1
                    )
                    raise SimRpcError(
                        f.status_code, f"injected {f.code} on {method}"
                    )
            for f in self._plan.active("rpc_latency", self.tick):
                if f.matches(method):
                    self.injected_latency_ms += f.latency_ms
            if method == "JobsInfo" and self._plan.active(
                "lost_status", self.tick
            ):
                # the batched status RPC freezes PER JOB, like JobInfo —
                # freezing the whole response would let new jobs entering
                # the batch thaw every other job's state mid-window
                from slurm_bridge_tpu.wire import pb

                missing = [
                    jid
                    for jid in request.job_ids
                    if ("JobsInfo", jid) not in self._stale
                ]
                if missing:
                    resp = inner_fn(
                        pb.JobsInfoRequest(job_ids=missing), timeout=timeout
                    )
                    for entry in resp.jobs:
                        self._stale[("JobsInfo", entry.job_id)] = entry
                return pb.JobsInfoResponse(
                    jobs=[
                        self._stale[("JobsInfo", jid)]
                        for jid in request.job_ids
                        if ("JobsInfo", jid) in self._stale
                    ]
                )
            freeze = (
                method in _SNAPSHOT_METHODS
                and self._plan.active("stale_snapshot", self.tick)
            ) or (
                method in _STATUS_METHODS
                and self._plan.active("lost_status", self.tick)
            )
            if freeze:
                if method == "Nodes":
                    # key on the NAME SET, not the serialized bytes: the
                    # incremental caller restamps `since_version` every
                    # tick, and a bytes key would mint a fresh freeze
                    # slot per tick — the window would serve live state
                    # and the fault would silently stop testing staleness
                    key = (method, tuple(request.names))
                else:
                    key = (
                        method, request.SerializeToString(deterministic=True)
                    )
                if key not in self._stale:
                    self._stale[key] = inner_fn(request, timeout=timeout)
                return self._stale[key]
            return inner_fn(request, timeout=timeout)

        return call
