"""``python -m slurm_bridge_tpu.sim`` — run simulation scenarios.

    python -m slurm_bridge_tpu.sim --list
    python -m slurm_bridge_tpu.sim steady_poisson node_churn --seed 7
    python -m slurm_bridge_tpu.sim --all --scale 0.25
    python -m slurm_bridge_tpu.sim --smoke          # the `make sim-smoke` gate
    python -m slurm_bridge_tpu.sim full_50kx10k     # slow headline (minutes)
    python -m slurm_bridge_tpu.sim sharded_gang_split --explain job-000007
                                    # one job's placement decision trail

One JSON object per scenario on stdout; ``--out`` additionally writes the
array to a file. The headline scenario also emits a one-line
``{"metric": "full_tick_p50_ms_50kx10k", ...}`` record, bench.py-style.

``--smoke`` runs every fast scenario at a toy scale TWICE with the same
seed and fails (exit 1) unless (a) the deterministic metrics sections are
byte-identical, (b) no invariant was violated, and (c) every fault
scenario that expects to drain actually recovered — the CI determinism +
recovery gate.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from slurm_bridge_tpu.sim.harness import SimHarness, run_scenario
from slurm_bridge_tpu.sim.scenarios import (
    ADMISSION_SCENARIOS,
    CHAOS_SCENARIOS,
    FLEET_SCENARIOS,
    QUALITY_SCENARIOS,
    SCENARIOS,
    SHARD_SCENARIOS,
    SMOKE_SCENARIOS,
)

SMOKE_SCALE = 0.12

#: quality-smoke floors (ISSUE 9 acceptance): the fairness split the
#: multi-tenant storm must show, the utilization margin backfill must
#: buy on diurnal load, and the wait bound the production gang must make
QUALITY_GATES = {
    "jain_on_floor": 0.9,
    "jain_off_ceiling": 0.7,
    "util_margin": 0.02,
    "max_wait_ticks": 3.0,
}

#: admission-smoke floors/ceilings (ISSUE 12 acceptance): the
#: interactive arrival→bind p99 the fast path must hold (virtual time —
#: a batch-tick bind costs ≥ half a tick period, so the ceiling is only
#: reachable through the fast path), the batch-utilization margin the
#: admission-off twin comparison allows, and the minimum fast-path
#: engagement below which the scenario stopped testing anything
ADMISSION_GATES = {
    "p99_ms": 100.0,
    "util_margin": 0.01,
    "min_fastpath_binds": 10,
}


def _build(name: str, *, seed: int | None, scale: float, ticks: int | None):
    sc = SCENARIOS[name](scale=scale, **({"seed": seed} if seed is not None else {}))
    if ticks is not None:
        sc = dataclasses.replace(sc, ticks=ticks)
    return sc


def _headline(result) -> dict:
    t = result.timing
    return {
        "metric": f"full_tick_p50_ms_{result.shape['pods'] // 1000}kx"
        f"{result.shape['nodes'] // 1000}k",
        "value": t["tick_p50_ms"],
        "unit": "ms",
        "p95_ms": t["tick_p95_ms"],
        "steady_tick_p50_ms": t.get("steady_tick_p50_ms"),
        "steady_ticks": t.get("steady_ticks"),
        "phases_p50_ms": t["phases_p50_ms"],
        # the per-phase split under its contract name, so BENCH json
        # consumers can track phase-level regressions (PR-3 satellite)
        "full_tick_phases_ms": t["phases_p50_ms"],
        "pods": result.shape["pods"],
        "nodes": result.shape["nodes"],
        "bound_total": result.determinism["bound_total"],
        "submits_batched": result.determinism["submits_batched"],
        "submits_fallback": result.determinism["submits_fallback"],
        "invariant_violations": len(result.determinism["invariant_violations"]),
        # the tick flight record: span-tree p50s, top self-time, per-kind
        # × per-callsite commit breakdown — the attribution dataset the
        # store decision (ROADMAP) needs
        "flight_record": result.flight_record,
    }


def _write_flight_diagnostics(result) -> str | None:
    """Per-tick flight records for the slow headline run →
    ``diagnostics/sim_flight_<scenario>.json`` (repo-relative when run
    from a checkout, cwd otherwise)."""
    import os

    if not result.flight_ticks:
        return None
    out_dir = "diagnostics"
    path = os.path.join(out_dir, f"sim_flight_{result.scenario.name}.json")
    try:
        os.makedirs(out_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(
                {
                    "scenario": result.scenario.name,
                    "seed": result.scenario.seed,
                    "aggregate": result.flight_record,
                    "per_tick": result.flight_ticks,
                },
                f,
                indent=1,
                sort_keys=True,
            )
    except OSError:
        # read-only checkout: the diagnostics artifact degrades to the
        # in-JSON aggregate; never abort the run over it
        return None
    return path


def _smoke(names: tuple[str, ...] = SMOKE_SCENARIOS, label: str = "sim-smoke") -> int:
    from slurm_bridge_tpu.sim.faults import AGENT_KINDS, BRIDGE_KINDS

    failures: list[str] = []
    for name in names:
        runs = []
        for _ in range(2):
            sc = _build(name, seed=None, scale=SMOKE_SCALE, ticks=None)
            runs.append(run_scenario(sc))
        a, b = runs
        det_a, det_b = a.determinism_json(), b.determinism_json()
        plan_kinds = {f.kind for f in a.scenario.faults.faults}
        bridge_faulted = bool(plan_kinds & set(BRIDGE_KINDS))
        agent_faulted = bool(plan_kinds & set(AGENT_KINDS))
        wait_reasons = a.quality.get("wait_reasons", {})
        line = {
            "scenario": name,
            "deterministic": det_a == det_b,
            "wait_reasons": wait_reasons,
            "violations": len(a.determinism["invariant_violations"]),
            "bound_total": a.determinism["bound_total"],
            "pending_final": a.determinism["pending_final"],
            "recovery_ticks": a.determinism["recovery_ticks"],
            "restarts": a.determinism["restarts"],
            "agent_restarts": a.determinism["agent_restarts"],
            "vnode_deletions": a.determinism["vnode_deletions"],
            "rpc_retries": sum(a.determinism["rpc_retries"].values()),
            "tick_p50_ms": a.timing["tick_p50_ms"],
            # flight-record glance: span-derived phase sum should track
            # tick_p50_ms (the ±5% reconciliation the tests enforce)
            "flight_phase_sum_p50_ms": a.flight_record.get("phase_sum_p50_ms"),
            "flight_commits_total": a.flight_record.get("commits_total"),
        }
        if a.scenario.sharding is not None:
            line["shard"] = a.determinism.get("shard")
        print(json.dumps(line))
        if det_a != det_b:
            failures.append(f"{name}: determinism broke (same seed, different run)")
        if a.determinism["invariant_violations"]:
            first = a.determinism["invariant_violations"][0]
            failures.append(f"{name}: invariant violated: {first}")
        if a.scenario.explain and wait_reasons.get("UNKNOWN"):
            # ISSUE 15 acceptance: with explain on, every unplaced job
            # carries a STRUCTURED reason — an UNKNOWN leaking through
            # means an attribution-less mark path regressed
            failures.append(
                f"{name}: {wait_reasons['UNKNOWN']} unplaced job-ticks "
                "fell back to the generic UNKNOWN reason with explain on"
            )
        if name == "sharded_gang_split" and a.scenario.explain and not wait_reasons:
            failures.append(
                f"{name}: no wait_reasons recorded — the explain plane "
                "is dead on the sharded tick"
            )
        if a.scenario.faults and a.scenario.expect_drain:
            rec = a.determinism["recovery_ticks"]
            bound = a.scenario.max_recovery_ticks
            if rec is None:
                failures.append(f"{name}: never recovered after fault window")
            elif bound is not None and rec > bound:
                failures.append(
                    f"{name}: recovery_ticks {rec} over the scenario "
                    f"bound {bound}"
                )
        if bridge_faulted:
            # a restart/failover may NEVER flap virtual nodes (ADVICE #1
            # under the new path) and must actually have happened
            if a.determinism["vnode_deletions"]:
                failures.append(
                    f"{name}: {a.determinism['vnode_deletions']} VirtualNode "
                    "deletions across a restart/failover (must be 0)"
                )
            if not a.determinism["restarts"]:
                failures.append(f"{name}: bridge fault never restarted the stack")
        if agent_faulted and not a.determinism["agent_restarts"]:
            failures.append(f"{name}: agent fault never reloaded the agent")
        if a.scenario.lossless_twin:
            # lossless recovery: the crashed run must END identical to
            # the same scenario with the bridge/agent crash faults
            # stripped (remaining chaos — rpc flaps, vanished partitions
            # — stays in the twin, isolating the crash's contribution).
            # "state" compares byte-identical placements+ids; "outcome"
            # the id/placement-insensitive lifecycle digest (composed
            # RPC faults legitimately reshuffle Slurm job ids).
            key = (
                "final_state_digest"
                if a.scenario.lossless_twin == "state"
                else "final_outcome_digest"
            )
            twin = run_scenario(
                dataclasses.replace(
                    a.scenario,
                    faults=a.scenario.faults.strip(BRIDGE_KINDS + AGENT_KINDS),
                )
            )
            same = twin.determinism[key] == a.determinism[key]
            print(json.dumps({
                "scenario": f"{name}[crash-free twin]",
                "compared": key,
                "final_identical": same,
            }))
            if not same:
                failures.append(
                    f"{name}: post-recovery {key} diverged from the "
                    "crash-free run at the same seed"
                )
        if a.scenario.incremental:
            # the PR-11 acceptance gate: the event-driven incremental
            # tick must be byte-identical IN OUTCOME to the full tick —
            # same determinism digest (every bind/preempt/pending count,
            # in order) and same final state — at the same seed, faults
            # included. O(changes) may only change WHERE time goes.
            off = run_scenario(
                dataclasses.replace(a.scenario, incremental=False)
            )
            inc_same = (
                off.determinism["digest"] == a.determinism["digest"]
                and off.determinism["final_state_digest"]
                == a.determinism["final_state_digest"]
            )
            print(json.dumps({
                "scenario": f"{name}[full-tick twin]",
                "incremental_identical": inc_same,
                "steady_ticks": a.timing.get("steady_ticks"),
                "steady_tick_p50_ms": a.timing.get("steady_tick_p50_ms"),
            }))
            if not inc_same:
                failures.append(
                    f"{name}: incremental tick diverged from the full "
                    "tick at the same seed"
                )
        if a.scenario.sharding is not None:
            # shard-specific gates: the plan must actually shard, and
            # the reconciliation scenario must actually reconcile —
            # either degrading silently would leave the subsystem
            # untested while the smoke line stays green
            sh = a.determinism.get("shard") or {}
            if (sh.get("shard_count") or 0) < 2:
                failures.append(
                    f"{name}: sharding on but the plan built "
                    f"{sh.get('shard_count')} shard(s) — the fan-out "
                    "never engaged"
                )
            if name == "sharded_gang_split" and not sh.get(
                "reconcile_placed"
            ):
                failures.append(
                    f"{name}: no gang placed via cross-shard "
                    "reconciliation — the pass is dead"
                )
    if failures:
        for f in failures:
            print(f"# {label} FAIL: {f}", file=sys.stderr)
        return 1
    print(f"# {label} OK: {len(names)} scenarios, deterministic, "
          "invariants held", file=sys.stderr)
    return 0


def _quality(label: str = "quality-smoke") -> int:
    """The placement-quality gate (ISSUE 9): each quality scenario runs
    TWICE (determinism over the scorecard too), then its policy-off —
    and, for diurnal, backfill-off — twin arms run at the same seed and
    the scorecard floors are enforced:

    - ``multi_tenant_storm``: Jain ≥ 0.9 with fair share on, < 0.7
      under the priority-FIFO baseline;
    - ``priority_inversion``: the production gang binds within
      ``max_wait_ticks`` via ≥1 preemption; the policy-off arm starves
      it (recorded);
    - ``diurnal_load``: utilization beats policy-off by the margin,
      backfill actually fired, and gang waits beat the backfill-off arm;
    - ``elastic_resize``: every resized job re-places, the scenario
      drains, zero invariant violations.
    """
    import dataclasses

    from slurm_bridge_tpu.policy.engine import PolicyConfig

    g = QUALITY_GATES
    failures: list[str] = []

    def run(name: str, **replace):
        sc = SCENARIOS[name](scale=SMOKE_SCALE)
        if replace:
            sc = dataclasses.replace(sc, **replace)
        return run_scenario(sc)

    for name in QUALITY_SCENARIOS:
        a = run(name)
        b = run(name)
        det = (
            a.determinism_json() == b.determinism_json()
            and a.quality == b.quality
        )
        if not det:
            failures.append(f"{name}: determinism broke (same seed, "
                            "different run — scorecard or digest)")
        if a.determinism["invariant_violations"]:
            first = a.determinism["invariant_violations"][0]
            failures.append(f"{name}: invariant violated: {first}")
        if a.scenario.incremental:
            # PR-11: the incremental tick must not move a single quality
            # number either — same digest, same final state, same
            # scorecard as the full tick at the same seed
            full = run(name, incremental=False)
            inc_same = (
                full.determinism["digest"] == a.determinism["digest"]
                and full.determinism["final_state_digest"]
                == a.determinism["final_state_digest"]
                and full.quality == a.quality
            )
            if not inc_same:
                failures.append(
                    f"{name}: incremental tick diverged from the full "
                    "tick (digest/state/scorecard) at the same seed"
                )
        q = a.quality
        line = {
            "scenario": name,
            "deterministic": det,
            "violations": len(a.determinism["invariant_violations"]),
            "bound_total": a.determinism["bound_total"],
            "utilization_mean": q["utilization_mean"],
            "jain_fairness": q["jain_fairness"],
            "gang_wait_p95_ticks": q["gang_wait_p95_ticks"],
            "preempted_total": q["preempted_total"],
            "backfill_binds": q.get("backfill_binds"),
            "resizes": q["resizes"],
            "wait_reasons": q.get("wait_reasons", {}),
        }
        if a.scenario.explain and q.get("wait_reasons", {}).get("UNKNOWN"):
            failures.append(
                f"{name}: {q['wait_reasons']['UNKNOWN']} unplaced "
                "job-ticks carry the generic UNKNOWN reason with "
                "explain on"
            )
        if name == "multi_tenant_storm" and a.scenario.explain and not q.get(
            "wait_reasons"
        ):
            failures.append(
                f"{name}: no wait_reasons recorded — the explain plane "
                "is dead on the oversubscribed storm"
            )

        if name == "multi_tenant_storm":
            off = run(name, policy=None)
            line["jain_policy_off"] = off.quality["jain_fairness"]
            if q["jain_fairness"] < g["jain_on_floor"]:
                failures.append(
                    f"{name}: Jain {q['jain_fairness']} under the "
                    f"{g['jain_on_floor']} fair-share floor"
                )
            if off.quality["jain_fairness"] >= g["jain_off_ceiling"]:
                failures.append(
                    f"{name}: policy-off Jain {off.quality['jain_fairness']} "
                    f"not under {g['jain_off_ceiling']} — the baseline "
                    "stopped being unfair, the comparison is vacuous"
                )
        elif name == "priority_inversion":
            off = run(name, policy=None)
            on_wait = q["class_wait_p95_ticks"].get("production")
            off_wait = off.quality["class_wait_p95_ticks"].get("production")
            line["production_wait_p95"] = on_wait
            line["production_wait_p95_policy_off"] = off_wait
            if on_wait is None or on_wait > g["max_wait_ticks"]:
                failures.append(
                    f"{name}: production gang wait p95 {on_wait} over the "
                    f"{g['max_wait_ticks']}-tick bound"
                )
            if q["preempted_total"] < 1:
                failures.append(
                    f"{name}: gang bound without preempting anyone — the "
                    "scenario no longer exercises class preemption"
                )
            if off_wait is not None and on_wait is not None \
                    and off_wait <= on_wait:
                failures.append(
                    f"{name}: policy-off wait {off_wait} not worse than "
                    f"policy-on {on_wait} — no inversion to fix"
                )
        elif name == "diurnal_load":
            off = run(name, policy=None)
            nobf = run(name, policy=PolicyConfig(backfill=False))
            line["utilization_policy_off"] = off.quality["utilization_mean"]
            line["utilization_backfill_off"] = nobf.quality["utilization_mean"]
            line["gang_wait_p95_backfill_off"] = nobf.quality[
                "gang_wait_p95_ticks"
            ]
            if q["utilization_mean"] < (
                off.quality["utilization_mean"] + g["util_margin"]
            ):
                failures.append(
                    f"{name}: utilization {q['utilization_mean']} not "
                    f"{g['util_margin']} over policy-off "
                    f"{off.quality['utilization_mean']}"
                )
            if not q.get("backfill_binds"):
                failures.append(f"{name}: backfill never placed anything")
            if q["gang_wait_p95_ticks"] >= nobf.quality["gang_wait_p95_ticks"]:
                failures.append(
                    f"{name}: gang wait p95 {q['gang_wait_p95_ticks']} not "
                    "under the backfill-off arm "
                    f"{nobf.quality['gang_wait_p95_ticks']} — backfill "
                    "isn't what starts the gangs"
                )
        elif name == "elastic_resize":
            if not q["resizes"]:
                failures.append(f"{name}: no resizes applied")
            if a.determinism["drained_at_tick"] is None:
                failures.append(f"{name}: resized workload never drained")
            if q["unbound_final"]:
                failures.append(
                    f"{name}: {q['unbound_final']} jobs never re-placed "
                    "after resize"
                )
            rec = a.determinism["recovery_ticks"]
            bound = a.scenario.max_recovery_ticks
            if rec is None or (bound is not None and rec > bound):
                failures.append(
                    f"{name}: recovery_ticks {rec} over bound {bound}"
                )
        print(json.dumps(line))
    if failures:
        for f in failures:
            print(f"# {label} FAIL: {f}", file=sys.stderr)
        return 1
    print(
        f"# {label} OK: {len(QUALITY_SCENARIOS)} scenarios, deterministic, "
        "scorecard floors held", file=sys.stderr,
    )
    return 0


def _stitch_coverage(result) -> tuple[float, float]:
    """Summed ``(stitched child ms, total ms)`` over every
    ``rpc.client.PlaceShard`` node in the run's per-tick flight trees —
    the ISSUE 20 trace-coverage gate's numerator and denominator. The
    children are the synthetic ``sidecar.*`` phase spans plus the
    ``rpc.overhead`` residual the stitching hook fabricates while the
    client span is still open."""
    covered = 0.0
    total = 0.0

    def walk(name: str, node: dict) -> None:
        nonlocal covered, total
        if name == "rpc.client.PlaceShard":
            total += node.get("ms", 0.0)
            for child in node.get("children", {}).values():
                covered += child.get("ms", 0.0)
        for child_name, child in node.get("children", {}).items():
            walk(child_name, child)

    for rec in result.flight_ticks:
        for name, node in rec.get("tree", {}).items():
            walk(name, node)
    return covered, total


def _fleet(label: str = "fleet-smoke") -> int:
    """The fleet gate (ISSUE 17): each fleet scenario runs TWICE
    (double-run determinism — membership facts included), then its
    single-process twin at the same seed:

    - **fleet twin**: the fleet run's ``final_state_digest`` must be
      byte-identical to the same scenario with ``fleet=None`` and the
      ``kill_replica`` faults stripped — remote solves are byte-parity
      with inline and a re-key only changes WHO solves, so any
      divergence is a lost bind or a corrupted shard merge;
    - **engagement**: ``remote_solves > 0`` — a fleet run that silently
      solved everything inline is a failed gate, not a pass;
    - **chaos** (``fleet_kill_owner``): the kill actually happened, the
      dead replica's sidecar was re-adopted (``live_final`` back to
      full strength) within ``max_recovery_ticks``, and zero
      VirtualNode deletions (no node flap from a fleet event);
    - **trace coverage** (ISSUE 20): ≥95% of every
      ``rpc.client.PlaceShard`` span's wall time is attributed to the
      stitched synthetic children (``sidecar.decode/solve/encode`` +
      the ``rpc.overhead`` residual) — unexplained client-span time
      means the stitching hook fell off the RPC path.
    """
    from slurm_bridge_tpu.sim.faults import FLEET_KINDS

    failures: list[str] = []
    for name in FLEET_SCENARIOS:
        runs = []
        for _ in range(2):
            sc = _build(name, seed=None, scale=SMOKE_SCALE, ticks=None)
            runs.append(run_scenario(sc))
        a, b = runs
        det_a, det_b = a.determinism_json(), b.determinism_json()
        fleet = a.determinism.get("fleet") or {}
        remote = a.quality.get("fleet_remote") or {}
        line = {
            "scenario": name,
            "deterministic": det_a == det_b,
            "violations": len(a.determinism["invariant_violations"]),
            "bound_total": a.determinism["bound_total"],
            "pending_final": a.determinism["pending_final"],
            "vnode_deletions": a.determinism["vnode_deletions"],
            "fleet": fleet,
            "fleet_remote": remote,
            "tick_p50_ms": a.timing["tick_p50_ms"],
        }
        print(json.dumps(line))
        if det_a != det_b:
            failures.append(f"{name}: determinism broke (same seed, different run)")
        if a.determinism["invariant_violations"]:
            first = a.determinism["invariant_violations"][0]
            failures.append(f"{name}: invariant violated: {first}")
        if not remote.get("remote_solves"):
            failures.append(
                f"{name}: fleet attached but remote_solves == 0 — every "
                "shard solved inline, the gRPC path never engaged"
            )
        covered, total = _stitch_coverage(a)
        coverage = covered / total if total > 0 else 0.0
        print(json.dumps({
            "scenario": f"{name}[trace-stitching]",
            "place_shard_ms": round(total, 3),
            "stitched_ms": round(covered, 3),
            "coverage": round(coverage, 4),
            "fleet_timeline_events": len(
                (a.flight_record.get("fleet") or {}).get("timeline", [])
            ),
        }))
        if remote.get("remote_solves") and total > 0 and coverage < 0.95:
            failures.append(
                f"{name}: trace stitching covered {coverage:.1%} of "
                "rpc.client.PlaceShard wall time (floor 95%) — the "
                "synthetic sidecar children + rpc.overhead residual "
                "left client-span time unexplained"
            )
        if not (a.flight_record.get("fleet") or {}).get("timeline"):
            failures.append(
                f"{name}: flight record carries no fleet lifecycle "
                "timeline — spawn/ready events never recorded"
            )
        twin = run_scenario(
            dataclasses.replace(
                a.scenario,
                fleet=None,
                faults=a.scenario.faults.strip(FLEET_KINDS),
            )
        )
        same = (
            twin.determinism["final_state_digest"]
            == a.determinism["final_state_digest"]
        )
        print(json.dumps({
            "scenario": f"{name}[single-process twin]",
            "compared": "final_state_digest",
            "final_identical": same,
        }))
        if not same:
            failures.append(
                f"{name}: final_state_digest diverged from the single-"
                "process run at the same seed — a remote solve or "
                "re-key changed placements"
            )
        if name == "fleet_kill_owner":
            if not fleet.get("kills"):
                failures.append(f"{name}: kill_replica fault never killed anyone")
            if fleet.get("live_final") != fleet.get("replicas"):
                failures.append(
                    f"{name}: fleet ended at {fleet.get('live_final')}/"
                    f"{fleet.get('replicas')} live — the killed replica "
                    "was never re-adopted"
                )
            bound = a.scenario.max_recovery_ticks
            rec = fleet.get("recovery_ticks", 0)
            if bound is not None and rec > bound:
                failures.append(
                    f"{name}: fleet recovery_ticks {rec} over the "
                    f"scenario bound {bound}"
                )
            if a.determinism["vnode_deletions"]:
                failures.append(
                    f"{name}: {a.determinism['vnode_deletions']} "
                    "VirtualNode deletions across a replica kill (must be 0)"
                )
    if failures:
        for f in failures:
            print(f"# {label} FAIL: {f}", file=sys.stderr)
        return 1
    print(
        f"# {label} OK: {len(FLEET_SCENARIOS)} scenarios, deterministic, "
        "fleet twins byte-identical, chaos re-key held", file=sys.stderr,
    )
    return 0


def _admission(label: str = "admission-smoke") -> int:
    """The streaming-admission gate (ISSUE 12): each admission scenario
    runs TWICE (double-run determinism over the decision stream —
    attempts, binds, misses, digests), then its twin arms at the same
    seed:

    - **latency**: interactive arrival→bind p99 ≤ ``p99_ms`` in virtual
      time. Fast-path binds cost their measured admission wall time
      (sub-ms); a batch-tick bind costs at least half a tick period
      (2.5 s here) — so the gate holds only if the fast path catches
      essentially every interactive arrival;
    - **engagement**: the fast path actually bound ≥ the floor — a
      silently-dormant admitter is a failed gate, not a pass;
    - **admission-off twin**: batch utilization within ``util_margin``
      of the same scenario with ``admission=None`` (the fast path must
      not wreck the packing it front-runs), and the twin's interactive
      p99 must be OVER the gate — otherwise the comparison is vacuous;
    - **full-tick twin**: the incremental tick under admission stays
      byte-identical in outcome to the full tick, same as every other
      subsystem.
    """
    import dataclasses

    g = ADMISSION_GATES
    failures: list[str] = []
    for name in ADMISSION_SCENARIOS:
        runs = [
            run_scenario(_build(name, seed=None, scale=SMOKE_SCALE, ticks=None))
            for _ in range(2)
        ]
        a, b = runs
        det = a.determinism_json() == b.determinism_json()
        q = a.quality
        adm = a.determinism.get("admission") or {}
        line = {
            "scenario": name,
            "deterministic": det,
            "violations": len(a.determinism["invariant_violations"]),
            "bound_total": a.determinism["bound_total"],
            "interactive_arrivals": q["interactive_arrivals"],
            "fastpath_binds": q["fastpath_binds"],
            "interactive_latency_p50_ms": q["interactive_latency_p50_ms"],
            "interactive_latency_p99_ms": q["interactive_latency_p99_ms"],
            "admission": adm,
            "utilization_mean": q["utilization_mean"],
        }
        if not det:
            failures.append(
                f"{name}: determinism broke (same seed, different run)"
            )
        if a.determinism["invariant_violations"]:
            first = a.determinism["invariant_violations"][0]
            failures.append(f"{name}: invariant violated: {first}")
        if q["interactive_latency_p99_ms"] > g["p99_ms"]:
            failures.append(
                f"{name}: interactive p99 {q['interactive_latency_p99_ms']} "
                f"ms over the {g['p99_ms']} ms gate"
            )
        if q["fastpath_binds"] < g["min_fastpath_binds"]:
            failures.append(
                f"{name}: only {q['fastpath_binds']} fast-path binds "
                f"(floor {g['min_fastpath_binds']}) — the fast path is "
                "dormant"
            )
        if not adm.get("misses"):
            # ISSUE 15 satellite: the by-reason miss ledger must be
            # live in the scenario JSON — cold-start arrivals alone
            # guarantee no_window/not_ready entries, so an empty dict
            # means the accounting broke, not that nothing missed
            failures.append(
                f"{name}: FastPathAdmitter.misses is empty — the "
                "by-reason miss accounting is dead"
            )
        if q.get("admission_misses") != adm.get("misses"):
            failures.append(
                f"{name}: quality.admission_misses diverged from the "
                "admitter's own ledger"
            )
        off = run_scenario(
            dataclasses.replace(
                _build(name, seed=None, scale=SMOKE_SCALE, ticks=None),
                admission=None,
            )
        )
        line["utilization_admission_off"] = off.quality["utilization_mean"]
        line["p99_admission_off"] = off.quality.get(
            "interactive_latency_p99_ms"
        )
        if (
            abs(q["utilization_mean"] - off.quality["utilization_mean"])
            > g["util_margin"]
        ):
            failures.append(
                f"{name}: utilization {q['utilization_mean']} not within "
                f"{g['util_margin']} of the admission-off twin "
                f"{off.quality['utilization_mean']}"
            )
        # vacuity check on the LATENCY claim: with admission off the
        # same interactive stream must miss the gate (it binds through
        # the batch tick at ≥ half a tick period). The off arm tracks
        # no interactive set, so compute from its wait distribution:
        # every wait is ≥ 0 ticks ⇒ ≥ 2.5 s with the +0.5 model — the
        # arithmetic floor already exceeds the gate; assert it to keep
        # the gate honest if the latency model ever changes.
        half_tick_ms = a.scenario.tick_interval_s * 500.0
        if half_tick_ms <= g["p99_ms"]:
            failures.append(
                f"{name}: tick interval {a.scenario.tick_interval_s}s "
                "makes the batch path faster than the gate — the "
                "comparison is vacuous"
            )
        full = run_scenario(
            dataclasses.replace(
                _build(name, seed=None, scale=SMOKE_SCALE, ticks=None),
                incremental=False,
            )
        )
        inc_same = (
            full.determinism["digest"] == a.determinism["digest"]
            and full.determinism["final_state_digest"]
            == a.determinism["final_state_digest"]
        )
        line["incremental_identical"] = inc_same
        if not inc_same:
            failures.append(
                f"{name}: incremental tick diverged from the full tick "
                "under admission at the same seed"
            )
        print(json.dumps(line))
    if failures:
        for f in failures:
            print(f"# {label} FAIL: {f}", file=sys.stderr)
        return 1
    print(
        f"# {label} OK: {len(ADMISSION_SCENARIOS)} scenarios, "
        "deterministic, latency + utilization gates held", file=sys.stderr,
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m slurm_bridge_tpu.sim",
        description="deterministic cluster simulator + fault harness",
    )
    parser.add_argument("scenarios", nargs="*", help="scenario names (see --list)")
    parser.add_argument("--list", action="store_true", help="list scenarios")
    parser.add_argument("--all", action="store_true",
                        help="run every fast scenario")
    parser.add_argument("--smoke", action="store_true",
                        help="CI gate: toy scale, double-run determinism check")
    parser.add_argument("--chaos", action="store_true",
                        help="CI gate: only the composed-fault chaos "
                        "scenarios (double-run + crash-free twin digests)")
    parser.add_argument("--quality", action="store_true",
                        help="CI gate: the placement-quality scenarios "
                        "(double-run + policy-on/off arms + scorecard "
                        "floors — fairness, wait bounds, backfill)")
    parser.add_argument("--shard", action="store_true",
                        help="CI gate: the sharded-placement scenarios "
                        "(double-run determinism + invariants + shard/"
                        "reconcile engagement gates)")
    parser.add_argument("--admission", action="store_true",
                        help="CI gate: the streaming-admission scenarios "
                        "(double-run determinism + interactive latency "
                        "p99 + admission-off utilization twin)")
    parser.add_argument("--fleet", action="store_true",
                        help="CI gate: the fleet scenarios (double-run "
                        "determinism + single-process twin digest + "
                        "remote-solve engagement + kill-shard-owner "
                        "chaos re-key)")
    parser.add_argument("--sidecars", type=int, default=None, metavar="N",
                        help="override the fleet replica count for named "
                        "fleet scenarios (each replica owns a shard-set "
                        "and a solver sidecar process)")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--explain", default="", metavar="JOB",
                        help="render one job's placement decision trail "
                        "(route -> solve -> backfill/reconcile -> "
                        "reason) for the named job or sizecar pod; "
                        "requires exactly one scenario")
    parser.add_argument("--scale", type=float, default=1.0,
                        help="multiply pod/node counts (default 1.0)")
    parser.add_argument("--ticks", type=int, default=None)
    parser.add_argument("--out", default="",
                        help="also write the result array to this JSON file")
    args = parser.parse_args(argv)

    if args.list:
        for name, f in SCENARIOS.items():
            sc = f()
            slow = " [slow]" if sc.slow else ""
            print(f"{name}{slow}: {sc.description}")
        return 0
    if args.chaos:
        return _smoke(CHAOS_SCENARIOS, label="chaos-smoke")
    if args.quality:
        return _quality()
    if args.shard:
        return _smoke(SHARD_SCENARIOS, label="shard-smoke")
    if args.admission:
        return _admission()
    if args.fleet:
        return _fleet()
    if args.smoke:
        return _smoke()

    names = args.scenarios or (
        # --all = every fast scenario, chaos + quality + shard subsets
        # included (the smoke GATES keep the sets disjoint; a human
        # asking for "all" wants all)
        [
            *SMOKE_SCENARIOS,
            *CHAOS_SCENARIOS,
            *QUALITY_SCENARIOS,
            *ADMISSION_SCENARIOS,
            *(n for n in SHARD_SCENARIOS if n not in SMOKE_SCENARIOS),
        ]
        if args.all
        else []
    )
    if not names:
        parser.error("name at least one scenario, or use --all / --smoke / --list")
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        parser.error(f"unknown scenarios {unknown}; see --list")

    if args.explain and len(names) != 1:
        parser.error("--explain traces one job through ONE scenario")

    results = []
    gate_failures: list[str] = []
    for name in names:
        sc = _build(name, seed=args.seed, scale=args.scale, ticks=args.ticks)
        if args.sidecars is not None:
            if sc.fleet is None:
                parser.error(
                    f"--sidecars only applies to fleet scenarios; "
                    f"{name} has no fleet config"
                )
            sc = dataclasses.replace(
                sc,
                fleet=dataclasses.replace(sc.fleet, replicas=args.sidecars),
            )
        if args.explain:
            # --explain <job>: trace one job's decision trail (ISSUE 15
            # sink 3). Accept the job name or the sizecar pod name —
            # the trail is recorded against the POD the scheduler sees.
            target = args.explain
            if not target.endswith("-sizecar"):
                target = f"{target}-sizecar"
            sc = dataclasses.replace(sc, explain_target=target)
        print(f"# running {name} "
              f"(~{sc.workload.jobs} jobs x {sc.cluster.num_nodes} nodes, "
              f"{sc.ticks} ticks)", file=sys.stderr, flush=True)
        if args.explain:
            harness = SimHarness(sc)
            result = harness.run()
            print(harness.scheduler.explain_trail.render(), flush=True)
        else:
            result = run_scenario(sc)
        results.append(result)
        print(json.dumps(result.as_dict()), flush=True)
        if name.startswith("full_") and "crash" not in name:
            # every full_* headline scenario emits its metric line +
            # flight diagnostics (full_50kx10k since PR-5, the sharded
            # full_500kx100k since PR-10)
            print(json.dumps(_headline(result)), flush=True)
            path = _write_flight_diagnostics(result)
            if path:
                print(f"# flight record: {path}", file=sys.stderr)
        if (
            sc.p50_gate_ms is not None
            and result.timing["tick_p50_ms"] > sc.p50_gate_ms
        ):
            gate_failures.append(
                f"{name}: tick_p50_ms {result.timing['tick_p50_ms']} over "
                f"the {sc.p50_gate_ms} ms gate"
            )
        if sc.phase_reconcile_pct is not None and sc.tracing:
            # the PR-5 ±5% flight-record contract, re-enforced at the
            # headline shape (ISSUE 14): the span-derived per-phase sum
            # must explain the tick span — a hollowed tree (dropped
            # spans, an unattributed phase) fails loudly instead of
            # silently lying about where the tick went
            fr = result.flight_record
            tick_span = fr.get("tick_span_p50_ms") or 0.0
            phase_sum = fr.get("phase_sum_p50_ms") or 0.0
            if tick_span <= 0.0:
                gate_failures.append(
                    f"{name}: phase_reconcile_pct set but no flight record"
                )
            elif (
                abs(tick_span - phase_sum) / tick_span * 100.0
                > sc.phase_reconcile_pct
            ):
                gate_failures.append(
                    f"{name}: phase_sum_p50_ms {phase_sum} vs tick span "
                    f"{tick_span} drifts over ±{sc.phase_reconcile_pct}%"
                )
        if sc.steady_gate_ms is not None and sc.incremental:
            steady = result.timing.get("steady_tick_p50_ms")
            if steady is None:
                gate_failures.append(
                    f"{name}: steady_gate_ms set but the run never "
                    "reached a steady tick"
                )
            elif steady > sc.steady_gate_ms:
                gate_failures.append(
                    f"{name}: steady_tick_p50_ms {steady} over the "
                    f"{sc.steady_gate_ms} ms gate"
                )
        if name == "full_50kx10k_crash":
            # the recovery-at-scale record BASELINE.md tracks
            print(json.dumps({
                "metric": "crash_recovery_ms_50kx10k",
                "recovery_ms": result.timing["recovery_ms"],
                "restored_objects": result.determinism["restored_objects"],
                "restarts": result.determinism["restarts"],
                "vnode_deletions": result.determinism["vnode_deletions"],
                "final_state_digest": result.determinism["final_state_digest"],
                "invariant_violations": len(
                    result.determinism["invariant_violations"]
                ),
            }), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump([r.as_dict() for r in results], f, indent=1, sort_keys=True)
    bad = [
        r.scenario.name
        for r in results
        if r.determinism["invariant_violations"]
    ]
    if bad:
        print(f"# invariant violations in: {', '.join(bad)}", file=sys.stderr)
        return 1
    if gate_failures:
        for f in gate_failures:
            print(f"# p50 gate FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
