"""sim/ — deterministic cluster simulator + fault-injection harness.

The round-5 VERDICT's biggest open gap: the north-star metric (full
reconcile-tick latency through store → scheduler → mirror) had only ever
been driven at 2k pods × 1k nodes, and every robustness claim rested on
hand-written unit fixtures. This package closes both: a seeded
discrete-event simulator that

- generates synthetic clusters and workload traces (Poisson/burst
  arrivals, gang jobs, heterogeneous partitions/features, node
  drain/resume churn) at up to 50k pods × 10k nodes (``trace``);
- drives the REAL bridge pipeline — :class:`ObjectStore`,
  :class:`BridgeOperator`, :class:`PlacementScheduler.tick`, the
  virtual-node mirror and statusmap — against an in-process fake agent
  with no wall-clock sleeps, advancing virtual time (``harness``,
  ``agent``);
- injects faults through a composable :class:`FaultPlan` (agent RPC
  errors/latency, stale snapshots, lost status updates, preemption
  storms, partition disappearance) and asserts invariants after every
  tick: no double-bind, gang atomicity, capacity never oversubscribed,
  eventual drain of the pending queue (``faults``, ``invariants``);
- emits per-scenario JSON metrics: tick p50/p95 broken into
  store/encode/solve/bind/mirror phases, placement quality, preemption
  count, recovery time after fault clear (``harness.ScenarioResult``).

Same seed ⇒ byte-identical deterministic section of the metrics JSON
(timing lives in a separate, explicitly non-deterministic section).

Entry points: ``python -m slurm_bridge_tpu.sim`` (``cli``), the named
scenario files under ``benchmarks/scenarios/sim_*.py``, and
``make sim-smoke``.
"""

from slurm_bridge_tpu.sim.agent import SimCluster, SimWorkloadClient
from slurm_bridge_tpu.sim.faults import Fault, FaultPlan, SimRpcError
from slurm_bridge_tpu.sim.harness import Scenario, ScenarioResult, run_scenario
from slurm_bridge_tpu.sim.trace import ClusterSpec, WorkloadSpec

__all__ = [
    "ClusterSpec",
    "Fault",
    "FaultPlan",
    "Scenario",
    "ScenarioResult",
    "SimCluster",
    "SimRpcError",
    "SimWorkloadClient",
    "WorkloadSpec",
    "run_scenario",
]
