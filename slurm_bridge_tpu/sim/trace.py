"""Synthetic cluster + workload trace generation (seeded, deterministic).

Distributions intentionally match ``solver/snapshot.py::random_inventory``
(the already-typed benchmark generator) so simulator scenarios and the
solver-only benchmarks describe the same population: node cpus from
{32, 64, 128}, mem 2–4 GiB/cpu, a GPU island, a small pre-existing
allocation, and jobs whose mean demand scales with cluster free capacity.
On top of that, this module adds what a *trace* needs and a static batch
doesn't: arrival processes (Poisson rate, front-loaded backlog, bursts),
per-job virtual durations, and heterogeneous partition/feature layout.

Everything derives from one ``numpy`` Generator the caller seeds; no
wall-clock, no global state.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from slurm_bridge_tpu.bridge.objects import BridgeJobSpec
from slurm_bridge_tpu.policy.classes import CLASS_LABEL, TENANT_LABEL
from slurm_bridge_tpu.sim.agent import SimNode

GPU_FEATURE = "gpu_type0"


@dataclass(frozen=True)
class ClusterSpec:
    """Shape of the synthetic cluster."""

    num_nodes: int
    num_partitions: int = 4
    cpu_choices: tuple[int, ...] = (32, 64, 128)
    mem_per_cpu_choices: tuple[int, ...] = (2048, 4096)
    gpu_fraction: float = 0.15
    gpu_choices: tuple[int, ...] = (4, 8)
    #: extra per-partition feature tags (partition k gets feature
    #: ``tier{k % len}``) — exercises the heterogeneous-features path
    partition_features: tuple[str, ...] = ()
    #: mean pre-existing (non-sim) allocation fraction, uniform [0, 2×mean]
    base_load: float = 0.15


@dataclass(frozen=True)
class WorkloadSpec:
    """Arrival process + per-job demand distributions.

    ``arrival``:
    - ``"front"``  — every job arrives at tick 0 (cold-start backlog);
    - ``"poisson"``— Poisson(jobs/spread_ticks) arrivals per tick over the
      first ``spread_ticks`` ticks;
    - ``"burst"``  — jobs split evenly across ``burst_ticks``;
    - ``"diurnal"``— Poisson with a sinusoidal day/night rate over the
      first ``spread_ticks`` ticks (``diurnal_cycles`` peaks).

    Tenancy/class fields are OFF by default and — deliberately — draw
    NOTHING from the RNG when off, so every pre-existing scenario's
    random stream (and therefore its determinism digest) is untouched:

    - ``tenants`` > 0 labels each job ``tenant-<k>`` (uniform draw);
    - ``tenant_priorities`` (len == tenants) maps each tenant's jobs
      into its own priority range via a deterministic transform of the
      already-drawn priority (no extra draws) — the skew the
      multi-tenant fairness scenario runs on;
    - ``priority_classes`` assigns a class label by weighted draw.
    """

    jobs: int
    arrival: str = "poisson"
    spread_ticks: int = 10
    burst_ticks: tuple[int, ...] = (0,)
    gang_fraction: float = 0.05
    gang_size: int = 4
    gpu_fraction: float = 0.1
    cpu_choices: tuple[int, ...] = (1, 2, 4, 8)
    mem_per_cpu_choices: tuple[int, ...] = (1024, 2048, 4096)
    #: virtual-seconds runtime, uniform over [lo, hi)
    duration_range: tuple[float, float] = (5.0, 60.0)
    priority_range: tuple[int, int] = (0, 100)
    #: sinusoid peaks across the spread window (arrival="diurnal")
    diurnal_cycles: int = 2
    #: tenants for fair-share scenarios; 0 = unlabeled (no RNG drawn)
    tenants: int = 0
    #: per-tenant (lo, hi) priority ranges (len == tenants)
    tenant_priorities: tuple[tuple[int, int], ...] = ()
    #: (class name, weight) distribution for priority-class labels
    priority_classes: tuple[tuple[str, float], ...] = ()


@dataclass
class JobArrival:
    """One trace entry: a BridgeJob spec arriving at ``tick``."""

    tick: int
    name: str
    spec: BridgeJobSpec
    duration_s: float
    #: CR metadata labels (tenant / priority-class); empty = unlabeled
    labels: dict = field(default_factory=dict)


def build_cluster(
    spec: ClusterSpec, rng: np.random.Generator
) -> tuple[list[SimNode], dict[str, tuple[str, ...]]]:
    """Nodes + partition membership for one scenario."""
    n = spec.num_nodes
    cpus = rng.choice(spec.cpu_choices, size=n)
    mem = cpus * rng.choice(spec.mem_per_cpu_choices, size=n)
    has_gpu = rng.random(n) < spec.gpu_fraction
    gpus = np.where(has_gpu, rng.choice(spec.gpu_choices, size=n), 0)
    part = rng.integers(0, spec.num_partitions, size=n)
    base = rng.uniform(0.0, 2.0 * spec.base_load, size=n)
    nodes: list[SimNode] = []
    members: dict[str, list[str]] = {
        f"part{k}": [] for k in range(spec.num_partitions)
    }
    for i in range(n):
        feats: tuple[str, ...] = (GPU_FEATURE,) if has_gpu[i] else ()
        if spec.partition_features:
            tag = spec.partition_features[
                int(part[i]) % len(spec.partition_features)
            ]
            feats = feats + (tag,)
        name = f"node{i:05d}"
        nodes.append(
            SimNode(
                name=name,
                cpus=int(cpus[i]),
                memory_mb=int(mem[i]),
                gpus=int(gpus[i]),
                gpu_type=GPU_FEATURE if has_gpu[i] else "",
                features=feats,
                base_alloc_cpus=int(cpus[i] * base[i]),
                base_alloc_memory_mb=int(mem[i] * base[i]),
            )
        )
        members[f"part{int(part[i])}"].append(name)
    partitions = {k: tuple(v) for k, v in members.items()}
    return nodes, partitions


def _arrival_ticks(
    spec: WorkloadSpec, ticks: int, rng: np.random.Generator
) -> np.ndarray:
    if spec.arrival == "front":
        return np.zeros(spec.jobs, dtype=np.int64)
    if spec.arrival == "burst":
        burst = np.asarray(spec.burst_ticks, dtype=np.int64)
        return burst[np.arange(spec.jobs) % len(burst)]
    if spec.arrival == "poisson":
        window = max(1, min(spec.spread_ticks, ticks))
        rate = spec.jobs / window
        counts = rng.poisson(rate, size=window)
        out = np.repeat(np.arange(window, dtype=np.int64), counts)
        return out[: spec.jobs]  # cap at the nominal total
    if spec.arrival == "diurnal":
        # sinusoidal day/night load: per-tick Poisson rate ∝ 1 + sin,
        # normalized so the window's expected total is ``jobs``
        window = max(1, min(spec.spread_ticks, ticks))
        t = np.arange(window, dtype=np.float64)
        wave = 1.0 + np.sin(2.0 * np.pi * spec.diurnal_cycles * t / window)
        rates = spec.jobs * wave / max(wave.sum(), 1e-9)
        counts = rng.poisson(rates)
        out = np.repeat(np.arange(window, dtype=np.int64), counts)
        return out[: spec.jobs]
    raise ValueError(f"unknown arrival process {spec.arrival!r}")


def generate_trace(
    spec: WorkloadSpec,
    cluster: ClusterSpec,
    ticks: int,
    rng: np.random.Generator,
    *,
    name_prefix: str = "sim",
    partition_sizes: list[int] | None = None,
    partition_gpu_caps: list[int] | None = None,
    partition_gpu_counts: list[int] | None = None,
) -> list[list[JobArrival]]:
    """Per-tick arrival lists (index = tick; length = ``ticks``).

    ``partition_sizes``/``partition_gpu_caps`` (from the BUILT cluster)
    keep the trace feasible by construction: GPU jobs only target
    partitions that actually have GPU nodes (capped at the partition's
    max per-node GPU count) and gangs only target partitions with at
    least ``gang_size`` members — a job that could never place anywhere
    would make "eventual drain" unfalsifiable, not robust.
    """
    arrive = _arrival_ticks(spec, ticks, rng)
    n = len(arrive)
    cpu = rng.choice(spec.cpu_choices, size=n)
    mem = rng.choice(spec.mem_per_cpu_choices, size=n)
    is_gpu = rng.random(n) < spec.gpu_fraction
    ngpu = rng.integers(1, 5, size=n)
    is_gang = rng.random(n) < spec.gang_fraction
    part = rng.integers(0, cluster.num_partitions, size=n)
    prio = rng.integers(spec.priority_range[0], spec.priority_range[1] + 1, size=n)
    dur = rng.uniform(*spec.duration_range, size=n)
    # tenancy/class draws happen ONLY when enabled — and strictly after
    # every pre-existing draw — so scenarios without them replay the
    # exact PR-8 random stream (digest byte-compat is gated on this)
    tenant_idx = (
        rng.integers(0, spec.tenants, size=n) if spec.tenants > 0 else None
    )
    cls_pick = rng.random(n) if spec.priority_classes else None
    if spec.priority_classes:
        cls_names = [c for c, _w in spec.priority_classes]
        w = np.asarray([w for _c, w in spec.priority_classes], np.float64)
        cls_cum = np.cumsum(w / max(w.sum(), 1e-9))
    # feasible target sets (see docstring): populated partitions for any
    # job — random node assignment can leave a partition EMPTY at small
    # node counts, and a job aimed there could never place — GPU-bearing
    # ones for GPU jobs, big-enough ones for gangs
    pop_parts = (
        [k for k, sz in enumerate(partition_sizes) if sz > 0]
        if partition_sizes is not None
        else list(range(cluster.num_partitions))
    )
    gpu_parts = (
        [k for k, cap in enumerate(partition_gpu_caps) if cap > 0]
        if partition_gpu_caps is not None
        else list(range(cluster.num_partitions))
    )
    gang_parts = (
        [k for k, sz in enumerate(partition_sizes) if sz >= spec.gang_size]
        if partition_sizes is not None
        else list(range(cluster.num_partitions))
    )
    out: list[list[JobArrival]] = [[] for _ in range(ticks)]
    for j in range(n):
        tick = int(arrive[j])
        if tick >= ticks:
            continue
        k = int(part[j])
        gpu_j = bool(is_gpu[j]) and bool(gpu_parts)
        gang_j = bool(is_gang[j]) and bool(gang_parts)
        if gpu_j and gang_j:
            # a GPU gang needs gang_size DISTINCT GPU nodes in one
            # partition — an all-or-nothing request no partition can ever
            # satisfy would wedge the drain check, so fall back to a
            # single-node GPU job when the cluster can't host the gang
            both = [
                p
                for p in gpu_parts
                if p in gang_parts
                and (
                    partition_gpu_counts is None
                    or partition_gpu_counts[p] >= spec.gang_size
                )
            ]
            if both:
                k = both[k % len(both)]
            else:
                gang_j = False
                k = gpu_parts[k % len(gpu_parts)]
        elif gpu_j:
            k = gpu_parts[k % len(gpu_parts)]
        elif gang_j:
            k = gang_parts[k % len(gang_parts)]
        elif pop_parts:
            k = pop_parts[k % len(pop_parts)]
        count = int(ngpu[j])
        if gpu_j and partition_gpu_caps is not None:
            count = min(count, partition_gpu_caps[k])
        prio_j = int(prio[j])
        labels: dict[str, str] = {}
        if tenant_idx is not None:
            t = int(tenant_idx[j])
            labels[TENANT_LABEL] = f"tenant-{t}"
            if spec.tenant_priorities:
                # per-tenant priority skew as a deterministic transform
                # of the already-drawn priority (no extra RNG)
                lo, hi = spec.tenant_priorities[t % len(spec.tenant_priorities)]
                prio_j = int(lo) + prio_j % (int(hi) - int(lo) + 1)
        if cls_pick is not None:
            labels[CLASS_LABEL] = cls_names[
                int(np.searchsorted(cls_cum, cls_pick[j], side="right").clip(
                    0, len(cls_names) - 1
                ))
            ]
        spec_j = BridgeJobSpec(
            partition=f"part{k}",
            sbatch_script="#!/bin/sh\n: sim workload\n",
            cpus_per_task=int(cpu[j]),
            ntasks=1,
            nodes=spec.gang_size if gang_j else 1,
            mem_per_cpu_mb=int(mem[j]),
            gres=f"gpu:{GPU_FEATURE}:{count}" if gpu_j else "",
            priority=prio_j,
        )
        out[tick].append(
            JobArrival(
                tick=tick,
                name=f"{name_prefix}-{j:06d}",
                spec=spec_j,
                duration_s=float(np.round(dur[j], 3)),
                labels=labels,
            )
        )
    return out


def storm_arrivals(
    tick: int,
    count: int,
    cluster: ClusterSpec,
    rng: np.random.Generator,
    *,
    priority: int = 1000,
    name_prefix: str = "storm",
    gang_size: int = 1,
    storm_class: str = "",
    eligible_parts: list[int] | None = None,
    cpus: tuple[int, ...] = (4, 8, 16),
) -> list[JobArrival]:
    """High-priority burst for a ``preemption_storm`` fault window.

    ``gang_size`` > 1 makes each storm job an all-or-nothing gang (the
    ``priority_inversion`` scenario's production gang), restricted to
    ``eligible_parts`` (partitions big enough to host it — the harness
    computes these from the BUILT cluster); ``storm_class`` stamps a
    priority-class label. Defaults reproduce the PR-2 storm exactly —
    same draws, same specs."""
    cpu = rng.choice(cpus, size=count)
    part = rng.integers(0, cluster.num_partitions, size=count)
    dur = rng.uniform(10.0, 30.0, size=count)
    labels = {CLASS_LABEL: storm_class} if storm_class else {}
    parts_of = list(eligible_parts) if eligible_parts else None
    return [
        JobArrival(
            tick=tick,
            name=f"{name_prefix}-{tick}-{j:05d}",
            spec=BridgeJobSpec(
                partition=(
                    f"part{parts_of[int(part[j]) % len(parts_of)]}"
                    if parts_of
                    else f"part{int(part[j])}"
                ),
                sbatch_script="#!/bin/sh\n: storm\n",
                cpus_per_task=int(cpu[j]),
                ntasks=1,
                nodes=gang_size if gang_size > 1 else 0,
                mem_per_cpu_mb=1024,
                priority=priority,
            ),
            duration_s=float(np.round(dur[j], 3)),
            labels=dict(labels),
        )
        for j in range(count)
    ]
