"""Named simulation scenarios — the catalog the CLI and smoke gate run.

Each factory takes a ``scale`` knob (default 1.0) that multiplies pod and
node counts, so ``make sim-smoke`` runs the same scenarios at toy shapes
and the CLI can run them full-size. The runnable per-scenario entry
points live in ``benchmarks/scenarios/sim_*.py`` (one file per scenario,
ISSUE 2); this module is the single source of truth they import.

``full_50kx10k`` is the slow headline: the previously-unmeasured full
bridge reconcile tick (store → encode → solve → bind → mirror) at 50k
pods × 10k nodes, reported as ``full_tick_p50_ms_50kx10k``.
"""

from __future__ import annotations

from slurm_bridge_tpu.admission import AdmissionConfig
from slurm_bridge_tpu.policy.engine import PolicyConfig
from slurm_bridge_tpu.shard.planner import ShardConfig
from slurm_bridge_tpu.sim.faults import Fault, FaultPlan
from slurm_bridge_tpu.sim.harness import Scenario
from slurm_bridge_tpu.sim.trace import ClusterSpec, WorkloadSpec
from slurm_bridge_tpu.solver.auction import AuctionConfig


def _n(base: int, scale: float, floor: int = 8) -> int:
    return max(floor, int(round(base * scale)))


def steady_poisson(scale: float = 1.0, seed: int = 42) -> Scenario:
    """Steady Poisson arrivals against a heterogeneous 4-partition
    cluster; no faults — the determinism/queue-drain baseline."""
    return Scenario(
        name="steady_poisson",
        description="Poisson arrivals, mixed cpu/mem/gpu demand, no faults",
        cluster=ClusterSpec(
            num_nodes=_n(400, scale), partition_features=("tier0", "tier1")
        ),
        workload=WorkloadSpec(
            jobs=_n(1500, scale, floor=20), arrival="poisson", spread_ticks=10
        ),
        ticks=20,
        seed=seed,
    )


def burst_backlog(scale: float = 1.0, seed: int = 43) -> Scenario:
    """Cold-start: the whole queue arrives at tick 0 (the headline
    shape's arrival pattern, scaled down)."""
    return Scenario(
        name="burst_backlog",
        description="front-loaded backlog, gang-heavy, drains from cold start",
        cluster=ClusterSpec(num_nodes=_n(600, scale)),
        workload=WorkloadSpec(
            jobs=_n(3000, scale, floor=30),
            arrival="front",
            gang_fraction=0.15,
        ),
        ticks=8,
        seed=seed,
    )


def agent_flaky_rpc(scale: float = 1.0, seed: int = 44) -> Scenario:
    """Agent RPC flaps: submissions and status queries fail 30% of the
    time (plus recorded latency) for a window; everything must converge
    after the flap clears — the retry/idempotency story end to end."""
    return Scenario(
        name="agent_flaky_rpc",
        description="30% UNAVAILABLE on SubmitJob/JobInfo/JobsInfo for ticks 4-12",
        cluster=ClusterSpec(num_nodes=_n(300, scale)),
        workload=WorkloadSpec(
            jobs=_n(1000, scale, floor=20), arrival="poisson", spread_ticks=8
        ),
        faults=FaultPlan(
            (
                Fault(
                    kind="rpc_error",
                    start_tick=4,
                    end_tick=12,
                    # JobsInfo is the bulk form the mirror dials since PR-3;
                    # keep the single-job form faulted too for the fallback
                    methods=("SubmitJob", "JobInfo", "JobsInfo"),
                    rate=0.3,
                ),
                Fault(
                    kind="rpc_latency",
                    start_tick=4,
                    end_tick=12,
                    methods=("SubmitJob",),
                    latency_ms=50.0,
                ),
            )
        ),
        ticks=18,
        seed=seed,
        max_recovery_ticks=24,
    )


def preemption_storm(scale: float = 1.0, seed: int = 45) -> Scenario:
    """A high-priority burst lands on a loaded cluster with preemption
    enabled: incumbents must be displaced (cancel + requeue + dedupe-safe
    resubmit) without ever double-binding or breaking gang atomicity."""
    return Scenario(
        name="preemption_storm",
        description="priority-1000 burst at tick 6 displaces incumbents",
        # deliberately oversubscribed (~1.4x free capacity in flight with
        # long runtimes): the storm cannot fit without displacing, so the
        # preemption path — cancel, requeue, dedupe-safe resubmit — runs
        # for real; the long grace + tick interval cover the worked-off
        # backlog so the drain invariant still closes the scenario
        cluster=ClusterSpec(num_nodes=_n(150, scale), gpu_fraction=0.0),
        workload=WorkloadSpec(
            jobs=_n(700, scale, floor=30),
            arrival="poisson",
            spread_ticks=4,
            gpu_fraction=0.0,
            cpu_choices=(8, 16, 32),
            duration_range=(60.0, 120.0),
        ),
        faults=FaultPlan(
            (
                Fault(
                    kind="preemption_storm",
                    start_tick=6,
                    end_tick=7,
                    jobs=_n(120, scale, floor=10),
                    priority=1000,
                ),
            )
        ),
        ticks=16,
        tick_interval_s=10.0,
        drain_grace_ticks=100,
        preemption=True,
        seed=seed,
        max_recovery_ticks=90,
    )


def node_churn(scale: float = 1.0, seed: int = 46) -> Scenario:
    """Drain/resume churn plus stale inventory snapshots and lost status
    updates — the scheduler must ride out a shrinking, lying inventory
    and drain once nodes return."""
    return Scenario(
        name="node_churn",
        description="20% of nodes drain ticks 4-12; stale snapshots + lost "
        "status ticks 5-10",
        cluster=ClusterSpec(num_nodes=_n(300, scale)),
        workload=WorkloadSpec(
            jobs=_n(900, scale, floor=20), arrival="poisson", spread_ticks=8
        ),
        faults=FaultPlan(
            (
                Fault(
                    kind="drain_nodes",
                    start_tick=4,
                    end_tick=12,
                    node_fraction=0.2,
                ),
                Fault(kind="stale_snapshot", start_tick=5, end_tick=10),
                Fault(kind="lost_status", start_tick=5, end_tick=10),
            )
        ),
        ticks=18,
        seed=seed,
        max_recovery_ticks=36,
    )


def partition_vanish(scale: float = 1.0, seed: int = 47) -> Scenario:
    """A whole partition disappears mid-run (agent stops listing it): its
    virtual node is torn down, its pending pods wait, and everything
    converges once the partition returns."""
    return Scenario(
        name="partition_vanish",
        description="partition part1 vanishes for ticks 3-10, then returns",
        cluster=ClusterSpec(num_nodes=_n(300, scale)),
        workload=WorkloadSpec(
            jobs=_n(800, scale, floor=20), arrival="poisson", spread_ticks=6
        ),
        faults=FaultPlan(
            (
                Fault(
                    kind="partition_vanish",
                    start_tick=3,
                    end_tick=10,
                    partition="part1",
                ),
            )
        ),
        ticks=16,
        seed=seed,
        max_recovery_ticks=12,
    )


def crash_restart(scale: float = 1.0, seed: int = 48) -> Scenario:
    """The bridge process dies mid-run — no graceful flush — and a fresh
    stack reloads from snapshot+WAL, re-converging against the sim
    agent's live ground truth. The smoke gate additionally proves the
    final state digest byte-identical to this scenario with the crash
    stripped (lossless recovery at the tick boundary)."""
    return Scenario(
        name="crash_restart",
        description="bridge crashes at tick 6; reloads snapshot+WAL and "
        "re-converges with zero node flap",
        cluster=ClusterSpec(num_nodes=_n(300, scale)),
        workload=WorkloadSpec(
            jobs=_n(900, scale, floor=20), arrival="poisson", spread_ticks=8
        ),
        faults=FaultPlan(
            (Fault(kind="crash_restart", start_tick=6, end_tick=7),)
        ),
        ticks=16,
        seed=seed,
        persistence=True,
        max_recovery_ticks=8,
        lossless_twin="state",
    )


def leader_failover(scale: float = 1.0, seed: int = 49) -> Scenario:
    """Two leadership handoffs over one run: a graceful step-down
    (lease released, standby takes over the same tick) and a leader
    crash (standby must wait out lease expiry — a real leaderless
    window, arrivals queue and replay). Both takeovers rebuild the
    stack from snapshot+WAL with ZERO VirtualNode deletions."""
    return Scenario(
        name="leader_failover",
        description="graceful step-down at tick 4, crash + lease-expiry "
        "takeover at tick 10; zero node flap across both",
        cluster=ClusterSpec(num_nodes=_n(300, scale)),
        workload=WorkloadSpec(
            jobs=_n(800, scale, floor=20), arrival="poisson", spread_ticks=8
        ),
        faults=FaultPlan(
            (
                Fault(
                    kind="leader_failover",
                    start_tick=4,
                    end_tick=5,
                    graceful=True,
                ),
                Fault(
                    kind="leader_failover",
                    start_tick=10,
                    end_tick=11,
                    graceful=False,
                ),
            )
        ),
        ticks=18,
        seed=seed,
        persistence=True,
        max_recovery_ticks=28,
    )


def agent_crash(scale: float = 1.0, seed: int = 50) -> Scenario:
    """The AGENT process dies mid-run: jobs, submit ledger, queue and
    per-node allocation all drop and rebuild from the job-state journal
    (``agent/journal.py``). The smoke gate proves the reload lossless —
    final state byte-identical to the crash-free run — which is exactly
    the dedupe + in-flight-state durability a real login-node daemon
    restart needs (JIRIAF's operating model)."""
    return Scenario(
        name="agent_crash",
        description="agent process state dies at tick 5; journal replay "
        "rebuilds ledger + in-flight jobs losslessly",
        cluster=ClusterSpec(num_nodes=_n(300, scale)),
        workload=WorkloadSpec(
            jobs=_n(800, scale, floor=20), arrival="poisson", spread_ticks=8
        ),
        faults=FaultPlan(
            (Fault(kind="agent_crash", start_tick=5, end_tick=6),)
        ),
        ticks=16,
        seed=seed,
        # the window closes at tick 6 but arrivals keep coming to tick 8
        # and jobs run tens of virtual seconds — the bound covers natural
        # workload drain, not journal replay (which is same-tick)
        max_recovery_ticks=24,
        lossless_twin="state",
    )


def chaos_dual_crash(scale: float = 1.0, seed: int = 51) -> Scenario:
    """The composed-durability headline: bridge AND agent crash at the
    SAME tick. The bridge reloads snapshot+WAL, the agent reloads its
    journal, and the reloaded bridge's resync runs against the reloaded
    agent — in-flight submits dedupe through the journaled ledger, so
    nothing double-submits and nothing is lost. Gated byte-identical to
    the crash-free twin."""
    return Scenario(
        name="chaos_dual_crash",
        description="simultaneous bridge+agent crash at tick 6; both "
        "reload (snapshot+WAL / journal) losslessly",
        cluster=ClusterSpec(num_nodes=_n(300, scale)),
        workload=WorkloadSpec(
            jobs=_n(900, scale, floor=20), arrival="poisson", spread_ticks=8
        ),
        faults=FaultPlan(
            (
                Fault(kind="crash_restart", start_tick=6, end_tick=7),
                Fault(kind="agent_crash", start_tick=6, end_tick=7),
            )
        ),
        ticks=16,
        seed=seed,
        persistence=True,
        max_recovery_ticks=8,
        lossless_twin="state",
    )


def chaos_crash_rpc_flap(scale: float = 1.0, seed: int = 52) -> Scenario:
    """Crash DURING a degraded-RPC window: 25% UNAVAILABLE on the
    mirror/submit/inventory RPCs for ticks 4-10 with injected latency,
    and the bridge crashes at tick 6 — recovery has to re-converge
    THROUGH the still-flapping RPC plane. Bounded retries
    (``rpc_retries``) absorb the transient errors, so no control-loop
    round fails outright; the crash-free twin (same flap, no crash) must
    end with identical lifecycle outcomes."""
    return Scenario(
        name="chaos_crash_rpc_flap",
        description="25% UNAVAILABLE + latency on ticks 4-10; bridge "
        "crashes at tick 6 and recovers through the flap (retries on)",
        cluster=ClusterSpec(num_nodes=_n(300, scale)),
        workload=WorkloadSpec(
            jobs=_n(900, scale, floor=20), arrival="poisson", spread_ticks=8
        ),
        faults=FaultPlan(
            (
                Fault(
                    kind="rpc_error",
                    start_tick=4,
                    end_tick=10,
                    # whole-RPC faults on the batched forms + inventory:
                    # every one is retry-healable (per-item "SubmitJob"
                    # faults would surface as ok=false entries instead,
                    # which retries cannot and should not mask)
                    methods=("SubmitJobs", "JobsInfo", "Partitions", "Nodes"),
                    rate=0.25,
                ),
                Fault(
                    kind="rpc_latency",
                    start_tick=4,
                    end_tick=10,
                    methods=("SubmitJobs", "JobsInfo"),
                    latency_ms=25.0,
                ),
                Fault(kind="crash_restart", start_tick=6, end_tick=7),
            )
        ),
        ticks=18,
        seed=seed,
        persistence=True,
        rpc_retries=True,
        max_recovery_ticks=10,
        lossless_twin="outcome",
    )


def chaos_crash_into_vanished_partition(
    scale: float = 1.0, seed: int = 53
) -> Scenario:
    """Crash recovering INTO a shrunken inventory: partition part1
    vanishes at tick 5 and the bridge crashes the same tick. The
    reloaded configurator never knew the partition, so the restored
    VirtualNode stays in the store unmanaged (ZERO deletions — the gate)
    until part1 returns at tick 12 and the fresh provider adopts it
    uid-stably. Everything converges after the window; final state
    byte-identical to the crash-free twin."""
    return Scenario(
        name="chaos_crash_into_vanished_partition",
        description="partition part1 vanishes ticks 5-12 and the bridge "
        "crashes at tick 5: recovery into the vanished partition, zero "
        "node flap, adoption on return",
        cluster=ClusterSpec(num_nodes=_n(300, scale)),
        workload=WorkloadSpec(
            jobs=_n(800, scale, floor=20), arrival="poisson", spread_ticks=6
        ),
        faults=FaultPlan(
            (
                Fault(
                    kind="partition_vanish",
                    start_tick=5,
                    end_tick=12,
                    partition="part1",
                ),
                Fault(kind="crash_restart", start_tick=5, end_tick=6),
            )
        ),
        ticks=18,
        seed=seed,
        persistence=True,
        max_recovery_ticks=14,
        # "outcome", not "state": the CRASH-FREE twin observes the vanish
        # live, deletes the partition's VirtualNode and re-binds its
        # not-yet-submitted pods on return — the crashed arm preserves
        # the original bindings (strictly less churn), so placements
        # legitimately permute among equivalent nodes while every
        # lifecycle outcome must still match byte-for-byte
        lossless_twin="outcome",
    )


def diurnal_load(scale: float = 1.0, seed: int = 54) -> Scenario:
    """Day/night sinusoidal arrivals, gang-heavy, on a deliberately
    APPROXIMATE auction (2 rounds, in-engine repair off): the main
    solve leaves genuine fragmentation holes and stranded gangs at
    every peak, and the policy's backfill pass fills them — the
    quality gate compares utilization + gang wait against this exact
    scenario with policy off (and with backfill alone off, isolating
    the backfill contribution)."""
    return Scenario(
        name="diurnal_load",
        description="sinusoidal load on an approximate auction; backfill "
        "fills the admission holes, gated vs policy-off",
        cluster=ClusterSpec(num_nodes=_n(120, scale), gpu_fraction=0.1),
        workload=WorkloadSpec(
            jobs=_n(1500, scale, floor=80),
            arrival="diurnal",
            spread_ticks=16,
            diurnal_cycles=2,
            gang_fraction=0.45,
            duration_range=(40.0, 80.0),
        ),
        ticks=24,
        expect_drain=False,
        drain_grace_ticks=0,
        backend="auction",
        auction_config=AuctionConfig(
            rounds=2, repair=False, gang_salvage_rounds=1
        ),
        policy=PolicyConfig(),
        seed=seed,
    )


def multi_tenant_storm(scale: float = 1.0, seed: int = 55) -> Scenario:
    """Four tenants with skewed priority ranges slam an oversubscribed
    cluster at tick 0; jobs outlive the window, so whoever admits first
    keeps the capacity. Policy-off priority-FIFO hands everything to
    the loud tenants (Jain ≈ 0.5); weighted dominant-resource fair
    share interleaves them (Jain ≥ 0.9) — the quality-smoke gate."""
    return Scenario(
        name="multi_tenant_storm",
        description="4 skewed tenants, front-loaded oversubscription; "
        "fair-share Jain gated vs priority-FIFO",
        cluster=ClusterSpec(
            num_nodes=_n(120, scale), gpu_fraction=0.0, base_load=0.0
        ),
        workload=WorkloadSpec(
            jobs=_n(1200, scale, floor=80),
            arrival="front",
            gpu_fraction=0.0,
            gang_fraction=0.0,
            cpu_choices=(16, 32, 64),
            duration_range=(500.0, 800.0),
            tenants=4,
            tenant_priorities=((80, 100), (55, 75), (30, 50), (0, 20)),
        ),
        ticks=10,
        expect_drain=False,
        drain_grace_ticks=0,
        policy=PolicyConfig(),
        seed=seed,
    )


def priority_inversion(scale: float = 1.0, seed: int = 56) -> Scenario:
    """The inversion shape: batch incumbents carrying HIGH numeric
    priorities fill the cluster, then node-sized production gangs with
    a LOW numeric priority arrive. Numeric-priority preemption (policy
    off) never displaces anyone — the gang starves behind lower-class
    work. With the class table on, class trumps numeric priority: the
    gang preempts preemptible batch incumbents and binds within its
    wait bound (gated in quality-smoke)."""
    return Scenario(
        name="priority_inversion",
        description="production gang at numeric priority 10 vs batch "
        "incumbents at 60-100; class preemption bounds its wait",
        cluster=ClusterSpec(
            num_nodes=_n(120, scale), gpu_fraction=0.0, base_load=0.0
        ),
        workload=WorkloadSpec(
            jobs=_n(500, scale, floor=40),
            arrival="front",
            gpu_fraction=0.0,
            gang_fraction=0.0,
            cpu_choices=(8, 16, 32),
            duration_range=(500.0, 800.0),
            priority_range=(60, 100),
        ),
        faults=FaultPlan(
            (
                Fault(
                    kind="preemption_storm",
                    start_tick=5,
                    end_tick=6,
                    jobs=_n(16, scale, floor=2),
                    priority=10,
                    gang_size=4,
                    storm_class="production",
                    storm_cpus=(96, 128),
                ),
            )
        ),
        ticks=14,
        expect_drain=False,
        drain_grace_ticks=0,
        preemption=True,
        policy=PolicyConfig(),
        seed=seed,
    )


def elastic_resize(scale: float = 1.0, seed: int = 57) -> Scenario:
    """Jobs change shard count mid-flight (VirtualFlow, arxiv
    2009.09523): two resize windows cancel running work, rewrite the
    demand's node count under a fresh submit generation, and the
    scheduler re-places every resized job at its new shape — gang
    atomicity, capacity, and eventual drain all still hold."""
    return Scenario(
        name="elastic_resize",
        description="mid-flight shard-count changes at ticks 6 and 10; "
        "everything re-places and drains",
        cluster=ClusterSpec(num_nodes=_n(300, scale)),
        workload=WorkloadSpec(
            jobs=_n(800, scale, floor=40),
            arrival="poisson",
            spread_ticks=8,
            gang_fraction=0.15,
            duration_range=(30.0, 80.0),
        ),
        faults=FaultPlan(
            (
                Fault(
                    kind="elastic_resize",
                    start_tick=6,
                    end_tick=7,
                    jobs=_n(60, scale, floor=8),
                ),
                Fault(
                    kind="elastic_resize",
                    start_tick=10,
                    end_tick=11,
                    jobs=_n(40, scale, floor=5),
                ),
            )
        ),
        ticks=18,
        policy=PolicyConfig(),
        seed=seed,
        max_recovery_ticks=30,
    )


def interactive_storm(scale: float = 1.0, seed: int = 61) -> Scenario:
    """The streaming-admission gate shape (ISSUE 12): the diurnal_load
    arrival pattern with a production-class interactive stream mixed
    into the batch background. Interactive-eligible arrivals
    (production singles and ≤4-node gangs, ~30% of the trace) must ride
    the fast path — ``make admission-smoke`` gates their arrival→bind
    p99 at ≤100 ms in virtual time (a batch-tick bind costs half a
    tick period minimum, 2.5 s at this interval, so the gate is only
    reachable through the fast path) — while batch utilization stays
    within 1% of the admission-off twin (the fast path must not wreck
    the packing it front-runs)."""
    return Scenario(
        name="interactive_storm",
        description="diurnal batch background + production-class "
        "interactive stream; fast-path p99 ≤ 100 ms, batch utilization "
        "within 1% of the admission-off twin",
        # roomy and CPU-only on purpose: the latency SLO is a
        # STEADY-STATE property — interactive arrivals must find tight
        # fits, not queue behind a saturated peak or a 3-node GPU island
        # (saturation shapes are diurnal_load's job). Two big partitions
        # so 4-node production gangs always have a feasible island even
        # at smoke scale.
        cluster=ClusterSpec(
            num_nodes=_n(240, scale), num_partitions=2, gpu_fraction=0.0
        ),
        workload=WorkloadSpec(
            jobs=_n(1100, scale, floor=110),
            arrival="diurnal",
            spread_ticks=16,
            diurnal_cycles=2,
            gang_fraction=0.15,
            gpu_fraction=0.0,
            duration_range=(30.0, 60.0),
            priority_classes=(("batch", 0.7), ("production", 0.3)),
        ),
        # the cold-start probe (ISSUE 15 satellite): two production
        # arrivals at tick 0, BEFORE any virtual node or admission
        # window exists — deterministic ``not_ready`` entries in
        # ``FastPathAdmitter.misses``, which admission-smoke asserts
        # non-empty (the by-reason ledger must be live in the scenario
        # JSON, not silently zeroed). They land inside the latency
        # warmup, so the p99 gate still measures steady state only.
        faults=FaultPlan(
            (
                Fault(
                    kind="preemption_storm",
                    start_tick=0,
                    end_tick=1,
                    jobs=2,
                    priority=10,
                    storm_class="production",
                    storm_cpus=(2, 4),
                ),
            )
        ),
        ticks=24,
        expect_drain=False,
        drain_grace_ticks=0,
        policy=PolicyConfig(),
        admission=AdmissionConfig(),
        seed=seed,
    )


def steady_state_soak(scale: float = 1.0, seed: int = 60) -> Scenario:
    """The O(changes) acceptance shape (PR-11): a front-loaded standing
    load whose jobs outlive the whole run, deliberately oversubscribed so
    an unschedulable backlog pends forever. Tick 0 is the cold bind,
    tick 1 mirrors the Pending→Running transitions, and every later tick
    is GENUINELY steady — nothing arrives, binds, completes or writes —
    which is what ``steady_tick_p50_ms`` medians over and what the
    bench-smoke zero-work gate (0 store commits, 0 solver invocations,
    ≤1 status RPC per shard) pins hard."""
    return Scenario(
        name="steady_state_soak",
        description="standing load + unschedulable backlog; ticks 2+ are "
        "zero-work steady state",
        cluster=ClusterSpec(num_nodes=_n(300, scale)),
        workload=WorkloadSpec(
            jobs=_n(1200, scale, floor=60),
            arrival="front",
            # far beyond the run horizon: the standing state never drains
            duration_range=(100_000.0, 200_000.0),
        ),
        ticks=10,
        expect_drain=False,
        drain_grace_ticks=0,
        seed=seed,
    )


def sharded_smoke(scale: float = 1.0, seed: int = 58) -> Scenario:
    """The fast sharded-tick gate (ISSUE 10): a gang-heavy mixed
    workload on 3 partitions, each split across several shards
    (``max_nodes_per_shard`` ≈ nodes/9), with a 2-wide solve fan-out.
    Double-run determinism proves the fan-out merges id-keyed; the
    shard-smoke gate additionally requires the plan to actually shard
    (≥2 shards) — a silently-monolithic run is a failed gate, not a
    pass."""
    n_nodes = _n(900, scale)
    return Scenario(
        name="sharded_smoke",
        description="partition/island fan-out on split partitions; "
        "double-run deterministic, invariants hold",
        cluster=ClusterSpec(
            num_nodes=n_nodes,
            num_partitions=3,
            partition_features=("tier0", "tier1"),
        ),
        workload=WorkloadSpec(
            jobs=_n(2400, scale, floor=60),
            arrival="poisson",
            spread_ticks=8,
            gang_fraction=0.2,
        ),
        ticks=16,
        seed=seed,
        sharding=ShardConfig(
            max_nodes_per_shard=max(12, n_nodes // 9), workers=2
        ),
    )


def fleet_smoke(scale: float = 1.0, seed: int = 58) -> Scenario:
    """The fleet-of-1 twin gate (ISSUE 17): ``sharded_smoke``'s exact
    shape and seed with a 1-replica fleet attached — every greedy/native
    shard solve round-trips through a real solver sidecar process over
    gRPC, and the ``final_state_digest`` must be byte-identical to the
    single-process run (the fleet-smoke gate strips ``fleet`` for the
    twin arm). The gate also requires ``remote_solves > 0``: a fleet run
    that silently solved inline is a failed gate, not a pass."""
    from slurm_bridge_tpu.fleet.runtime import FleetConfig

    base = sharded_smoke(scale=scale, seed=seed)
    import dataclasses

    return dataclasses.replace(
        base,
        name="fleet_smoke",
        description="1-replica fleet over real gRPC; digest byte-"
        "identical to the single-process twin",
        fleet=FleetConfig(replicas=1),
    )


def fleet_kill_owner(scale: float = 1.0, seed: int = 61) -> Scenario:
    """The fleet chaos gate (ISSUE 17): 3 replicas each owning a
    shard-set; a priority storm lands at tick 6 and the owner of shard 0
    is SIGKILLed at tick 7, mid-storm. Its shard-set must re-key to
    survivors on the same tick's membership heartbeat (remote solves are
    byte-parity with inline, so the re-key is invisible to digests — the
    gate compares ``final_state_digest`` against the kill-stripped twin),
    with zero lost binds, zero VirtualNode deletions, and recovery
    (restart-with-backoff re-adopting the sidecar) within
    ``max_recovery_ticks``."""
    from slurm_bridge_tpu.fleet.runtime import FleetConfig

    n_nodes = _n(600, scale)
    return Scenario(
        name="fleet_kill_owner",
        description="kill the shard-0 owner mid-storm: re-key to "
        "survivors, zero lost binds, bounded recovery",
        cluster=ClusterSpec(
            num_nodes=n_nodes,
            num_partitions=3,
            partition_features=("tier0", "tier1"),
        ),
        workload=WorkloadSpec(
            jobs=_n(1600, scale, floor=60),
            arrival="poisson",
            spread_ticks=8,
            gang_fraction=0.2,
        ),
        faults=FaultPlan(
            (
                Fault(
                    kind="preemption_storm",
                    start_tick=6,
                    end_tick=7,
                    jobs=_n(120, scale, floor=10),
                    priority=1000,
                ),
                Fault(kind="kill_replica", start_tick=7, end_tick=8),
            )
        ),
        ticks=16,
        preemption=True,
        drain_grace_ticks=100,
        seed=seed,
        sharding=ShardConfig(
            max_nodes_per_shard=max(12, n_nodes // 9), workers=2
        ),
        fleet=FleetConfig(replicas=3, restart_backoff_ticks=2),
        max_recovery_ticks=6,
    )


def sharded_gang_split(scale: float = 1.0, seed: int = 59) -> Scenario:
    """The cross-shard reconciliation shape: gangs of 8 on partitions
    deliberately split into shards too small to host them
    (``max_nodes_per_shard`` < gang size at smoke scale) — every gang
    FAILS its home shard and must place through the merged-residual
    reconcile pass, all-or-nothing. The shard-smoke gate requires
    ``reconcile_placed ≥ 1`` so the pass can never silently stop
    running."""
    n_nodes = _n(240, scale)
    return Scenario(
        name="sharded_gang_split",
        description="8-node gangs vs sub-gang-size shards; gangs place "
        "only via cross-shard reconciliation",
        cluster=ClusterSpec(
            num_nodes=n_nodes, num_partitions=2, gpu_fraction=0.0
        ),
        workload=WorkloadSpec(
            jobs=_n(400, scale, floor=40),
            arrival="poisson",
            spread_ticks=6,
            gang_fraction=0.5,
            gang_size=8,
            gpu_fraction=0.0,
        ),
        ticks=14,
        seed=seed,
        sharding=ShardConfig(
            max_nodes_per_shard=max(6, n_nodes // 40), workers=2
        ),
    )


def full_500kx100k(scale: float = 1.0, seed: int = 42) -> Scenario:
    """The 10×-scale headline (ISSUE 10, slow — tens of minutes): 500k
    pods × 100k nodes through the FULL bridge pipeline with the
    partition/island shard fan-out on. 16 partitions of ~6.2k nodes
    each split across ~8k-node shards; gangs straddling split
    partitions place all-or-nothing (reconcile pass), with the
    rank-locality score on the quality scorecard. Records
    ``full_tick_p50_ms_500kx100k`` with the standard phase breakdown,
    gated by ``p50_gate_ms``."""
    return Scenario(
        name="full_500kx100k",
        description="full-bridge sharded reconcile tick at the "
        "500k x 100k product shape (slow)",
        cluster=ClusterSpec(num_nodes=_n(100_000, scale), num_partitions=16),
        workload=WorkloadSpec(
            jobs=_n(500_000, scale, floor=200),
            arrival="front",
            gang_fraction=0.05,
            gpu_fraction=0.15,
            duration_range=(30.0, 120.0),
        ),
        ticks=3,
        expect_drain=False,
        drain_grace_ticks=0,
        seed=seed,
        slow=True,
        sharding=ShardConfig(max_nodes_per_shard=8192, workers=2),
        # the ISSUE 14 acceptance bar: the COLD tick — now including the
        # arrive phase the pre-14 number silently excluded — must hold
        # the gate (measured 25.5 s post-coldec; the old gate was 120 s
        # over a 53.7 s phases-only p50). The flight record must also
        # explain the tick: span phase-sum within ±5% of the tick span.
        # Widened 35 s → 60 s in ISSUE 16: back-to-back runs of
        # IDENTICAL code measured 33.5 s and 50.6 s on this shared-host
        # container (±50% steal variance, digests byte-equal) — the
        # gate has to catch the structural 2× regression, not the
        # neighbor's compile job.
        p50_gate_ms=60_000.0,
        phase_reconcile_pct=5.0,
    )


def full_1mx200k(scale: float = 1.0, seed: int = 42) -> Scenario:
    """The 20×-scale headline (ISSUE 16, slow — the biggest shape in
    the suite): 1M pods × 200k nodes through the FULL bridge pipeline
    with the shard fan-out, per-shard mirror grouping and the
    overlapped mirror pipeline all on. 16 partitions of ~12.5k nodes
    across ~8k-node shards. Records ``full_tick_p50_ms_1mx200k`` with
    the standard phase breakdown. The gate is completion-shaped: the
    run must finish with zero invariant violations and the flight
    record must still reconcile (span phase-sum within ±5% of the tick
    span) under the overlapped pipeline; the p50 gate is set at 2× the
    500k gate — the shape doubles both axes but the cold tick is
    dominated by per-job work, which scales ~linearly in jobs — with
    the same shared-host steal-variance headroom (see
    ``full_500kx100k``)."""
    return Scenario(
        name="full_1mx200k",
        description="full-bridge sharded reconcile tick at the "
        "1M x 200k product shape (slow)",
        cluster=ClusterSpec(num_nodes=_n(200_000, scale), num_partitions=16),
        workload=WorkloadSpec(
            jobs=_n(1_000_000, scale, floor=200),
            arrival="front",
            gang_fraction=0.05,
            gpu_fraction=0.15,
            duration_range=(30.0, 120.0),
        ),
        ticks=3,
        expect_drain=False,
        drain_grace_ticks=0,
        seed=seed,
        slow=True,
        sharding=ShardConfig(max_nodes_per_shard=8192, workers=2),
        p50_gate_ms=120_000.0,
        phase_reconcile_pct=5.0,
    )


def full_500kx100k_steady(scale: float = 1.0, seed: int = 42) -> Scenario:
    """The 10×-scale STEADY-STATE headline (ISSUE 11, slow): the
    ``full_500kx100k`` shape run three ticks longer, so after the cold
    bind (tick 1), the submit fan-out (tick 1's mirror) and the
    Running-status sweep (tick 2), ticks 3-5 are genuinely steady —
    nothing arrives, binds, completes or writes (job durations outlive
    the horizon by construction at 5 s/tick). Records
    ``steady_tick_p50_ms`` over those ticks, gated at ≤1 s — the
    "heavy traffic from millions of users" acceptance bar, where
    arrivals are a trickle against 500k standing pods. Kept separate
    from ``full_500kx100k`` so that scenario's 3-tick
    ``full_tick_p50_ms`` lineage (PR-2 → PR-10) stays comparable."""
    return Scenario(
        name="full_500kx100k_steady",
        description="steady-state sharded tick at 500k x 100k: ticks 3-5 "
        "must be O(changes) (slow)",
        cluster=ClusterSpec(num_nodes=_n(100_000, scale), num_partitions=16),
        workload=WorkloadSpec(
            jobs=_n(500_000, scale, floor=200),
            arrival="front",
            gang_fraction=0.05,
            gpu_fraction=0.15,
            duration_range=(30.0, 120.0),
        ),
        ticks=6,
        expect_drain=False,
        drain_grace_ticks=0,
        seed=seed,
        slow=True,
        sharding=ShardConfig(max_nodes_per_shard=8192, workers=2),
        # the PR-11 acceptance bar: a steady-state tick at 500k×100k —
        # standing state unchanged, arrivals zero — completes within 1 s
        steady_gate_ms=1_000.0,
    )


def full_50kx10k(scale: float = 1.0, seed: int = 42) -> Scenario:
    """The headline: 50k pods × 10k nodes through the FULL bridge
    pipeline. Slow (minutes); records ``full_tick_p50_ms_50kx10k`` with
    the store/encode/solve/bind/mirror phase breakdown — the number the
    round-5 VERDICT called the unmeasured 90%."""
    return Scenario(
        name="full_50kx10k",
        description="full-bridge reconcile tick at the 50k x 10k product shape",
        cluster=ClusterSpec(num_nodes=_n(10_000, scale)),
        workload=WorkloadSpec(
            jobs=_n(50_000, scale, floor=100),
            arrival="front",
            gang_fraction=0.05,
            gpu_fraction=0.15,
            duration_range=(30.0, 120.0),
        ),
        ticks=3,
        expect_drain=False,
        drain_grace_ticks=0,
        seed=seed,
        slow=True,
    )


def full_50kx10k_steady(scale: float = 1.0, seed: int = 42) -> Scenario:
    """The STEADY-STATE headline at 50k×10k (ISSUE 11, slow): the
    ``full_50kx10k`` shape plus three post-convergence ticks (see
    ``full_500kx100k_steady`` for the tick anatomy). Records
    ``steady_tick_p50_ms`` over ticks 3-5, gated at ≤50 ms."""
    return Scenario(
        name="full_50kx10k_steady",
        description="steady-state full-bridge tick at 50k x 10k: ticks "
        "3-5 must be O(changes) (slow)",
        cluster=ClusterSpec(num_nodes=_n(10_000, scale)),
        workload=WorkloadSpec(
            jobs=_n(50_000, scale, floor=100),
            arrival="front",
            gang_fraction=0.05,
            gpu_fraction=0.15,
            duration_range=(30.0, 120.0),
        ),
        ticks=6,
        expect_drain=False,
        drain_grace_ticks=0,
        seed=seed,
        slow=True,
        # the PR-11 acceptance bar: a steady-state tick at 50k×10k —
        # standing state unchanged, arrivals zero — completes within 50 ms
        steady_gate_ms=50.0,
    )


def full_50kx10k_crash(scale: float = 1.0, seed: int = 42) -> Scenario:
    """Recovery at the HEADLINE shape (slow, minutes): the 50k×10k
    front-loaded scenario with a bridge crash after the cold-start tick.
    Until PR-8 every crash scenario ran at smoke scale only — this one
    proves snapshot+WAL reload and level-triggered re-convergence stay
    bounded when the snapshot carries ~60k objects (``recovery_ms`` in
    the timing section is the number BASELINE.md records)."""
    return Scenario(
        name="full_50kx10k_crash",
        description="bridge crash + snapshot/WAL reload at the 50k x 10k "
        "product shape (slow)",
        cluster=ClusterSpec(num_nodes=_n(10_000, scale)),
        workload=WorkloadSpec(
            jobs=_n(50_000, scale, floor=100),
            arrival="front",
            gang_fraction=0.05,
            gpu_fraction=0.15,
            duration_range=(30.0, 120.0),
        ),
        faults=FaultPlan(
            (Fault(kind="crash_restart", start_tick=2, end_tick=3),)
        ),
        ticks=4,
        expect_drain=False,
        drain_grace_ticks=0,
        seed=seed,
        persistence=True,
        slow=True,
    )


SCENARIOS = {
    f.__name__: f
    for f in (
        steady_poisson,
        burst_backlog,
        agent_flaky_rpc,
        preemption_storm,
        node_churn,
        partition_vanish,
        crash_restart,
        leader_failover,
        agent_crash,
        chaos_dual_crash,
        chaos_crash_rpc_flap,
        chaos_crash_into_vanished_partition,
        diurnal_load,
        multi_tenant_storm,
        priority_inversion,
        elastic_resize,
        interactive_storm,
        steady_state_soak,
        sharded_smoke,
        sharded_gang_split,
        fleet_smoke,
        fleet_kill_owner,
        full_500kx100k,
        full_500kx100k_steady,
        full_1mx200k,
        full_50kx10k,
        full_50kx10k_steady,
        full_50kx10k_crash,
    )
}

#: the composed-fault subset `make chaos-smoke` double-runs: crash
#: windows overlapping degraded-RPC/vanished-partition windows, agent
#: crashes, and the simultaneous bridge+agent crash — all twin-gated
CHAOS_SCENARIOS = (
    "agent_crash",
    "chaos_dual_crash",
    "chaos_crash_rpc_flap",
    "chaos_crash_into_vanished_partition",
)

#: the placement-quality subset `make quality-smoke` runs (ISSUE 9):
#: double-run determinism PLUS policy-on/off arm comparisons gated on
#: the scorecard (fairness, wait bounds, backfill utilization)
QUALITY_SCENARIOS = (
    "diurnal_load",
    "multi_tenant_storm",
    "priority_inversion",
    "elastic_resize",
)

#: the sharded-placement subset `make shard-smoke` double-runs (ISSUE
#: 10): determinism + invariants on the fan-out, plus shard-specific
#: gates (the plan actually shards; sharded_gang_split actually
#: reconciles). ``sharded_smoke`` ALSO rides sim-smoke — the tentpole
#: wants the fast sharded scenario in the default gate, and the extra
#: run is seconds at smoke scale
SHARD_SCENARIOS = (
    "sharded_smoke",
    "sharded_gang_split",
)

#: the streaming-admission subset `make admission-smoke` runs (ISSUE
#: 12): double-run determinism, the fast-path latency gate, engagement
#: (the fast path actually bound things), and the admission-off twin
#: comparison (batch utilization within the margin; the twin's latency
#: must be WORSE than the gate or the comparison is vacuous)
ADMISSION_SCENARIOS = ("interactive_storm",)

#: the fleet subset `make fleet-smoke` runs (ISSUE 17): double-run
#: determinism, the fleet-of-1 single-process twin digest, the
#: remote-solve engagement floor, and the kill-shard-owner chaos gate
#: (re-key to survivors, zero lost binds, bounded recovery). Excluded
#: from sim-smoke: each fleet run spawns real sidecar subprocesses
FLEET_SCENARIOS = (
    "fleet_smoke",
    "fleet_kill_owner",
)

#: the fast set `make sim-smoke` double-runs: everything not slow-marked,
#: MINUS the chaos and quality subsets (and the shard subset except
#: sharded_smoke, see above) — `make check` and CI run sim-smoke,
#: chaos-smoke, quality-smoke and shard-smoke side by side, so overlap
#: would execute each scenario (and its twin arms) twice for zero added
#: coverage
SMOKE_SCENARIOS = tuple(
    n for n, f in SCENARIOS.items()
    if not f().slow
    and n not in CHAOS_SCENARIOS
    and n not in QUALITY_SCENARIOS
    and n not in ADMISSION_SCENARIOS
    and n not in FLEET_SCENARIOS
    and (n not in SHARD_SCENARIOS or n == "sharded_smoke")
)
