"""In-process fake Slurm agent — the simulator's ground-truth cluster.

Duck-types the ``WorkloadManager`` :class:`ServiceClient` surface the
bridge dials (Partitions/Partition/Nodes/SubmitJob/JobInfo/JobState/
CancelJob), so the REAL bridge components — configurator, virtual-node
providers, placement scheduler — run unmodified against it with zero
gRPC or subprocess cost. Behind the client sits :class:`SimCluster`, a
deterministic model of Slurm's side of the contract:

- submission allocates immediately when the requested node set fits
  (honouring ``--nodelist`` hints, falling back to first-fit over the
  partition), otherwise the job queues PENDING — exactly the lag the
  statusmap translation layer has to ride out;
- jobs run for ``time_limit_s`` *virtual* seconds (the trace generator
  stamps each job's duration there) and complete when the harness
  advances the clock past their end time — no wall-clock sleeps anywhere;
- allocation is guarded: a start that would oversubscribe any node's
  capacity raises, so the "capacity never oversubscribed" invariant is
  enforced by ground truth, not just sampled;
- the submit ledger dedupes by ``submitter_id`` like the real agent
  (``agent/server.py``), keeping retried submissions idempotent under
  injected RPC faults.

Time is a ``clock()`` callable supplied by the harness (virtual seconds
since scenario start); determinism needs no patching of ``time``.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, field

from slurm_bridge_tpu.core.arrays import array_len
from slurm_bridge_tpu.core.fastpath import frozen_new
from slurm_bridge_tpu.core.scontrol import parse_gres_gpus
from slurm_bridge_tpu.core.types import JobInfo, JobStatus, NodeInfo, PartitionInfo
from slurm_bridge_tpu.wire import pb
from slurm_bridge_tpu.wire.coldec import uvarint
from slurm_bridge_tpu.wire.convert import (
    job_info_to_proto,
    node_to_proto,
    partition_to_proto,
)

log = logging.getLogger("sbt.sim.agent")


class OversubscribedError(AssertionError):
    """A job start would exceed a node's capacity — ground-truth invariant
    breach (the scheduler or the sim's own fit check mis-accounted)."""


@dataclass
class SimNode:
    """One simulated Slurm node: static capacity + live allocation."""

    name: str
    cpus: int
    memory_mb: int
    gpus: int = 0
    gpu_type: str = ""
    features: tuple[str, ...] = ()
    #: pre-existing (non-sim-job) allocation, as random_inventory models it
    base_alloc_cpus: int = 0
    base_alloc_memory_mb: int = 0
    state: str = "IDLE"
    #: live allocation from sim jobs
    job_cpus: int = 0
    job_memory_mb: int = 0
    job_gpus: int = 0
    #: (sig, serialized Node message) — the NodesBytes per-node cache;
    #: rebuilt only when the mutable slice (allocation, state) moves.
    #: Pure memo, excluded from comparison/repr.
    wire_cache: tuple | None = field(default=None, repr=False, compare=False)

    @property
    def alloc_cpus(self) -> int:
        return self.base_alloc_cpus + self.job_cpus

    @property
    def alloc_memory_mb(self) -> int:
        return self.base_alloc_memory_mb + self.job_memory_mb

    def info(self) -> NodeInfo:
        state = self.state
        if state in ("IDLE", "MIXED") and not self.drained:
            state = "MIXED" if (self.alloc_cpus or self.job_gpus) else "IDLE"
        return NodeInfo(
            name=self.name,
            cpus=self.cpus,
            alloc_cpus=min(self.cpus, self.alloc_cpus),
            memory_mb=self.memory_mb,
            alloc_memory_mb=min(self.memory_mb, self.alloc_memory_mb),
            gpus=self.gpus,
            alloc_gpus=min(self.gpus, self.job_gpus),
            gpu_type=self.gpu_type,
            features=self.features,
            state=state,
        )

    @property
    def drained(self) -> bool:
        return "DRAIN" in self.state or "DOWN" in self.state

    def wire_bytes(self) -> bytes:
        """This node's serialized ``Node`` message (length-prefixed as a
        ``NodesResponse.nodes`` entry), cached against the mutable slice
        — the serialize-from-ground-truth half of the ISSUE 14 bytes
        path. Decodes identically to ``node_to_proto(self.info())``."""
        sig = (self.state, self.job_cpus, self.job_memory_mb, self.job_gpus)
        c = self.wire_cache
        if c is not None and c[0] == sig:
            return c[1]
        info = self.info()
        out = bytearray()
        nb = info.name.encode()
        out += b"\x0a" + uvarint(len(nb)) + nb
        for tag, v in (
            (b"\x10", info.cpus), (b"\x18", info.alloc_cpus),
            (b"\x20", info.memory_mb), (b"\x28", info.alloc_memory_mb),
            (b"\x30", info.gpus), (b"\x38", info.alloc_gpus),
        ):
            if v:
                out += tag + uvarint(v)
        if info.gpu_type:
            gb = info.gpu_type.encode()
            out += b"\x42" + uvarint(len(gb)) + gb
        for f in info.features:
            fb = f.encode()
            out += b"\x4a" + uvarint(len(fb)) + fb
        if info.state:
            sb = info.state.encode()
            out += b"\x52" + uvarint(len(sb)) + sb
        wrapped = b"\x0a" + uvarint(len(out)) + bytes(out)
        self.wire_cache = (sig, wrapped)
        return wrapped


@dataclass
class SimJob:
    """One submitted job — per-node quantities, gang-expanded over
    ``num_nodes`` distinct nodes (Slurm ``--nodes`` semantics)."""

    id: int
    name: str
    submitter_id: str
    partition: str
    num_nodes: int
    cpus_per_node: int
    mem_per_node_mb: int
    gpus_per_node: int
    duration_s: float
    priority: int
    nodelist: tuple[str, ...] = ()
    state: JobStatus = JobStatus.PENDING
    submit_vt: float = 0.0
    start_vt: float = -1.0
    end_vt: float = -1.0
    assigned: tuple[str, ...] = ()
    reason: str = ""
    #: (entry, info_msg, signature) — the JobsInfo response cache; see
    #: SimAgent.JobsInfo. Excluded from comparison/repr: pure memo.
    pb_cache: tuple | None = field(default=None, repr=False, compare=False)
    #: (sig, entry head, info pre, info post) — the JobsInfoBytes wire
    #: cache: the serialized entry split around the always-ticking
    #: ``run_time_s`` field (number 8), so a call splices the fresh
    #: runtime varint between cached halves instead of re-serializing
    #: 12 fields. Pure memo, excluded from comparison/repr.
    wire_cache: tuple | None = field(default=None, repr=False, compare=False)
    #: last journaled mutable-state signature — keeps journal records
    #: proportional to actual transitions, not queue length (a failed
    #: start re-checks every pending job every step). Pure memo.
    journal_sig: tuple | None = field(default=None, repr=False, compare=False)
    #: jobs-state version at this job's last mutable-state change — the
    #: per-job half of the ``JobsInfo`` cursor contract (PR-11): a
    #: request carrying ``since_version >= version`` may omit this job.
    version: int = field(default=0, repr=False, compare=False)
    #: last signature the version counter saw. Pure memo.
    sync_sig: tuple | None = field(default=None, repr=False, compare=False)

    def _run_time(self, now: float | None) -> int:
        # elapsed runtime like Slurm's RunTime: virtual now, capped at the
        # job's end — NOT the planned duration (a job 1 s into a 120 s run
        # must not already read as at its limit)
        if self.start_vt < 0:
            return 0
        if now is None:
            return int(max(0.0, self.end_vt - self.start_vt))
        return int(max(0.0, min(now, self.end_vt) - self.start_vt))

    def fill_info_proto(self, m: pb.JobInfo, now: float | None = None) -> None:
        """Write this job's state straight into a wire ``JobInfo`` — the
        batched-status fan-out path (45k rows per mirror tick at the
        headline shape), skipping the intermediate dataclass AND the
        proto copy an entry constructed via kwargs would pay. Field-for-
        field identical to ``job_info_to_proto(self.info(now))`` (the
        unary path keeps that form; a test holds the two together)."""
        m.id = self.id
        m.name = self.name
        m.status = int(self.state)
        m.run_time_s = self._run_time(now)
        m.time_limit_s = int(self.duration_s)
        m.partition = self.partition
        m.node_list = ",".join(self.assigned)
        m.batch_host = self.assigned[0] if self.assigned else ""
        m.num_nodes = self.num_nodes
        out = f"/sim/{self.id}.out"
        m.std_out = out
        m.std_err = out
        m.reason = self.reason

    def _wire_parts(self) -> tuple[bytes, bytes, bytes]:
        """(entry head, info-before-run_time, info-after-run_time) —
        field-ordered proto3 encoding of exactly what
        :meth:`fill_info_proto` writes, defaults omitted. Held to the
        pb2 serialization by a decode-parity test."""
        pre = bytearray()
        pre += b"\x08" + uvarint(self.id)  # JobInfo.id (1)
        nb = self.name.encode()
        if nb:
            pre += b"\x1a" + uvarint(len(nb)) + nb  # name (3)
        st = int(self.state)
        if st:
            pre += b"\x28" + uvarint(st)  # status (5)
        post = bytearray()
        tl = int(self.duration_s)
        if tl:
            post += b"\x48" + uvarint(tl)  # time_limit_s (9)
        ob = f"/sim/{self.id}.out".encode()
        olp = uvarint(len(ob)) + ob
        post += b"\x5a" + olp + b"\x62" + olp  # std_out (11) / std_err (12)
        if self.partition:
            p = self.partition.encode()
            post += b"\x6a" + uvarint(len(p)) + p  # partition (13)
        if self.assigned:
            nl = ",".join(self.assigned).encode()
            post += b"\x72" + uvarint(len(nl)) + nl  # node_list (14)
            bh = self.assigned[0].encode()
            post += b"\x7a" + uvarint(len(bh)) + bh  # batch_host (15)
        if self.num_nodes:
            post += b"\x80\x01" + uvarint(self.num_nodes)  # num_nodes (16)
        if self.reason:
            r = self.reason.encode()
            post += b"\x92\x01" + uvarint(len(r)) + r  # reason (18)
        head = b"\x08" + uvarint(self.id) + b"\x10\x01"  # job_id + found
        return head, bytes(pre), bytes(post)

    def entry_bytes(self, now: float | None) -> bytes:
        """One serialized, length-prefixed ``JobsInfoEntry`` for this job
        with the current run time spliced in — the JobsInfoBytes row."""
        sig = (self.state, self.assigned, self.reason)
        c = self.wire_cache
        if c is None or c[0] != sig:
            c = (sig, *self._wire_parts())
            self.wire_cache = c
        _, head, pre, post = c
        rt = self._run_time(now)
        mid = (b"\x40" + uvarint(rt)) if rt else b""  # run_time_s (8)
        info = pre + mid + post
        body = head + b"\x1a" + uvarint(len(info)) + info
        return b"\x0a" + uvarint(len(body)) + body

    def info(self, now: float | None = None) -> JobInfo:
        run_time = self._run_time(now)
        # frozen_new (every field explicit): built once per live job per
        # status query — skipping the guarded __init__ and the freeze walk
        out = f"/sim/{self.id}.out"
        return frozen_new(
            JobInfo,
            id=self.id,
            user_id="",
            name=self.name,
            exit_code="",
            state=self.state,
            submit_time=None,
            start_time=None,
            run_time_s=run_time,
            time_limit_s=int(self.duration_s),
            working_dir="",
            std_out=out,
            std_err=out,
            partition=self.partition,
            node_list=",".join(self.assigned),
            batch_host=self.assigned[0] if self.assigned else "",
            num_nodes=self.num_nodes,
            array_id="",
            reason=self.reason,
        )


@dataclass
class SimStats:
    submitted: int = 0
    deduped: int = 0
    started: int = 0
    completed: int = 0
    cancelled: int = 0

    def as_dict(self) -> dict:
        return {
            "submitted": self.submitted,
            "deduped": self.deduped,
            "started": self.started,
            "completed": self.completed,
            "cancelled": self.cancelled,
        }


class SimCluster:
    """Deterministic ground-truth Slurm: nodes, partitions, job lifecycle.

    Every mutation happens either in an RPC handler (submit/cancel) or in
    :meth:`step` — both driven synchronously by the harness, so identical
    call sequences yield identical state (no threads, no wall clock).
    """

    def __init__(
        self,
        nodes: list[SimNode],
        partitions: dict[str, tuple[str, ...]],
        *,
        clock,
        default_duration_s: float = 30.0,
    ):
        self.nodes: dict[str, SimNode] = {n.name: n for n in nodes}
        self.partitions = dict(partitions)
        self.hidden: set[str] = set()
        self.jobs: dict[int, SimJob] = {}
        self.clock = clock
        self.default_duration_s = default_duration_s
        self.stats = SimStats()
        self._ledger: dict[str, int] = {}
        self._next_id = 1000
        self._queue: list[int] = []  # PENDING job ids, submit order
        #: jobs-state version (PR-11): bumped on every job mutable-state
        #: transition (submit/start/complete/cancel/reason change) — the
        #: ``JobsInfo`` cursor an incremental mirror hands back so an
        #: idle tick's status query returns no rows. Starts at 1 so a
        #: first response already carries a usable cursor (0 on the wire
        #: means "no cursor support").
        self.state_version = 1
        #: nodes-state version: bumped on any allocation/drain change —
        #: the ``Nodes`` cursor that turns an idle inventory fetch into
        #: one tiny unchanged=true round-trip.
        self.nodes_version = 1
        #: the agent job-state journal (PR-8): when attached, every
        #: ledger entry and job lifecycle transition is appended durably,
        #: and :meth:`crash_reload` rebuilds the whole agent-process
        #: state from replay — the ``agent_crash`` fault's recovery path
        self.journal = None

    # ---- agent job-state journal (PR-8) ----

    def attach_journal(self, journal) -> None:
        """Start journaling — and rebase the journal around the current
        (usually empty) state so a previous incarnation's tail can never
        mix with this process's records."""
        self.journal = journal
        ledger, jobs = self.journal_state()
        journal.checkpoint(ledger, jobs)

    @staticmethod
    def _job_doc(job: SimJob) -> dict:
        """Full journal document for one job — every field
        :meth:`crash_reload` needs to reconstruct the ``SimJob`` exactly
        (the sim journal carries complete state because ``SimCluster``
        plays both the login-node daemon AND Slurm; the real agent's
        journal carries identity only — Slurm holds its job state)."""
        return {
            "id": job.id,
            "name": job.name,
            "submitter_id": job.submitter_id,
            "partition": job.partition,
            "num_nodes": job.num_nodes,
            "cpus_per_node": job.cpus_per_node,
            "mem_per_node_mb": job.mem_per_node_mb,
            "gpus_per_node": job.gpus_per_node,
            "duration_s": job.duration_s,
            "priority": job.priority,
            "nodelist": list(job.nodelist),
            "state": int(job.state),
            "submit_vt": job.submit_vt,
            "start_vt": job.start_vt,
            "end_vt": job.end_vt,
            "assigned": list(job.assigned),
            "reason": job.reason,
        }

    @staticmethod
    def _job_from_doc(doc: dict) -> SimJob:
        return SimJob(
            id=int(doc["id"]),
            name=doc["name"],
            submitter_id=doc["submitter_id"],
            partition=doc["partition"],
            num_nodes=int(doc["num_nodes"]),
            cpus_per_node=int(doc["cpus_per_node"]),
            mem_per_node_mb=int(doc["mem_per_node_mb"]),
            gpus_per_node=int(doc["gpus_per_node"]),
            duration_s=float(doc["duration_s"]),
            priority=int(doc["priority"]),
            nodelist=tuple(doc["nodelist"]),
            state=JobStatus(int(doc["state"])),
            submit_vt=float(doc["submit_vt"]),
            start_vt=float(doc["start_vt"]),
            end_vt=float(doc["end_vt"]),
            assigned=tuple(doc["assigned"]),
            reason=doc["reason"],
        )

    @staticmethod
    def _mut_sig(job: SimJob) -> tuple:
        """The mutable slice of a job the journal doc captures."""
        return (
            int(job.state), job.assigned, job.reason,
            job.start_vt, job.end_vt,
        )

    def _touch(self, job: SimJob) -> None:
        """Advance the jobs-state version iff this job's mirror-visible
        state (state machine, assignment, reason — the ``pb_cache``
        signature) actually moved. Called at every transition site, so
        ``job.version`` is exactly the cursor the JobsInfo contract
        promises: unchanged jobs sit at or below any applied cursor."""
        sig = (job.state, job.assigned, job.reason)
        if job.sync_sig == sig:
            return
        job.sync_sig = sig
        self.state_version += 1
        job.version = self.state_version

    def _journal_job(self, job: SimJob) -> None:
        if self.journal is None:
            return
        sig = self._mut_sig(job)
        if job.journal_sig == sig:
            return  # nothing the doc captures has moved
        job.journal_sig = sig
        self.journal.record_job(job.id, self._job_doc(job))

    def journal_state(self) -> tuple[dict[str, int], dict[int, dict]]:
        """(ledger, job docs) for a journal checkpoint."""
        return dict(self._ledger), {
            jid: self._job_doc(j) for jid, j in sorted(self.jobs.items())
        }

    def crash_reload(self) -> int:
        """The ``agent_crash`` fault: drop every piece of agent-process
        state — jobs, ledger, queue, per-node allocations — and rebuild
        it from journal replay, in place (the client wrapper keeps its
        reference). Node hardware state (drained flags, base allocation)
        and hidden partitions are cluster-side truth and survive; so does
        :attr:`stats`, which is the simulator's measurement layer, not
        agent state. Returns the number of jobs restored; a lossless
        replay leaves the cluster byte-identical to the moment of the
        crash — the ``final_state_digest`` twin gate proves exactly that.
        """
        if self.journal is None:
            raise RuntimeError("agent_crash without an attached journal")
        state = self.journal.load()
        self.jobs.clear()
        self._ledger = dict(state.ledger)
        self._queue = []
        for node in self.nodes.values():
            node.job_cpus = 0
            node.job_memory_mb = 0
            node.job_gpus = 0
        for jid in sorted(state.jobs):
            job = self._job_from_doc(state.jobs[jid])
            self.jobs[job.id] = job
            if job.state == JobStatus.RUNNING:
                for name in job.assigned:
                    node = self.nodes.get(name)
                    if node is None:
                        continue
                    node.job_cpus += job.cpus_per_node
                    node.job_memory_mb += job.mem_per_node_mb
                    node.job_gpus += job.gpus_per_node
            elif job.state == JobStatus.PENDING:
                self._queue.append(job.id)  # ids are submit-ordered
        self._next_id = max(self.jobs, default=self._next_id - 1) + 1
        # cursor hygiene: every caller-held cursor predates this reload's
        # rebuilt state, so every job must read as "changed" — one shared
        # bump past every outstanding cursor does it (versions only need
        # to EXCEED cursors, not be distinct per job). Node state was
        # rebuilt too, so the inventory cursor moves with it.
        self.state_version += 1
        for job in self.jobs.values():
            job.version = self.state_version
            job.sync_sig = (job.state, job.assigned, job.reason)
        self.nodes_version += 1
        # rebase: fold the replayed state into a fresh snapshot under the
        # new incarnation (mirrors Bridge.start()'s compact-first)
        ledger, jobs = self.journal_state()
        self.journal.checkpoint(ledger, jobs)
        return len(self.jobs)

    # ---- inventory ----

    def visible_partitions(self) -> list[str]:
        return [p for p in self.partitions if p not in self.hidden]

    def partition_info(self, name: str) -> PartitionInfo:
        members = self.partitions[name]
        total_cpus = sum(self.nodes[m].cpus for m in members)
        return PartitionInfo(
            name=name,
            nodes=tuple(members),
            total_cpus=total_cpus,
            total_nodes=len(members),
        )

    def node_infos(self, names: list[str]) -> list[NodeInfo]:
        return [self.nodes[n].info() for n in names if n in self.nodes]

    # ---- fault-plan surface (mutated by the harness, not by RPCs) ----

    def drain(self, names: list[str]) -> None:
        for n in names:
            node = self.nodes.get(n)
            if node is not None and not node.drained:
                node.state = "DRAINED"
                self.nodes_version += 1

    def resume(self, names: list[str]) -> None:
        for n in names:
            node = self.nodes.get(n)
            if node is not None and node.drained:
                node.state = "IDLE"
                self.nodes_version += 1

    def hide_partition(self, name: str) -> None:
        self.hidden.add(name)

    def show_partition(self, name: str) -> None:
        self.hidden.discard(name)

    # ---- job lifecycle ----

    def submit(self, req: pb.SubmitJobRequest) -> int:
        submitter = req.submitter_id
        if submitter and submitter in self._ledger:
            self.stats.deduped += 1
            return self._ledger[submitter]
        arr = array_len(req.array) if req.array else 1
        total_cpus = (
            max(1, int(req.cpus_per_task)) * max(1, int(req.ntasks)) * max(1, arr)
        )
        nnodes = max(1, int(req.nodes))
        cpus_per_node = math.ceil(total_cpus / nnodes)
        mem_per_node = math.ceil(int(req.mem_per_cpu_mb) * total_cpus / nnodes)
        gpus_per_node, _ = parse_gres_gpus(req.gres) if req.gres else (0, "")
        job = SimJob(
            id=self._next_id,
            name=req.job_name or f"job-{self._next_id}",
            submitter_id=submitter,
            partition=req.partition,
            num_nodes=nnodes,
            cpus_per_node=cpus_per_node,
            mem_per_node_mb=mem_per_node,
            gpus_per_node=gpus_per_node * max(1, arr),
            duration_s=float(req.time_limit_s) or self.default_duration_s,
            priority=int(req.priority),
            nodelist=tuple(req.nodelist),
            submit_vt=self.clock(),
        )
        self._next_id += 1
        self.jobs[job.id] = job
        if submitter:
            self._ledger[submitter] = job.id
        self.stats.submitted += 1
        started = self._try_start(job)
        if not started:
            self._queue.append(job.id)
        self._touch(job)
        if self.journal is not None:
            # ledger + post-placement job state behind ONE durability
            # barrier (the dedupe token is what a crashed agent must
            # never lose)
            job.journal_sig = self._mut_sig(job)
            self.journal.record_submit(submitter, job.id, self._job_doc(job))
        return job.id

    def cancel(self, job_id: int) -> None:
        job = self.jobs.get(job_id)
        if job is None or job.state.is_terminal:
            return  # scancel of an unknown/finished job is a no-op
        if job.state == JobStatus.RUNNING:
            self._free(job)
        job.state = JobStatus.CANCELLED
        job.end_vt = self.clock()
        self.stats.cancelled += 1
        self._touch(job)
        self._journal_job(job)

    def step(self) -> None:
        """Advance the cluster to the current virtual time: complete jobs
        whose runtime elapsed, then start queued jobs that now fit."""
        now = self.clock()
        for job in self.jobs.values():
            if job.state == JobStatus.RUNNING and job.end_vt <= now:
                self._free(job)
                job.state = JobStatus.COMPLETED
                self.stats.completed += 1
                self._touch(job)
                self._journal_job(job)
        still: list[int] = []
        for jid in self._queue:
            job = self.jobs[jid]
            if job.state != JobStatus.PENDING:
                continue  # cancelled while queued
            if not self._try_start(job):
                still.append(jid)
            # journal BOTH outcomes: a failed start still rewrites the
            # job's ``reason`` (Resources / partition unavailable), and a
            # crash replaying the stale reason would diverge from the
            # crash-free twin when agent_crash composes with
            # drain/vanish windows
            self._touch(job)
            self._journal_job(job)
        self._queue = still

    def _fits(self, node: SimNode, job: SimJob) -> bool:
        if node.drained:
            return False
        return (
            node.alloc_cpus + job.cpus_per_node <= node.cpus
            and node.alloc_memory_mb + job.mem_per_node_mb <= node.memory_mb
            and node.job_gpus + job.gpus_per_node <= node.gpus
        )

    def _try_start(self, job: SimJob) -> bool:
        if job.partition in self.hidden or job.partition not in self.partitions:
            job.reason = f"partition {job.partition!r} unavailable"
            return False
        chosen: list[str] = []
        # the solver's --nodelist hint first, in hint order; Slurm remains
        # the final arbiter, so an infeasible hint falls back to first-fit
        for name in job.nodelist:
            node = self.nodes.get(name)
            if node is not None and name not in chosen and self._fits(node, job):
                chosen.append(name)
                if len(chosen) == job.num_nodes:
                    break
        if len(chosen) < job.num_nodes:
            for name in self.partitions[job.partition]:
                if name in chosen:
                    continue
                if self._fits(self.nodes[name], job):
                    chosen.append(name)
                    if len(chosen) == job.num_nodes:
                        break
        if len(chosen) < job.num_nodes:
            job.reason = "Resources"
            return False
        self.nodes_version += 1
        for name in chosen:
            node = self.nodes[name]
            node.job_cpus += job.cpus_per_node
            node.job_memory_mb += job.mem_per_node_mb
            node.job_gpus += job.gpus_per_node
            if (
                node.alloc_cpus > node.cpus
                or node.alloc_memory_mb > node.memory_mb
                or node.job_gpus > node.gpus
            ):
                raise OversubscribedError(
                    f"node {name} oversubscribed by job {job.id}"
                )
        job.assigned = tuple(chosen)
        job.state = JobStatus.RUNNING
        job.start_vt = self.clock()
        job.end_vt = job.start_vt + job.duration_s
        job.reason = ""
        self.stats.started += 1
        return True

    def _free(self, job: SimJob) -> None:
        self.nodes_version += 1
        for name in job.assigned:
            node = self.nodes.get(name)
            if node is None:
                continue
            node.job_cpus -= job.cpus_per_node
            node.job_memory_mb -= job.mem_per_node_mb
            node.job_gpus -= job.gpus_per_node

    # ---- introspection for invariants/metrics ----

    def running_jobs(self) -> list[SimJob]:
        return [j for j in self.jobs.values() if j.state == JobStatus.RUNNING]

    def pending_jobs(self) -> list[SimJob]:
        return [j for j in self.jobs.values() if j.state == JobStatus.PENDING]


class SimWorkloadClient:
    """The ``WorkloadManager`` client surface over a :class:`SimCluster`.

    Method-for-method compatible with the dynamic :class:`ServiceClient`
    stub (``wire/rpc.py``) for every RPC the bridge dials; each method
    accepts the stub's keyword ``timeout`` and ignores it (there is no
    wall-clock in the simulator). Unknown-partition/unknown-file errors
    surface as :class:`SimRpcError` so the bridge's grpc error handling
    runs for real.

    Tracing parity with the real wire: where the gRPC stub would inject
    ``traceparent`` metadata and the agent's server interceptor would open
    an ``rpc.<Method>`` span under it, the in-process fake honors the
    SAME contract through the ambient contextvar (the in-process
    equivalent of the metadata) — each RPC named in ``TRACED_RPCS`` opens
    an agent-side span that parents into the caller's tick trace, so sim
    flight records are end-to-end. Outside an active sampled trace the
    wrapper costs one contextvar read.
    """

    #: RPCs wrapped in agent-side spans (the surface the bridge dials)
    TRACED_RPCS = (
        "Partitions", "Partition", "Nodes", "SubmitJob", "SubmitJobs",
        "CancelJob", "JobInfo", "JobsInfo", "JobState",
    )

    #: raw-bytes twins of the bulk RPCs (ISSUE 14): same logical call —
    #: counted and span-named under the BASE method, so call-count gates
    #: and flight trees read identically whichever form the mirror dials
    BYTES_RPCS = ("JobsInfo", "Nodes", "SubmitJobs")

    def __init__(self, cluster: SimCluster):
        self.cluster = cluster
        #: RPC calls served, per method — the steady-state zero-work gate
        #: reads this (one dict increment per call; the real agent's
        #: Prometheus counters play this role in production)
        self.calls: dict[str, int] = {}
        #: partition responses are immutable at sim scope (membership and
        #: node capacities never change), so each is built once and the
        #: SAME proto object is replayed — identity-stable responses are
        #: what lets caller-side decode memos run at O(1)
        self._part_cache: dict[str, pb.PartitionResponse] = {}
        #: version-keyed whole-response bytes caches, pinned on the
        #: caller's (reused) request proto: an unchanged shard re-serves
        #: the SAME bytes object, so content-keyed decode memos hit on
        #: an identity probe
        self._jobs_bytes_cache: dict[int, tuple] = {}
        self._nodes_bytes_cache: dict[int, tuple] = {}
        from slurm_bridge_tpu.obs.tracing import TRACER, current_span

        calls = self.calls

        def traced(name, fn):
            def call(request, timeout=None):
                calls[name] = calls.get(name, 0) + 1
                parent = current_span()
                if parent is None or not parent.sampled:
                    return fn(request, timeout=timeout)
                with TRACER.span(f"rpc.{name}", agent="sim"):
                    return fn(request, timeout=timeout)

            return call

        for name in self.TRACED_RPCS:
            setattr(self, name, traced(name, getattr(self, name)))
        for name in self.BYTES_RPCS:
            setattr(
                self, name + "Bytes", traced(name, getattr(self, name + "Bytes"))
            )

    def close(self) -> None:  # ServiceClient parity
        pass

    # ---- inventory RPCs ----

    def Partitions(self, request, timeout=None) -> pb.PartitionsResponse:
        return pb.PartitionsResponse(partitions=self.cluster.visible_partitions())

    def Partition(self, request, timeout=None) -> pb.PartitionResponse:
        name = request.partition
        if name in self.cluster.hidden or name not in self.cluster.partitions:
            from slurm_bridge_tpu.sim.faults import SimRpcError
            import grpc

            raise SimRpcError(
                grpc.StatusCode.NOT_FOUND, f"partition {name!r} not found"
            )
        resp = self._part_cache.get(name)
        if resp is None:
            resp = partition_to_proto(self.cluster.partition_info(name))
            self._part_cache[name] = resp
        return resp

    def Nodes(self, request, timeout=None) -> pb.NodesResponse:
        # the cursor short-circuit (PR-11): a caller whose last applied
        # inventory is still exact gets `unchanged=true` and NO node rows
        # — an idle mirror's fetch skips the O(nodes) proto build AND the
        # caller's decode. since_version=0 (old caller) = full response.
        ver = self.cluster.nodes_version
        if request.since_version and request.since_version == ver:
            return pb.NodesResponse(version=ver, unchanged=True)
        infos = self.cluster.node_infos(list(request.names))
        resp = pb.NodesResponse(nodes=[node_to_proto(n) for n in infos])
        resp.version = ver
        return resp

    # ---- job RPCs ----

    def SubmitJob(self, request, timeout=None) -> pb.SubmitJobResponse:
        return pb.SubmitJobResponse(job_id=self.cluster.submit(request))

    def SubmitJobs(self, request, timeout=None) -> pb.SubmitJobsResponse:
        """Batched submit — agent/server.py parity: one entry per request,
        order preserved, answered from ground truth (SimCluster.submit
        never rejects a script, so every entry is ok; per-item failures
        are the FaultyClient's job)."""
        resp = pb.SubmitJobsResponse()
        add = resp.results.add  # in-place: no per-entry message copy
        for r in request.requests:
            add(job_id=self.cluster.submit(r), ok=True)
        return resp

    def CancelJob(self, request, timeout=None) -> pb.CancelJobResponse:
        self.cluster.cancel(int(request.job_id))
        return pb.CancelJobResponse()

    def JobInfo(self, request, timeout=None) -> pb.JobInfoResponse:
        job = self.cluster.jobs.get(int(request.job_id))
        if job is None:
            from slurm_bridge_tpu.sim.faults import SimRpcError
            import grpc

            raise SimRpcError(
                grpc.StatusCode.NOT_FOUND, f"job {request.job_id} not found"
            )
        return pb.JobInfoResponse(
            info=[job_info_to_proto(job.info(now=self.cluster.clock()))]
        )

    def JobsInfo(self, request, timeout=None) -> pb.JobsInfoResponse:
        """Batched JobInfo — agent/server.py parity: unknown ids come back
        found=false, the batch never aborts on one bad id.

        Each job keeps a cached, pre-filled ``JobsInfoEntry``: a call
        refills it only when the job's mutable state (state machine,
        assignment, reason) moved, patches the always-ticking
        ``run_time_s``, and C-level-copies it into the response
        (``jobs.append`` copies, so no mutable message ever escapes —
        the FaultyClient's lost_status freeze keeps true snapshots).
        Byte-identical to the 18-Python-setattr in-place fill it
        replaces, ~3× cheaper on the steady 45k-row mirror tick."""
        now = self.cluster.clock()
        jobs = self.cluster.jobs
        resp = pb.JobsInfoResponse()
        ver = self.cluster.state_version
        resp.version = ver
        since = request.since_version
        if since and since >= ver:
            # no job anywhere has changed since the caller's cursor: the
            # whole chunk is unchanged — O(1), no id scan at all (unknown
            # ids were already reported found=false when first seen, and
            # an id can't become unknown without a state transition)
            return resp
        add = resp.jobs.add
        append = resp.jobs.append
        for job_id in request.job_ids:
            job = jobs.get(int(job_id))
            if job is None:
                add(job_id=job_id, found=False)
                continue
            if since and job.version <= since:
                continue  # unchanged since the caller's cursor: omitted
            cache = job.pb_cache
            sig = (job.state, job.assigned, job.reason)
            if cache is None or cache[2] != sig:
                e = pb.JobsInfoEntry(job_id=job.id, found=True)
                m = e.info.add()
                job.fill_info_proto(m, now=now)
                job.pb_cache = (e, m, sig)
            else:
                e, m, _ = cache
                m.run_time_s = job._run_time(now)
            append(e)
        return resp

    # ---- the serialize-from-ground-truth bytes paths (ISSUE 14) ----
    #
    # Each is the byte-level twin of its pb RPC above: identical cursor
    # semantics, identical entry order, decoding column-identical to the
    # pb2 path (parity tests in tests/test_coldec.py) — but the response
    # is assembled from per-object serialized caches and splices, so a
    # 45k-row mirror pass builds ZERO protobuf objects on either side.

    def JobsInfoBytes(self, request, timeout=None) -> bytes:
        now = self.cluster.clock()
        jobs = self.cluster.jobs
        ver = self.cluster.state_version
        since = request.since_version
        ver_field = b"\x10" + uvarint(ver)
        if since and since >= ver:
            return ver_field  # whole chunk unchanged: version only
        key = id(request)
        slot = self._jobs_bytes_cache.get(key)
        seen = slot is not None and slot[0] is request
        if seen and len(slot) == 4 and slot[1] == since and slot[2] == ver:
            return slot[3]
        parts = []
        append = parts.append
        for job_id in request.job_ids:
            job = jobs.get(int(job_id))
            if job is None:
                e = b"\x08" + uvarint(job_id)  # found=False omitted
                append(b"\x0a" + uvarint(len(e)) + e)
                continue
            if since and job.version <= since:
                continue  # unchanged since the caller's cursor: omitted
            append(job.entry_bytes(now))
        data = b"".join(parts) + ver_field
        if len(self._jobs_bytes_cache) > 1024:
            self._jobs_bytes_cache.clear()  # dead request pins
        # two-touch caching: the PR-11 incremental mirror REUSES its
        # chunk request protos, so the second sighting of the same
        # object is worth a payload slot; one-shot requests (the cold
        # full path builds fresh protos per sync) only pin a tiny seen
        # marker instead of a full response buffer per miss
        self._jobs_bytes_cache[key] = (
            (request, since, ver, data) if seen else (request,)
        )
        return data

    def NodesBytes(self, request, timeout=None) -> bytes:
        ver = self.cluster.nodes_version
        tail = b"\x10" + uvarint(ver)
        if request.since_version and request.since_version == ver:
            return tail + b"\x18\x01"  # version + unchanged=true
        key = id(request)
        slot = self._nodes_bytes_cache.get(key)
        seen = slot is not None and slot[0] is request
        if seen and len(slot) == 3 and slot[1] == ver:
            return slot[2]
        nodes = self.cluster.nodes
        data = b"".join(
            nodes[n].wire_bytes() for n in request.names if n in nodes
        ) + tail
        if len(self._nodes_bytes_cache) > 1024:
            self._nodes_bytes_cache.clear()
        # two-touch, like the jobs cache: only reused request protos
        # earn a payload slot
        self._nodes_bytes_cache[key] = (
            (request, ver, data) if seen else (request,)
        )
        return data

    def SubmitJobsBytes(self, request, timeout=None) -> bytes:
        if isinstance(request, (bytes, bytearray, memoryview)):
            # the provider's worker-pool pre-encode ships raw wire bytes
            # (ISSUE 18) — a real channel's request serializer passes
            # them through; this in-process seam parses them back, so
            # the submit draws are a pure function of the wire content
            # either way
            request = pb.SubmitJobsRequest.FromString(bytes(request))
        parts = []
        for r in request.requests:
            e = b"\x08" + uvarint(self.cluster.submit(r)) + b"\x10\x01"
            parts.append(b"\x0a" + uvarint(len(e)) + e)
        return b"".join(parts)

    def JobState(self, request, timeout=None) -> pb.JobStateResponse:
        job = self.cluster.jobs.get(int(request.job_id))
        status = int(job.state) if job is not None else int(JobStatus.UNKNOWN)
        return pb.JobStateResponse(status=status)
