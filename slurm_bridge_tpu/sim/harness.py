"""The simulation harness: real bridge components, virtual time.

One :class:`SimHarness` owns the REAL control plane — :class:`ObjectStore`,
:class:`BridgeOperator` (reconciled synchronously, event-driven off the
store watch like its production pump thread), :class:`Configurator` with
its :class:`VirtualNodeProvider` mirrors (tickers disabled), and
:class:`PlacementScheduler` — wired to a :class:`SimWorkloadClient`
(optionally behind a :class:`FaultyClient`). Nothing sleeps; the harness
advances a virtual clock and drives every control loop one synchronous
step per tick, so a scenario is deterministic given its seed.

Tick order (one reconcile round):

1. fault boundaries — drain/resume nodes, hide/show partitions, inject
   preemption-storm arrivals;
2. arrivals — create BridgeJob CRs, reconcile them (sizecar pods appear);
3. scheduler tick — the real ``PlacementScheduler.tick`` (store → encode
   → solve → bind, phase-timed by the scheduler itself);
4. mirror — configurator partition diff, provider sync (node refresh,
   submit to "Slurm", statusmap translation), operator status sync for
   owners of changed pods;
5. sim step — complete jobs whose virtual runtime elapsed, start queued
   work;
6. invariants — see ``sim/invariants.py``;
7. advance virtual time.

After the scripted ticks, drain-grace ticks (no arrivals, faults over)
run until the pending queues empty or the grace budget is spent.
"""

from __future__ import annotations

import gc
import hashlib
import json
import logging
import os
import shutil
import tempfile
import time
from dataclasses import dataclass, field

import grpc
import numpy as np

from slurm_bridge_tpu.bridge.configurator import Configurator
from slurm_bridge_tpu.bridge.leader import LeaderElector
from slurm_bridge_tpu.bridge.freeze import FrozenDict
from slurm_bridge_tpu.bridge.objects import (
    BridgeJob,
    BridgeJobStatus,
    FetchState,
    JobState,
    Meta,
    Pod,
    PodPhase,
    PodRole,
    VirtualNode,
    new_uid,
)
from slurm_bridge_tpu.core.fastpath import fast_new, frozen_new

#: shared empty frozen map for born-frozen arrival CRs
_EMPTY_FROZEN_DICT = FrozenDict()


def _freeze_scalar_spec(spec):
    """Flag a scalar-only BridgeJobSpec frozen without the per-field
    walk ``freeze`` pays (trace specs are one-per-arrival, 500k deep on
    a storm front; every field is a str/int, so the walk finds nothing
    to do anyway). ``freeze`` itself short-circuits on the flag."""
    from slurm_bridge_tpu.core.fastpath import FROZEN_FLAG, enable_guard

    enable_guard(spec.__class__)
    spec.__dict__[FROZEN_FLAG] = True
    return spec
from slurm_bridge_tpu.bridge.operator import BridgeOperator
from slurm_bridge_tpu.bridge.persist import StorePersistence, load_into
from slurm_bridge_tpu.bridge.scheduler import PlacementScheduler
from slurm_bridge_tpu.bridge.store import ObjectStore
from slurm_bridge_tpu.core.types import JobStatus
from slurm_bridge_tpu.obs.events import EventRecorder
from slurm_bridge_tpu.obs.flight import FlightRecorder
from slurm_bridge_tpu.obs.metrics import REGISTRY
from slurm_bridge_tpu.obs.tracing import TRACER, with_current_span
from slurm_bridge_tpu.agent.journal import AgentJournal
from slurm_bridge_tpu.parallel import colpool
from slurm_bridge_tpu.policy.classes import CLASS_LABEL, TENANT_LABEL
from slurm_bridge_tpu.policy.engine import PlacementPolicy
from slurm_bridge_tpu.policy.score import QualityTracker
from slurm_bridge_tpu.sim.agent import SimCluster, SimWorkloadClient
from slurm_bridge_tpu.sim.faults import AGENT_KINDS, FaultPlan, FaultyClient
from slurm_bridge_tpu.wire.rpc import RetryingClient, RetryPolicy
from slurm_bridge_tpu.sim.invariants import (
    Violation,
    check_drain,
    check_tick,
    per_node_demand,
)
from slurm_bridge_tpu.sim.trace import (
    ClusterSpec,
    WorkloadSpec,
    build_cluster,
    generate_trace,
    storm_arrivals,
)

log = logging.getLogger("sbt.sim")

_tick_seconds = REGISTRY.histogram(
    "sbt_sim_tick_seconds", "full simulated reconcile tick wall time"
)

#: the phases the full-tick headline decomposes into. ``other`` is the
#: scheduler-tick time OUTSIDE the four named phases (RPC-fault aborts,
#: remote skips, any new cost a future change adds) — an explicit bucket
#: so the numbers stop lying by silently folding it into "store".
#: ``arrive`` (ISSUE 14) is the arrival-ingest phase — CR creation,
#: operator sweep, admission fast path — which used to sit OUTSIDE the
#: tick sum entirely: at 500k×100k that was ~25 s of real per-tick work
#: the headline number silently excluded and the flight record could
#: not reconcile (phase_sum 36.4 s vs tick span 63.0 s).
PHASES = ("arrive", "store", "encode", "solve", "bind", "mirror", "other")


@dataclass(frozen=True)
class Scenario:
    """A named, fully-seeded simulation run."""

    name: str
    cluster: ClusterSpec
    workload: WorkloadSpec
    faults: FaultPlan = field(default_factory=FaultPlan)
    ticks: int = 30
    #: virtual seconds per tick — 5 keeps the drain horizon (grace ticks ×
    #: interval) comfortably above the worst serialization chain on a
    #: scarce resource (a few max-duration jobs queued on one GPU node)
    tick_interval_s: float = 5.0
    seed: int = 42
    preemption: bool = False
    backend: str = "auto"
    expect_drain: bool = True
    drain_grace_ticks: int = 60
    description: str = ""
    slow: bool = False
    #: the tick flight recorder (span capture + attribution records);
    #: off is the control arm of the bench-smoke overhead gate
    tracing: bool = True
    #: WAL-backed store persistence, flushed synchronously at every tick
    #: boundary (and compacted periodically). Forced on when the fault
    #: plan contains a bridge-level fault (crash_restart /
    #: leader_failover) — recovery needs something to recover FROM; the
    #: WAL-overhead bench gate flips it on a fault-free scenario
    persistence: bool = False
    #: sim-smoke gate: fault scenarios must report recovery_ticks ≤ this
    #: (None = only the existing non-None check applies)
    max_recovery_ticks: int | None = None
    #: stack a bounded-retry wrapper (backoff+jitter, virtual sleeps)
    #: over the client, so transient injected RPC errors heal inside the
    #: tick instead of surfacing as failed control-loop rounds
    rpc_retries: bool = False
    #: simulated per-fsync device latency for the WAL (ms). 0 keeps the
    #: sim's fsync-off mode; >0 turns real fsyncs ON with that much
    #: injected latency — the fsync-realism bench arm
    wal_fsync_ms: float = 0.0
    #: smoke-gate twin comparison for crash scenarios: "" = none,
    #: "state" = final_state_digest must be byte-identical to the twin
    #: with bridge/agent crash faults stripped, "outcome" = the
    #: id/placement-insensitive final_outcome_digest must be (used when
    #: composed RPC faults legitimately reshuffle job ids/placements)
    lossless_twin: str = ""
    #: placement-policy config (policy.PolicyConfig) — priority classes,
    #: fair share, bounded preemption, backfill. None = policy OFF, the
    #: PR-8 tick byte-for-byte (the quality-smoke gate proves it)
    policy: object | None = None
    #: explicit AuctionConfig for backend="auction" scenarios (None =
    #: scheduler defaults). diurnal_load pins an APPROXIMATE config
    #: (repair off, few rounds) so the backfill pass has real
    #: fragmentation holes to fill — the shape the quality gate measures
    auction_config: object | None = None
    #: sharded-placement config (shard.ShardConfig) — partition/island
    #: fan-out + cross-shard gang reconciliation. None = sharding OFF,
    #: the monolithic tick byte-for-byte (fixture-pinned, like policy)
    sharding: object | None = None
    #: CLI-enforced tick p50 ceiling (ms) for slow headline scenarios;
    #: None = record only
    p50_gate_ms: float | None = None
    #: event-driven incremental tick (PR-11): cursor-scoped mirror sync,
    #: dirty-set pending scan, warm-start solve reuse. On by default —
    #: byte-identical determinism digest and final_state_digest to the
    #: full tick is the acceptance bar (the smoke gates run an
    #: incremental=False twin per scenario to prove it); False is the
    #: PR-10 tick byte-for-byte (fixture-pinned)
    incremental: bool = True
    #: CLI-enforced STEADY-STATE tick p50 ceiling (ms): the median over
    #: ticks in which nothing arrived, bound, preempted, faulted or
    #: wrote — the O(changes) acceptance number; None = record only
    steady_gate_ms: float | None = None
    #: streaming-admission config (admission.AdmissionConfig) — the
    #: always-on fast path that binds interactive-class arrivals
    #: against the residual free_after view at ARRIVAL time, before the
    #: batch tick sees them. None = admission OFF, the PR-11 tick
    #: byte-for-byte (fixture-pinned, like policy/sharding/incremental)
    admission: object | None = None
    #: zero-object wire→column decode on the bulk RPCs (ISSUE 14). On by
    #: default — digests must be byte-identical either way; False is the
    #: PR-12 pb2 bulk path byte-for-byte (fixture-pinned,
    #: tests/fixtures/coldec_off_baseline.json)
    coldec: bool = True
    #: CLI-enforced flight-record reconciliation gate (percent): the
    #: span-derived phase_sum_p50 must match the tick span p50 within
    #: this tolerance — the PR-5 ±5% contract, re-enforced at the
    #: headline shape now that the recorder's rollup survives span
    #: drops. None = record only.
    phase_reconcile_pct: float | None = None
    #: placement explainability (ISSUE 15): structured per-job reason
    #: codes + the per-tick pressure ledger (flight record,
    #: ``quality.wait_reasons``, /debug/schedz). On by default —
    #: digest-byte-identical to off BY CONSTRUCTION (attribution only
    #: observes solve artifacts; ``profile_explain_overhead`` gates the
    #: claim); False restores the generic reason strings byte-for-byte.
    explain: bool = True
    #: trace ONE job's decision trail (``--explain <job>`` on the CLI):
    #: the sizecar pod name (or job name — the CLI normalizes) whose
    #: route/solve/backfill/reason decisions are recorded per tick
    explain_target: str = ""
    #: per-shard mirror ownership (ISSUE 16): group the provider syncs
    #: by OWNING shard (executor.mirror_groups), so each shard mirrors
    #: its own contiguous slice of the partition list; the flattened
    #: order equals the sorted global order and the owner sweep stays
    #: global, keeping digests byte-identical. A no-op unless
    #: ``sharding`` is set (one group ≡ the global pass); False keeps the
    #: single global provider pass as the byte-identical oracle
    shard_mirror: bool = True
    #: pipelined mirror (ISSUE 16 phase overlap): run one provider's
    #: chunked status fetch on an overlap thread while the NEXT
    #: provider's classification/converge runs on the main thread — all
    #: store writes stay on the main thread in provider order, so
    #: digests are byte-identical to the sequential mirror (the staged
    #: equivalence suite proves it). Auto-disabled when the fault plan
    #: is non-empty: fault draws must stay on the plain sequenced path.
    #: False is the sequential oracle
    mirror_pipeline: bool = True
    #: partitioned store commit (ISSUE 19): the pool workers that
    #: decode+diff a mirror chunk also pack its commit frame, and the
    #: status write merges per-chunk writer partitions through
    #: ``store.apply_frames`` under one short lock. Engages only when a
    #: colpool is active (``SBT_COLPOOL_WORKERS`` ≥ 1 or multi-core
    #: affinity) — on this repo's 1-core CI the flag is inert and the
    #: serial scatter runs regardless. False is the PR-18 serial
    #: column-scatter oracle byte-for-byte (fixture-pinned,
    #: tests/fixtures/frames_off_baseline.json)
    mirror_frames: bool = True
    #: fleet runtime config (fleet.FleetConfig): replicas + solver
    #: sidecar processes; per-shard solves dispatch to the shard
    #: owner's sidecar over real gRPC (byte-parity with inline — the
    #: fleet twin gate proves it). None = single-process, zero overhead
    fleet: object | None = None
    #: fleet-wide observability (ISSUE 20): PlaceShard trace stitching,
    #: colpool worker self-timing folds, metrics federation and the
    #: lifecycle timeline. Digest-neutral either way; False is the
    #: control arm of the paired profile_fleet_obs_overhead gate
    fleet_obs: bool = True


@dataclass
class ScenarioResult:
    scenario: Scenario
    determinism: dict
    timing: dict
    shape: dict
    #: placement-quality scorecard (policy/score.py) — utilization,
    #: fragmentation, wait percentiles, preemption churn, fairness;
    #: computed for EVERY scenario (virtual-time deterministic) and
    #: gated for the quality subset in `make quality-smoke`
    quality: dict = field(default_factory=dict)
    #: run-level flight record (span tree p50s, top self-time, commit
    #: breakdown); {} when the scenario ran with tracing off
    flight_record: dict = field(default_factory=dict)
    #: per-tick flight records — written to diagnostics/ for the slow
    #: headline run, kept off the one-line scenario JSON otherwise
    flight_ticks: list = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "scenario": self.scenario.name,
            "seed": self.scenario.seed,
            "shape": self.shape,
            "faults": self.scenario.faults.describe(),
            "determinism": self.determinism,
            "timing": self.timing,
            "quality": self.quality,
            "flight_record": self.flight_record,
        }

    def determinism_json(self) -> str:
        """The byte-comparable section: everything except wall-clock."""
        return json.dumps(
            {
                "scenario": self.scenario.name,
                "seed": self.scenario.seed,
                "shape": self.shape,
                "determinism": self.determinism,
            },
            sort_keys=True,
        )


def _quiet_event_logs() -> None:
    # the recorder logs every event; at 50k binds/tick that is pure drag
    # (and Unschedulable churn would spam stderr through the lastResort
    # handler) — scenario metrics carry the same information
    logging.getLogger("sbt.events").setLevel(logging.CRITICAL)
    logging.getLogger("sbt.scheduler").setLevel(logging.ERROR)
    logging.getLogger("sbt.vnode").setLevel(logging.ERROR)
    logging.getLogger("sbt.configurator").setLevel(logging.ERROR)


class SimHarness:
    #: snapshot-compaction cadence (ticks): keeps both recovery inputs —
    #: a recent snapshot AND a WAL tail since it — live in every
    #: crash-window, so a mid-run reload exercises snapshot+replay
    _COMPACT_EVERY = 4

    def __init__(self, scenario: Scenario):
        _quiet_event_logs()
        self.scenario = scenario
        self.vt = 0.0
        rng = np.random.default_rng(scenario.seed)
        nodes, partitions = build_cluster(scenario.cluster, rng)
        self.cluster = SimCluster(nodes, partitions, clock=lambda: self.vt)
        by_name = {n.name: n for n in nodes}
        part_names = list(partitions)
        sizes = [len(partitions[p]) for p in part_names]
        gpu_caps = [
            max((by_name[m].gpus for m in partitions[p]), default=0)
            for p in part_names
        ]
        gpu_counts = [
            sum(1 for m in partitions[p] if by_name[m].gpus > 0)
            for p in part_names
        ]
        self.trace = generate_trace(
            scenario.workload,
            scenario.cluster,
            scenario.ticks,
            rng,
            partition_sizes=sizes,
            partition_gpu_caps=gpu_caps,
            partition_gpu_counts=gpu_counts,
        )
        for f in scenario.faults.faults:
            if f.kind == "preemption_storm" and f.start_tick < scenario.ticks:
                # extra kwargs only on the NEW gang/class shape, so plain
                # storms replay the PR-2 byte stream exactly (the
                # defaults are draw-identical either way)
                kw: dict = {}
                if f.gang_size > 1 or f.storm_class or f.storm_cpus:
                    eligible = [
                        k
                        for k, size in enumerate(sizes)
                        if size >= max(1, f.gang_size)
                    ]
                    if f.gang_size > 1 and not eligible:
                        # a storm gang no partition can host would pend
                        # forever and surface as a misleading wait-bound
                        # failure — refuse the config loudly instead
                        raise ValueError(
                            f"preemption_storm gang_size={f.gang_size} "
                            "fits no partition at this scale "
                            f"(sizes={sizes})"
                        )
                    kw = dict(
                        gang_size=f.gang_size,
                        storm_class=f.storm_class,
                        eligible_parts=eligible,
                    )
                    if f.storm_cpus:
                        kw["cpus"] = f.storm_cpus
                self.trace[f.start_tick].extend(
                    storm_arrivals(
                        f.start_tick, f.jobs, scenario.cluster, rng,
                        priority=f.priority, **kw,
                    )
                )
        # ---- placement-quality accounting (ISSUE 9) ----
        tenant_of: dict[str, str] = {}
        is_gang: dict[str, bool] = {}
        class_of: dict[str, str] = {}
        shard_cpus: list[float] = []
        for arrivals in self.trace:
            for a in arrivals:
                tenant_of[a.name] = a.labels.get(TENANT_LABEL, "")
                is_gang[a.name] = (a.spec.nodes or 1) > 1
                class_of[a.name] = a.labels.get(CLASS_LABEL, "")
                shard_cpus.append(
                    max(1, a.spec.cpus_per_task)
                    * max(1, a.spec.ntasks)
                    / max(1, a.spec.nodes or 1)
                )
        self.quality = QualityTracker(
            tenant_of=tenant_of,
            is_gang=is_gang,
            class_of=class_of,
            tenant_weights=(
                dict(scenario.policy.tenant_weights)
                if scenario.policy is not None
                else {}
            ),
            ref_cpu=float(np.median(shard_cpus)) if shard_cpus else 1.0,
            tick_interval_s=scenario.tick_interval_s,
        )
        base_client = SimWorkloadClient(self.cluster)
        #: the unwrapped fake agent — the steady-state gate reads its
        #: per-method call counter (calls that reach the agent, so
        #: injected failures don't count — the gate runs fault-free)
        self.agent_client = base_client
        #: the FaultyClient (tick advance + injection counters) — kept
        #: separate from ``self.client`` because a retry wrapper may
        #: stack on top of it
        self.faulty: FaultyClient | None = (
            FaultyClient(base_client, scenario.faults, seed=scenario.seed + 1)
            if scenario.faults
            else None
        )
        self.client = self.faulty if self.faulty is not None else base_client
        self.retrier: RetryingClient | None = None
        if scenario.rpc_retries:
            # virtual time: retries cost no wall clock (sleep is a no-op)
            # and draw jitter from a seeded RNG so injection sequences —
            # and therefore whole runs — stay deterministic
            self.retrier = RetryingClient(
                self.client,
                policy=RetryPolicy(max_attempts=8),
                sleep=lambda s: None,
                seed=scenario.seed + 2,
            )
            self.client = self.retrier
        # deterministic drain targets resolved up front (plan seed, not
        # call order): node_fraction picks evenly-spaced names
        self._drain_targets: dict[int, tuple[str, ...]] = {}
        names = sorted(self.cluster.nodes)
        for i, f in enumerate(scenario.faults.faults):
            if f.kind != "drain_nodes":
                continue
            picked = list(f.nodes)
            if f.node_fraction > 0:
                k = max(1, int(round(f.node_fraction * len(names))))
                stride = max(1, len(names) // k)
                picked.extend(names[(i % stride) :: stride][:k])
            self._drain_targets[id(f)] = tuple(picked)

        self.store = ObjectStore()
        self.events = EventRecorder()
        self._event_counts: dict[str, int] = {}
        self._preempt_events = 0
        self.events.add_sink(self._count_event)
        #: the pipelined mirror's overlap thread (lazy; stack-scoped)
        self._mirror_pool = None
        #: fleet runtime (ISSUE 17) — built after the state dir below;
        #: None until then so _build_stack's attach guard no-ops
        self.fleet = None
        #: ISSUE 20: parent-side folding of colpool worker timing headers
        #: follows the scenario's obs arm (headers always ride the wire;
        #: _cleanup restores the process default)
        colpool.set_obs(scenario.fleet_obs)
        self._build_stack()
        #: the tick flight recorder — always-on unless the scenario opts
        #: out (the overhead gate's control arm); every run_tick is one
        #: capture window rooted at a "sim.tick" span
        self.flight = FlightRecorder(
            tracer=TRACER, store=self.store, enabled=scenario.tracing
        )
        self.rpc_failures: dict[str, int] = {}
        self.violations: list[Violation] = []
        self._digest = hashlib.sha256()
        self._bound_total = 0
        self._preempted_total = 0
        #: pod names bound by the streaming fast path THIS tick — folded
        #: into the bound accounting + capacity invariants (they are not
        #: in pending_before, so the batch diff cannot see them)
        self._fast_bound_tick: list[str] = []
        self._tick_phases: list[dict[str, float]] = []
        #: per-tick pressure ledgers (ISSUE 15): (tick, ledger) for every
        #: solve tick that attributed reasons — what the flight record
        #: carries per tick and the explain tests pin (per-reason counts
        #: sum to the unplaced count by construction)
        self._explain_ledgers: list[tuple[int, dict]] = []
        #: per-tick steady-state accounting (PR-11): arrivals, binds,
        #: commits, agent RPCs, solver invocations and the derived
        #: ``steady`` verdict — what ``steady_tick_p50_ms`` and the
        #: bench-smoke zero-work gate read
        self._tick_meta: list[dict] = []
        self._arrive_ms: list[float] = []
        self._pending_by_tick: list[int] = []
        self._drained_at: int | None = None
        self._recovered_at: int | None = None

        # ---- durability + leadership (PR-7/PR-8) ----
        plan_kinds = {f.kind for f in scenario.faults.faults}
        self._needs_persistence = scenario.persistence or bool(
            plan_kinds & {"crash_restart", "leader_failover"}
        )
        self._needs_agent_journal = bool(plan_kinds & set(AGENT_KINDS))
        self._state_dir: str | None = None
        self.persistence: StorePersistence | None = None
        self.agent_journal: AgentJournal | None = None
        #: whether the control plane is alive this tick (False only in
        #: the leaderless window between a leader dying and the standby's
        #: lease takeover)
        self._stack_up = True
        #: arrivals landing in a leaderless window queue here (the
        #: client retrying against a dead control plane) and replay on
        #: the first tick the standby is up
        self._arrival_backlog: list = []
        self._restarts = 0
        self._agent_restarts = 0
        self.vnode_deletions = 0
        self._takeover_ticks: list[int] = []
        self._wal_records_prior = 0
        self._snapshots_prior = 0
        self._recovery_ms: list[float] = []
        self._wal_flush_ms: list[float] = []
        self._restored_objects: list[int] = []
        self._agent_restored_jobs: list[int] = []
        self.elector: LeaderElector | None = None
        self._standby: LeaderElector | None = None
        self._active_elector: LeaderElector | None = None
        self._dead_elector: LeaderElector | None = None
        if (
            self._needs_persistence
            or self._needs_agent_journal
            or scenario.fleet is not None
        ):
            self._state_dir = tempfile.mkdtemp(prefix="sbt-sim-state-")
        if self._needs_persistence:
            self.state_file = os.path.join(self._state_dir, "bridge-state.json")
            self.persistence = self._make_persistence()
        if self._needs_agent_journal:
            # fsync off, like the bridge WAL: sim durability is
            # within-process; the journal's every-transition appends are
            # driven purely by virtual events, so replay is deterministic
            self.agent_journal = AgentJournal(
                os.path.join(self._state_dir, "agent-journal.json"),
                fsync=False,
            )
            self.cluster.attach_journal(self.agent_journal)
        if "leader_failover" in plan_kinds:
            lease_path = os.path.join(self._state_dir, "leader.lease")
            # 8 virtual seconds: outlives one 5 s tick gap, expires
            # during the second — expiry takeover exercises a real
            # leaderless window, graceful handover is immediate
            self.elector = LeaderElector(
                lease_path,
                identity="bridge-0",
                lease_duration=8.0,
                clock=lambda: self.vt,
            )
            if not self.elector.try_acquire():  # pragma: no cover - fresh dir
                raise RuntimeError("sim leader could not acquire a fresh lease")
            self._standby = LeaderElector(
                lease_path,
                identity="bridge-1",
                lease_duration=8.0,
                clock=lambda: self.vt,
            )
            self._active_elector = self.elector

        if scenario.fleet is not None:
            from slurm_bridge_tpu.fleet.runtime import FleetRuntime

            # leases run on virtual time (the sim drives heartbeats);
            # sidecar spawn/handshake is wall-time OS work, like any
            # other subprocess the harness owns
            self.fleet = FleetRuntime(
                scenario.fleet, self._state_dir, clock=lambda: self.vt,
                obs=scenario.fleet_obs,
            )
            self.fleet.start()
            self._attach_fleet()

    def _attach_fleet(self) -> None:
        """Point the executor's remote seam at the fleet (re-run after a
        crash reload rebuilds the scheduler)."""
        if self.fleet is not None and self.scheduler.shard is not None:
            self.scheduler.shard.remote = self.fleet

    def _make_persistence(self) -> StorePersistence:
        """StorePersistence in the sim's deterministic posture: manual
        flush (no pump thread, no timers). fsync stays OFF at 0 ms —
        sim "durability" is within-process, and a real fsync per virtual
        tick would dominate the toy-scale overhead measurement the bench
        gate pairs against — and flips ON with injected device latency
        when the scenario carries ``wal_fsync_ms`` (the fsync-realism
        bench arm)."""
        fsync_ms = self.scenario.wal_fsync_ms
        return StorePersistence(
            self.store,
            self.state_file,
            auto_flush=False,
            fsync=fsync_ms > 0,
            fsync_delay_s=(fsync_ms / 1e3) if fsync_ms > 0 else None,
        )

    def _build_stack(self) -> None:
        """(Re)build the real control plane over ``self.store`` — called
        at init and again by the crash/failover reload. Watches are
        re-established on the new store; its synthetic ADDED backlog is
        exactly the level-triggered resync a restarted operator needs."""
        scenario = self.scenario
        self.operator = BridgeOperator(
            self.store, agent_endpoint="sim://agent", events=self.events
        )
        self.configurator = Configurator(
            self.store,
            self.client,
            agent_endpoint="sim://agent",
            events=self.events,
            node_sync_interval=0.0,  # no tickers: the harness drives sync
            pod_sync_workers=1,  # serial converge: deterministic order
            provider_inventory_ttl=0.0,  # no wall-clock cache window
            # heartbeat never forces a node write: the 10 s default is a
            # WALL clock, so a slow box running a long tick would write
            # VirtualNode heartbeats mid-run — nondeterministic commit
            # counts and a false "not steady" verdict on idle ticks.
            # Capacity changes still rewrite the node.
            provider_status_interval=float("inf"),
            incremental=scenario.incremental,
            use_coldec=scenario.coldec,
            mirror_frames=scenario.mirror_frames,
            # admission-window maintenance from the periodic inventory
            # probe (ROADMAP follow-up c) — late-bound: the scheduler is
            # constructed a few lines below, before any provider syncs
            inventory_listener=lambda part, nodes: (
                self.scheduler.note_inventory(part, nodes)
            ),
        )
        # fresh policy engine per stack incarnation: a crash loses the
        # in-memory fair-share accumulator exactly as production would
        self.policy_engine = (
            PlacementPolicy(scenario.policy)
            if scenario.policy is not None
            else None
        )
        self.scheduler = PlacementScheduler(
            self.store,
            self.client,
            backend=scenario.backend,
            auction_config=scenario.auction_config,
            events=self.events,
            preemption=scenario.preemption,
            inventory_ttl=0.0,  # virtual time: always take a fresh snapshot
            policy=self.policy_engine,
            # a fresh executor per stack incarnation: its per-shard caches
            # are in-memory tick state, rebuilt from scratch after a crash
            # exactly like the monolithic encode caches
            shard=scenario.sharding,
            incremental=scenario.incremental,
            # a fresh admitter too: the residual view and in-flight
            # deductions are in-memory tick state — after a crash the
            # fast path stays dormant until the first post-reload solve
            # re-bases its window (arrivals fall through to the batch
            # tick meanwhile, the safe direction)
            admission=scenario.admission,
            explain=scenario.explain,
            explain_target=scenario.explain_target,
        )
        if self.scheduler.explain_trail is not None:
            # one trail per RUN: a crash/failover rebuild keeps the
            # lines recorded by the previous incarnation
            prev_lines = getattr(self, "_trail_lines", None)
            if prev_lines is not None:
                self.scheduler.explain_trail.lines = prev_lines
            self._trail_lines = self.scheduler.explain_trail.lines
        self._pod_watch = self.store.watch((Pod.KIND,))
        self._node_watch = self.store.watch((VirtualNode.KIND,))
        # fleet re-attach (no-op at init: the fleet is built after the
        # first _build_stack; crash reloads re-point the fresh executor)
        if getattr(self, "fleet", None) is not None:
            self._attach_fleet()

    # ---- crash / failover machinery ----

    def _drain_node_watch(self) -> None:
        """Count VirtualNode DELETED events — the node-flap detector the
        failover scenarios gate to zero (synthetic ADDED events from a
        fresh watch pass through uncounted)."""
        while True:
            try:
                ev = self._node_watch.get_nowait()
            except Exception:
                break
            if ev.type == "DELETED":
                self.vnode_deletions += 1

    def _teardown_stack(self, *, flush: bool) -> None:
        """Kill the control plane. ``flush=True`` is the graceful path
        (step-down: WAL flushed first); ``False`` is a crash — whatever
        the last tick-boundary flush captured is all recovery gets."""
        if flush and self.persistence is not None:
            self.persistence.flush()
        self._drain_node_watch()
        # pool/ticker teardown only — Configurator.stop() must leave
        # every VirtualNode in the store (the ADVICE #1 contract; the
        # failover scenarios assert zero node deletions)
        self.configurator.stop()
        if self.scheduler.shard is not None:
            self.scheduler.shard.close()
        if self._mirror_pool is not None:
            self._mirror_pool.shutdown(wait=False)
            self._mirror_pool = None
        self.store.unwatch(self._pod_watch)
        self.store.unwatch(self._node_watch)

    def _reload_stack(self, tick: int) -> None:
        """Bring up a fresh bridge over snapshot+WAL: new store, rebased
        persistence incarnation, new operator/configurator/scheduler.
        The sim agent (ground truth "Slurm") is untouched — partitions
        and jobs outlive the controller, the JIRIAF operating model."""
        t0 = time.perf_counter()
        self.store = ObjectStore()
        restored = load_into(self.store, self.state_file)
        if self.persistence is not None:
            self._wal_records_prior += self.persistence.wal_records_total
            self._snapshots_prior += self.persistence.snapshots_written
            # crash semantics: no flush — but the dead incarnation's WAL
            # file handle must not outlive it (one leaked fd per restart,
            # and two live handles on one WAL invite interleaved writes)
            self.persistence.abandon()
        self.persistence = self._make_persistence()
        self.persistence.compact()
        self._build_stack()
        self._recovery_ms.append((time.perf_counter() - t0) * 1e3)
        self._restored_objects.append(restored)
        self.flight.store = self.store
        self._restarts += 1
        self._note(tick, "restart", restored)

    def _agent_faults(self, tick: int) -> None:
        """Apply agent-level faults at the tick boundary. ``agent_crash``
        drops the fake agent's process state and rebuilds it from the
        job-state journal — applied BEFORE the bridge faults so a
        simultaneous bridge+agent crash has the reloaded bridge resync
        against the reloaded agent (the composed-durability shape)."""
        plan = self.scenario.faults
        for _ in plan.starting("agent_crash", tick):
            t0 = time.perf_counter()
            restored = self.cluster.crash_reload()
            self._recovery_ms.append((time.perf_counter() - t0) * 1e3)
            self._agent_restored_jobs.append(restored)
            self._agent_restarts += 1
            self._note(tick, "agent-crash", restored)

    def _bridge_faults(self, tick: int) -> None:
        """Apply bridge-level faults at the tick boundary, then renew or
        chase the lease."""
        plan = self.scenario.faults
        for _ in plan.starting("crash_restart", tick):
            self._note(tick, "crash")
            self._teardown_stack(flush=False)
            self._reload_stack(tick)
        for f in plan.starting("leader_failover", tick):
            self._note(
                tick, "leader-down", "graceful" if f.graceful else "expiry"
            )
            self._teardown_stack(flush=f.graceful)
            dead = self._active_elector
            if dead is not None and f.graceful:
                dead.release()
            # a supervisor restarts the dead process — it rejoins the
            # election as the standby for any later failover window
            self._dead_elector = dead
            self._active_elector = None
            self._stack_up = False
        if not self._stack_up and self._standby is not None:
            if self._standby.try_acquire():
                self._note(tick, "leader-up", self._standby.identity)
                self._reload_stack(tick)
                self._active_elector = self._standby
                self._standby = self._dead_elector
                self._dead_elector = None
                self._stack_up = True
                self._takeover_ticks.append(tick)
        elif self._active_elector is not None:
            self._active_elector.try_acquire()  # periodic renewal

    # ---- bookkeeping ----

    def _count_event(self, ev) -> None:
        self._event_counts[ev.reason] = self._event_counts.get(ev.reason, 0) + 1
        if ev.message.startswith("preempted:"):
            self._preempt_events += 1

    def _note(self, *parts: object) -> None:
        self._digest.update("|".join(str(p) for p in parts).encode())
        self._digest.update(b"\n")

    def _rpc_fail(self, where: str) -> None:
        self.rpc_failures[where] = self.rpc_failures.get(where, 0) + 1

    @staticmethod
    def _pending_names(pods: list[Pod]) -> set[str]:
        """PlacementScheduler.pending_pods()'s filter over an
        already-fetched list (one store copy per tick, not one per use) —
        keep in lockstep with bridge/scheduler.py."""
        return {
            p.name
            for p in pods
            if p.spec.role == PodRole.SIZECAR
            and not p.spec.node_name
            and not p.meta.deleted
            and p.status.phase == PodPhase.PENDING
        }

    # ---- tick machinery ----

    def _apply_fault_boundaries(self, tick: int) -> None:
        plan = self.scenario.faults
        for f in plan.starting("drain_nodes", tick):
            self.cluster.drain(list(self._drain_targets.get(id(f), f.nodes)))
        for f in plan.ending("drain_nodes", tick):
            self.cluster.resume(list(self._drain_targets.get(id(f), f.nodes)))
        for f in plan.starting("partition_vanish", tick):
            self.cluster.hide_partition(f.partition)
        for f in plan.ending("partition_vanish", tick):
            self.cluster.show_partition(f.partition)
        for f in plan.starting("elastic_resize", tick):
            self._apply_resizes(tick, f)

    # ---- elastic resize (VirtualFlow, arxiv 2009.09523) ----

    def _apply_resizes(self, tick: int, fault) -> None:
        """Change ``fault.jobs`` bound jobs' shard counts mid-flight:
        singles grow to 2 nodes, gangs halve (total demand is spread
        across shards, so growing always stays feasible). Targets are
        the first eligible pods in name order — deterministic."""
        part_size = {
            name: len(members)
            for name, members in self.cluster.partitions.items()
        }
        pods = sorted(
            (
                p
                for p in self.store.list(Pod.KIND)
                if p.spec.role == PodRole.SIZECAR
                and p.spec.node_name
                and p.spec.demand is not None
                and not p.meta.deleted
                and p.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
            ),
            key=lambda p: p.name,
        )
        done = 0
        for pod in pods:
            if done >= fault.jobs:
                break
            nodes = max(1, pod.spec.demand.nodes)
            new_nodes = nodes // 2 if nodes > 1 else 2
            if new_nodes > part_size.get(pod.spec.partition, 0):
                continue
            if self._resize_pod(pod.name, new_nodes, tick):
                done += 1

    def _resize_pod(self, name: str, new_nodes: int, tick: int) -> bool:
        """One mid-flight resize: cancel the running Slurm jobs, rewrite
        the demand's shard count under a fresh submit generation, and
        requeue — the scheduler re-places it at the new shape next tick.
        Mirrors the scheduler's ``_preempt`` reset-before-cancel order so
        the terminal CANCELLED state can never race the requeue."""
        from slurm_bridge_tpu.bridge.store import NotFound
        from slurm_bridge_tpu.wire import pb

        job_ids: list[int] = []

        def record(p):
            job_ids.clear()
            if not p.spec.node_name or p.meta.deleted:
                return False
            job_ids.extend(p.status.job_ids)
            gen = int(p.meta.annotations.get("submit-generation", "0")) + 1
            p.meta.annotations["submit-generation"] = str(gen)
            p.spec.node_name = ""
            p.spec.placement_hint = ()
            p.spec.demand.nodes = new_nodes  # mutate() hands a thawed copy
            p.status.job_ids = ()
            p.status.job_infos = []
            p.status.phase = PodPhase.PENDING
            p.status.reason = "Resizing: shard count changed"

        try:
            self.store.mutate(Pod.KIND, name, record, site="sim.resize")
        except NotFound:
            return False
        pod = self.store.try_get(Pod.KIND, name)
        if pod is None or pod.spec.node_name:
            return False
        for jid in job_ids:
            try:
                self.client.CancelJob(pb.CancelJobRequest(job_id=jid))
            except grpc.RpcError:
                self._rpc_fail("sim.resize")
        owner = pod.meta.owner or name

        def stamp_job(j):
            j.spec.nodes = new_nodes

        try:
            self.store.mutate(BridgeJob.KIND, owner, stamp_job, site="sim.resize")
        except NotFound:
            pass
        self._note(tick, "resize", name, new_nodes)
        self.quality.note_rearrival(owner, tick)
        self.quality.note_resize()
        return True

    def _arrive(self, tick: int) -> int:
        arrivals = self._arrival_backlog + (
            self.trace[tick] if tick < len(self.trace) else []
        )
        self._arrival_backlog = []
        if not self._stack_up:
            # leaderless window: the control plane is down — the client
            # queues its submissions and retries once a leader is back
            self._arrival_backlog = arrivals
            return 0
        if not arrivals:
            return 0
        admitter = self.scheduler.admission
        warmup = (
            admitter.config.latency_warmup_ticks
            if admitter is not None
            else 0
        )
        # ---- batched arrival ingest (ISSUE 14) ----
        # The per-arrival trickle (create → reconcile → stamp, ~5 store
        # round-trips + one single-key reconcile per job) was ~25 s of
        # UNATTRIBUTED tick time at the 500k front. Batched: one
        # create_batch for the tick's CRs, one operator sweep for their
        # sizecars (sweep ≡ N reconciles, fuzz-pinned in
        # tests/test_operator_sweep.py; arrival names are zero-padded
        # ascending, so the sweep's sorted order IS arrival order), one
        # row-batch duration stamp. Admission still runs per arrival, in
        # arrival order — identical fast-path decisions and latency
        # capture. Outcome-identical to the trickle: digests are pinned
        # by tests/fixtures/coldec_off_baseline.json and every smoke.
        with TRACER.span("sim.arrive.create") as cspan:
            # born-frozen children (ISSUE 14): commit-time freeze probes
            # meta and stops instead of re-walking every spec field per
            # CR — the same idiom as the operator's sizecar build
            jobs = [
                fast_new(
                    BridgeJob,
                    meta=fast_new(
                        Meta,
                        name=a.name,
                        uid=new_uid(),
                        labels=(
                            FrozenDict(a.labels)
                            if a.labels
                            else _EMPTY_FROZEN_DICT
                        ),
                        annotations=_EMPTY_FROZEN_DICT,
                        owner="",
                        resource_version=0,
                        deleted=False,
                    ),
                    spec=_freeze_scalar_spec(a.spec),
                    status=frozen_new(
                        BridgeJobStatus,
                        state=JobState.PENDING,
                        reason="",
                        subjobs=_EMPTY_FROZEN_DICT,
                        fetch_result=FetchState.NONE,
                        cluster_endpoint="",
                    ),
                )
                for a in arrivals
            ]
            results = self.store.create_batch(jobs, site="sim.arrive")
            created = [
                a
                for a, r in zip(arrivals, results)
                if not isinstance(r, Exception)
            ]
            cspan.count("jobs", len(created))
        for a in created:
            self.quality.note_arrival(a.name, tick)
        names = [a.name for a in created]
        if names:
            # keys the sweep won't settle (validation failures, finished
            # jobs, conflicts) go through the single-key oracle, exactly
            # like the mirror's event pump
            for key in self.operator.sweep(names):
                self.operator.reconcile(key)
        # the trace's virtual duration rides the demand's time limit —
        # the sim agent runs each job for exactly that long. One batched
        # column write; object stores keep the per-pod replacement.
        with TRACER.span("sim.arrive.stamp") as sspan:
            has_sizecar = self._stamp_durations(created)
            sspan.count("pods", len(has_sizecar))
        if admitter is not None:
            for a in created:
                if a.name not in has_sizecar:
                    continue
                pod_name = f"{a.name}-sizecar"
                # the streaming fast path runs AT arrival (event-driven):
                # eligible interactive work binds here, in wall-clock
                # milliseconds, without waiting for the batch tick
                t0 = time.perf_counter()
                res = self.scheduler.admit(pod_name)
                admit_ms = (time.perf_counter() - t0) * 1e3
                if res.eligible and tick >= warmup:
                    # the latency axis starts after the cold-start
                    # warmup: no window exists before the first solve
                    # and no virtual node is ready before the first
                    # mirror — steady-state latency is the SLO
                    self.quality.note_interactive(a.name)
                    if res.bound:
                        self.quality.note_fastpath_bind(a.name, admit_ms)
                if res.bound:
                    self._fast_bound_tick.append(pod_name)
                    self.quality.note_bound(a.name, tick)
                    self._note(
                        tick, "fastbind", pod_name, ",".join(res.hint)
                    )
        return len(arrivals)

    def _stamp_durations(self, created: list) -> set[str]:
        """Write each arrival's virtual duration into its sizecar's
        demand (``time_limit_s``) — the batched form of the per-pod
        ``replace_update`` stamp. Returns the arrival names whose
        sizecar existed (what the admission loop may admit)."""
        from slurm_bridge_tpu.bridge.freeze import fast_replace, frozen_replace

        table = self.store.table(Pod.KIND)
        has_sizecar: set[str] = set()
        if table is None:
            for a in created:
                pod = self.store.try_get(Pod.KIND, f"{a.name}-sizecar")
                if pod is None:
                    continue
                has_sizecar.add(a.name)
                if pod.spec.demand is None:
                    continue

                def stamp(p: Pod, dur=a.duration_s):
                    return fast_replace(
                        p,
                        meta=fast_replace(p.meta),
                        spec=fast_replace(
                            p.spec,
                            demand=fast_replace(
                                p.spec.demand,
                                time_limit_s=max(1, int(round(dur))),
                            ),
                        ),
                    )

                self.store.replace_update(
                    Pod.KIND, pod.name, stamp, site="sim.arrive"
                )
            return has_sizecar
        c = table.cols
        pod_names: list[str] = []
        expected: list[int] = []
        new_demands: list[object] = []
        stamped_arrivals: list[str] = []
        with self.store.locked():
            rows = table.rows_for([f"{a.name}-sizecar" for a in created])
            for a, row in zip(created, rows.tolist()):
                if row < 0:
                    continue
                has_sizecar.add(a.name)
                demand = c.demand[row]
                if demand is None:
                    continue
                pod_names.append(f"{a.name}-sizecar")
                expected.append(int(c.rv[row]))
                new_demands.append(
                    frozen_replace(
                        demand, time_limit_s=max(1, int(round(a.duration_s)))
                    )
                )
                stamped_arrivals.append(a.name)
        if not pod_names:
            return has_sizecar
        demand_col = np.empty(len(new_demands), object)
        demand_col[:] = new_demands

        def writer(rws, sel):
            c.demand[rws] = demand_col[sel]

        results = self.store.update_rows(
            Pod.KIND,
            pod_names,
            np.asarray(expected, np.int64),
            writer,
            site="sim.arrive",
        )
        for name, dem, rc in zip(stamped_arrivals, new_demands, results.tolist()):
            if rc > 0:
                continue
            # conflict/vanished: the per-pod oracle (same thread, so this
            # is belt-and-braces, not a hot path)
            def stamp(p: Pod, d=dem):
                return fast_replace(
                    p,
                    meta=fast_replace(p.meta),
                    spec=fast_replace(p.spec, demand=d),
                )

            try:
                self.store.replace_update(
                    Pod.KIND, f"{name}-sizecar", stamp, site="sim.arrive"
                )
            except Exception:
                pass
        return has_sizecar

    def _mirror(self) -> None:
        """Partition diff + provider sync + event-driven operator sync —
        the production mirror half of the reconcile loop.

        ISSUE 16 shape: the providers run in shard-ownership GROUPS
        (``shard_mirror`` — each group is one shard's contiguous run of
        the sorted partition list, see ``ShardExecutor.mirror_groups``),
        and within a group each provider's status fetch overlaps the
        next provider's classification on an overlap thread
        (``mirror_pipeline``). Store writes all stay on this thread in
        provider order, the flattened group order IS the sorted order,
        and the owner sweep stays global — so both knobs are
        digest-neutral; with sharding off there is exactly one group
        and the flags-off path is the original sequential mirror,
        byte-for-byte."""
        with TRACER.span("sim.mirror"):
            try:
                self.configurator.reconcile()
            except grpc.RpcError:
                self._rpc_fail("configurator.reconcile")
            partitions = sorted(self.configurator.providers)
            if (
                self.scenario.shard_mirror
                and self.scheduler.shard is not None
            ):
                groups = self.scheduler.shard.mirror_groups(partitions)
            else:
                groups = [partitions] if partitions else []
            pipelined = (
                self.scenario.mirror_pipeline
                and not self.scenario.faults.faults
            )
            # writer-partition stamping (ISSUE 19): when the mirror runs
            # in shard-ownership groups AND frames are on, each group's
            # providers record their dirty names under the group index —
            # mirror_groups IS the writer-partition map. Frames off
            # leaves the stamp at None so the dirty-set stays exactly
            # the PR-18 global per-kind dict.
            stamp_parts = (
                self.scenario.mirror_frames and len(groups) > 1
            )
            for gidx, group in enumerate(groups):
                for partition in group:
                    self.configurator.providers[partition]._dirty_partition = (
                        gidx if stamp_parts else None
                    )
                if pipelined:
                    self._sync_group_pipelined(group)
                else:
                    for partition in group:
                        provider = self.configurator.providers[partition]
                        try:
                            provider.sync()
                        except grpc.RpcError:
                            self._rpc_fail(f"provider.sync:{partition}")
            # drain the pod watch queue and sweep owners of changed
            # pods in batch — exactly what the operator's _pump_events
            # thread does, made synchronous (and therefore
            # deterministic); keys the sweep can't settle go through
            # the single-key oracle, like the pump's controller queue
            # would. ONE global sweep after every group: the sweep's
            # owner iteration (and therefore its uid draw order) must
            # match the global pass byte-for-byte, and a per-group
            # sweep would interleave differently whenever owner names
            # straddle shards
            owners: set[str] = set()
            while True:
                try:
                    ev = self._pod_watch.get_nowait()
                except Exception:
                    break
                self.operator._collect_owner(ev, owners)
            for owner in self.operator.sweep(owners) if owners else ():
                self.operator.reconcile(owner)

    def _sync_group_pipelined(self, group: list[str]) -> None:
        """One mirror group with the status fetch overlapped: provider
        i's chunked JobsInfo round-trips run on the overlap thread while
        provider i+1's prepare (classification + converge + submits)
        runs here. ``sync_staged``'s contract keeps every store write on
        this thread, applies in provider order — the pipeline moves only
        wire-and-decode wait off the critical path. A provider that
        cannot stage (bulk fallback engaged, no bytes twin) drains the
        in-flight fetch first and takes the plain path."""
        pool = self._mirror_fetch_pool()
        parent = TRACER.current()
        pending: tuple[str, object, object] | None = None

        def drain() -> None:
            nonlocal pending
            if pending is None:
                return
            part, apply_fn, fut = pending
            pending = None
            try:
                apply_fn(fut.result())
            except grpc.RpcError:
                self._rpc_fail(f"provider.sync:{part}")

        for partition in group:
            provider = self.configurator.providers[partition]
            try:
                staged = provider.sync_staged()
            except grpc.RpcError:
                self._rpc_fail(f"provider.sync:{partition}")
                continue
            if staged is None:
                drain()
                try:
                    provider.sync()
                except grpc.RpcError:
                    self._rpc_fail(f"provider.sync:{partition}")
                continue
            fetch, apply_fn = staged
            drain()

            def traced_fetch(f=fetch):
                with with_current_span(parent):
                    return f()

            pending = (partition, apply_fn, pool.submit(traced_fetch))
        drain()

    def _mirror_fetch_pool(self):
        """The single overlap thread for the pipelined mirror (lazy —
        non-pipelined runs never start it; torn down with the stack)."""
        if self._mirror_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._mirror_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="sbt-mirror-fetch"
            )
        return self._mirror_pool

    def _free_now(self) -> dict[str, tuple[float, float, float]]:
        out = {}
        for name, node in self.cluster.nodes.items():
            info = node.info()
            free = (
                (float(info.free_cpus), float(info.free_memory_mb), float(info.free_gpus))
                if info.schedulable
                else (0.0, 0.0, 0.0)
            )
            out[name] = free
        return out

    def run_tick(self, tick: int, *, arrivals: bool = True) -> dict[str, float]:
        with self.flight.tick(tick):
            phases = self._run_tick(tick, arrivals=arrivals)
        # pressure ledger (ISSUE 15 sink 2): the solve tick's reason ×
        # partition × class × tenant counts ride the per-tick flight
        # record and the quality scorecard's wait_reasons axis
        ledger = getattr(self.scheduler, "last_explain_ledger", None)
        if ledger is not None:
            self._explain_ledgers.append((tick, ledger))
            self.quality.note_pressure(ledger)
            if self.flight.records:
                self.flight.records[-1]["pressure"] = ledger
        return phases

    def _run_tick(self, tick: int, *, arrivals: bool = True) -> dict[str, float]:
        cpu0 = time.process_time()
        rpc0 = sum(self.agent_client.calls.values())
        ji0 = self.agent_client.calls.get("JobsInfo", 0)
        restarts0 = self._restarts + self._agent_restarts
        fault_boundary = any(
            f.start_tick == tick or f.end_tick == tick
            for f in self.scenario.faults.faults
        )
        if self.faulty is not None:
            self.faulty.set_tick(tick)
        self._agent_faults(tick)
        self._bridge_faults(tick)
        self._apply_fault_boundaries(tick)
        if self.fleet is not None:
            # kill BEFORE the heartbeat so death + re-key land in the
            # same tick deterministically (kill_replica is synchronous)
            for f in self.scenario.faults.starting("kill_replica", tick):
                rid = f.replica or self.fleet.membership.owner_of(0) or ""
                self.fleet.kill_replica(rid)
            self.fleet.heartbeat(tick)
        if self.scheduler.explain_trail is not None:
            self.scheduler.explain_trail.tick = tick
        # store/scheduler may have been replaced by a bridge fault above —
        # snapshot the write/solve baselines on the objects this tick runs
        commits0 = sum(self.store.commit_counts_snapshot().values())
        solves0 = self.scheduler.solves_total

        t0 = time.perf_counter()
        self._fast_bound_tick = []
        with TRACER.span("sim.arrive") as arrive_span:
            n_arrived = self._arrive(tick) if arrivals else 0
            arrive_span.count("arrivals", n_arrived)
            arrive_span.count("fastpath_bound", len(self._fast_bound_tick))
        arrive_ms = (time.perf_counter() - t0) * 1e3
        self._arrive_ms.append(arrive_ms)

        stale = bool(self.scenario.faults.active("stale_snapshot", tick))
        # sim.verify spans: the harness's OWN bookkeeping (ground-truth
        # snapshots, invariant checks, digest notes) — named so the
        # flight record's phase sum reconciles with the tick span at the
        # 500k shape instead of leaving seconds of root self-time blank
        with TRACER.span("sim.verify"):
            free_before = None if stale else self._free_now()
            pods_before = self.store.list(Pod.KIND)
            pre = {
                p.name: (p.spec.placement_hint, p.spec.demand)
                for p in pods_before
                if p.spec.role == PodRole.SIZECAR and p.spec.node_name
            }
            pending_before = self._pending_names(pods_before)

        t1 = time.perf_counter()
        if self._stack_up:
            try:
                self.scheduler.tick()
            except grpc.RpcError:
                self._rpc_fail("scheduler.tick")
        sched_ms = (time.perf_counter() - t1) * 1e3
        phases = dict(self.scheduler.last_phase_ms) if self._stack_up else {}
        # arrival ingest is a first-class tick phase since ISSUE 14 — it
        # was real per-tick work the headline silently excluded
        phases["arrive"] = arrive_ms

        t2 = time.perf_counter()
        if self._stack_up:
            self._mirror()
        phases["mirror"] = (time.perf_counter() - t2) * 1e3
        # anything tick() spent outside its own phase decomposition
        # (RPC-fault aborts, remote skips, future costs) gets its own
        # explicit bucket instead of silently inflating "store"
        accounted = sum(phases.get(k, 0.0) for k in ("store", "encode", "solve", "bind"))
        phases["other"] = max(0.0, sched_ms - accounted)

        with TRACER.span("sim.verify"):
            self.cluster.step()
            self.quality.sample(self.cluster)

            pods = self.store.list(Pod.KIND)
            by_name = {p.name: p for p in pods}
            newly_bound = [
                p for p in pods if p.name in pending_before and p.spec.node_name
            ]
            preempted = [
                name
                for name in pre
                if (cur := by_name.get(name)) is not None
                and not cur.spec.node_name
                and cur.status.reason.startswith("Preempted")
            ]
            released: dict[str, list[float]] = {}
            for name in preempted:
                hints, demand = pre[name]
                if demand is None:
                    continue
                cpu, mem, gpu = per_node_demand(demand)
                for node in hints:
                    u = released.setdefault(node, [0.0, 0.0, 0.0])
                    u[0] += cpu
                    u[1] += mem
                    u[2] += gpu
            # fast-path binds: bound during the arrive phase, so invisible to
            # the pending_before diff — still bound work this tick (counted,
            # and capacity-checked below alongside the batch binds; their
            # quality/digest notes were taken at admit time)
            fast_pods = [
                p
                for n in self._fast_bound_tick
                if (p := by_name.get(n)) is not None and p.spec.node_name
            ]
            self._bound_total += len(newly_bound) + len(fast_pods)
            self._preempted_total += len(preempted)
            for p in newly_bound:
                self.quality.note_bound(p.meta.owner or p.name, tick)
            self.quality.note_preempts(len(preempted))
            for p in sorted(newly_bound, key=lambda p: p.name):
                self._note(tick, "bind", p.name, p.spec.node_name,
                           ",".join(p.spec.placement_hint))
            for name in sorted(preempted):
                self._note(tick, "preempt", name)

            self.violations.extend(
                check_tick(
                    tick,
                    pods,
                    self.cluster,
                    newly_bound=newly_bound + fast_pods,
                    free_before=free_before,
                    released={k: tuple(v) for k, v in released.items()},
                )
            )
            pending_after = len(self._pending_names(pods))
            self._pending_by_tick.append(pending_after)
            self._note(tick, "pending", pending_after, "arrived", n_arrived)
            fault_end = self.scenario.faults.last_end_tick
            if (
                self._recovered_at is None
                and fault_end
                and tick >= fault_end
                and pending_after == 0
                and not self.cluster.pending_jobs()
            ):
                self._recovered_at = tick
            if (
                self._drained_at is None
                and pending_after == 0
                and not self.cluster.pending_jobs()
                and tick >= self.scenario.ticks - 1
            ):
                self._drained_at = tick

        self._drain_node_watch()
        if self.persistence is not None and self._stack_up:
            # tick-boundary durability: everything the control loops
            # committed this tick is WAL-appended before virtual time
            # moves — the state a crash at the NEXT boundary recovers.
            # Timed separately from the phase clock (``wal_flush_ms``):
            # this is where injected fsync latency lands, and folding it
            # into a phase would break the flight-record reconciliation
            t3 = time.perf_counter()
            self.persistence.flush()
            if (tick + 1) % self._COMPACT_EVERY == 0:
                self.persistence.compact()
            self._wal_flush_ms.append((time.perf_counter() - t3) * 1e3)

        tick_ms = sum(phases.get(k, 0.0) for k in PHASES)
        phases["tick"] = tick_ms
        # ---- steady-state verdict (PR-11) ----
        # A tick is STEADY when nothing arrived, bound or was preempted,
        # no fault window opened or closed, no stack restarted, and —
        # the hard part — the whole control plane performed ZERO store
        # commits. steady_tick_p50_ms over these ticks is the O(changes)
        # acceptance number; the bench-smoke gate additionally pins the
        # RPC and solver-invocation budgets per steady tick.
        commits = sum(self.store.commit_counts_snapshot().values()) - commits0
        self._tick_meta.append({
            "tick": tick,
            "arrived": n_arrived,
            "bound": len(newly_bound),
            "preempted": len(preempted),
            "commits": commits,
            "rpc_calls": sum(self.agent_client.calls.values()) - rpc0,
            "jobsinfo_calls": self.agent_client.calls.get("JobsInfo", 0) - ji0,
            "solves": self.scheduler.solves_total - solves0,
            "steady": (
                tick > 0
                and self._stack_up
                and n_arrived == 0
                and not newly_bound
                and not preempted
                and commits == 0
                and not fault_boundary
                and self._restarts + self._agent_restarts == restarts0
            ),
            "tick_ms": tick_ms,
        })
        # CPU seconds actually burned this tick (whole run_tick, including
        # the arrive/invariant bookkeeping outside the phase clock):
        # divergence between this and wall time is noisy-neighbor steal,
        # which otherwise masquerades as a perf regression in diagnostics
        phases["cpu"] = (time.process_time() - cpu0) * 1e3
        _tick_seconds.observe(tick_ms / 1e3)
        self._tick_phases.append(phases)
        self.vt += self.scenario.tick_interval_s
        return phases

    def _final_state_digest(self) -> str:
        """SHA-256 over the run's FINAL logical state — bindings,
        placements, lifecycle outcomes — on both sides of the wire
        (bridge store AND sim ground truth). This is the recovery
        acceptance digest: a crash-restart run must end byte-identical
        to the fault-free run at the same seed. Volatile fields (rvs,
        heartbeats, run_time ticks, free-text reasons) are excluded —
        they carry process history, not cluster state."""
        pods = [
            (
                p.name,
                p.spec.node_name,
                p.status.phase,
                list(p.status.job_ids),
                list(p.spec.placement_hint),
                p.meta.owner,
                bool(p.meta.deleted),
            )
            for p in self.store.list(Pod.KIND)
        ]
        jobs = [
            (
                j.name,
                j.status.state,
                [
                    (k, int(s.state), s.exit_code)
                    for k, s in sorted(j.status.subjobs.items())
                ],
            )
            for j in self.store.list(BridgeJob.KIND)
        ]
        nodes = sorted(n.name for n in self.store.list(VirtualNode.KIND))
        sim = sorted(
            (int(jid), int(j.state), sorted(j.assigned))
            for jid, j in self.cluster.jobs.items()
        )
        payload = json.dumps(
            {"pods": pods, "jobs": jobs, "nodes": nodes, "sim": sim},
            sort_keys=True,
        )
        return hashlib.sha256(payload.encode()).hexdigest()

    def _final_outcome_digest(self) -> str:
        """SHA-256 over the run's final LIFECYCLE outcomes — the
        id/placement-insensitive sibling of :meth:`_final_state_digest`.

        Composed chaos (a crash inside an ``rpc_error`` window) can
        legitimately delay a submission past the window: the job then
        draws a later Slurm id and possibly different nodes than the
        crash-free twin, so byte-identical *state* is unachievable even
        though nothing was lost. What MUST still hold — and what this
        digest captures — is that every pod reaches the same phase with
        a job behind it, every CR ends in the same state with the same
        subjob-state multiset, every sim-side job (by name) reaches the
        same terminal state, and the node set matches. Numeric job ids,
        node assignments and volatile fields are excluded by design."""
        pods = sorted(
            (
                p.name,
                p.status.phase,
                p.meta.owner,
                bool(p.meta.deleted),
                bool(p.status.job_ids),
            )
            for p in self.store.list(Pod.KIND)
        )
        jobs = sorted(
            (
                j.name,
                j.status.state,
                sorted(int(s.state) for s in j.status.subjobs.values()),
            )
            for j in self.store.list(BridgeJob.KIND)
        )
        nodes = sorted(n.name for n in self.store.list(VirtualNode.KIND))
        sim = sorted(
            (j.name, int(j.state)) for j in self.cluster.jobs.values()
        )
        payload = json.dumps(
            {"pods": pods, "jobs": jobs, "nodes": nodes, "sim": sim},
            sort_keys=True,
        )
        return hashlib.sha256(payload.encode()).hexdigest()

    def _cleanup(self) -> None:
        if self.fleet is not None:
            # before the rmtree below: sidecars hold sockets + the
            # membership WAL inside the state dir
            self.fleet.close()
            self.fleet = None
        if self.agent_journal is not None:
            self.agent_journal.close()
        if self._mirror_pool is not None:
            self._mirror_pool.shutdown(wait=False)
            self._mirror_pool = None
        if self._state_dir is not None:
            shutil.rmtree(self._state_dir, ignore_errors=True)
            self._state_dir = None
        # reap the process-wide colpool workers (ISSUE 18): run() is
        # finally-guarded, so a scenario raising MID-TICK still joins the
        # forked workers and closes their pipe fds instead of leaking
        # them until atexit; the next run lazily re-forks. close() is
        # idempotent/lock-free, so racing atexit or a nested reset is
        # safe. (Deliberately NOT in _teardown_stack — the crash-fault
        # path restarts the bridge stack, not the process, and keeps its
        # warm workers.)
        colpool.reset()
        colpool.set_obs(True)  # restore the process default obs arm

    # ---- the full run ----

    def _progress(self, tick: int, phases: dict[str, float]) -> None:
        if not self.scenario.slow:
            return
        import sys

        print(
            f"# tick {tick}: {phases.get('tick', 0.0):.0f} ms "
            f"(store {phases.get('store', 0.0):.0f} / encode "
            f"{phases.get('encode', 0.0):.0f} / solve "
            f"{phases.get('solve', 0.0):.0f} / bind "
            f"{phases.get('bind', 0.0):.0f} / mirror "
            f"{phases.get('mirror', 0.0):.0f} / other "
            f"{phases.get('other', 0.0):.0f}; cpu "
            f"{phases.get('cpu', 0.0):.0f}), pending "
            f"{self._pending_by_tick[-1] if self._pending_by_tick else 0}",
            file=sys.stderr,
            flush=True,
        )

    def run(self) -> ScenarioResult:
        # finally-guarded so a raising run (invariant failure, store
        # conflict) still reclaims the snapshot+WAL state tempdir
        try:
            return self._run()
        finally:
            self._cleanup()

    def _run(self) -> ScenarioResult:
        sc = self.scenario
        # GC policy (PR-4): a cold-start tick allocates ~100k long-lived
        # store objects while ~600k are already live, and CPython's
        # generational collector re-scans that heap dozens of times per
        # tick — measured at HALF the whole tick at the 50k×10k headline
        # shape. Collection moves BETWEEN ticks: refcounting frees the
        # non-cyclic ~100% in-line (store graphs are trees — ownership is
        # by name, not pointer), and the explicit collect catches any
        # cycle stragglers off the reconcile latency path. gc.freeze()
        # keeps the baseline heap (trace, cluster, JAX) out of scans.
        # Purely a scheduling change for identical work: determinism is
        # untouched, and `make sim-smoke`'s double-run proves it.
        was_enabled = gc.isenabled()
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            tick = 0
            for tick in range(sc.ticks):
                self._progress(tick, self.run_tick(tick))
                gc.collect()
            grace_used = 0
            while (
                grace_used < sc.drain_grace_ticks
                and self._drained_at is None
            ):
                tick += 1
                grace_used += 1
                self._progress(tick, self.run_tick(tick, arrivals=False))
                gc.collect()
        finally:
            gc.unfreeze()
            if was_enabled:
                gc.enable()
        total_ticks = tick + 1
        self._drain_node_watch()

        if sc.expect_drain:
            self.violations.extend(
                check_drain(
                    tick,
                    self._pending_by_tick[-1] if self._pending_by_tick else 0,
                    len(self.cluster.pending_jobs()),
                    expect_drain=True,
                )
            )

        jobs = self.cluster.jobs.values()
        providers = self.configurator.providers.values()
        determinism = {
            "bound_total": self._bound_total,
            # pods submitted through the batched SubmitJobs path vs the
            # per-pod fallback: a silent fallback to the slow path shows
            # up here instead of only as a latency regression
            "submits_batched": sum(p.submits_batched for p in providers),
            "submits_fallback": sum(p.submits_fallback for p in providers),
            "preempted_total": self._preempted_total,
            "preempt_events": self._preempt_events,
            "events": dict(sorted(self._event_counts.items())),
            "sim": self.cluster.stats.as_dict(),
            "pending_final": self._pending_by_tick[-1] if self._pending_by_tick else 0,
            "sim_running_final": sum(
                1 for j in jobs if j.state == JobStatus.RUNNING
            ),
            "sim_pending_final": sum(
                1 for j in jobs if j.state == JobStatus.PENDING
            ),
            "rpc_failures": dict(sorted(self.rpc_failures.items())),
            "injected_errors": dict(
                sorted(self.faulty.injected_errors.items())
            )
            if self.faulty is not None
            else {},
            # bounded-retry healing (PR-8): attempts retried per method —
            # the difference between injected_errors and rpc_failures is
            # exactly what the retry layer absorbed
            "rpc_retries": dict(sorted(self.retrier.retries.items()))
            if self.retrier is not None
            else {},
            "invariant_violations": [v.as_dict() for v in self.violations],
            "recovery_ticks": (
                self._recovered_at - sc.faults.last_end_tick
                if self._recovered_at is not None and sc.faults
                else None
            ),
            "drained_at_tick": self._drained_at,
            "grace_ticks_used": grace_used,
            # crash/failover robustness (PR-7): restart count, node-flap
            # detector, lease history, and the final-state digest the
            # crash scenario compares against its fault-free twin
            "restarts": self._restarts,
            "vnode_deletions": self.vnode_deletions,
            "leader_takeover_ticks": list(self._takeover_ticks),
            "leader_final": (
                self._active_elector.identity
                if self._active_elector is not None
                else ""
            ),
            # composed chaos (PR-8): agent-side crash/reload count, the
            # object/job counts each recovery restored (deterministic —
            # tick-boundary state is), and the outcome digest the
            # composed-fault twin gate compares when id/placement
            # reshuffles make byte-identical state unachievable
            "agent_restarts": self._agent_restarts,
            "restored_objects": list(self._restored_objects),
            "agent_restored_jobs": list(self._agent_restored_jobs),
            "final_state_digest": self._final_state_digest(),
            "final_outcome_digest": self._final_outcome_digest(),
            "digest": self._digest.hexdigest(),
        }
        if self.scheduler.shard is not None:
            # sharded-tick aggregates (plan size, routing, reconcile
            # outcomes, rank-locality) are id-keyed and deterministic —
            # they ride the determinism section so the double-run gate
            # covers the fan-out, and the shard-smoke gate reads them
            determinism["shard"] = self.scheduler.shard.stats()
        if self.scheduler.admission is not None:
            # streaming-admission aggregates (attempts/binds/misses by
            # reason) are decision facts, fully virtual-deterministic —
            # the admission-smoke double-run gate covers the fast path
            determinism["admission"] = self.scheduler.admission.stats()
        if self.fleet is not None:
            # membership facts only (replica count, rekeys, expiries,
            # kills, recovery) — deterministic on virtual time, so they
            # ride the byte-compared section. Transport counters (remote
            # vs inline solves) are OS-scheduling-volatile and ride the
            # quality section instead (policy_extra["fleet_remote"])
            determinism["fleet"] = self.fleet.stats()
        phase_arr = {
            k: np.asarray([p.get(k, 0.0) for p in self._tick_phases])
            for k in (*PHASES, "tick", "cpu")
        }
        timing = {
            "tick_p50_ms": round(float(np.median(phase_arr["tick"])), 3),
            "tick_p95_ms": round(float(np.percentile(phase_arr["tick"], 95)), 3),
            "tick_max_ms": round(float(phase_arr["tick"].max()), 3),
            "tick_cpu_p50_ms": round(float(np.median(phase_arr["cpu"])), 3),
            "phases_p50_ms": {
                k: round(float(np.median(phase_arr[k])), 3) for k in PHASES
            },
            "phases_p95_ms": {
                k: round(float(np.percentile(phase_arr[k], 95)), 3) for k in PHASES
            },
            "arrive_p50_ms": round(float(np.median(self._arrive_ms)), 3),
            # the steady-state headline (PR-11): tick p50 over ticks in
            # which nothing arrived/bound/preempted/faulted and the
            # control plane wrote NOTHING — the cost of observing an
            # unchanged cluster, which the incremental tick drives to
            # O(changes). None = the run never reached a steady tick.
            "steady_tick_p50_ms": (
                round(
                    float(np.median(
                        [m["tick_ms"] for m in self._tick_meta if m["steady"]]
                    )),
                    3,
                )
                if any(m["steady"] for m in self._tick_meta)
                else None
            ),
            "steady_ticks": sum(1 for m in self._tick_meta if m["steady"]),
            # view-materialization pressure (PR-6): frozen views built /
            # commits through the columnar row path over the whole run,
            # so re-anchors can see whether reads are eating the columnar
            # win without re-running the flight recorder
            "decoded_views_total": self.store.view_builds_total(),
            "rows_written_total": self.store.rows_written_total(),
            "injected_latency_ms": round(
                self.faulty.injected_latency_ms, 3
            )
            if self.faulty is not None
            else 0.0,
            # recovery cost (PR-8): wall ms per stack/agent reload —
            # what the slow full_50kx10k_crash scenario proves bounded
            # at the headline shape
            "recovery_ms": [round(v, 3) for v in self._recovery_ms],
            # the tick-boundary WAL flush+compact cost, where injected
            # fsync latency lands (outside the phase clock by design)
            "wal_flush_p50_ms": round(
                float(np.median(self._wal_flush_ms)), 3
            )
            if self._wal_flush_ms
            else 0.0,
            "wal_flush_p95_ms": round(
                float(np.percentile(self._wal_flush_ms, 95)), 3
            )
            if self._wal_flush_ms
            else 0.0,
            # WAL pressure (timing, not determinism: a VirtualNode
            # heartbeat rides wall time, so record counts can wiggle):
            # records appended + snapshots compacted across the run,
            # summed over every bridge incarnation
            "wal_records_total": self._wal_records_prior
            + (
                self.persistence.wal_records_total
                if self.persistence is not None
                else 0
            ),
            "wal_snapshots_total": self._snapshots_prior
            + (
                self.persistence.snapshots_written
                if self.persistence is not None
                else 0
            ),
            # agent journal pressure (PR-8): records appended + fsyncs
            # issued (the group-commit ratio shows up here under the
            # real agent; the sim journal runs fsync-off)
            "agent_journal_records_total": (
                self.agent_journal.records_total
                if self.agent_journal is not None
                else 0
            ),
            "agent_journal_snapshots_total": (
                self.agent_journal.snapshots_written
                if self.agent_journal is not None
                else 0
            ),
        }
        shape = {
            "pods": sum(len(t) for t in self.trace),
            "nodes": sc.cluster.num_nodes,
            "partitions": sc.cluster.num_partitions,
            "ticks": total_ticks,
        }
        policy_extra = {"policy": "off"}
        if self.policy_engine is not None:
            policy_extra = {
                "policy": "on",
                "backfill_binds": self.policy_engine.backfill_binds_total,
                "preempt_pool_last": self.policy_engine.pool_size_last,
                "preempt_pool_excluded_last": (
                    self.policy_engine.pool_excluded_last
                ),
            }
        if self.scheduler.shard is not None:
            # the rank-locality score + reconcile outcomes belong on the
            # quality scorecard: they are placement-quality facts of the
            # sharded tick (ISSUE 10 acceptance)
            policy_extra["shard"] = self.scheduler.shard.stats()
        if self.scheduler.admission is not None:
            # fast-path miss attribution (ISSUE 15 satellite): why
            # eligible arrivals fell through to the batch tick — the
            # admission-side half of the wait_reasons story
            policy_extra["admission_misses"] = dict(
                sorted(self.scheduler.admission.misses.items())
            )
        if self.fleet is not None:
            # volatile transport counters (remote solves vs inline
            # fallbacks depend on OS scheduling of real subprocesses) —
            # quality section only; the fleet smoke asserts
            # remote_solves > 0 here so a silently-inline run fails
            policy_extra["fleet_remote"] = self.fleet.remote_stats()
        flight_record = self.flight.aggregate()
        if self.fleet is not None and self.scenario.fleet_obs:
            # ISSUE 20: lifecycle timeline + federated per-replica
            # counters ride the flight record (volatile, never digested)
            # so scenario JSON and /debug/fleetz read the same story
            flight_record["fleet"] = self.fleet.fleet_section()
        result = ScenarioResult(
            scenario=sc,
            determinism=determinism,
            timing=timing,
            shape=shape,
            quality=self.quality.scorecard(total_ticks, extra=policy_extra),
            flight_record=flight_record,
            flight_ticks=list(self.flight.records),
        )
        return result


def run_scenario(scenario: Scenario) -> ScenarioResult:
    return SimHarness(scenario).run()
