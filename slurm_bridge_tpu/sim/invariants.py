"""Invariant checks run after every simulated tick.

Four families (ISSUE 2 acceptance):

- **no double-bind** — a sizecar pod is bound to at most one virtual
  node, carries hints iff bound, and no Slurm job id is owned by two
  pods;
- **gang atomicity** — a bound ``nodes=k`` job holds exactly ``k``
  distinct placement hints (all-or-nothing admission);
- **capacity never oversubscribed** — ground truth first (the
  :class:`SimCluster` raises on any allocation past capacity; re-checked
  here), plus a solver-level check that the demand newly bound this tick
  fits the free capacity the scheduler solved against (skipped inside a
  ``stale_snapshot`` window, where binding past *current* truth is the
  expected, queue-absorbed behaviour — the sim agent queues what no
  longer fits, so ground truth still holds);
- **eventual drain** — scenario-end check (harness): once arrivals stop
  and faults clear, the pending queue empties within the drain grace.

Violations are collected, not raised: a scenario reports every breach in
its deterministic metrics section and the smoke gate fails on any.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from slurm_bridge_tpu.bridge.objects import Pod, PodRole
from slurm_bridge_tpu.core.arrays import array_len
from slurm_bridge_tpu.core.scontrol import parse_gres_gpus
from slurm_bridge_tpu.core.types import JobDemand
from slurm_bridge_tpu.obs.metrics import REGISTRY
from slurm_bridge_tpu.sim.agent import SimCluster

_violations_total = REGISTRY.counter(
    "sbt_sim_invariant_violations_total",
    "simulator invariant breaches detected after a tick",
)


@dataclass(frozen=True)
class Violation:
    tick: int
    invariant: str
    detail: str

    def as_dict(self) -> dict:
        return {"tick": self.tick, "invariant": self.invariant, "detail": self.detail}


def per_node_demand(demand: JobDemand) -> tuple[float, float, float]:
    """(cpus, mem_mb, gpus) per placement shard — the encode_jobs sizing
    rule (solver/snapshot.py), gres being a per-node quantity."""
    arr = array_len(demand.array) if demand.array else 1
    nshards = max(1, demand.nodes)
    cpu = demand.total_cpus(arr) / nshards
    mem = cpu * float(demand.mem_per_cpu_mb or 1024.0)
    gpus = float(parse_gres_gpus(demand.gres)[0] if demand.gres else 0) * max(1, arr)
    return cpu, mem, gpus


def _sizecars(pods: list[Pod]) -> list[Pod]:
    return [p for p in pods if p.spec.role == PodRole.SIZECAR and not p.meta.deleted]


def check_tick(
    tick: int,
    pods: list[Pod],
    cluster: SimCluster,
    *,
    newly_bound: list[Pod] | None = None,
    free_before: dict[str, tuple[float, float, float]] | None = None,
    released: dict[str, tuple[float, float, float]] | None = None,
) -> list[Violation]:
    """All post-tick checks; ``free_before``/``released`` enable the
    solver-level fit check for this tick's fresh bindings."""
    out: list[Violation] = []
    sizecars = _sizecars(pods)

    # ---- no double-bind ----
    owners: dict[int, str] = {}
    for p in sizecars:
        if p.spec.node_name and not p.spec.placement_hint:
            out.append(
                Violation(tick, "no_double_bind", f"{p.name} bound without hints")
            )
        if p.spec.placement_hint and not p.spec.node_name:
            out.append(
                Violation(tick, "no_double_bind", f"{p.name} hinted but unbound")
            )
        for jid in p.status.job_ids:
            if jid in owners:
                out.append(
                    Violation(
                        tick,
                        "no_double_bind",
                        f"job {jid} owned by {owners[jid]} and {p.name}",
                    )
                )
            owners[jid] = p.name

    # ---- gang atomicity ----
    for p in sizecars:
        d = p.spec.demand
        if d is None or not p.spec.node_name:
            continue
        k = max(1, d.nodes)
        hints = p.spec.placement_hint
        if len(hints) != k or len(set(hints)) != k:
            out.append(
                Violation(
                    tick,
                    "gang_atomicity",
                    f"{p.name} wants {k} nodes, hints {hints!r}",
                )
            )

    # ---- capacity never oversubscribed (ground truth) ----
    usage: dict[str, list[float]] = {
        name: [0.0, 0.0, 0.0] for name in cluster.nodes
    }
    for job in cluster.running_jobs():
        for node in job.assigned:
            u = usage[node]
            u[0] += job.cpus_per_node
            u[1] += job.mem_per_node_mb
            u[2] += job.gpus_per_node
    for name, node in cluster.nodes.items():
        u = usage[name]
        if (
            node.base_alloc_cpus + u[0] > node.cpus + 1e-6
            or node.base_alloc_memory_mb + u[1] > node.memory_mb + 1e-6
            or u[2] > node.gpus + 1e-6
        ):
            out.append(
                Violation(
                    tick,
                    "capacity",
                    f"node {name} oversubscribed: {u} over "
                    f"({node.cpus},{node.memory_mb},{node.gpus})",
                )
            )

    # ---- solver-level fit of this tick's fresh bindings ----
    if newly_bound and free_before is not None:
        bound_usage: dict[str, list[float]] = {}
        for p in newly_bound:
            d = p.spec.demand
            if d is None:
                continue
            cpu, mem, gpu = per_node_demand(d)
            for node in p.spec.placement_hint:
                u = bound_usage.setdefault(node, [0.0, 0.0, 0.0])
                u[0] += cpu
                u[1] += mem
                u[2] += gpu
        for node, u in bound_usage.items():
            free = free_before.get(node)
            if free is None:
                out.append(
                    Violation(
                        tick, "capacity", f"bound to unknown node {node!r}"
                    )
                )
                continue
            rel = (released or {}).get(node, (0.0, 0.0, 0.0))
            have = [free[i] + rel[i] for i in range(3)]
            if any(u[i] > have[i] + 1e-3 for i in range(3)):
                out.append(
                    Violation(
                        tick,
                        "capacity",
                        f"tick bindings oversubscribe {node}: "
                        f"need {u}, free {have}",
                    )
                )

    if out:
        _violations_total.inc(len(out))
    return out


def check_drain(
    tick: int, pending_pods: int, sim_pending: int, *, expect_drain: bool
) -> list[Violation]:
    """Scenario-end drain check: the scheduler queue AND the simulated
    Slurm queue must both be empty once arrivals stop and faults clear."""
    if not expect_drain:
        return []
    out = []
    if pending_pods:
        out.append(
            Violation(
                tick, "eventual_drain", f"{pending_pods} pods still pending"
            )
        )
    if sim_pending:
        out.append(
            Violation(
                tick,
                "eventual_drain",
                f"{sim_pending} slurm jobs still queued",
            )
        )
    if out:
        _violations_total.inc(len(out))
    return out
