import sys

from slurm_bridge_tpu.sim.cli import main

sys.exit(main())
