"""The residual free_after view the fast path admits against.

One :class:`ResidualView` holds the capacity picture the last batch
solve left behind — ``placement.free_after`` after backfill, on the
tick's global node axis — plus the snapshot columns (partition codes,
feature masks, node names) needed to answer "where does one shard of
this demand fit" without any RPC or re-encode.

The view is maintained **incrementally**: the batch tick re-bases it
once per solve (``begin_window``), and every fast-path bind between
ticks subtracts its demand in place (``apply_bind``). It is never
rebuilt per admission — an admission is a masked vector compare over
the partition's nodes, O(partition), typically microseconds.

Staleness discipline: between SOLVE rebases the window only ever
*understates* free capacity — a fit in the view is a fit in the model
the guarded backfill would have used, the conservative direction — and
a miss falls through to the normal pending scan untouched. Since ISSUE
15 (ROADMAP streaming-admission follow-up c) an idle cluster's window
is additionally maintained from the provider's periodic inventory probe
(:meth:`~slurm_bridge_tpu.admission.fastpath.FastPathAdmitter.rebase_from_inventory`),
so capacity freed by completions re-opens to the fast path without
waiting for a solve that — with nothing pending — would never come; the
scheduler gates that path to ticks where no solve re-based the window,
keeping the solve's own residual authoritative.
"""

from __future__ import annotations

import numpy as np

from slurm_bridge_tpu.policy.engine import feasible_nodes


class ResidualView:
    """Residual free capacity on the last solve's node axis."""

    def __init__(self) -> None:
        #: the ClusterSnapshot(-shaped) window: node_names, partition_of,
        #: features, partition_codes, feature_codes — shared read-only
        #: with the encoder caches; only ``free`` below is owned
        self.snapshot = None
        #: [N, 3] float32 residual free (cpu, mem, gpu) — OWNED copy,
        #: mutated in place by fast-path binds
        self.free: np.ndarray | None = None
        #: bumped per re-base — observability + staleness assertions
        self.generation = 0
        #: fast-path binds applied since the last re-base
        self.binds_since_window = 0

    @property
    def ready(self) -> bool:
        return self.free is not None

    def begin_window(self, snapshot, free_after: np.ndarray) -> None:
        """Re-base on a fresh solve's post-backfill residual. The copy
        is the view's entire per-tick cost — everything else is shared
        by reference with the solve's own snapshot."""
        self.snapshot = snapshot
        self.free = np.array(free_after, np.float32, copy=True)
        self.generation += 1
        self.binds_since_window = 0

    def feasible(self, d: np.ndarray, part: int, req: int) -> np.ndarray:
        """Boolean node mask for one shard of ``d`` — the same
        :func:`policy.engine.feasible_nodes` rule guarded backfill uses."""
        s = self.snapshot
        return feasible_nodes(self.free, s.partition_of, s.features, d, part, req)

    def apply_bind(self, positions: list[int], d: np.ndarray) -> None:
        """Subtract one shard of ``d`` on each chosen node position —
        the one-shot form of the debit
        :meth:`~slurm_bridge_tpu.admission.fastpath.FastPathAdmitter.admit`
        performs node-by-node DURING its guard walk (the guard must
        read each take before choosing the next node, so the admitter
        cannot batch through this method); kept as the maintenance seam
        for external window owners and the equivalence oracle
        (tests/test_admission.py). Both forms share the same invariant:
        ``free == base - Σ outstanding takes``."""
        for n in positions:
            self.free[n] -= d
        self.binds_since_window += 1

    def release(self, positions: list[int], d: np.ndarray) -> None:
        """Roll back one bind's debit (the store-bind conflict path —
        the admitter pairs it with restoring the guard bookkeeping)."""
        for n in positions:
            self.free[n] += d
        self.binds_since_window -= 1
