"""The fast-path binder: eligibility → route → tight fit → guard → bind.

One :class:`FastPathAdmitter` sits between arrival and the periodic
solve. The scheduler re-bases it after every batch tick
(:meth:`begin_window`: the solve's post-backfill residual plus the
unplaced-gang backlog to protect); between ticks, each interactive
arrival gets one :meth:`admit` call:

1. **eligibility** — the pod's priority class (PR-9 table, the same
   resolution the policy engine uses) must be in
   ``AdmissionConfig.interactive_classes`` and its gang small enough
   (``nodes ≤ max_gang_nodes``): production/system singles and small
   gangs ride the fast path, bulk batch work stays on the solve;
2. **route** — the single-job form of the PR-10 shard router: with a
   shard plan attached, the gang goes WHOLE to the one shard of its
   partition with the most feasible residual capacity (ties to the
   lowest shard id — deterministic), so fast-path gangs keep the same
   no-shard-straddling contract the batch fan-out enforces;
3. **tight fit** — feasible nodes ordered tightest-fit first (least cpu
   headroom after placement), exactly backfill's node-choice rule;
4. **no-delay guard** — a take is rejected if it would shrink the
   feasible node set of any protected (unplaced, equal-or-higher-class,
   currently-feasible) gang below its size: the fast path can never
   delay the batch backlog's feasible starts. The guard bookkeeping is
   line-for-line the ``policy.engine.PlacementPolicy.backfill`` guard —
   the fuzzed oracle in tests/test_admission.py holds the two together;
5. **bind** — the caller commits the store write; on a commit conflict
   the reservation rolls back (:meth:`rollback`).

Misses fall through to the normal pending scan untouched, and the
periodic solve may later preempt fast-path placements under the
existing bounded-preemption rules — a fast-path pod is an ordinary
bound pod from the batch tick's point of view.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

from slurm_bridge_tpu.admission.residual import ResidualView
from slurm_bridge_tpu.obs.metrics import REGISTRY, Histogram
from slurm_bridge_tpu.policy.classes import (
    DEFAULT_CLASSES,
    ClassTable,
    PriorityClass,
)
from slurm_bridge_tpu.solver.snapshot import job_scalars

_attempts = REGISTRY.counter(
    "sbt_admission_attempts_total",
    "fast-path admission attempts (eligible arrivals)",
)
_binds = REGISTRY.counter(
    "sbt_admission_binds_total", "arrivals bound via the fast path"
)
_misses = REGISTRY.counter(
    "sbt_admission_misses_total",
    "fast-path misses that fell through to the batch tick, by reason",
)
_latency = REGISTRY.histogram(
    "sbt_admission_latency_seconds",
    "wall time of one fast-path admission attempt",
    buckets=Histogram.FAST_BUCKETS,
)


@dataclass(frozen=True)
class AdmissionConfig:
    """Declarative streaming-admission knobs — frozen + tuple-valued so
    a :class:`~slurm_bridge_tpu.sim.harness.Scenario` can carry one."""

    #: classes whose arrivals ride the fast path (PR-9 table names)
    interactive_classes: tuple[str, ...] = ("production", "system")
    #: "singles and small gangs": a gang asking for more nodes than this
    #: goes to the batch solve (big gangs want the solver's packing)
    max_gang_nodes: int = 4
    #: class table used when no policy engine is attached (a scheduler
    #: WITH a policy resolves through the policy's own table, so the two
    #: can never disagree about a pod's class)
    classes: tuple[PriorityClass, ...] = DEFAULT_CLASSES
    default_class: str = "batch"
    #: distinct nodes tried per shard before giving up — backfill's
    #: ``backfill_node_tries`` for the fast path
    node_tries: int = 8
    #: sim-harness knob: arrivals in the first N ticks are excluded from
    #: the latency scorecard (no window exists before the first solve
    #: and no virtual node is ready before the first mirror — cold-start
    #: placement is the batch tick's job, the latency SLO is steady-state)
    latency_warmup_ticks: int = 2


@dataclass(frozen=True)
class AdmitResult:
    """One admission attempt's outcome."""

    eligible: bool
    #: chosen node names (placement hint) when bound, else ()
    hint: tuple[str, ...] = ()
    #: miss reason when eligible but not bound: no_window | not_ready |
    #: unknown_partition | no_fit | guard | conflict
    reason: str = ""

    @property
    def bound(self) -> bool:
        return bool(self.hint)


class FastPathAdmitter:
    """Streaming-admission state for one scheduler."""

    def __init__(self, config: AdmissionConfig | None = None, *, policy=None):
        self.config = config or AdmissionConfig()
        self.table: ClassTable = (
            policy.table
            if policy is not None
            else ClassTable(
                self.config.classes, default=self.config.default_class
            )
        )
        self._interactive_ranks = {
            self.table.rank_of(self.table.by_name[name])
            for name in self.config.interactive_classes
            if name in self.table.by_name
        }
        self.view = ResidualView()
        #: serializes every window/deduction mutation: ``admit()`` is an
        #: ARRIVAL-time entry (event-driven, off the tick thread in a
        #: real bridge), so the residual debits, guard bookkeeping and
        #: deduction map must not race the tick's prune/subtract/re-base
        #: seams. Lock ordering: this lock is taken OUTSIDE the store
        #: lock (admit's bind and the prune's column reads nest inside).
        self.lock = threading.Lock()
        #: shard plan of the window (None = monolithic tick)
        self._plan = None
        #: protected unplaced gangs, backfill-shaped records:
        #: {need, rank, d, part(code), req, mask, count}
        self.protected: list[dict] = []
        #: pod name → (hint names, per-shard demand vec) for fast-path
        #: binds not yet visible in the agent inventory — the batch solve
        #: subtracts these so it cannot double-claim the capacity
        self.deductions: dict[str, tuple[tuple[str, ...], np.ndarray]] = {}
        #: (window snapshot ref) → node name → position memo, built
        #: lazily on the first inventory rebase of a window
        self._pos_memo: tuple | None = None
        #: whether a provider inventory report may currently maintain
        #: the window. Flipped UNDER :attr:`lock`, in lockstep with the
        #: window itself: ``begin_window`` (a solve re-base) forbids it
        #: — a provider probes BEFORE converging its submits, so on
        #: solve ticks its view predates the tick's binds — and the
        #: scheduler re-allows it on ticks no solve re-based the window
        #: (idle / steady-skip). Because gate and re-base are evaluated
        #: under the same lock as the window install, a probe racing a
        #: concurrent solve either lands before ``begin_window`` (and
        #: is overwritten by the fresh residual) or after (and is
        #: refused) — never on top of a fresher window.
        self._inventory_ok = False
        # ---- run accounting (scheduler/harness observability) ----
        self.attempts_total = 0
        self.binds_total = 0
        self.misses: dict[str, int] = {}
        #: inventory re-bases that actually moved the view (ROADMAP
        #: streaming-admission follow-up c)
        self.inventory_rebases = 0

    # ---- eligibility ----

    def eligibility_rank(self, labels, demand) -> int | None:
        """The pod's class rank when fast-path eligible, else None."""
        if demand is None:
            return None
        if max(1, demand.nodes) > self.config.max_gang_nodes:
            return None
        cls = self.table.resolve(labels)
        rank = self.table.rank_of(cls)
        return rank if rank in self._interactive_ranks else None

    # ---- the per-tick window ----

    def begin_window(self, snapshot, free_after, backlog, *, plan=None) -> None:
        """Re-base the residual view on a fresh solve and rebuild the
        protected-gang set. ``backlog`` is the tick's unplaced pending
        work as ``(demand, class_rank)`` pairs; only multi-shard gangs
        feasible NOW are protected — exactly backfill's contract (a gang
        already infeasible cannot be delayed by a fast-path take).
        Serialized against concurrent arrivals via :attr:`lock`."""
        with self.lock:
            self._begin_window_locked(snapshot, free_after, backlog, plan)

    def _begin_window_locked(self, snapshot, free_after, backlog, plan) -> None:
        self._inventory_ok = False
        self.view.begin_window(snapshot, free_after)
        self._plan = plan
        self.protected = []
        for demand, rank in backlog:
            cpu, mem, gpu, part, req, need, _prio = job_scalars(
                demand, snapshot
            )
            if need <= 1 or part < 0:
                continue
            d = np.asarray([cpu, mem, gpu], np.float32)
            mask = self.view.feasible(d, part, req)
            count = int(mask.sum())
            if count < need:
                continue
            self.protected.append(
                {
                    "need": need,
                    "rank": rank,
                    "d": d,
                    "part": part,
                    "req": int(req),
                    "mask": mask,
                    "count": count,
                }
            )

    def allow_inventory_rebase(self) -> None:
        """Re-open the window to inventory maintenance — called by the
        scheduler on ticks NO solve re-based the window (the idle early
        return and the steady-bind skip). Lock-serialized against
        ``begin_window``, which forbids it again (see
        :attr:`_inventory_ok` for the race analysis)."""
        with self.lock:
            self._inventory_ok = True

    def rebase_from_inventory(self, nodes, *, skip_nodes=None) -> int:
        """Maintain the window from a periodic inventory probe (ROADMAP
        streaming-admission follow-up c): an IDLE cluster re-bases only
        on solve ticks, so capacity freed by completions stayed invisible
        to the fast path until the next solve — which, with nothing
        pending, never comes. The provider's per-tick Nodes probe already
        carries the truth; this folds it into the residual view:

        - each reported node's free capacity replaces the view's row,
          MINUS the outstanding in-flight fast-bind deductions on that
          node (those binds are not agent-visible yet);
        - nodes in ``skip_nodes`` keep the window's own (conservative)
          value untouched — the scheduler passes the hint nodes of
          store-BOUND pods whose submission has not reached the agent
          yet (``job_ids`` still empty): the agent reports their
          capacity free, but the window's solve residual already
          committed it, and raising those rows would let the fast path
          double-claim a batch bind in flight;
        - protected-gang masks/counts recompute against the refreshed
          free, so the no-delay guard keeps judging current feasibility.

        Gated by :attr:`_inventory_ok` UNDER the lock (set by
        :meth:`allow_inventory_rebase`, cleared by ``begin_window``), so
        a probe racing a concurrent solve can never clobber a fresher
        window. Returns the number of view rows that moved.
        """
        with self.lock:
            view = self.view
            if not view.ready or not self._inventory_ok:
                return 0
            snap = view.snapshot
            memo = self._pos_memo
            if memo is None or memo[0] is not snap:
                memo = self._pos_memo = (
                    snap, {n: i for i, n in enumerate(snap.node_names)}
                )
            idx = memo[1]
            ded: dict[str, np.ndarray] = {}
            for _nm, (hint, d) in self.deductions.items():
                for h in hint:
                    prev = ded.get(h)
                    ded[h] = d.copy() if prev is None else prev + d
            touched = 0
            for nd in nodes:
                if skip_nodes and nd.name in skip_nodes:
                    continue
                pos = idx.get(nd.name)
                if pos is None:
                    continue
                if nd.schedulable:
                    f = np.asarray(
                        [nd.free_cpus, nd.free_memory_mb, nd.free_gpus],
                        np.float32,
                    )
                else:
                    f = np.zeros(3, np.float32)
                sub = ded.get(nd.name)
                if sub is not None:
                    f = np.maximum(f - sub, 0.0)
                if not np.array_equal(view.free[pos], f):
                    view.free[pos] = f
                    touched += 1
            if touched:
                self.inventory_rebases += 1
                for g in self.protected:
                    mask = view.feasible(g["d"], g["part"], g["req"])
                    g["mask"] = mask
                    g["count"] = int(mask.sum())
        return touched

    # ---- in-flight deduction bookkeeping ----

    def drop_deduction(self, name: str) -> None:
        self.deductions.pop(name, None)

    def deduction_signature(self) -> tuple:
        """Solve-memo key component: the in-flight fast binds the batch
        solve subtracts (a dropped deduction must invalidate the warm
        start even when nothing else moved)."""
        with self.lock:
            return tuple(
                (n, hint, d.tobytes())
                for n, (hint, d) in sorted(self.deductions.items())
            )

    def deductions_copy(self) -> dict:
        """A consistent snapshot of the in-flight deductions for the
        solve to subtract — the solve must not iterate the live map
        while an arrival commits into it."""
        with self.lock:
            return dict(self.deductions)

    # ---- the admission attempt ----

    def _route(self, fit_mask: np.ndarray, partition: str, need: int):
        """Candidate node positions for one gang — the single-job form
        of the PR-10 shard router: the gang goes whole to the one shard
        of its partition with the most feasible residual capacity."""
        plan = self._plan
        if plan is None:
            return np.nonzero(fit_mask)[0]
        sids = plan.part_shards.get(partition)
        if not sids:
            return np.nonzero(fit_mask)[0]
        best = None
        best_key = None
        for sid in sids:
            members = plan.members.get((sid, partition))
            if members is None:
                continue
            pos = members[fit_mask[members]]
            key = (pos.size >= need, int(pos.size), -sid)
            if best_key is None or key > best_key:
                best_key, best = key, pos
        if best is None:
            return np.nonzero(fit_mask)[0]
        return np.sort(best)

    # NOTE: miss_only / admit / note_bound / rollback are called by the
    # scheduler's arrival entry UNDER :attr:`lock` (one critical section
    # covering reserve → store bind → commit-or-rollback); they do not
    # re-acquire it themselves.

    def miss_only(self, reason: str) -> str:
        """Count an attempt that missed before reaching :meth:`admit`
        (e.g. the caller's virtual-node ready check)."""
        self.attempts_total += 1
        _attempts.inc()
        return self._miss(reason)

    def admit(self, demand, rank: int):
        """One guarded admission attempt against the residual view.

        Returns ``(node_names, miss_reason, token)`` — names empty on a
        miss. On success the residual is already debited and ``token``
        holds the reservation; the CALLER commits the store bind, then
        either :meth:`note_bound` (committed) or :meth:`rollback`
        (conflict) with that token.
        """
        self.attempts_total += 1
        _attempts.inc()
        if not self.view.ready:
            return (), self._miss("no_window"), None
        snapshot = self.view.snapshot
        cpu, mem, gpu, part, req, need, _prio = job_scalars(demand, snapshot)
        if part < 0:
            return (), self._miss("unknown_partition"), None
        # admit at the workload manager's INTEGRAL per-node granularity:
        # Slurm allocates whole cpus/MBs per node (ceil of the gang's
        # per-shard spread), while the solver's float model divides
        # evenly. Rounding up keeps the residual view truthful against
        # allocations the window cannot see yet — and a ceil-accept is
        # strictly conservative, so it is also a float-model (guarded
        # backfill) accept.
        d = np.ceil(np.asarray([cpu, mem, gpu], np.float32))
        free = self.view.free
        fit_mask = self.view.feasible(d, part, req)
        cands = self._route(fit_mask, demand.partition, need)
        if cands.size < need:
            return (), self._miss("no_fit"), None
        # tightest fit first: least cpu headroom after placement — the
        # backfill node-choice rule, stable so ties stay deterministic
        cands = cands[np.argsort(free[cands, 0] - d[0], kind="stable")]
        chosen: list[int] = []
        hits: list = []  # (protected gang, node) feasibility reductions
        guard_blocked = False
        limit = max(need, self.config.node_tries)
        for n in cands[:limit].tolist():
            # the no-delay guard — policy.backfill's predicate with one
            # strengthening: feasibility BOOKKEEPING runs for EVERY
            # protected gang (a higher-class candidate's takes update a
            # lower-class gang's mask too, so counts never go stale),
            # while the VETO stays class-scoped — only an equal-or-
            # higher-class gang that is still feasible may block a take.
            # Strictly more conservative than backfill's incremental
            # masks, so every fast accept is still a backfill accept.
            bad = False
            n_hits = []
            for g in self.protected:
                if not g["mask"][n]:
                    continue
                if not (free[n] - d >= g["d"]).all():
                    if (
                        g["rank"] >= rank
                        and g["count"] >= g["need"]  # dead gangs don't veto
                        and g["count"] - 1 < g["need"]
                    ):
                        bad = True
                        break
                    n_hits.append(g)
            if bad:
                guard_blocked = True
                continue
            free[n] -= d
            for g in n_hits:
                g["mask"] = g["mask"].copy()
                g["mask"][n] = False
                g["count"] -= 1
            hits.extend((g, n) for g in n_hits)
            chosen.append(n)
            if len(chosen) == need:
                break
        if len(chosen) < need:
            # all-or-nothing: roll the tentative takes back (restoring
            # free restores exactly the feasibility the takes removed)
            for n in chosen:
                free[n] += d
            for g, n in hits:
                g["mask"] = g["mask"].copy()
                g["mask"][n] = True
                g["count"] += 1
            return (), self._miss("guard" if guard_blocked else "no_fit"), None
        self.view.binds_since_window += 1
        names = tuple(snapshot.node_names[i] for i in chosen)
        return names, "", (chosen, d, hits)

    def note_bound(self, name: str, hint: tuple[str, ...], token) -> None:
        """The store bind committed: remember the in-flight deduction
        until the pod's submission is visible agent-side."""
        _chosen, d, _hits = token
        self.binds_total += 1
        _binds.inc()
        self.deductions[name] = (hint, d)

    def rollback(self, token) -> None:
        """The store bind conflicted: release the reservation — the
        residual free AND the protected-gang bookkeeping the takes
        decremented (restoring only free would leave the guard counting
        a still-feasible gang as partially starved for the rest of the
        window)."""
        chosen, d, hits = token
        self.view.release(chosen, d)
        for g, n in hits:
            g["mask"] = g["mask"].copy()
            g["mask"][n] = True
            g["count"] += 1
        self._miss("conflict")

    def _miss(self, reason: str) -> str:
        self.misses[reason] = self.misses.get(reason, 0) + 1
        _misses.inc(reason=reason)
        return reason

    def observe_latency(self, seconds: float) -> None:
        _latency.observe(seconds)

    def stats(self) -> dict:
        """Deterministic run aggregates (scenario determinism section)."""
        return {
            "attempts": self.attempts_total,
            "binds": self.binds_total,
            "misses": dict(sorted(self.misses.items())),
            "inventory_rebases": self.inventory_rebases,
        }
