"""Streaming admission — the always-on fast path next to the batch tick.

ISSUE 12 tentpole. The periodic solve batches everything, so a single
interactive pod waits a full tick period (seconds at the 500k×100k
shape) before placement even *looks* at it. This package is the
architectural split every planet-scale scheduler makes: a low-latency
admission path for interactive singles and small gangs, with the batch
tick demoted to the repair/repack pass behind it.

- :mod:`admission.residual` — the per-node **residual free_after view**:
  the capacity picture left by the last batch solve, maintained
  incrementally off bind commits (never rebuilt per admission);
- :mod:`admission.fastpath` — the event-driven binder: eligibility via
  the PR-9 priority-class table (production/system singles and small
  gangs), shard routing via the PR-10 plan, tight-fit node choice under
  backfill's no-delay guard — a fast-path bind may never shrink an
  unplaced equal-or-higher-class gang's feasible node set below its
  size, so the fast path can never starve the batch backlog.

``PlacementScheduler(admission=None)`` — the default — is the PR-11
tick byte-for-byte (fixture-pinned); everything here runs only when an
:class:`AdmissionConfig` is attached.
"""

from slurm_bridge_tpu.admission.fastpath import (
    AdmissionConfig,
    AdmitResult,
    FastPathAdmitter,
)
from slurm_bridge_tpu.admission.residual import ResidualView

__all__ = [
    "AdmissionConfig",
    "AdmitResult",
    "FastPathAdmitter",
    "ResidualView",
]
