"""Mesh construction for the sharded solver.

The solve's two big axes map onto a 2-D device mesh:
- ``dp`` shards the *pods* axis (the "sequence" of pending work — the
  SP-style axis called out in SURVEY.md §5 "Long-context");
- ``mp`` shards the *nodes* axis (the model/capacity axis).

Intra-slice these collectives ride ICI; across slices jax.distributed +
DCN carry the same program (the gRPC control plane stays on the host —
SURVEY.md §2.9).
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh


def solver_mesh(
    devices: list | None = None,
    *,
    dp: int | None = None,
    mp: int | None = None,
) -> Mesh:
    """Build a 2-D ("dp", "mp") mesh over the given (default: all) devices.

    Without explicit factors, devices are split as square as possible with
    the larger factor on "dp" (the pods axis usually dwarfs the nodes axis).
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if dp is None and mp is None:
        mp = 1
        for f in range(int(math.isqrt(n)), 0, -1):
            if n % f == 0:
                mp = f
                break
        dp = n // mp
    elif dp is None:
        if n % mp:
            raise ValueError(f"mp={mp} does not divide {n} devices")
        dp = n // mp
    elif mp is None:
        if n % dp:
            raise ValueError(f"dp={dp} does not divide {n} devices")
        mp = n // dp
    if dp * mp != n:
        raise ValueError(f"dp×mp = {dp}×{mp} != {n} devices")
    arr = np.asarray(devs).reshape(dp, mp)
    return Mesh(arr, axis_names=("dp", "mp"))


def pad_to_multiple(x: np.ndarray, multiple: int, *, axis: int = 0, value=0):
    """Pad ``x`` along ``axis`` to the next multiple; returns (padded, orig_len)."""
    size = x.shape[axis]
    target = ((size + multiple - 1) // multiple) * multiple if size else multiple
    if target == size:
        return x, size
    pad_width = [(0, 0)] * x.ndim
    pad_width[axis] = (0, target - size)
    return np.pad(x, pad_width, constant_values=value), size
