"""Device-mesh and sharding helpers for the solver's distributed path."""

from slurm_bridge_tpu.parallel.mesh import solver_mesh, pad_to_multiple

__all__ = ["solver_mesh", "pad_to_multiple"]
