"""Write-side column ops for the colpool workers (ISSUE 18).

PR 15/16 made the cold tick's *read* side parallel (worker-pool
decode+diff); what remained single-threaded were the two write loops the
roadmap names: the submit fan-out's per-request proto encode and the
operator sweep's per-owner demand/label build. Both are pure functions
of immutable inputs, so they ship to the forked colpool workers the same
way the decode op does — raw little-endian column frames in, raw frames
out, no object graph crossing a pipe in either direction.

Two ops live here (dispatched by :mod:`~slurm_bridge_tpu.parallel.colpool`):

``_OP_ENCODE_SUBMIT``
    parent packs effective (demand, submitter) rows into one frame per
    submit chunk (:func:`pack_submit_frame`); the worker emits the
    serialized ``SubmitJobsRequest`` wire bytes for the chunk
    (:func:`encode_submit_frame`) — byte-identical to pb2
    ``SerializeToString`` by way of
    :func:`~slurm_bridge_tpu.wire.convert.encode_submit_entry`, so the
    agent sees exactly the bytes the serial arm would have sent.

``_OP_BUILD_ROWS``
    parent packs sizecar-create spec columns
    (:func:`pack_build_chunk`); the worker runs the #SBATCH header
    parse + spec-override resolution of ``operator.demand_for_spec``
    and returns the resolved demand scalars plus the request-cpu /
    request-memory-mb label strings (:func:`build_rows_frame` /
    :func:`unpack_build_result`). The parent keeps everything with
    side effects — ``frozen_new`` demand construction, uid draws, the
    locked ``create_rows`` scatter — so store commit order stays
    byte-identical to the serial sweep.

Dependency-light on purpose: core + wire only, no bridge imports — the
workers fork from whatever the parent has loaded, and this module must
be importable inside them without dragging the store/controller stack.
"""

from __future__ import annotations

import struct
from functools import lru_cache

import numpy as np

from slurm_bridge_tpu.core.arrays import array_len
from slurm_bridge_tpu.core.sbatch import extract_batch_resources
from slurm_bridge_tpu.wire.convert import _T_REQUESTS, encode_submit_entry
from slurm_bridge_tpu.wire.coldec import uvarint

__all__ = [
    "pack_submit_frame",
    "encode_submit_frame",
    "pack_build_chunk",
    "build_rows_frame",
    "unpack_build_result",
]

_Q = struct.Struct("<q")

#: JobDemand fields shipped as int64 columns in a submit frame, in frame
#: order (run_as_user/run_as_group are ``or 0``-normalized on pack, the
#: same coalescing :func:`~slurm_bridge_tpu.wire.convert.fill_submit_request`
#: applies)
_SUBMIT_I64 = (
    "run_as_user", "run_as_group", "cpus_per_task", "ntasks",
    "ntasks_per_node", "nodes", "mem_per_cpu_mb", "time_limit_s",
    "priority",
)
#: JobDemand string fields shipped as packed str columns, frame order
_SUBMIT_STR = (
    "script", "partition", "array", "job_name", "working_dir",
    "gres", "licenses",
)

#: BridgeJobSpec fields a build frame ships (the sweep's inputs to
#: ``demand_for_spec``); resolution against the #SBATCH header happens
#: in the worker
_BUILD_STR = ("sbatch_script", "partition", "array", "working_dir", "gres")
_BUILD_I64 = ("cpus_per_task", "ntasks", "ntasks_per_node", "nodes", "mem_per_cpu_mb")

#: resolved columns a build result frame carries back, frame order
_BUILT_STR = ("partition", "array", "working_dir", "gres", "request_cpu", "request_mem")
_BUILT_I64 = (
    "cpus_per_task", "ntasks", "ntasks_per_node", "nodes",
    "mem_per_cpu_mb", "time_limit_s",
)


# ---- frame primitives --------------------------------------------------


def _pack_scol(vals: list[str]) -> bytes:
    """One str column: payload length, int64 per-row lengths, utf8 payload."""
    bs = [s.encode("utf-8") for s in vals]
    lens = np.fromiter(map(len, bs), np.int64, len(bs))
    payload = b"".join(bs)
    return _Q.pack(len(payload)) + lens.tobytes() + payload


def _unpack_scol(buf, off: int, n: int) -> tuple[list[str], int]:
    (plen,) = _Q.unpack_from(buf, off)
    off += 8
    lens = np.frombuffer(buf, np.int64, n, off)
    off += n * 8
    payload = bytes(buf[off : off + plen])
    out = []
    pos = 0
    for ln in lens.tolist():
        out.append(payload[pos : pos + ln].decode("utf-8"))
        pos += ln
    return out, off + plen


def _pack_icol(vals, n: int) -> bytes:
    return np.fromiter(vals, np.int64, n).tobytes()


def _unpack_icol(buf, off: int, n: int) -> tuple[list[int], int]:
    return np.frombuffer(buf, np.int64, n, off).tolist(), off + n * 8


# ---- _OP_ENCODE_SUBMIT -------------------------------------------------


def pack_submit_frame(rows: list[tuple]) -> bytes:
    """Effective submit rows → one request frame. ``rows`` are
    ``(demand, submitter_id)`` pairs AFTER the converge pass's filtering
    and hint substitution (``vnode._submit_rows``) — the frame carries
    exactly what the wire request will say, nothing derived remains."""
    n = len(rows)
    dems = [r[0] for r in rows]
    parts = [_Q.pack(n)]
    parts.append(_pack_scol([r[1] for r in rows]))
    for name in _SUBMIT_STR:
        parts.append(_pack_scol([getattr(d, name) for d in dems]))
    for name in _SUBMIT_I64:
        parts.append(_pack_icol(
            ((getattr(d, name) or 0) for d in dems), n))
    counts = [len(d.nodelist) for d in dems]
    parts.append(_pack_icol(counts, n))
    flat = [h for d in dems for h in d.nodelist]
    parts.append(_Q.pack(len(flat)))
    parts.append(_pack_scol(flat))
    return b"".join(parts)


def encode_submit_frame(buf) -> bytes:
    """Worker side of ``_OP_ENCODE_SUBMIT``: unpack one submit frame and
    emit the chunk's serialized ``SubmitJobsRequest`` — the request-order
    concatenation of length-delimited field-1 entries, each built by the
    fuzz-pinned :func:`encode_submit_entry`."""
    (n,) = _Q.unpack_from(buf, 0)
    off = 8
    submitter, off = _unpack_scol(buf, off, n)
    scols = {}
    for name in _SUBMIT_STR:
        scols[name], off = _unpack_scol(buf, off, n)
    icols = {}
    for name in _SUBMIT_I64:
        icols[name], off = _unpack_icol(buf, off, n)
    counts, off = _unpack_icol(buf, off, n)
    (total,) = _Q.unpack_from(buf, off)
    off += 8
    flat, off = _unpack_scol(buf, off, total)
    out = []
    pos = 0
    for i in range(n):
        c = counts[i]
        body = encode_submit_entry(
            scols["script"][i],
            scols["partition"][i],
            submitter[i],
            icols["run_as_user"][i],
            icols["run_as_group"][i],
            icols["cpus_per_task"][i],
            icols["ntasks"][i],
            icols["ntasks_per_node"][i],
            icols["nodes"][i],
            icols["mem_per_cpu_mb"][i],
            scols["array"][i],
            scols["job_name"][i],
            scols["working_dir"][i],
            scols["gres"][i],
            scols["licenses"][i],
            icols["time_limit_s"][i],
            icols["priority"][i],
            flat[pos : pos + c],
        )
        pos += c
        out += (_T_REQUESTS, uvarint(len(body)), body)
    return b"".join(out)


# ---- _OP_BUILD_ROWS ----------------------------------------------------


@lru_cache(maxsize=512)
def _parsed_header(script: str):
    """The worker's own memo of ``operator._parsed_header`` — same
    source function, same cache shape, but a per-process cache: a forked
    worker cannot see the parent's lru entries, and the storm's handful
    of distinct script bodies makes both hit-dominated."""
    return extract_batch_resources(script).demand


def pack_build_chunk(creates: list[tuple]) -> bytes:
    """One sizecar-create chunk → a request frame. ``creates`` are the
    sweep's captured ``(owner, spec, job labels)`` triples; only the
    spec columns the demand resolution reads ride the wire — owner,
    labels and the residual spec fields (run_as_user, licenses,
    priority, …) stay with the parent, which re-attaches them when it
    rebuilds the frozen demand."""
    n = len(creates)
    specs = [s for _o, s, _l in creates]
    parts = [_Q.pack(n)]
    for name in _BUILD_STR:
        parts.append(_pack_scol([getattr(s, name) for s in specs]))
    for name in _BUILD_I64:
        parts.append(_pack_icol(
            ((getattr(s, name) or 0) for s in specs), n))
    return b"".join(parts)


def build_rows_frame(buf) -> bytes:
    """Worker side of ``_OP_BUILD_ROWS``: run ``demand_for_spec``'s
    header-parse + override chain per row and return the resolved
    scalars, plus the request-cpu / request-memory-mb label strings
    (``JobDemand.total_cpus`` / ``total_mem_mb`` over the resolved array
    length — pod.go:143-187's sizing rule). Field-for-field equality
    with the serial ``demand_for_spec`` is fuzz-pinned."""
    (n,) = _Q.unpack_from(buf, 0)
    off = 8
    scols = {}
    for name in _BUILD_STR:
        scols[name], off = _unpack_scol(buf, off, n)
    icols = {}
    for name in _BUILD_I64:
        icols[name], off = _unpack_icol(buf, off, n)
    out: dict[str, list] = {name: [] for name in _BUILT_STR}
    iout: dict[str, list] = {name: [] for name in _BUILT_I64}
    for i in range(n):
        hdr = _parsed_header(scols["sbatch_script"][i])
        partition = scols["partition"][i] or hdr.partition
        array = scols["array"][i] or hdr.array
        cpus_per_task = icols["cpus_per_task"][i] or hdr.cpus_per_task or 1
        ntasks = icols["ntasks"][i] or hdr.ntasks or 1
        ntasks_per_node = icols["ntasks_per_node"][i] or hdr.ntasks_per_node
        nodes = icols["nodes"][i] or hdr.nodes or 1
        working_dir = scols["working_dir"][i] or hdr.working_dir
        mem_per_cpu_mb = icols["mem_per_cpu_mb"][i] or hdr.mem_per_cpu_mb or 1024
        gres = scols["gres"][i] or hdr.gres
        arr = array_len(array)
        total_cpus = max(1, cpus_per_task) * max(1, ntasks) * max(1, arr)
        out["partition"].append(partition)
        out["array"].append(array)
        out["working_dir"].append(working_dir)
        out["gres"].append(gres)
        out["request_cpu"].append(str(total_cpus))
        out["request_mem"].append(str(mem_per_cpu_mb * total_cpus))
        iout["cpus_per_task"].append(cpus_per_task)
        iout["ntasks"].append(ntasks)
        iout["ntasks_per_node"].append(ntasks_per_node)
        iout["nodes"].append(nodes)
        iout["mem_per_cpu_mb"].append(mem_per_cpu_mb)
        iout["time_limit_s"].append(hdr.time_limit_s)
    parts = [_Q.pack(n)]
    for name in _BUILT_STR:
        parts.append(_pack_scol(out[name]))
    for name in _BUILT_I64:
        parts.append(_pack_icol(iout[name], n))
    return b"".join(parts)


def unpack_build_result(buf) -> dict[str, list]:
    """Parent side of ``_OP_BUILD_ROWS``: one result frame → resolved
    columns (plain Python lists — str and int, ready for ``frozen_new``)."""
    (n,) = _Q.unpack_from(buf, 0)
    off = 8
    cols: dict[str, list] = {}
    for name in _BUILT_STR:
        cols[name], off = _unpack_scol(buf, off, n)
    for name in _BUILT_I64:
        cols[name], off = _unpack_icol(buf, off, n)
    return cols
