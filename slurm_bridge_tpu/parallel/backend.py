"""Hang-proof JAX backend acquisition for production daemons.

A broken/unreachable accelerator must degrade the control plane, never
wedge it: JAX backend initialisation can hang indefinitely (observed with
a tunneled TPU whose setup stalls), and once a thread is stuck inside the
init lock the whole process is poisoned — no other thread can reach a
backend either. So the decision is made *before* any in-process
initialisation, with the risky probe in a subprocess: a wedged init dies
with the child, and the parent falls back to the CPU platform via a config
update (which beats env/sitecustomize pins as long as nothing initialised
yet).

``ensure_backend()`` is called by every solver entry point
(:class:`~slurm_bridge_tpu.solver.session.DeviceSolver`,
:func:`~slurm_bridge_tpu.solver.auction.auction_place`,
:func:`~slurm_bridge_tpu.solver.sharded.sharded_place`) — once per
process; subsequent calls return the cached decision.

Operator override: ``SBT_BACKEND=cpu`` skips the probe and pins CPU;
``SBT_BACKEND=trust`` skips the probe and trusts whatever JAX picks
(restoring pre-probe behavior when the accelerator is known-good).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading

log = logging.getLogger("sbt.backend")

_decided: str | None = None
_lock = threading.Lock()


def _force_cpu() -> None:
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        import jax.extend.backend

        jax.extend.backend.clear_backends()
        jax.config.update("jax_platforms", "cpu")


def _backends_initialized() -> bool:
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:  # private-API drift: assume not initialised
        return False


def _probe_subprocess(timeout: float) -> str:
    """Ask a child process which backend JAX would pick. Empty = failed."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except (subprocess.TimeoutExpired, OSError):
        return ""
    if out.returncode != 0 or not out.stdout.strip():
        return ""
    return out.stdout.strip().splitlines()[-1]


def ensure_backend(probe_timeout: float = 60.0) -> str:
    """Decide (once) which JAX backend this process uses; returns its name.

    Never blocks longer than ``probe_timeout`` + CPU init time, even when
    the accelerator's PJRT plugin hangs during setup.
    """
    global _decided
    with _lock:
        if _decided is not None:
            return _decided

        import jax

        forced = os.environ.get("SBT_BACKEND", "").lower()
        if forced == "cpu":
            _force_cpu()
            _decided = "cpu"
            return _decided
        if _backends_initialized():
            _decided = jax.default_backend()  # someone chose already; safe
            return _decided
        platforms = str(
            getattr(jax.config, "jax_platforms", None)
            or os.environ.get("JAX_PLATFORMS", "")
        )
        if platforms == "cpu":
            _decided = "cpu"  # already pinned (tests, forced envs)
            return _decided
        if forced == "trust":
            _decided = jax.default_backend()
            return _decided

        # The availability watcher's verdict short-circuits the probe: a
        # chip on record as dead (≥2 recent consecutive failures,
        # utils/chipstate.py) would otherwise burn the FULL probe budget
        # inside whatever called us first — measured in round 5 as a 60 s
        # stall inside the first scheduler tick of a cold bridge. The
        # state is advisory and ages out, so a revived chip is re-probed
        # within the staleness window; SBT_BACKEND still overrides both.
        try:
            from slurm_bridge_tpu.utils import chipstate
        except Exception:  # noqa: BLE001 — state is advisory
            chipstate = None
        if chipstate is not None:
            try:
                if chipstate.chip_known_dead():
                    log.warning(
                        "chip watcher records the accelerator DEAD — "
                        "pinning CPU without probing (SBT_BACKEND overrides)"
                    )
                    _force_cpu()
                    _decided = "cpu"
                    return _decided
            except Exception:  # noqa: BLE001
                pass

        name = _probe_subprocess(probe_timeout)
        if chipstate is not None and name and name != "cpu":
            # record SUCCESS only: it resets the failure count when the
            # chip revives. Failures stay the watcher's call — this
            # probe's '' is ambiguous (spawn error, broken venv, 60 s of
            # host load), and two such non-chip misses within the
            # staleness window would falsely certify the chip dead for
            # every consumer of the shared state.
            try:
                chipstate.record(True, f"backend probe acquired {name}")
            except Exception:  # noqa: BLE001
                pass
        if name:
            _decided = name
            return _decided
        log.warning(
            "accelerator backend probe failed or hung (>%.0fs) — "
            "falling back to CPU; set SBT_BACKEND=trust to skip the probe",
            probe_timeout,
        )
        _force_cpu()
        _decided = "cpu"
        return _decided


def reset_for_tests() -> None:
    global _decided
    with _lock:
        _decided = None
