"""Process worker pool for the bulk-decode cold path (ISSUE 16).

The mirror's dominant cold-tick cost is no longer protobuf parsing —
:mod:`~slurm_bridge_tpu.wire.coldec` already vectorized that — it is
that every chunk's NumPy decode still runs on the ONE interpreter the
classification, diff and write machinery also needs. This module moves
the per-chunk ``coldec`` work into forked worker processes and ships the
resulting columns back **pickle-free**: each response is one raw-bytes
frame of concatenated little-endian column buffers (``np.frombuffer``
on the receive side — no object graph crosses the pipe in either
direction; the request frame is the wire blob itself).

Topology: N fork()ed workers, one duplex pipe each, fed round-robin by
index so chunk → worker assignment is deterministic. Results merge in
REQUEST order regardless of completion order — the decoded columns are
byte-identical to the serial path's by construction, and the fuzz suite
(``tests/test_colpool.py``) holds pool ≡ serial over randomized protos.

Sizing: ``SBT_COLPOOL_WORKERS`` pins the width (0 disables); otherwise
the pool takes ``cores - 1`` from the CPU affinity mask, so a 1-core
box (or a constrained cgroup) degrades to the inline serial path with
zero pool overhead — the serial oracle is not a fallback mode, it IS
the pool at width 0. Fork is required (the workers inherit the coldec
tables by address); platforms without it also degrade to width 0.

Failure posture: a malformed blob raises :class:`coldec.DecodeError`
in the worker and is re-raised per-chunk in the parent — exactly the
serial path's per-chunk fallback contract. An infrastructure failure
(worker death, torn pipe) permanently disables the pool for the
process and decodes the remaining chunks inline; it can never corrupt
a column, only cost the speedup.

Write side (ISSUE 18): two more ops run the cold path's remaining
single-thread loops in the workers — ``_OP_ENCODE_SUBMIT`` serializes
``SubmitJobsRequest`` chunk bytes from demand columns, and
``_OP_BUILD_ROWS`` resolves the operator sweep's sizecar demand/label
scalars. The frames live in :mod:`~slurm_bridge_tpu.parallel.writeops`;
a payload failure on either op (a malformed array spec, say) reports
per-chunk like a DecodeError and sends the CALLER back to its serial
arm — which re-raises the real exception in context — without breaking
the pool. Infrastructure failures break the pool exactly as on the
decode side: remembered, inline from then on.

Partitioned commit (ISSUE 19): ``_OP_DIFF_FRAMES`` extends the diff op —
the worker that decoded+diffed a chunk also packs the commit frame
(:func:`colstore.build_commit_frame`) for the chunk's changed rows, so
the tier-2 string spans the store commit will need arrive pre-sliced
with the decode instead of being materialized on the main thread. A
frame-build failure inside the worker degrades to a frameless chunk
(the parent materializes spans as before) — never an error.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import struct
import threading
import time

import numpy as np

from slurm_bridge_tpu.obs.metrics import REGISTRY
from slurm_bridge_tpu.wire import coldec

__all__ = [
    "ColPool",
    "active_pool",
    "configured_width",
    "decode_serial",
    "diff_signals",
    "empty_prior",
    "reset",
    "set_obs",
]

log = logging.getLogger("sbt.colpool")

_OP_DECODE = 0x01
_OP_SET_PRIOR = 0x02
_OP_DECODE_DIFF = 0x03
_OP_ENCODE_SUBMIT = 0x04
_OP_BUILD_ROWS = 0x05
_OP_DIFF_FRAMES = 0x06
_OP_METRICS = 0x07
_ST_OK = 0x00
_ST_DECODE_ERR = 0x01
_ST_ERROR = 0x02

#: the write-side ops: request body and reply body are writeops frames
_WRITE_OPS = (_OP_ENCODE_SUBMIT, _OP_BUILD_ROWS)

#: op byte → metric/span label
_OP_NAMES = {
    _OP_DECODE: "decode",
    _OP_SET_PRIOR: "set_prior",
    _OP_DECODE_DIFF: "decode_diff",
    _OP_ENCODE_SUBMIT: "encode_submit",
    _OP_BUILD_ROWS: "build_rows",
    _OP_DIFF_FRAMES: "diff_frames",
    _OP_METRICS: "metrics",
}

#: request framing (ISSUE 20): op byte + the parent's monotonic_ns send
#: stamp. CLOCK_MONOTONIC is system-wide on Linux and the workers are
#: fork()ed on the same host, so worker recv stamp − this = queue wait.
_REQ = struct.Struct("<Bq")
_REQ_OFF = _REQ.size
#: reply timing header (ISSUE 20): queue-wait ns, op ns, body bytes in,
#: body bytes out — fixed width, after the status byte on EVERY reply
#: (errors included), so the parent strips it unconditionally.
_THDR = struct.Struct("<qqqq")
_RESP_OFF = 1 + _THDR.size

# -- parent-side worker self-timing (folded from the reply headers) ------

_busy_seconds = REGISTRY.counter(
    "sbt_colpool_worker_busy_seconds_total",
    "worker-side op compute time by op, from the reply timing headers",
)
_queue_wait_seconds = REGISTRY.counter(
    "sbt_colpool_queue_wait_seconds_total",
    "request time spent queued in worker pipes, by op",
)
_bytes_total = REGISTRY.counter(
    "sbt_colpool_bytes_total",
    "frame payload bytes through the pool, by op and direction",
)
_chunks_total = REGISTRY.counter(
    "sbt_colpool_chunks_total", "chunks served by the pool, by op"
)

#: parent-side fold switch (ISSUE 20): headers always ride the frames —
#: the workers need no config — but metric/span folding can be disabled
#: (the paired profile_fleet_obs_overhead off-arm).
_OBS_ENABLED = True


def set_obs(enabled: bool) -> None:
    """Enable/disable parent-side folding of worker timing headers into
    metrics + synthetic ``colpool.<op>`` spans. Digest-neutral either
    way; the off-arm exists for the paired overhead profile."""
    global _OBS_ENABLED
    _OBS_ENABLED = bool(enabled)


class _OpStats:
    """Per-batch accumulator for reply timing headers (thread-safe: the
    fan-out threads all add to the one batch's stats)."""

    __slots__ = ("queue_ns", "op_ns", "bytes_in", "bytes_out", "chunks", "_lock")

    def __init__(self):
        self.queue_ns = 0
        self.op_ns = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.chunks = 0
        self._lock = threading.Lock()

    def add(self, queue_ns: int, op_ns: int, bi: int, bo: int) -> None:
        with self._lock:
            self.queue_ns += queue_ns
            self.op_ns += op_ns
            self.bytes_in += bi
            self.bytes_out += bo
            self.chunks += 1


def _fold_op(label: str, stats: _OpStats, wall_s: float) -> None:
    """Fold one batch's worker timing into the parent's metrics and — when
    an ambient sampled span is open (the flight recorder's per-tick
    window) — a synthetic ``colpool.<label>`` child span whose duration is
    the summed worker busy time. Queue wait, wall time and byte volumes
    ride as counters; nothing here enters determinism digests."""
    if not _OBS_ENABLED or stats.chunks == 0:
        return
    busy_s = stats.op_ns / 1e9
    queue_s = stats.queue_ns / 1e9
    _busy_seconds.inc(busy_s, op=label)
    _queue_wait_seconds.inc(queue_s, op=label)
    _bytes_total.inc(float(stats.bytes_in), op=label, direction="in")
    _bytes_total.inc(float(stats.bytes_out), op=label, direction="out")
    _chunks_total.inc(float(stats.chunks), op=label)
    from slurm_bridge_tpu.obs.tracing import TRACER

    parent = TRACER.current()
    if parent is not None and parent.sampled:
        TRACER.emit_synthetic(
            f"colpool.{label}",
            parent=parent,
            duration_s=busy_s,
            counters={
                "chunks": float(stats.chunks),
                "queue_wait_ms": queue_s * 1e3,
                "wall_ms": wall_s * 1e3,
                "bytes_in": float(stats.bytes_in),
                "bytes_out": float(stats.bytes_out),
            },
        )

#: response-frame column order for the fixed int64 block (length = rows
#: each); must match JobsInfoChunk's numeric slots
_I64_COLS = (
    "jid", "id", "state", "start_ts", "limit",
    "submit_ts", "run_time", "num_nodes",
)
#: lazy string-span fields, in frame order (matches coldec's tier-2 set)
_SPAN_COLS = tuple(name for name, _ in coldec._INFO_STR_FIELDS)

#: header: version, rows, exit-payload bytes, reason-payload bytes
_HDR = struct.Struct("<qqqq")

#: signal columns the diff op compares (the mirror's tier-1 contract —
#: keep in lockstep with bridge/vnode.py's _SIGNAL_DIFF_COLS)
_DIFF_I64 = ("id", "state", "start_ts", "limit")
_DIFF_STR = ("exit_code", "reason")


# ---- frame pack/unpack (shared by worker and parent) -------------------


def _pack_str_col(col: np.ndarray) -> tuple[bytes, bytes]:
    """(lens int64 buffer, utf8 payload) for one object str column."""
    bs = [s.encode("utf-8") for s in col.tolist()]
    lens = np.fromiter(map(len, bs), np.int64, len(bs))
    return lens.tobytes(), b"".join(bs)


def _unpack_str_col(buf, off: int, rows: int, payload_len: int):
    """Inverse of :func:`_pack_str_col`; returns (column, next offset)."""
    lens = np.frombuffer(buf, np.int64, rows, off)
    off += rows * 8
    out = np.full(rows, "", object)
    if payload_len:
        payload = bytes(buf[off : off + payload_len])
        ends = np.cumsum(lens)
        starts = ends - lens
        for i in np.nonzero(lens)[0].tolist():
            out[i] = payload[starts[i] : ends[i]].decode("utf-8")
    return out, off + payload_len


def _pack_chunk(chunk) -> bytes:
    """One JobsInfoChunk as a raw column frame (no ``data`` — the parent
    re-attaches its own copy of the wire blob for the lazy spans)."""
    rows = chunk.rows
    exit_lens, exit_pay = _pack_str_col(chunk.exit_code)
    rsn_lens, rsn_pay = _pack_str_col(chunk.reason)
    parts = [_HDR.pack(chunk.version, rows, len(exit_pay), len(rsn_pay))]
    for name in _I64_COLS:
        parts.append(np.ascontiguousarray(
            getattr(chunk, name), np.int64).tobytes())
    parts += [exit_lens, exit_pay, rsn_lens, rsn_pay]
    for name in _SPAN_COLS:
        start, length = chunk.str_spans[name]
        parts.append(np.ascontiguousarray(start, np.int64).tobytes())
        parts.append(np.ascontiguousarray(length, np.int64).tobytes())
    return b"".join(parts)


def _unpack_chunk(buf, data: bytes):
    """Rebuild a JobsInfoChunk from a column frame + the original wire
    blob (span fields index into ``data`` exactly as a local decode's
    would). Columns are writable copies — indistinguishable from the
    serial decode's freshly-allocated arrays."""
    version, rows, exit_n, rsn_n = _HDR.unpack_from(buf, 0)
    off = _HDR.size
    cols = {}
    for name in _I64_COLS:
        cols[name] = np.frombuffer(buf, np.int64, rows, off).copy()
        off += rows * 8
    exit_code, off = _unpack_str_col(buf, off, rows, exit_n)
    reason, off = _unpack_str_col(buf, off, rows, rsn_n)
    spans = {}
    for name in _SPAN_COLS:
        start = np.frombuffer(buf, np.int64, rows, off).copy()
        off += rows * 8
        length = np.frombuffer(buf, np.int64, rows, off).copy()
        off += rows * 8
        spans[name] = (start, length)
    jid = cols.pop("jid")
    return coldec.JobsInfoChunk(
        data, version, rows, jid,
        {k: cols[k] for k in (
            "id", "state", "start_ts", "limit", "submit_ts",
            "run_time", "num_nodes",
        )},
        exit_code, reason, spans,
    ), off


def _pack_prior(prior: dict) -> bytes:
    """Prior signal columns (jid-ascending) as one frame."""
    n = int(prior["jid"].size)
    exit_lens, exit_pay = _pack_str_col(prior["exit_code"])
    rsn_lens, rsn_pay = _pack_str_col(prior["reason"])
    parts = [struct.pack("<qqq", n, len(exit_pay), len(rsn_pay))]
    for name in ("jid",) + _DIFF_I64:
        parts.append(np.ascontiguousarray(prior[name], np.int64).tobytes())
    parts += [exit_lens, exit_pay, rsn_lens, rsn_pay]
    return b"".join(parts)


def _unpack_prior(buf) -> dict:
    n, exit_n, rsn_n = struct.unpack_from("<qqq", buf, 0)
    off = struct.calcsize("<qqq")
    prior = {}
    for name in ("jid",) + _DIFF_I64:
        prior[name] = np.frombuffer(buf, np.int64, n, off)
        off += n * 8
    prior["exit_code"], off = _unpack_str_col(buf, off, n, exit_n)
    prior["reason"], off = _unpack_str_col(buf, off, n, rsn_n)
    return prior


def diff_signals(chunk, prior: dict) -> np.ndarray:
    """Changed-row mask for one decoded chunk against prior signal
    columns: True where the row's job id is absent from ``prior`` or any
    signal column differs from the prior value. ``prior`` maps column
    name → array with ``jid`` ascending — the serial oracle the worker
    op and the fuzz suite both run."""
    pj = prior["jid"]
    rows = chunk.rows
    if pj.size == 0:
        return np.ones(rows, bool)
    pos = np.searchsorted(pj, chunk.jid)
    pos_c = np.minimum(pos, pj.size - 1)
    known = pj[pos_c] == chunk.jid
    changed = ~known
    for name in _DIFF_I64:
        changed |= getattr(chunk, name) != prior[name][pos_c]
    for name in _DIFF_STR:
        changed |= getattr(chunk, name) != prior[name][pos_c]
    changed[~known] = True
    return changed


def empty_prior() -> dict:
    """An empty prior for the diff/frames ops: :func:`diff_signals`
    marks every row changed against it, so a frames caller with no
    incremental cursor (the cold mirror) gets frames covering all
    returned rows — which is exactly the cold tick's changed-set."""
    prior: dict = {"jid": np.empty(0, np.int64)}
    for name in _DIFF_I64:
        prior[name] = np.empty(0, np.int64)
    for name in _DIFF_STR:
        prior[name] = np.empty(0, object)
    return prior


def decode_serial(blobs: list[bytes]) -> list:
    """The serial oracle: per-blob results in order, each a
    ``JobsInfoChunk`` or the ``DecodeError`` it raised — exactly the
    pool's per-chunk contract, minus the processes."""
    out = []
    for raw in blobs:
        try:
            out.append(coldec.decode_jobs_info(raw))
        except coldec.DecodeError as e:
            out.append(e)
    return out


# ---- the worker process ------------------------------------------------


def _worker_main(conn) -> None:  # pragma: no cover - runs in the child
    # Fork hygiene (ISSUE 20): the child inherits the parent's registry
    # (and every total it had accumulated) by COW. Swap in a FRESH
    # registry instead of resetting in place: a parent thread may have
    # held a metric lock at fork time, so touching inherited locks here
    # could deadlock the worker before it serves its first op. Anything
    # the worker registers from now on lands on the clean registry, so a
    # worker-side scrape (_OP_METRICS) can never double-count parent
    # totals.
    from slurm_bridge_tpu.obs import metrics as _obs_metrics

    _obs_metrics.REGISTRY = _registry = _obs_metrics.MetricsRegistry()
    _ops_served = _registry.counter(
        "sbt_colpool_worker_ops_total",
        "ops served by this forked colpool worker, by op",
    )
    prior: dict | None = None
    while True:
        try:
            frame = conn.recv_bytes()
        except (EOFError, OSError):
            break
        if not frame:
            break  # shutdown sentinel
        recv_ns = time.monotonic_ns()
        op = frame[0]
        (sent_ns,) = struct.unpack_from("<q", frame, 1)
        body_in = len(frame) - _REQ_OFF
        t0 = time.monotonic_ns()
        try:
            if op == _OP_SET_PRIOR:
                prior = _unpack_prior(memoryview(frame)[_REQ_OFF:])
                st, body = _ST_OK, b""
            elif op in (_OP_DECODE, _OP_DECODE_DIFF, _OP_DIFF_FRAMES):
                blob = frame[_REQ_OFF:]
                chunk = coldec.decode_jobs_info(blob)
                body = _pack_chunk(chunk)
                if op == _OP_DECODE_DIFF:
                    mask = diff_signals(
                        chunk, prior if prior is not None else
                        {"jid": np.empty(0, np.int64)},
                    )
                    body += np.ascontiguousarray(mask, np.uint8).tobytes()
                elif op == _OP_DIFF_FRAMES:
                    # lazy, like the write ops: colstore only loads in
                    # workers once a frames caller engages
                    from slurm_bridge_tpu.bridge import colstore

                    mask = diff_signals(
                        chunk, prior if prior is not None else
                        {"jid": np.empty(0, np.int64)},
                    )
                    try:
                        cf = colstore.build_commit_frame(
                            chunk, np.nonzero(mask)[0]
                        )
                    except Exception:
                        # frame build is an optimization, not a result:
                        # degrade to a frameless chunk and let the
                        # parent materialize spans as before
                        cf = b""
                    body += struct.pack("<q", len(cf)) + cf
                st = _ST_OK
            elif op in _WRITE_OPS:
                # lazy: the ops only need writeops once a write-side
                # caller engages; a decode-only worker never imports it
                from slurm_bridge_tpu.parallel import writeops

                fn = (
                    writeops.encode_submit_frame
                    if op == _OP_ENCODE_SUBMIT
                    else writeops.build_rows_frame
                )
                try:
                    st, body = _ST_OK, fn(memoryview(frame)[_REQ_OFF:])
                except Exception as e:
                    # payload problem (malformed array spec, bad utf8):
                    # per-chunk like a DecodeError — the caller reruns
                    # its serial arm, which raises the real exception in
                    # context; the pool itself stays healthy
                    st, body = _ST_DECODE_ERR, repr(e).encode("utf-8")
            elif op == _OP_METRICS:
                # debug scrape: this worker's own (post-fork) counters
                st = _ST_OK
                body = json.dumps({
                    "pid": os.getpid(),
                    "counters": _registry.counter_totals(),
                }).encode("utf-8")
            else:
                st, body = _ST_ERROR, f"unknown op {op}".encode()
        except coldec.DecodeError as e:
            st, body = _ST_DECODE_ERR, str(e).encode("utf-8")
        except BaseException as e:
            st, body = _ST_ERROR, repr(e).encode("utf-8")
        op_ns = time.monotonic_ns() - t0
        _ops_served.inc(1.0, op=_OP_NAMES.get(op, str(op)))
        out = (
            bytes([st])
            + _THDR.pack(max(0, recv_ns - sent_ns), op_ns, body_in, len(body))
            + body
        )
        try:
            conn.send_bytes(out)
        except (BrokenPipeError, OSError):
            break


class PoolBroken(RuntimeError):
    """Infrastructure failure (worker death / torn pipe) — the caller
    decodes inline; never surfaced as a DecodeError."""


class PayloadError(RuntimeError):
    """A write-op chunk failed INSIDE its compute (malformed array spec,
    undecodable frame) — the pool is healthy, but the caller must rerun
    its serial arm so the real exception surfaces in context."""


class _WriteJob:
    """One in-flight write-op fan-out, kicked without blocking the
    caller: packing AND the pipe round-trips run on the fan-out threads,
    so the kicking thread (the operator sweep's locked capture, say)
    keeps the interpreter while the workers chew. ``wait()`` joins and
    returns per-chunk reply bytes in request order, or ``None`` when the
    caller must run its serial arm — pool broken (remembered, like the
    decode side) or a per-chunk payload failure (pool stays up)."""

    def __init__(self, pool: "ColPool", op: int, chunks: list, pack_fn):
        self._pool = pool
        self._op = op
        self._stats = _OpStats()
        self._t0 = time.perf_counter()
        n = len(chunks)
        self._results: list = [None] * n
        self._infra: list[BaseException] = []
        self._payload: list[str] = []
        width = min(pool.width, n)

        def run(w: int) -> None:
            try:
                for i in range(w, n, width):
                    try:
                        body = pack_fn(chunks[i])
                    except Exception as e:
                        # pack blew up on chunk data: a payload problem,
                        # not pool infrastructure — serial arm re-raises
                        self._payload.append(repr(e))
                        return
                    st, rbody = self._pool._round_trip(
                        w, op, body, self._stats
                    )
                    if st == _ST_OK:
                        self._results[i] = bytes(rbody)
                    elif st == _ST_DECODE_ERR:
                        self._payload.append(
                            bytes(rbody).decode("utf-8", "replace")
                        )
                        return
                    else:
                        raise PoolBroken(
                            bytes(rbody).decode("utf-8", "replace")
                        )
            except (EOFError, OSError, IndexError, PoolBroken) as e:
                self._infra.append(e)

        self._threads = [
            threading.Thread(target=run, args=(w,), daemon=True)
            for w in range(width)
        ]
        for t in self._threads:
            t.start()

    def wait(self) -> list[bytes] | None:
        for t in self._threads:
            t.join()
        if self._infra:
            log.warning(
                "colpool broken; write ops inline from now on: %s",
                self._infra[0],
            )
            self._pool._break()
            return None
        if self._payload:
            log.warning(
                "colpool write op payload failure; serial arm re-runs: %s",
                self._payload[0],
            )
            return None
        # fold at collect time: the waiting thread carries the ambient
        # span (the kicking thread may have moved on long ago)
        _fold_op(
            _OP_NAMES.get(self._op, str(self._op)),
            self._stats,
            time.perf_counter() - self._t0,
        )
        return self._results


class ColPool:
    """N forked decode workers over raw-bytes pipes (lazily started)."""

    def __init__(self, width: int):
        self.width = max(1, int(width))
        self._procs: list = []
        self._conns: list = []
        self._locks: list[threading.Lock] = []
        self._start_lock = threading.Lock()
        self._broken = False

    # -- lifecycle --

    def _ensure(self) -> bool:
        if self._conns:
            return True
        if self._broken:
            return False
        with self._start_lock:
            if self._conns or self._broken:
                return bool(self._conns)
            try:
                import multiprocessing

                ctx = multiprocessing.get_context("fork")
                for _ in range(self.width):
                    parent, child = ctx.Pipe(duplex=True)
                    proc = ctx.Process(
                        target=_worker_main, args=(child,), daemon=True
                    )
                    proc.start()
                    child.close()
                    self._procs.append(proc)
                    self._conns.append(parent)
                    self._locks.append(threading.Lock())
            except (ValueError, OSError) as e:
                log.warning("colpool start failed; decoding inline: %s", e)
                self._break()
                return False
        return True

    def _break(self) -> None:
        self._broken = True
        self.close()

    def close(self) -> None:
        """Reap the workers. Idempotent and deliberately LOCK-FREE: the
        list swaps are single bytecodes under the GIL, so a second close
        (harness teardown racing atexit, say) finds empty lists and
        returns — and ``_break()`` may call this while ``_ensure`` still
        holds ``_start_lock``, so taking it here would deadlock."""
        conns, self._conns = self._conns, []
        procs, self._procs = self._procs, []
        self._locks = []
        for conn in conns:
            try:
                conn.send_bytes(b"")
            except (BrokenPipeError, OSError):
                pass
            try:
                conn.close()
            except OSError:
                pass
        for proc in procs:
            proc.join(timeout=2.0)
            if proc.is_alive():  # pragma: no cover - stuck worker
                proc.terminate()

    # -- ops --

    def _round_trip(
        self, w: int, op: int, body: bytes, stats: _OpStats | None = None
    ) -> tuple[int, memoryview]:
        """One request/reply exchange with worker ``w`` — the single choke
        point for ALL pool traffic. Stamps the request with monotonic_ns
        (the worker derives queue wait from it), strips the reply's fixed
        timing header into ``stats``, and returns ``(status, body view)``."""
        conn = self._conns[w]
        frame = _REQ.pack(op, time.monotonic_ns()) + body
        with self._locks[w]:
            conn.send_bytes(frame)
            resp = conn.recv_bytes()
        if stats is not None:
            queue_ns, op_ns, bi, bo = _THDR.unpack_from(resp, 1)
            stats.add(queue_ns, op_ns, bi, bo)
        return resp[0], memoryview(resp)[_RESP_OFF:]

    def _run_op(
        self, op: int, blobs: list[bytes], with_mask: bool,
        with_frame: bool = False, stats: _OpStats | None = None,
    ) -> list:
        """Fan ``blobs`` across the workers (round-robin by index) and
        collect per-blob results in request order: JobsInfoChunk (or
        (chunk, mask) for the diff op, (chunk, frame bytes | None) for
        the frames op) or DecodeError. Raises :class:`PoolBroken` on
        infrastructure failure."""
        results: list = [None] * len(blobs)
        width = min(self.width, len(blobs))
        errors: list[BaseException] = []

        def run(w: int) -> None:
            try:
                for i in range(w, len(blobs), width):
                    st, body = self._round_trip(w, op, blobs[i], stats)
                    if st == _ST_DECODE_ERR:
                        results[i] = coldec.DecodeError(
                            bytes(body).decode("utf-8", "replace")
                        )
                    elif st == _ST_OK:
                        chunk, off = _unpack_chunk(body, blobs[i])
                        if with_frame:
                            (frame_n,) = struct.unpack_from("<q", body, off)
                            fbytes = bytes(
                                body[off + 8 : off + 8 + frame_n]
                            )
                            results[i] = (chunk, fbytes or None)
                        elif with_mask:
                            mask = np.frombuffer(
                                body, np.uint8, chunk.rows, off
                            ).astype(bool)
                            results[i] = (chunk, mask)
                        else:
                            results[i] = chunk
                    else:
                        raise PoolBroken(
                            bytes(body).decode("utf-8", "replace")
                        )
            except (EOFError, OSError, IndexError, PoolBroken) as e:
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(w,), daemon=True)
            for w in range(width)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise PoolBroken(str(errors[0]))
        return results

    def _run_frames(
        self, op: int, frames: list[bytes], stats: _OpStats | None = None
    ) -> list[bytes]:
        """Fan pre-packed write-op frames across the workers (round-robin
        by index, like :meth:`_run_op`) and collect per-frame reply bytes
        in request order. Raises :class:`PoolBroken` on infrastructure
        failure, :class:`PayloadError` when any chunk's compute failed —
        the caller's serial arm re-raises the real exception in context."""
        results: list = [None] * len(frames)
        width = min(self.width, len(frames))
        infra: list[BaseException] = []
        payload: list[str] = []

        def run(w: int) -> None:
            try:
                for i in range(w, len(frames), width):
                    st, body = self._round_trip(w, op, frames[i], stats)
                    if st == _ST_OK:
                        results[i] = bytes(body)
                    elif st == _ST_DECODE_ERR:
                        payload.append(bytes(body).decode("utf-8", "replace"))
                        return
                    else:
                        raise PoolBroken(bytes(body).decode("utf-8", "replace"))
            except (EOFError, OSError, IndexError, PoolBroken) as e:
                infra.append(e)

        threads = [
            threading.Thread(target=run, args=(w,), daemon=True)
            for w in range(width)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if infra:
            raise PoolBroken(str(infra[0]))
        if payload:
            raise PayloadError(payload[0])
        return results

    def encode_submit_many(self, frames: list[bytes]) -> list[bytes] | None:
        """Pre-packed submit frames (:func:`writeops.pack_submit_frame`)
        → serialized ``SubmitJobsRequest`` bytes per frame, request
        order, or ``None`` when the caller must encode inline — pool
        unavailable, broken (remembered), or a payload failure (the
        serial arm surfaces the real error)."""
        if not frames:
            return []
        if not self._ensure():
            return None
        stats = _OpStats()
        t0 = time.perf_counter()
        try:
            out = self._run_frames(_OP_ENCODE_SUBMIT, frames, stats)
            _fold_op("encode_submit", stats, time.perf_counter() - t0)
            return out
        except PoolBroken as e:
            log.warning(
                "colpool broken; write ops inline from now on: %s", e
            )
            self._break()
            return None
        except PayloadError as e:
            log.warning(
                "colpool submit-encode payload failure; "
                "serial arm re-runs: %s", e,
            )
            return None

    def start_frames(self, op: int, chunks: list, pack_fn) -> _WriteJob | None:
        """Kick a write-op fan-out WITHOUT blocking: ``pack_fn(chunk)``
        builds each request frame on the fan-out threads, so the caller
        (holding a store lock, say) overlaps the whole pack + round-trip
        with its own work and collects via ``handle.wait()``. Returns
        ``None`` when the pool can't start — the caller runs its serial
        arm at collect time, same as a ``wait() is None``."""
        if not chunks or not self._ensure():
            return None
        return _WriteJob(self, op, chunks, pack_fn)

    def decode_jobs_info_many(self, blobs: list[bytes]) -> list:
        """Decode each blob in a worker; per-blob JobsInfoChunk or
        DecodeError, request order. Falls back to the inline serial
        decode (and stays there) on any pool-infrastructure failure."""
        if not blobs:
            return []
        if not self._ensure():
            return decode_serial(blobs)
        stats = _OpStats()
        t0 = time.perf_counter()
        try:
            out = self._run_op(_OP_DECODE, blobs, with_mask=False, stats=stats)
            _fold_op("decode", stats, time.perf_counter() - t0)
            return out
        except PoolBroken as e:
            log.warning("colpool broken; decoding inline from now on: %s", e)
            self._break()
            return decode_serial(blobs)

    def decode_diff_many(self, blobs: list[bytes], prior: dict) -> list:
        """Decode + signal-diff each blob in a worker: per-blob
        ``(JobsInfoChunk, changed mask)`` or DecodeError, request order.
        ``prior`` is shipped once per participating worker, then each
        chunk diffs against it in-process — the "decode plus mirror
        diff" op of ISSUE 16."""
        if not blobs:
            return []
        if not self._ensure():
            return [
                r if isinstance(r, coldec.DecodeError)
                else (r, diff_signals(r, prior))
                for r in decode_serial(blobs)
            ]
        stats = _OpStats()
        t0 = time.perf_counter()
        try:
            pbody = _pack_prior(prior)
            width = min(self.width, len(blobs))
            for w in range(width):
                st, body = self._round_trip(w, _OP_SET_PRIOR, pbody, stats)
                if st != _ST_OK:
                    raise PoolBroken(bytes(body).decode("utf-8", "replace"))
            out = self._run_op(
                _OP_DECODE_DIFF, blobs, with_mask=True, stats=stats
            )
            _fold_op("decode_diff", stats, time.perf_counter() - t0)
            return out
        except (PoolBroken, EOFError, OSError) as e:
            # raw pipe death in the SET_PRIOR round-trips (workers died
            # between ops) is the same infra failure _run_op reports as
            # PoolBroken — remember it and run the inline arm
            log.warning("colpool broken; decoding inline from now on: %s", e)
            self._break()
            return [
                r if isinstance(r, coldec.DecodeError)
                else (r, diff_signals(r, prior))
                for r in decode_serial(blobs)
            ]

    def decode_diff_frames_many(
        self, blobs: list[bytes], prior: dict
    ) -> list | None:
        """Decode + diff each blob in a worker AND pack the commit frame
        for its changed rows: per-blob ``(JobsInfoChunk, frame bytes or
        None)`` or DecodeError, request order. Returns ``None`` when the
        pool can't serve — unavailable or broken (remembered) — and the
        caller runs its frameless arm (``decode_jobs_info_many`` degrades
        further to inline serial decode, so mid-tick breakage completes
        the tick on the inline arm)."""
        if not blobs:
            return []
        if not self._ensure():
            return None
        stats = _OpStats()
        t0 = time.perf_counter()
        try:
            pbody = _pack_prior(prior)
            width = min(self.width, len(blobs))
            for w in range(width):
                st, body = self._round_trip(w, _OP_SET_PRIOR, pbody, stats)
                if st != _ST_OK:
                    raise PoolBroken(bytes(body).decode("utf-8", "replace"))
            out = self._run_op(
                _OP_DIFF_FRAMES, blobs, with_mask=False, with_frame=True,
                stats=stats,
            )
            _fold_op("diff_frames", stats, time.perf_counter() - t0)
            return out
        except (PoolBroken, EOFError, OSError) as e:
            log.warning("colpool broken; decoding inline from now on: %s", e)
            self._break()
            return None

    def worker_metrics(self, w: int = 0) -> dict | None:
        """Counter snapshot from worker ``w``'s own post-fork registry
        (``{"pid": ..., "counters": {...}}``) — the fork-hygiene probe:
        a freshly forked worker must NOT report the parent's inherited
        totals. Returns ``None`` when the pool can't serve."""
        if not self._ensure() or w >= self.width:
            return None
        try:
            st, body = self._round_trip(w, _OP_METRICS, b"")
            if st != _ST_OK:
                return None
            return json.loads(bytes(body).decode("utf-8"))
        except (EOFError, OSError) as e:
            log.warning("colpool broken; metrics probe failed: %s", e)
            self._break()
            return None


# ---- process-wide pool -------------------------------------------------

_pool: ColPool | None = None
_pool_width: int | None = None
_pool_lock = threading.Lock()


def configured_width() -> int:
    """Worker count: ``SBT_COLPOOL_WORKERS`` when set (0 disables),
    else CPU-affinity cores minus one — the main process keeps a core
    for the diff/write half of the mirror. ≤1 available core means 0:
    forking a worker that time-slices against the parent would be pure
    overhead, so the pool degrades to the inline serial path."""
    env = os.environ.get("SBT_COLPOOL_WORKERS")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            return 0
    if not hasattr(os, "fork"):  # pragma: no cover - non-posix
        return 0
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # pragma: no cover - non-linux
        cores = os.cpu_count() or 1
    return max(0, cores - 1)


def active_pool() -> ColPool | None:
    """The process-wide pool, or None when the configured width is 0
    (the caller runs the serial path inline — zero pool overhead)."""
    global _pool, _pool_width
    width = configured_width()
    if width <= 0:
        return None
    with _pool_lock:
        if _pool is None or _pool_width != width:
            if _pool is not None:
                _pool.close()
            _pool = ColPool(width)
            _pool_width = width
        return _pool


def reset() -> None:
    """Tear down the process pool (tests; also runs at exit)."""
    global _pool, _pool_width
    with _pool_lock:
        if _pool is not None:
            _pool.close()
        _pool = None
        _pool_width = None


atexit.register(reset)
