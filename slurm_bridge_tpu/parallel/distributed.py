"""Multi-host bootstrap and DCN/ICI-aware meshes for the solver.

The reference's distributed backend is gRPC between processes
(SURVEY.md §2.9); the rebuild keeps gRPC for control traffic and carries
the solver's data plane over XLA collectives — ICI within a TPU slice, DCN
across slices/hosts via ``jax.distributed``. This module is the bootstrap
seam:

- :func:`init_distributed` initialises ``jax.distributed`` from explicit
  arguments, the JAX coordinator env, or — fitting, for a framework whose
  job is Slurm — the Slurm step environment itself (SLURM_PROCID /
  SLURM_NTASKS / SLURM_STEP_NODELIST), the same variables ``srun`` exports
  for every task of a job the bridge submitted.
- :func:`hybrid_solver_mesh` builds a ("dp", "mp") mesh whose "mp" (nodes)
  axis stays inside a slice and whose "dp" (pods) axis spans slices: the
  per-round cross-"mp" gather moves O(P/dp × mp) elements every round and
  must ride ICI, while the cross-"dp" gather is one O(P) vector that DCN
  absorbs easily (the scaling-book rule: put the chatty axis on the fast
  interconnect).
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np
from jax.sharding import Mesh

from slurm_bridge_tpu.parallel.mesh import solver_mesh

log = logging.getLogger("sbt.distributed")

_initialized = False


def slurm_process_env() -> dict | None:
    """Coordinator spec derived from a Slurm step's environment, or None.

    Uses the first host of SLURM_STEP_NODELIST as the coordinator — every
    task of the step sees the same value, which is all ``jax.distributed``
    needs. Hostlist expressions are expanded with the same parser the agent
    uses for scontrol output.
    """
    if "SLURM_PROCID" not in os.environ or "SLURM_NTASKS" not in os.environ:
        return None
    nodelist = os.environ.get("SLURM_STEP_NODELIST") or os.environ.get(
        "SLURM_JOB_NODELIST", ""
    )
    if not nodelist:
        return None
    from slurm_bridge_tpu.core.hostlist import expand_hostlist

    hosts = expand_hostlist(nodelist)
    port = int(os.environ.get("SBT_COORDINATOR_PORT", "8476"))
    return {
        "coordinator_address": f"{hosts[0]}:{port}",
        "num_processes": int(os.environ["SLURM_NTASKS"]),
        "process_id": int(os.environ["SLURM_PROCID"]),
    }


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialise jax.distributed once; returns True when multi-process.

    Resolution order: explicit args → JAX's own auto-detection env
    (JAX_COORDINATOR_ADDRESS et al.) → the Slurm step environment →
    single-process no-op. Safe to call repeatedly.
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    spec = None
    if coordinator_address is not None:
        spec = {
            "coordinator_address": coordinator_address,
            "num_processes": num_processes,
            "process_id": process_id,
        }
    elif os.environ.get("JAX_COORDINATOR_ADDRESS"):
        # jax reads its own env — initialize with no args; an empty spec
        # must not fall through the single-process guard below.
        jax.distributed.initialize()
        _initialized = True
        log.info(
            "jax.distributed up from JAX env: process %d/%d",
            jax.process_index(),
            jax.process_count(),
        )
        return jax.process_count() > 1
    else:
        spec = slurm_process_env()
    if spec is None or (spec.get("num_processes") or 1) <= 1:
        _initialized = True
        return False
    jax.distributed.initialize(**{k: v for k, v in spec.items() if v is not None})
    _initialized = True
    log.info(
        "jax.distributed up: process %d/%d",
        jax.process_index(),
        jax.process_count(),
    )
    return True


def hybrid_solver_mesh(
    *,
    mp_per_slice: int | None = None,
) -> Mesh:
    """("dp", "mp") mesh with "mp" confined to one slice/host.

    Device order from ``jax.devices()`` groups by process; keeping "mp"
    within a process's devices keeps the per-round node-block gather on
    ICI. With one process this degrades to :func:`solver_mesh`.
    """
    devs = jax.devices()
    n_local = len([d for d in devs if d.process_index == jax.process_index()])
    if jax.process_count() <= 1:
        return solver_mesh(devs, mp=mp_per_slice)
    mp = mp_per_slice or n_local
    if mp > n_local:
        raise ValueError(
            f"mp_per_slice={mp} exceeds {n_local} local devices — the mp axis "
            "must not cross the slice boundary (its gather is per-round bulk)"
        )
    if len(devs) % mp:
        raise ValueError(f"mp={mp} does not divide {len(devs)} global devices")
    arr = np.array(devs).reshape(len(devs) // mp, mp)
    return Mesh(arr, axis_names=("dp", "mp"))
