"""OTLP/HTTP trace exporter — spans leave the process in a wire format.

Reference parity: the VK's Jaeger exporter
(cmd/slurm-virtual-kubelet/app/options/tracing_register_jaeger.go:29-52,
env-driven endpoint) and OC-agent exporter (tracing_register_ocagent.go).
The rebuild speaks today's lingua franca instead: OTLP/HTTP with JSON
encoding (``POST <endpoint>/v1/traces``), which Jaeger ≥1.35, Grafana
Tempo, and every OpenTelemetry collector ingest natively. Stdlib-only
(urllib), batched with a background flusher so ``export()`` never blocks
a traced code path, bounded queue with drop counting so a dead collector
cannot wedge the process.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import urllib.error
import urllib.request
from collections import deque

from slurm_bridge_tpu.obs.metrics import REGISTRY
from slurm_bridge_tpu.obs.tracing import Span, register_exporter

log = logging.getLogger("sbt.otlp")

#: exporter health on /metrics — a dead collector is a warning log today
#: and silence tomorrow; these make it a visible, alertable signal
_exported_total = REGISTRY.counter(
    "sbt_otlp_exported_spans_total", "spans delivered to the OTLP collector"
)
_dropped_total = REGISTRY.counter(
    "sbt_otlp_dropped_spans_total",
    "spans dropped (queue overflow or failed POST to the collector)",
)
_queue_depth = REGISTRY.gauge(
    "sbt_otlp_queue_depth", "spans waiting in the OTLP export queue"
)

#: standard OTel env var, same spelling the collector ecosystem uses
ENDPOINT_ENV = "OTEL_EXPORTER_OTLP_ENDPOINT"
DEFAULT_ENDPOINT = "http://localhost:4318"


def _attr(key: str, value: str) -> dict:
    return {"key": key, "value": {"stringValue": str(value)}}


def span_to_otlp(span: Span) -> dict:
    """One Span → an OTLP JSON span object (trace/v1 schema).

    Ids are zero-padded to OTLP's fixed widths (16-byte trace, 8-byte
    span); a span with no parent omits parentSpanId entirely.
    """
    out = {
        "traceId": span.trace_id.zfill(32),
        "spanId": span.span_id.zfill(16),
        "name": span.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(int(span.start * 1e9)),
        "endTimeUnixNano": str(int((span.end or span.start) * 1e9)),
        "attributes": [_attr(k, v) for k, v in span.tags.items()],
        "events": [
            {"timeUnixNano": str(int(t * 1e9)), "name": msg}
            for t, msg in span.annotations
        ],
        "status": (
            {"code": 1}
            if span.status == "OK"
            else {"code": 2, "message": span.status}
        ),
    }
    if span.parent_id:
        out["parentSpanId"] = span.parent_id.zfill(16)
    return out


def encode_batch(
    spans: list[Span], service: str, resource_attrs: dict | None = None
) -> bytes:
    """OTLP/HTTP JSON request body for one batch.

    ``resource_attrs`` extends the resource identity beyond
    ``service.name`` — the sidecar sets process role attributes
    (``process.pid``, ``sbt.replica``, ``sbt.incarnation``) so stitched
    traces group per process in Jaeger/Tempo (ISSUE 20).
    """
    attrs = [_attr("service.name", service)]
    for key in sorted(resource_attrs or {}):
        attrs.append(_attr(key, resource_attrs[key]))
    return json.dumps(
        {
            "resourceSpans": [
                {
                    "resource": {"attributes": attrs},
                    "scopeSpans": [
                        {
                            "scope": {"name": "slurm-bridge-tpu"},
                            "spans": [span_to_otlp(s) for s in spans],
                        }
                    ],
                }
            ]
        },
        separators=(",", ":"),
    ).encode()


class OtlpHttpExporter:
    """Batched OTLP/HTTP JSON exporter.

    ``export()`` enqueues and returns; a daemon thread flushes every
    ``flush_interval`` seconds or as soon as ``batch_size`` spans are
    pending. The queue is bounded: when the collector is down, old spans
    are dropped (counted in ``dropped``) rather than growing without
    bound or blocking the traced path.
    """

    def __init__(
        self,
        endpoint: str | None = None,
        *,
        service: str = "slurm-bridge-tpu",
        batch_size: int = 64,
        flush_interval: float = 2.0,
        queue_limit: int = 4096,
        timeout: float = 5.0,
        resource_attrs: dict | None = None,
    ):
        base = (endpoint or os.environ.get(ENDPOINT_ENV) or DEFAULT_ENDPOINT)
        self.url = base.rstrip("/") + "/v1/traces"
        self.service = service
        self.resource_attrs = dict(resource_attrs or {})
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.timeout = timeout
        self.dropped = 0
        self.sent = 0
        self._queue: deque[Span] = deque(maxlen=queue_limit)
        self._cv = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="otlp-exporter", daemon=True
        )
        self._thread.start()

    # -- exporter interface -------------------------------------------------
    def export(self, span: Span) -> None:
        with self._cv:
            if len(self._queue) == self._queue.maxlen:
                self.dropped += 1
                _dropped_total.inc()
            self._queue.append(span)
            _queue_depth.set(len(self._queue))
            if len(self._queue) >= self.batch_size:
                self._cv.notify()

    def flush(self) -> None:
        """Synchronously drain the queue (tests / shutdown)."""
        self._send(self._take_all())

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify()
        self._thread.join(self.timeout + 1.0)
        self.flush()

    # -- internals ----------------------------------------------------------
    def _take_all(self) -> list[Span]:
        with self._cv:
            batch = list(self._queue)
            self._queue.clear()
            _queue_depth.set(0)
        return batch

    def _run(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                self._cv.wait(self.flush_interval)
                if self._closed:
                    return
            self._send(self._take_all())

    def _send(self, batch: list[Span]) -> None:
        if not batch:
            return
        body = encode_batch(batch, self.service, self.resource_attrs)
        req = urllib.request.Request(
            self.url, data=body, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                resp.read()
            self.sent += len(batch)
            _exported_total.inc(len(batch))
        except (urllib.error.URLError, OSError) as e:
            self.dropped += len(batch)
            _dropped_total.inc(len(batch))
            log.warning(
                "OTLP export of %d spans to %s failed: %s",
                len(batch), self.url, e,
            )


register_exporter("otlp", OtlpHttpExporter)
