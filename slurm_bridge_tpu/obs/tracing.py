"""Distributed tracing — spans, samplers, exporter registry, tracez page.

Reference parity: the VK's OpenCensus wiring (SURVEY.md §5): an exporter
registry (cmd/slurm-virtual-kubelet/app/options/tracing_register.go:37-58)
with pluggable backends (Jaeger tracing_register_jaeger.go:29-52, OC-agent
tracing_register_ocagent.go — here: log / json-file / in-memory), sampling
policies ``always|never|0-100`` (tracing.go:64-89), reserved service tags
(operatingSystem/provider/nodeName, tracing.go:33-38), and a zpages-style
``/debug/tracez`` debug view (tracing.go:94-114). Spans propagate through
threads explicitly (pass the parent) and within a thread implicitly via a
context variable, mirroring how the virtual-kubelet library wraps pod-sync
operations in spans.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field

log = logging.getLogger("sbt.trace")

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "sbt_current_span", default=None
)


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    start: float = 0.0
    end: float = 0.0
    tags: dict[str, str] = field(default_factory=dict)
    annotations: list[tuple[float, str]] = field(default_factory=list)
    status: str = "OK"
    sampled: bool = True

    @property
    def duration(self) -> float:
        return (self.end or time.time()) - self.start

    def annotate(self, message: str) -> None:
        self.annotations.append((time.time(), message))

    def set_tag(self, key: str, value) -> None:
        self.tags[key] = str(value)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "start": self.start,
            "durationMs": round(self.duration * 1e3, 3),
            "tags": self.tags,
            "annotations": [
                {"t": t, "msg": m} for t, m in self.annotations
            ],
            "status": self.status,
        }


# --------------------------------------------------------------------------
# Samplers — policy grammar of tracing.go:64-89: "always", "never", or a
# percentage 0-100 interpreted as a probability.
# --------------------------------------------------------------------------

def parse_sampler(policy: str):
    """policy → () -> bool. Raises ValueError on nonsense (as the VK does)."""
    p = policy.strip().lower()
    if p in ("", "always"):
        return lambda: True
    if p == "never":
        return lambda: False
    try:
        rate = float(p)
    except ValueError:
        raise ValueError(
            f"unsupported tracing sample policy {policy!r} "
            "(want always|never|0-100)"
        ) from None
    if not 0 <= rate <= 100:
        raise ValueError(f"tracing sample rate {rate} outside [0,100]")
    frac = rate / 100.0
    return lambda: random.random() < frac


# --------------------------------------------------------------------------
# Exporters + registry
# --------------------------------------------------------------------------

class LogExporter:
    """Writes one structured log line per finished span."""

    def export(self, span: Span) -> None:
        log.info(
            "span %s trace=%s dur=%.1fms status=%s %s",
            span.name, span.trace_id[:8], span.duration * 1e3, span.status,
            " ".join(f"{k}={v}" for k, v in span.tags.items()),
        )


class JsonFileExporter:
    """Appends spans as JSON lines (the collector-friendly backend)."""

    DEFAULT_PATH = "/tmp/sbt-trace.jsonl"

    def __init__(self, path: str = DEFAULT_PATH):
        self.path = path
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        line = json.dumps(span.to_dict(), separators=(",", ":"))
        with self._lock, open(self.path, "a") as fh:
            fh.write(line + "\n")


class InMemoryExporter:
    """Keeps the last N spans (tests + tracez)."""

    def __init__(self, capacity: int = 512):
        self.spans: deque[Span] = deque(maxlen=capacity)

    def export(self, span: Span) -> None:
        self.spans.append(span)


#: name → factory, mirroring AvailableTraceExporters
_EXPORTERS: dict[str, object] = {
    "log": LogExporter,
    "jsonfile": JsonFileExporter,
    "memory": InMemoryExporter,
}


def register_exporter(name: str, factory) -> None:
    _EXPORTERS[name.lower()] = factory


def available_exporters() -> list[str]:
    return sorted(_EXPORTERS)


def make_exporter(name: str, **kwargs):
    key = name.lower()
    if key == "otlp" and key not in _EXPORTERS:
        # registers itself on import; lazy so the base registry stays dep-free
        import slurm_bridge_tpu.obs.otlp  # noqa: F401
    try:
        factory = _EXPORTERS[key]
    except KeyError:
        raise ValueError(
            f"unknown trace exporter {name!r}; available: {available_exporters()}"
        ) from None
    return factory(**kwargs)


# --------------------------------------------------------------------------
# Tracer
# --------------------------------------------------------------------------

class _SpanContext:
    """Context manager produced by Tracer.span()."""

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span
        self._token = None

    def __enter__(self) -> Span:
        self._span.start = time.time()
        self._token = _current_span.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        self._span.end = time.time()
        if exc is not None:
            self._span.status = f"ERROR: {exc_type.__name__}: {exc}"
        _current_span.reset(self._token)
        self._tracer._finish(self._span)
        return None  # never swallow


class Tracer:
    """Creates spans; owns the sampling decision and the exporter fan-out.

    Service-level tags are attached to every span (the reserved
    operatingSystem/provider/nodeName tags of tracing.go:33-38,49-51).
    The sampling decision is made at the trace root and inherited by
    children, so a trace is exported whole or not at all.
    """

    def __init__(
        self,
        service: str = "slurm-bridge-tpu",
        *,
        sample: str = "always",
        tags: dict[str, str] | None = None,
    ):
        self.service = service
        self.service_tags = dict(tags or {})
        self._sampler = parse_sampler(sample)
        self._exporters: list = []
        self._recent = deque(maxlen=256)  # tracez ring, sampled spans only
        self._lock = threading.Lock()

    # -- configuration ----------------------------------------------------
    def configure(
        self,
        *,
        sample: str | None = None,
        service: str | None = None,
        tags: dict[str, str] | None = None,
    ) -> "Tracer":
        if sample is not None:
            self._sampler = parse_sampler(sample)
        if service is not None:
            self.service = service
        if tags:
            self.service_tags.update(tags)
        return self

    def add_exporter(self, exporter) -> "Tracer":
        with self._lock:
            self._exporters.append(exporter)
        return self

    def clear_exporters(self) -> None:
        with self._lock:
            self._exporters.clear()

    # -- span creation ----------------------------------------------------
    def span(
        self, name: str, *, parent: Span | None = None, **tags
    ) -> _SpanContext:
        if parent is None:
            parent = _current_span.get()
        if parent is not None:
            trace_id, parent_id, sampled = parent.trace_id, parent.span_id, parent.sampled
        else:
            trace_id, parent_id, sampled = _new_id(16), None, self._sampler()
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=_new_id(8),
            parent_id=parent_id,
            tags={**self.service_tags, **{k: str(v) for k, v in tags.items()}},
            sampled=sampled,
        )
        return _SpanContext(self, span)

    def current(self) -> Span | None:
        return _current_span.get()

    def _finish(self, span: Span) -> None:
        if not span.sampled:
            return
        with self._lock:
            self._recent.append(span)
            exporters = list(self._exporters)
        for e in exporters:
            try:
                e.export(span)
            except Exception:
                log.exception("trace exporter %r failed", e)

    # -- tracez -----------------------------------------------------------
    def render_tracez(self) -> str:
        """Plain-text zpages-style summary: per-span-name latency stats plus
        the most recent spans (tracing.go:94-114's debug server)."""
        with self._lock:
            recent = list(self._recent)
        by_name: dict[str, list[Span]] = {}
        for s in recent:
            by_name.setdefault(s.name, []).append(s)
        lines = [f"tracez — service={self.service} spans={len(recent)}", ""]
        lines.append(f"{'span':40s} {'count':>6s} {'avg_ms':>9s} {'max_ms':>9s} {'errors':>6s}")
        for name in sorted(by_name):
            spans = by_name[name]
            durs = [s.duration * 1e3 for s in spans]
            errs = sum(1 for s in spans if s.status != "OK")
            lines.append(
                f"{name:40s} {len(spans):6d} {sum(durs)/len(durs):9.2f} "
                f"{max(durs):9.2f} {errs:6d}"
            )
        lines.append("")
        lines.append("recent spans:")
        for s in recent[-25:]:
            lines.append(
                f"  {s.name:38s} trace={s.trace_id[:8]} {s.duration*1e3:8.2f}ms "
                f"{s.status}"
            )
        return "\n".join(lines) + "\n"


#: process-wide default tracer (never-sampled until configured, so unwired
#: code paths pay only a contextvar read)
TRACER = Tracer(sample="never")


def setup_tracing(
    service: str,
    *,
    sample: str | None = None,
    exporter: str | None = None,
    node_name: str = "",
    **exporter_kwargs,
) -> Tracer:
    """One-call configuration mirroring vk.Run's SetupTracing
    (virtual-kubelet.go:244): reads ``SBT_TRACE_SAMPLE`` / ``SBT_TRACE_EXPORTER``
    env defaults the way the Jaeger exporter is env-driven in the reference.
    """
    sample = sample if sample is not None else os.environ.get("SBT_TRACE_SAMPLE", "never")
    exporter = exporter if exporter is not None else os.environ.get("SBT_TRACE_EXPORTER", "")
    tags = {"service": service, "operatingSystem": "Linux", "provider": "slurm-bridge-tpu"}
    if node_name:
        tags["nodeName"] = node_name
    TRACER.configure(sample=sample, service=service, tags=tags)
    if exporter:
        TRACER.add_exporter(make_exporter(exporter, **exporter_kwargs))
    return TRACER


# --------------------------------------------------------------------------
# gRPC server interceptor — one span per RPC, the process-boundary hook the
# reference gets from the virtual-kubelet library's span wrappers.
# --------------------------------------------------------------------------

def tracing_interceptor(tracer: Tracer | None = None):
    import grpc

    tracer = tracer or TRACER

    class _Interceptor(grpc.ServerInterceptor):
        def intercept_service(self, continuation, handler_call_details):
            handler = continuation(handler_call_details)
            if handler is None:
                return None
            method = handler_call_details.method.rsplit("/", 1)[-1]

            def wrap_unary(behavior):
                def inner(request, context):
                    with tracer.span(f"rpc.{method}"):
                        return behavior(request, context)
                return inner

            def wrap_stream(behavior):
                def inner(request_or_iter, context):
                    with tracer.span(f"rpc.{method}") as span:
                        n = 0
                        for item in behavior(request_or_iter, context):
                            n += 1
                            yield item
                        span.set_tag("messages", n)
                return inner

            kind_attrs = (
                ("unary_unary", grpc.unary_unary_rpc_method_handler, wrap_unary),
                ("unary_stream", grpc.unary_stream_rpc_method_handler, wrap_stream),
                ("stream_unary", grpc.stream_unary_rpc_method_handler, wrap_unary),
                ("stream_stream", grpc.stream_stream_rpc_method_handler, wrap_stream),
            )
            for attr, maker, wrapper in kind_attrs:
                behavior = getattr(handler, attr)
                if behavior is not None:
                    return maker(
                        wrapper(behavior),
                        request_deserializer=handler.request_deserializer,
                        response_serializer=handler.response_serializer,
                    )
            return handler

    return _Interceptor()
