"""Distributed tracing — spans, samplers, exporter registry, tracez page.

Reference parity: the VK's OpenCensus wiring (SURVEY.md §5): an exporter
registry (cmd/slurm-virtual-kubelet/app/options/tracing_register.go:37-58)
with pluggable backends (Jaeger tracing_register_jaeger.go:29-52, OC-agent
tracing_register_ocagent.go — here: log / json-file / in-memory), sampling
policies ``always|never|0-100`` (tracing.go:64-89), reserved service tags
(operatingSystem/provider/nodeName, tracing.go:33-38), and a zpages-style
``/debug/tracez`` debug view (tracing.go:94-114). Spans propagate through
threads explicitly (pass the parent) and within a thread implicitly via a
context variable, mirroring how the virtual-kubelet library wraps pod-sync
operations in spans.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field

log = logging.getLogger("sbt.trace")

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "sbt_current_span", default=None
)

#: per-thread id generator, seeded ONCE from os.urandom — span/trace id
#: generation used to be one urandom syscall per id, the same per-object
#: cost PR-4 removed from ``new_uid`` (a 45k-bind tick with tracing on
#: would have paid 90k+ syscalls just for ids)
_id_local = threading.local()


def _new_id(nbytes: int) -> str:
    rng = getattr(_id_local, "rng", None)
    if rng is None:
        rng = _id_local.rng = random.Random(os.urandom(16))
    return rng.getrandbits(nbytes * 8).to_bytes(nbytes, "big").hex()


@dataclass(slots=True)
class Span:
    """One span. ``slots=True`` and lazy tag/annotation dicts keep
    construction cheap — the flight recorder opens spans inside the hot
    tick phases, so per-span cost is tick overhead."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    start: float = 0.0
    end: float = 0.0
    tags: dict = field(default_factory=dict)
    annotations: list = field(default_factory=list)
    status: str = "OK"
    sampled: bool = True
    #: numeric payload (rows decoded, commits written, pods scanned) —
    #: kept apart from the string ``tags`` so the flight recorder can
    #: aggregate without parsing, and ``count()`` stays a dict add
    counters: dict = field(default_factory=dict)
    #: monotonic start/stop pair — ``start``/``end`` stay wall-clock for
    #: OTLP export, but durations come from perf_counter so the flight
    #: recorder's phase arithmetic matches the perf-timed tick headline
    _mono0: float = 0.0
    _mono1: float = 0.0
    #: the parent Span OBJECT (not just its id) — children finish before
    #: their ancestors, so an exporter can resolve the full name path of
    #: a finishing span by walking this chain while the ancestors are
    #: still open. The flight recorder's per-path rollup (ISSUE 14)
    #: depends on it; never exported, never compared.
    parent: "Span | None" = None

    @property
    def duration(self) -> float:
        if self._mono0:
            return (self._mono1 or time.perf_counter()) - self._mono0
        return (self.end or time.time()) - self.start

    def annotate(self, message: str) -> None:
        self.annotations.append((time.time(), message))

    def set_tag(self, key: str, value) -> None:
        self.tags[key] = str(value)

    def count(self, key: str, n: float = 1.0) -> None:
        """Accumulate a numeric attribute on this span."""
        self.counters[key] = self.counters.get(key, 0.0) + n

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "start": self.start,
            "durationMs": round(self.duration * 1e3, 3),
            "tags": self.tags,
            "counters": self.counters,
            "annotations": [
                {"t": t, "msg": m} for t, m in self.annotations
            ],
            "status": self.status,
        }


# --------------------------------------------------------------------------
# Samplers — policy grammar of tracing.go:64-89: "always", "never", or a
# percentage 0-100 interpreted as a probability.
# --------------------------------------------------------------------------

def parse_sampler(policy: str):
    """policy → () -> bool. Raises ValueError on nonsense (as the VK does)."""
    p = policy.strip().lower()
    if p in ("", "always"):
        return lambda: True
    if p == "never":
        return lambda: False
    try:
        rate = float(p)
    except ValueError:
        raise ValueError(
            f"unsupported tracing sample policy {policy!r} "
            "(want always|never|0-100)"
        ) from None
    if not 0 <= rate <= 100:
        raise ValueError(f"tracing sample rate {rate} outside [0,100]")
    frac = rate / 100.0
    return lambda: random.random() < frac


# --------------------------------------------------------------------------
# Exporters + registry
# --------------------------------------------------------------------------

class LogExporter:
    """Writes one structured log line per finished span."""

    def export(self, span: Span) -> None:
        log.info(
            "span %s trace=%s dur=%.1fms status=%s %s",
            span.name, span.trace_id[:8], span.duration * 1e3, span.status,
            " ".join(f"{k}={v}" for k, v in span.tags.items()),
        )


class JsonFileExporter:
    """Appends spans as JSON lines (the collector-friendly backend)."""

    DEFAULT_PATH = "/tmp/sbt-trace.jsonl"

    def __init__(self, path: str = DEFAULT_PATH):
        self.path = path
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        line = json.dumps(span.to_dict(), separators=(",", ":"))
        with self._lock, open(self.path, "a") as fh:
            fh.write(line + "\n")


class InMemoryExporter:
    """Keeps the last N spans (tests + tracez)."""

    def __init__(self, capacity: int = 512):
        self.spans: deque[Span] = deque(maxlen=capacity)

    def export(self, span: Span) -> None:
        self.spans.append(span)


#: name → factory, mirroring AvailableTraceExporters
_EXPORTERS: dict[str, object] = {
    "log": LogExporter,
    "jsonfile": JsonFileExporter,
    "memory": InMemoryExporter,
}


def register_exporter(name: str, factory) -> None:
    _EXPORTERS[name.lower()] = factory


def available_exporters() -> list[str]:
    return sorted(_EXPORTERS)


def make_exporter(name: str, **kwargs):
    key = name.lower()
    if key == "otlp" and key not in _EXPORTERS:
        # registers itself on import; lazy so the base registry stays dep-free
        import slurm_bridge_tpu.obs.otlp  # noqa: F401
    try:
        factory = _EXPORTERS[key]
    except KeyError:
        raise ValueError(
            f"unknown trace exporter {name!r}; available: {available_exporters()}"
        ) from None
    return factory(**kwargs)


# --------------------------------------------------------------------------
# Tracer
# --------------------------------------------------------------------------

class _SpanContext:
    """Context manager produced by Tracer.span()."""

    __slots__ = ("_tracer", "_span", "_token")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span
        self._token = None

    def __enter__(self) -> Span:
        self._span.start = time.time()
        self._span._mono0 = time.perf_counter()
        self._token = _current_span.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        self._span._mono1 = time.perf_counter()
        self._span.end = time.time()
        if exc is not None:
            self._span.status = f"ERROR: {exc_type.__name__}: {exc}"
        _current_span.reset(self._token)
        self._tracer._finish(self._span)
        return None  # never swallow


def current_span() -> Span | None:
    """The ambient span of this thread/context (tracer-independent)."""
    return _current_span.get()


@contextlib.contextmanager
def with_current_span(span: Span | None):
    """Make ``span`` the ambient parent in THIS thread/context.

    The explicit-parent half of cross-thread propagation: a pool worker
    runs its items under the submitting thread's span so any spans the
    item opens (via the contextvar) parent correctly. No span is created
    and nothing is exported — this only seeds the contextvar.
    """
    token = _current_span.set(span)
    try:
        yield span
    finally:
        _current_span.reset(token)


# --------------------------------------------------------------------------
# W3C-style traceparent propagation — the process-boundary wire format.
# --------------------------------------------------------------------------

#: gRPC metadata key (lowercase per gRPC rules; same spelling the W3C
#: Trace Context spec and every OTel SDK use)
TRACEPARENT_KEY = "traceparent"


def format_traceparent(span: Span) -> str:
    """``00-<32 hex trace>-<16 hex span>-<flags>`` for one span."""
    flags = "01" if span.sampled else "00"
    return f"00-{span.trace_id.zfill(32)}-{span.span_id.zfill(16)}-{flags}"


def current_traceparent() -> str | None:
    """The active span's traceparent header value, or None outside a span."""
    span = _current_span.get()
    return format_traceparent(span) if span is not None else None


def parse_traceparent(value: str) -> Span | None:
    """A remote-parent stub Span from a traceparent header, or None.

    The stub carries trace id / span id / sampled flag only — it is never
    entered or exported; it exists so ``Tracer.span(parent=stub)`` parents
    a server-side span into the caller's trace.
    """
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    return Span(
        name="remote-parent",
        trace_id=trace_id,
        span_id=span_id,
        sampled=bool(int(flags, 16) & 1),
    )


def parent_from_metadata(metadata) -> Span | None:
    """Extract the remote parent from gRPC invocation metadata (a
    sequence of (key, value) pairs), or None when absent/malformed."""
    for key, value in metadata or ():
        if key == TRACEPARENT_KEY:
            return parse_traceparent(value)
    return None


class Tracer:
    """Creates spans; owns the sampling decision and the exporter fan-out.

    Service-level tags are attached to every span (the reserved
    operatingSystem/provider/nodeName tags of tracing.go:33-38,49-51).
    The sampling decision is made at the trace root and inherited by
    children, so a trace is exported whole or not at all.
    """

    def __init__(
        self,
        service: str = "slurm-bridge-tpu",
        *,
        sample: str = "always",
        tags: dict[str, str] | None = None,
    ):
        self.service = service
        self.service_tags = dict(tags or {})
        self._sampler = parse_sampler(sample)
        self._exporters: list = []
        #: immutable snapshot for the _finish hot path: no lock, no
        #: defensive copy per finished span (the flight recorder finishes
        #: dozens of spans inside every tick phase)
        self._exporters_snapshot: tuple = ()
        self._recent = deque(maxlen=256)  # tracez ring, sampled spans only
        self._lock = threading.Lock()

    # -- configuration ----------------------------------------------------
    def configure(
        self,
        *,
        sample: str | None = None,
        service: str | None = None,
        tags: dict[str, str] | None = None,
    ) -> "Tracer":
        if sample is not None:
            self._sampler = parse_sampler(sample)
        if service is not None:
            self.service = service
        if tags:
            self.service_tags.update(tags)
        return self

    def add_exporter(self, exporter) -> "Tracer":
        with self._lock:
            self._exporters.append(exporter)
            self._exporters_snapshot = tuple(self._exporters)
        return self

    def remove_exporter(self, exporter) -> None:
        with self._lock:
            self._exporters = [e for e in self._exporters if e is not exporter]
            self._exporters_snapshot = tuple(self._exporters)

    def clear_exporters(self) -> None:
        with self._lock:
            self._exporters.clear()
            self._exporters_snapshot = ()

    @contextlib.contextmanager
    def recording(self, sink):
        """Temporarily force sampling on and fan spans out to ``sink``
        (an exporter) — the flight recorder's per-tick capture window.
        Restores the previous sampler and removes the sink on exit."""
        with self._lock:
            prev_sampler = self._sampler
            self._sampler = lambda: True
            self._exporters.append(sink)
            self._exporters_snapshot = tuple(self._exporters)
        try:
            yield sink
        finally:
            with self._lock:
                self._sampler = prev_sampler
                self._exporters = [e for e in self._exporters if e is not sink]
                self._exporters_snapshot = tuple(self._exporters)

    # -- span creation ----------------------------------------------------
    def span(
        self, name: str, *, parent: Span | None = None, **tags
    ) -> _SpanContext:
        if parent is None:
            parent = _current_span.get()
        if parent is not None:
            trace_id, parent_id, sampled = parent.trace_id, parent.span_id, parent.sampled
        else:
            trace_id, parent_id, sampled = _new_id(16), None, self._sampler()
        if self.service_tags:
            merged = dict(self.service_tags)
            for k, v in tags.items():
                merged[k] = str(v)
        elif tags:
            merged = {k: str(v) for k, v in tags.items()}
        else:
            merged = {}
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=_new_id(8),
            parent_id=parent_id,
            tags=merged,
            sampled=sampled,
            parent=parent,
        )
        return _SpanContext(self, span)

    def current(self) -> Span | None:
        return _current_span.get()

    def emit_synthetic(
        self,
        name: str,
        *,
        parent: Span,
        duration_s: float,
        start_offset_s: float = 0.0,
        tags: dict | None = None,
        counters: dict | None = None,
    ) -> Span:
        """Materialize an already-finished child span under ``parent``.

        The cross-process stitching primitive (ISSUE 20): a remote or
        forked worker reports measured phase durations after the fact
        (PlaceShardResponse timing ns, colpool reply timing headers) and
        the parent turns them into child spans, so flight-record
        attribution crosses fork() and gRPC. Exported immediately — call
        while ``parent`` is still OPEN so the recorder's child-sum
        bookkeeping (parent self-time = wall − children) accounts for it.
        """
        span = Span(
            name=name,
            trace_id=parent.trace_id,
            span_id=_new_id(8),
            parent_id=parent.span_id,
            tags={k: str(v) for k, v in (tags or {}).items()},
            counters=dict(counters or {}),
            sampled=parent.sampled,
            parent=parent,
        )
        span.start = (parent.start or time.time()) + start_offset_s
        span.end = span.start + duration_s
        if parent._mono0:
            span._mono0 = parent._mono0 + start_offset_s
            span._mono1 = span._mono0 + duration_s
        self._finish(span)
        return span

    def _finish(self, span: Span) -> None:
        if not span.sampled:
            return
        # deque.append is atomic under the GIL; the exporter snapshot is
        # immutable — no lock on the per-span finish path
        self._recent.append(span)
        for e in self._exporters_snapshot:
            try:
                e.export(span)
            except Exception:
                log.exception("trace exporter %r failed", e)

    # -- tracez -----------------------------------------------------------
    def render_tracez(self) -> str:
        """Plain-text zpages-style summary: per-span-name latency stats plus
        the most recent spans (tracing.go:94-114's debug server)."""
        with self._lock:
            recent = list(self._recent)
        by_name: dict[str, list[Span]] = {}
        for s in recent:
            by_name.setdefault(s.name, []).append(s)
        lines = [f"tracez — service={self.service} spans={len(recent)}", ""]
        lines.append(f"{'span':40s} {'count':>6s} {'avg_ms':>9s} {'max_ms':>9s} {'errors':>6s}")
        for name in sorted(by_name):
            spans = by_name[name]
            durs = [s.duration * 1e3 for s in spans]
            errs = sum(1 for s in spans if s.status != "OK")
            lines.append(
                f"{name:40s} {len(spans):6d} {sum(durs)/len(durs):9.2f} "
                f"{max(durs):9.2f} {errs:6d}"
            )
        lines.append("")
        lines.append("recent spans:")
        for s in recent[-25:]:
            lines.append(
                f"  {s.name:38s} trace={s.trace_id[:8]} {s.duration*1e3:8.2f}ms "
                f"{s.status}"
            )
        lines.extend(self._render_recent_ticks(recent))
        return "\n".join(lines) + "\n"

    @staticmethod
    def _render_recent_ticks(recent: list[Span], limit: int = 3) -> list[str]:
        """The per-tick view: the newest root ``*.tick`` spans rendered as
        indented trees (children by parent id, insertion order), with
        durations and counters — a flight-record glance without pulling
        the JSON artifact."""
        roots = [
            s for s in recent if s.parent_id is None and s.name.endswith(".tick")
        ][-limit:]
        if not roots:
            return []
        by_parent: dict[str, list[Span]] = {}
        for s in recent:
            if s.parent_id:
                by_parent.setdefault((s.trace_id, s.parent_id), []).append(s)
        lines = ["", "recent ticks:"]
        for root in roots:
            header = f"tick trace={root.trace_id[:8]}"
            tick_no = root.tags.get("tick")
            if tick_no is not None:
                header += f" tick={tick_no}"
            lines.append(header)
            stack = [(root, 1)]
            budget = 40  # a storm of rpc spans must not flood the page
            while stack and budget:
                span, depth = stack.pop()
                budget -= 1
                counters = " ".join(
                    f"{k}={v:g}" for k, v in sorted(span.counters.items())
                )
                lines.append(
                    f"{'  ' * depth}{span.name:{max(1, 40 - 2 * depth)}s} "
                    f"{span.duration * 1e3:9.2f}ms"
                    + (f"  {counters}" if counters else "")
                )
                children = by_parent.get((span.trace_id, span.span_id), [])
                for child in reversed(children):
                    stack.append((child, depth + 1))
        return lines


#: process-wide default tracer (never-sampled until configured, so unwired
#: code paths pay only a contextvar read)
TRACER = Tracer(sample="never")


def setup_tracing(
    service: str,
    *,
    sample: str | None = None,
    exporter: str | None = None,
    node_name: str = "",
    **exporter_kwargs,
) -> Tracer:
    """One-call configuration mirroring vk.Run's SetupTracing
    (virtual-kubelet.go:244): reads ``SBT_TRACE_SAMPLE`` / ``SBT_TRACE_EXPORTER``
    env defaults the way the Jaeger exporter is env-driven in the reference.
    """
    sample = sample if sample is not None else os.environ.get("SBT_TRACE_SAMPLE", "never")
    exporter = exporter if exporter is not None else os.environ.get("SBT_TRACE_EXPORTER", "")
    tags = {"service": service, "operatingSystem": "Linux", "provider": "slurm-bridge-tpu"}
    if node_name:
        tags["nodeName"] = node_name
    TRACER.configure(sample=sample, service=service, tags=tags)
    if exporter:
        TRACER.add_exporter(make_exporter(exporter, **exporter_kwargs))
    return TRACER


# --------------------------------------------------------------------------
# gRPC server interceptor — one span per RPC, the process-boundary hook the
# reference gets from the virtual-kubelet library's span wrappers. Incoming
# ``traceparent`` metadata (injected by the ServiceClient) parents the RPC
# span into the caller's trace, so an agent-side SubmitJobs span hangs off
# the bridge's scheduler tick instead of starting a trace of its own.
# --------------------------------------------------------------------------

def tracing_interceptor(tracer: Tracer | None = None):
    import grpc

    tracer = tracer or TRACER

    class _Interceptor(grpc.ServerInterceptor):
        def intercept_service(self, continuation, handler_call_details):
            handler = continuation(handler_call_details)
            if handler is None:
                return None
            method = handler_call_details.method.rsplit("/", 1)[-1]
            parent = parent_from_metadata(
                getattr(handler_call_details, "invocation_metadata", ())
            )

            def wrap_unary(behavior):
                def inner(request, context):
                    with tracer.span(f"rpc.{method}", parent=parent):
                        return behavior(request, context)
                return inner

            def wrap_stream(behavior):
                def inner(request_or_iter, context):
                    with tracer.span(f"rpc.{method}", parent=parent) as span:
                        n = 0
                        for item in behavior(request_or_iter, context):
                            n += 1
                            yield item
                        span.set_tag("messages", n)
                return inner

            kind_attrs = (
                ("unary_unary", grpc.unary_unary_rpc_method_handler, wrap_unary),
                ("unary_stream", grpc.unary_stream_rpc_method_handler, wrap_stream),
                ("stream_unary", grpc.stream_unary_rpc_method_handler, wrap_unary),
                ("stream_stream", grpc.stream_stream_rpc_method_handler, wrap_stream),
            )
            for attr, maker, wrapper in kind_attrs:
                behavior = getattr(handler, attr)
                if behavior is not None:
                    return maker(
                        wrapper(behavior),
                        request_deserializer=handler.request_deserializer,
                        response_serializer=handler.response_serializer,
                    )
            return handler

    return _Interceptor()
