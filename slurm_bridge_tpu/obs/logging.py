"""Structured logging setup shared by every daemon.

The reference mixes four logging stacks (logrus/klog/zap/vk-adapter —
SURVEY.md §5 "Metrics/logging"); here one configuration serves all
binaries: key=value text for humans, or JSON lines with ``json_lines=True``
for collectors.

Log↔trace correlation (ISSUE 15 satellite): when a line is emitted
inside a SAMPLED span, both formatters append the active span's
``trace_id``/``span_id`` (read from the tracing contextvar — zero setup,
zero cost outside a span), so JSON log lines join against flight records
and OTLP traces instead of standing alone with ts/level/logger/msg.
Unsampled spans stay silent: a never-sampled production path logs
exactly the pre-ISSUE-15 bytes.
"""

from __future__ import annotations

import json
import logging
import sys
import time

from slurm_bridge_tpu.obs.tracing import current_span


class KVFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(record.created))
        base = f"{ts} {record.levelname:<7} {record.name} {record.getMessage()}"
        span = current_span()
        if span is not None and span.sampled:
            base += f" trace={span.trace_id} span={span.span_id}"
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": record.created,
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        span = current_span()
        if span is not None and span.sampled:
            payload["trace_id"] = span.trace_id
            payload["span_id"] = span.span_id
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload)


def setup_logging(*, verbose: bool = False, json_lines: bool = False) -> None:
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(JSONFormatter() if json_lines else KVFormatter())
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(logging.DEBUG if verbose else logging.INFO)
