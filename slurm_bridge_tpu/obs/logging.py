"""Structured logging setup shared by every daemon.

The reference mixes four logging stacks (logrus/klog/zap/vk-adapter —
SURVEY.md §5 "Metrics/logging"); here one configuration serves all
binaries: key=value text for humans, or JSON lines with ``json_lines=True``
for collectors.
"""

from __future__ import annotations

import json
import logging
import sys
import time


class KVFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(record.created))
        base = f"{ts} {record.levelname:<7} {record.name} {record.getMessage()}"
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": record.created,
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload)


def setup_logging(*, verbose: bool = False, json_lines: bool = False) -> None:
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(JSONFormatter() if json_lines else KVFormatter())
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(logging.DEBUG if verbose else logging.INFO)
