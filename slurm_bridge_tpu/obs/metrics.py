"""Minimal Prometheus-exposition metrics.

Reference parity: the controller-runtime metrics servers on :8080 and the
declared-but-dead VK stats endpoints (SURVEY.md §5). Here one registry
serves every daemon, exposed in Prometheus text format over a tiny
stdlib HTTP server — no client_golang equivalent needed.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] += amount

    def value(self, **labels: str) -> float:
        """Current value for one label set — for tests and in-process
        consumers, without parsing the exposition text."""
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def total(self) -> float:
        """Sum over every label set — the flight recorder's counter-delta
        reads, without enumerating label combinations."""
        with self._lock:
            return sum(self._values.values())

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def collect(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            for key, val in self._values.items():
                out.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value

    def handle(self, **labels: str):
        """A bound setter with the label key resolved ONCE — for hot-path
        callers (the work-queue depth updates on every add/pop) that would
        otherwise rebuild the sorted label tuple per observation."""
        key = tuple(sorted(labels.items()))

        def set_value(value: float) -> None:
            with self._lock:
                self._values[key] = value

        return set_value

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def collect(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            for key, val in self._values.items():
                out.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return out


class Histogram:
    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.2, 0.5, 1.0, 5.0, 30.0)
    #: For sub-millisecond phases (e.g. the cached encode path): the default
    #: buckets would dump every observation into the first bucket, hiding
    #: any regression below 1 ms.
    FAST_BUCKETS = (
        0.00001, 0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
        0.5, 1.0,
    )

    def __init__(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._total += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._total = 0

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket counts (upper bound)."""
        with self._lock:
            if not self._total:
                return 0.0
            target = q * self._total
            acc = 0
            for i, b in enumerate(self.buckets):
                acc += self._counts[i]
                if acc >= target:
                    return b
            return float("inf")

    def collect(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            acc = 0
            for i, b in enumerate(self.buckets):
                acc += self._counts[i]
                out.append(f'{self.name}_bucket{{le="{b}"}} {acc}')
            acc += self._counts[-1]
            out.append(f'{self.name}_bucket{{le="+Inf"}} {acc}')
            out.append(f"{self.name}_sum {self._sum}")
            out.append(f"{self.name}_count {self._total}")
        return out


class MetricsRegistry:
    def __init__(self):
        self._metrics: list = []
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._register(Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._register(Gauge(name, help_))

    def histogram(self, name: str, help_: str = "", **kw) -> Histogram:
        return self._register(Histogram(name, help_, **kw))

    def _register(self, metric):
        with self._lock:
            self._metrics.append(metric)
        return metric

    def register(self, collector):
        """Register a custom collector: any object with a ``collect() ->
        list[str]`` of exposition lines (and optionally a ``name``). Used
        by metrics whose source of truth lives elsewhere — the store's
        commit counters are plain dicts incremented under the store lock,
        and the collector reads them only at scrape time."""
        return self._register(collector)

    def reset_values(self) -> None:
        """Zero every metric's observed values, keeping registrations.

        Fork hygiene (ISSUE 20): a forked colpool worker inherits the
        parent's registry by COW — calling this first thing post-fork
        means a future worker-side scrape can never double-count parent
        totals. Custom collectors (no ``reset`` attr) are skipped: their
        source of truth lives elsewhere.
        """
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            reset = getattr(m, "reset", None)
            if callable(reset):
                reset()

    def counter_totals(self) -> dict[str, float]:
        """``{name: summed value}`` for every Counter — the flight
        recorder snapshots this per tick and reports the deltas."""
        with self._lock:
            metrics = list(self._metrics)
        return {m.name: m.total() for m in metrics if isinstance(m, Counter)}

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"

    def serve(
        self,
        port: int,
        host: str = "0.0.0.0",
        *,
        extra_routes: dict | None = None,
        health_checks: dict | None = None,
        ready_checks: dict | None = None,
    ) -> ThreadingHTTPServer:
        """Start /metrics + /healthz + /readyz on a background thread.

        ``extra_routes`` maps a path prefix to ``() -> (content_type, body)``
        — used for the /debug/tracez zpages view (SURVEY.md §5 tracing).
        ``health_checks`` / ``ready_checks`` map name → ``() -> None`` checks
        that raise on failure, reproducing the operator's named healthz/readyz
        checkers (bridge-operator.go:100-107); a failing check turns the
        probe into a 500 listing the failures.
        """
        registry = self
        extra = dict(extra_routes or {})

        def run_checks(checks: dict) -> tuple[int, bytes]:
            failures = []
            for name, check in checks.items():
                try:
                    check()
                except Exception as exc:  # a probe must never crash the server
                    failures.append(f"{name}: {exc}")
            if failures:
                return 500, ("\n".join(failures) + "\n").encode()
            return 200, b"ok"

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                status = 200
                if self.path.startswith("/healthz"):
                    status, body = run_checks(health_checks or {})
                    ctype = "text/plain"
                elif self.path.startswith("/readyz"):
                    status, body = run_checks(ready_checks or {})
                    ctype = "text/plain"
                elif self.path.startswith("/metrics"):
                    body = registry.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif any(self.path.startswith(p) for p in extra):
                    prefix = next(p for p in extra if self.path.startswith(p))
                    try:
                        ctype, text = extra[prefix]()
                        body = text.encode() if isinstance(text, str) else text
                    except Exception as exc:  # a debug page must never drop the conn
                        status, ctype = 500, "text/plain"
                        body = f"handler for {prefix} failed: {exc}\n".encode()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        httpd = ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd


#: process-wide default registry
REGISTRY = MetricsRegistry()
