"""Tick flight recorder — one span tree + attribution record per tick.

The ROADMAP's open question after PR-4 ("the residual 5.2 s is spread
across proto decode, commit machinery and object builds") was a guess
from ad-hoc timers. This module turns every full-bridge tick into
measured data: the sim harness (and any embedder) opens a recording
window per tick, every product-layer span lands in it (scheduler phases,
operator sweep, provider sync, RPC spans — wired through the ambient
contextvar and the gRPC traceparent metadata), and the window closes into
a compact machine-readable record:

- the **phase tree**: spans grouped by name under their parent, with
  durations and the numeric counters they carried (rows decoded, commits
  written, pods scanned);
- **top spans by self-time** (duration minus child durations) — where the
  tick actually went, not just which phase wrapped it;
- the **commit breakdown**: per-kind × per-callsite store commit deltas
  for the tick (the store's always-on attribution ledger), which sum to
  the tick's total commits by construction;
- **counter deltas**: every REGISTRY counter that moved during the tick.

Recording swaps the tracer's sampler to always-on for the window and
restores it after, so the flight recorder works regardless of the
process-wide sampling policy, and tests/embedders leave no global state
behind.
"""

from __future__ import annotations

import contextlib
import threading
from collections import deque

import numpy as np

from slurm_bridge_tpu.obs.metrics import REGISTRY
from slurm_bridge_tpu.obs.tracing import TRACER, Span, Tracer

#: span names whose subtrees are the canonical tick phases — used to lift
#: a ``phases_ms`` view out of the span tree (must stay in lockstep with
#: the wiring in bridge/scheduler.py and sim/harness.py)
PHASE_SPANS = {
    "arrive": ("sim.arrive",),
    "store": ("scheduler.store",),
    "encode": ("scheduler.encode",),
    "solve": ("scheduler.solve",),
    "bind": ("scheduler.bind",),
    "mirror": ("sim.mirror",),
    #: the harness's own bookkeeping — ground-truth step, invariant
    #: checks, quality sampling, digest notes. Named (ISSUE 14) so the
    #: phase-sum reconciliation holds at the 500k shape, where this used
    #: to be seconds of unattributed root-span self time.
    "verify": ("sim.verify",),
}


def _tree(spans: list[Span], root: Span, max_depth: int = 6) -> dict:
    """Group the captured spans into a name-keyed tree under ``root``.

    Children with the same name merge into one node carrying ``count``,
    summed ``ms`` and summed counters — an RPC fan-out of 23 JobsInfo
    chunks renders as one node, not 23.
    """
    by_parent: dict[str, list[Span]] = {}
    for s in spans:
        if s.parent_id:
            by_parent.setdefault(s.parent_id, []).append(s)

    def build(group: list[Span], depth: int) -> dict:
        node: dict = {
            "ms": round(sum(s.duration for s in group) * 1e3, 3),
            "count": len(group),
        }
        counters: dict[str, float] = {}
        for s in group:
            for k, v in s.counters.items():
                counters[k] = counters.get(k, 0.0) + v
        if counters:
            node["counters"] = {k: counters[k] for k in sorted(counters)}
        if depth < max_depth:
            children: dict[str, list[Span]] = {}
            for s in group:
                for c in by_parent.get(s.span_id, ()):
                    children.setdefault(c.name, []).append(c)
            if children:
                node["children"] = {
                    name: build(kids, depth + 1)
                    for name, kids in sorted(children.items())
                }
        return node

    return {root.name: build([root], 0)}


def _self_times(spans: list[Span], root: Span) -> dict[str, tuple[int, float, float]]:
    """name -> (count, total_ms, self_ms) over the captured window."""
    child_sum: dict[str, float] = {}
    for s in spans:
        if s.parent_id:
            child_sum[s.parent_id] = child_sum.get(s.parent_id, 0.0) + s.duration
    agg: dict[str, tuple[int, float, float]] = {}
    for s in [*spans, root]:
        self_s = max(0.0, s.duration - child_sum.get(s.span_id, 0.0))
        n, tot, slf = agg.get(s.name, (0, 0.0, 0.0))
        agg[s.name] = (n + 1, tot + s.duration * 1e3, slf + self_s * 1e3)
    return agg


class FlightRecorder:
    """Per-tick span capture + attribution records.

    Usage (the sim harness's shape)::

        rec = FlightRecorder(store=harness.store)
        with rec.tick(5) as root:          # root span "sim.tick"
            ... run the tick ...
        rec.records[-1]                    # the flight record just built

    Disabled (``enabled=False``) it is a true no-op: no sampler swap, no
    root span, no capture — the tracing-off half of the overhead gate.
    """

    def __init__(
        self,
        *,
        tracer: Tracer | None = None,
        store=None,
        enabled: bool = True,
        root_name: str = "sim.tick",
        capacity: int = 30_000,
        top_n: int = 10,
    ):
        self.tracer = tracer or TRACER
        self.store = store
        self.enabled = enabled
        self.root_name = root_name
        self.capacity = capacity
        self.top_n = top_n
        self.records: list[dict] = []
        #: raw-span ring (debugging / tracez): keep-NEWEST, evictions
        #: counted in ``spans_dropped``. Since ISSUE 14 the RECORD no
        #: longer depends on it — every finishing span folds into the
        #: per-path/per-name rollups below at export time, so a 500k-span
        #: storm tick overflowing the ring still produces exact path
        #: totals and the phase-sum reconciliation holds at any scale.
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._dropped = 0
        self._truncated = 0
        self._seen = 0
        self._lock = threading.Lock()
        #: name-path tuple → [count, total_ms, counters|None]
        self._paths: dict[tuple, list] = {}
        #: name → [count, total_ms, self_ms]
        self._names: dict[str, list] = {}
        #: open-span id → summed child duration (popped at finish)
        self._child_sum: dict[str, float] = {}

    # -- exporter interface (the capture sink) -----------------------------
    def export(self, span: Span) -> None:
        dur = span.duration
        # resolve the full name path NOW: ancestors are still open (a
        # span always finishes before its parent), and the Span.parent
        # chain reaches them without any lookup table
        parts = [span.name]
        p = span.parent
        depth = 0
        while p is not None and depth < 64:
            parts.append(p.name)
            p = p.parent
            depth += 1
        truncated = p is not None  # >64 ancestors: pathological nesting
        parts.reverse()
        path = tuple(parts)
        ms = dur * 1e3
        with self._lock:
            self._seen += 1
            if truncated:
                # a truncated path cannot anchor under the root and
                # would silently vanish from the tree — count it so the
                # reconciliation gate's failure is explicable
                self._truncated += 1
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._spans.append(span)
            child = self._child_sum.pop(span.span_id, 0.0)
            if span.parent_id:
                self._child_sum[span.parent_id] = (
                    self._child_sum.get(span.parent_id, 0.0) + dur
                )
            self_ms = max(0.0, dur - child) * 1e3
            ent = self._names.get(span.name)
            if ent is None:
                self._names[span.name] = [1, ms, self_ms]
            else:
                ent[0] += 1
                ent[1] += ms
                ent[2] += self_ms
            pent = self._paths.get(path)
            if pent is None:
                pent = self._paths[path] = [0, 0.0, None]
            pent[0] += 1
            pent[1] += ms
            if span.counters:
                if pent[2] is None:
                    pent[2] = dict(span.counters)
                else:
                    acc = pent[2]
                    for k, v in span.counters.items():
                        acc[k] = acc.get(k, 0.0) + v

    # -- the capture window ------------------------------------------------
    @contextlib.contextmanager
    def tick(self, tick_no: int, **tags):
        if not self.enabled:
            yield None
            return
        self._spans.clear()
        self._dropped = 0
        self._truncated = 0
        self._seen = 0
        self._paths = {}
        self._names = {}
        self._child_sum = {}
        commits0 = (
            self.store.commit_counts_snapshot() if self.store is not None else {}
        )
        counters0 = REGISTRY.counter_totals()
        root = None
        try:
            with self.tracer.recording(self):
                with self.tracer.span(self.root_name, tick=tick_no, **tags) as r:
                    root = r
                    yield r
        finally:
            if root is not None:
                self.records.append(
                    self._build(tick_no, root, commits0, counters0)
                )

    def _tree_from_paths(self, root: Span) -> dict:
        """The name-keyed span tree rebuilt from the per-path rollup —
        same shape ``_tree`` produced from raw spans, but exact under
        ring eviction (dropped spans already contributed at export)."""
        root_node: dict = {"ms": 0.0, "count": 0}
        for path in sorted(self._paths):
            if not path or path[0] != root.name:
                continue  # ambient spans outside the tick trace
            count, ms, counters = self._paths[path]
            node = root_node
            for name in path[1:]:
                node = node.setdefault("children", {}).setdefault(
                    name, {"ms": 0.0, "count": 0}
                )
            node["ms"] = round(node["ms"] + ms, 3)
            node["count"] += count
            if counters:
                node["counters"] = {
                    k: counters[k] for k in sorted(counters)
                }
        return {root.name: root_node}

    def _build(self, tick_no, root, commits0, counters0) -> dict:
        commits: dict[str, int] = {}
        if self.store is not None:
            for key, n in self.store.commit_counts_snapshot().items():
                d = n - commits0.get(key, 0)
                if d:
                    commits[f"{key[0]}.{key[1]}"] = d
        counters = {
            name: round(total - counters0.get(name, 0.0), 3)
            for name, total in REGISTRY.counter_totals().items()
            if total != counters0.get(name, 0.0)
        }
        agg = self._names
        top = sorted(agg.items(), key=lambda kv: -kv[1][2])[: self.top_n]
        return {
            "tick": tick_no,
            "total_ms": round(root.duration * 1e3, 3),
            "spans": self._seen,
            "spans_dropped": self._dropped,
            "paths_truncated": self._truncated,
            "tree": self._tree_from_paths(root),
            "top_self_ms": [
                {
                    "name": name,
                    "count": n,
                    "total_ms": round(tot, 3),
                    "self_ms": round(slf, 3),
                }
                for name, (n, tot, slf) in top
            ],
            # UNtruncated by-name totals (span names are few dozen at
            # most) — the run aggregate sums these, so a cost that is
            # 11th-by-self-time every tick still shows up in the run view
            "self_ms_by_name": {
                name: {
                    "count": n,
                    "total_ms": round(tot, 3),
                    "self_ms": round(slf, 3),
                }
                for name, (n, tot, slf) in sorted(agg.items())
            },
            "commits": dict(sorted(commits.items())),
            "commits_total": sum(commits.values()),
            "counters": dict(sorted(counters.items())),
        }

    # -- aggregation -------------------------------------------------------
    def phases_ms(self, record: dict) -> dict[str, float]:
        """Lift the canonical phase durations out of one record's tree,
        including the ``other`` bucket (scheduler tick time outside the
        four named phases) — the same decomposition the harness timing
        reports, derived purely from spans."""

        def find(node: dict, name: str) -> float:
            for child_name, child in node.get("children", {}).items():
                if child_name == name:
                    return child["ms"]
                found = find(child, name)
                if found:
                    return found
            return 0.0

        root = next(iter(record["tree"].values()))
        out = {}
        for phase, names in PHASE_SPANS.items():
            out[phase] = sum(find(root, n) for n in names)
        sched = find(root, "scheduler.tick")
        out["other"] = max(
            0.0,
            sched - sum(out[p] for p in ("store", "encode", "solve", "bind")),
        )
        return out

    def aggregate(self) -> dict:
        """The run-level flight record for the headline JSON: p50 span
        tree by path, aggregate top self-time, summed commit breakdown."""
        if not self.records:
            return {}
        # Per-path p50 over ticks. A path ABSENT from a record counts as
        # 0.0 ms in that record — the span genuinely cost nothing that
        # tick. Medianing only over records where the path appeared gave
        # each path its own support: a child that exists only in the one
        # cold tick (sim.arrive/operator.reconcile — 50k reconciles at
        # tick 0, none after) medianed to its cold-tick cost while its
        # every-tick parent medianed to ~0, printing a tree where a
        # child "takes" 5,884 ms inside a 0.025 ms parent (ISSUE 11).
        # With one shared support per record, a sequential child's p50
        # can never exceed its parent's (parallel fan-outs can still sum
        # children past the parent's wall time — that is real
        # concurrency, not an aggregation artifact).
        per_rec: list[dict[str, float]] = []

        def walk(name: str, node: dict, prefix: str, acc: dict):
            path = f"{prefix}/{name}" if prefix else name
            acc[path] = node["ms"]
            for child_name, child in node.get("children", {}).items():
                walk(child_name, child, path, acc)

        for rec in self.records:
            acc: dict[str, float] = {}
            for name, node in rec["tree"].items():
                walk(name, node, "", acc)
            per_rec.append(acc)
        all_paths = sorted({p for acc in per_rec for p in acc})
        tree_p50 = {
            path: round(
                float(np.median([acc.get(path, 0.0) for acc in per_rec])), 3
            )
            for path in all_paths
        }
        commits: dict[str, int] = {}
        for rec in self.records:
            for key, n in rec["commits"].items():
                commits[key] = commits.get(key, 0) + n
        self_tot: dict[str, list[float]] = {}
        for rec in self.records:
            for name, row in rec["self_ms_by_name"].items():
                self_tot.setdefault(name, [0, 0.0])
                self_tot[name][0] += row["count"]
                self_tot[name][1] += row["self_ms"]
        top = sorted(self_tot.items(), key=lambda kv: -kv[1][1])[: self.top_n]
        counters: dict[str, float] = {}
        for rec in self.records:
            for name, d in rec["counters"].items():
                counters[name] = round(counters.get(name, 0.0) + d, 3)
        per_tick_phases = [self.phases_ms(r) for r in self.records]
        return {
            "ticks": len(self.records),
            "spans_total": sum(r["spans"] for r in self.records),
            "spans_dropped": sum(r["spans_dropped"] for r in self.records),
            "tick_span_p50_ms": round(
                float(np.median([r["total_ms"] for r in self.records])), 3
            ),
            "span_tree_p50_ms": tree_p50,
            "phases_p50_ms": {
                phase: round(
                    float(np.median([p.get(phase, 0.0) for p in per_tick_phases])),
                    3,
                )
                for phase in (*PHASE_SPANS, "other")
            },
            # the reconciliation handle: per-tick sum of span-derived
            # phases, medianed — must track timing["tick_p50_ms"] (±5%),
            # since both decompose the same tick from the same spans
            "phase_sum_p50_ms": round(
                float(
                    np.median([sum(p.values()) for p in per_tick_phases])
                ),
                3,
            ),
            "top_self_ms": [
                {"name": name, "count": n, "self_ms": round(slf, 3)}
                for name, (n, slf) in top
            ],
            "commits": dict(sorted(commits.items())),
            "commits_total": sum(commits.values()),
            "counters": dict(sorted(counters.items())),
        }
