"""Placement explainability — per-job "why" attribution + pressure ledger.

The scheduler has always been able to say *that* 42,994 of 500,000 jobs
are still pending, and (since PR-5/13) exactly how many milliseconds the
tick spent deciding so — but every unplaced job collapsed into one
interned string, ``"Unschedulable: insufficient capacity"``. This module
is the decision-attribution layer (ISSUE 15): a small CLOSED taxonomy of
structured reason codes, computed **vectorized from artifacts the hot
path already produces** — the solve's post-backfill residual
``free_after``, the encoder's capacity/feature columns, the shard
router's routing table, the reconcile pass's spill set, the policy
engine's admission order — never a per-job store probe at storm scale.

Reasons land in three sinks:

1. the pod's ``status.reason`` becomes ``Unschedulable: CODE: text``
   (events and ``kubectl describe`` parity preserved — the string is
   interned per code, so 43k unplaced pods share a handful of objects);
2. a per-tick **pressure ledger** (reason × partition × class × tenant
   counts + top-bottleneck attribution per shard) riding the flight
   record, the scenario JSON (``quality.wait_reasons``) and the live
   ``/debug/schedz`` zpage;
3. a per-job **decision trail** (``--explain <job>`` on the sim CLI)
   tracing one job through route → solve → backfill/reconcile → reason.

The taxonomy (primary code = FIRST matching rung of the ladder):

==================== =====================================================
``NO_READY_VNODE``   the partition has no ready virtual node (bind gate)
``NO_FEASIBLE_NODE`` no node in the partition can EVER host one shard
                     (total capacity / feature mask), or the partition is
                     unknown to the inventory
``GANG_ATOMIC``      members fit individually, but fewer than ``need``
                     structurally-eligible nodes exist — the gang can
                     never co-locate in this partition
``SHARD_SPILL``      the gang failed its routed shard, went to the
                     cross-shard reconcile pass, and stayed unplaced even
                     though the merged residual holds ``need`` feasible
                     nodes (the pass's guard/cap/tries blocked it)
``NO_DELAY_GUARD``   the job fits the post-solve residual RIGHT NOW, but
                     the backfill pass withheld it (no-delay guard /
                     bounded tries), or no second pass ran
``PREEMPTION_CAP``   infeasible now, but preemptible lower-class
                     incumbents in the partition were excluded from the
                     bounded preemption pool — a higher cap could free
                     capacity
``FAIRSHARE_DEFERRED`` infeasible now, and a same-class job with LOWER
                     raw priority placed in the same partition this tick
                     — fair-share banding deferred this one behind it
``FRAGMENTED``       aggregate free capacity in the partition covers the
                     job's total ask, but no ``need`` single nodes fit —
                     the capacity exists as dust
``PARTITION_FULL``   the partition genuinely lacks the aggregate free
                     capacity
``UNKNOWN``          no attribution available (remote-solver ticks,
                     explain off) — the pre-ISSUE-15 generic verdict
==================== =====================================================

The streaming-admission fast path keeps its own miss codes
(``no_window | not_ready | unknown_partition | no_fit | guard |
conflict`` — admission/fastpath.py); they describe an *attempt* that
fell through to the batch tick, not a pod's standing verdict, and ride
the same pressure ledger under ``admission_misses``.

Everything here is pure post-processing over NumPy arrays: attribution
never mutates a solve artifact, draws from an RNG, or reorders anything
— explain ON is digest-byte-identical to explain OFF by construction
(the bench-smoke ``profile_explain_overhead`` gate enforces it, ≤3%
paired-delta like the trace/WAL gates).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "CODES",
    "REASON_TEXT",
    "UNKNOWN",
    "reason_string",
    "code_of_reason",
    "UnplacedJob",
    "ExplainInputs",
    "PolicyContext",
    "attribute",
    "build_ledger",
    "merge_ledgers",
    "ExplainTrail",
    "SchedzPage",
    "SCHEDZ",
]

NO_READY_VNODE = "NO_READY_VNODE"
NO_FEASIBLE_NODE = "NO_FEASIBLE_NODE"
GANG_ATOMIC = "GANG_ATOMIC"
SHARD_SPILL = "SHARD_SPILL"
NO_DELAY_GUARD = "NO_DELAY_GUARD"
PREEMPTION_CAP = "PREEMPTION_CAP"
FAIRSHARE_DEFERRED = "FAIRSHARE_DEFERRED"
FRAGMENTED = "FRAGMENTED"
PARTITION_FULL = "PARTITION_FULL"
UNKNOWN = "UNKNOWN"

#: the closed taxonomy, ladder order (docs/observability.md mirrors it)
CODES = (
    NO_READY_VNODE,
    NO_FEASIBLE_NODE,
    GANG_ATOMIC,
    SHARD_SPILL,
    NO_DELAY_GUARD,
    PREEMPTION_CAP,
    FAIRSHARE_DEFERRED,
    FRAGMENTED,
    PARTITION_FULL,
    UNKNOWN,
)

REASON_TEXT = {
    NO_READY_VNODE: "no ready virtual node for the partition",
    NO_FEASIBLE_NODE: "no node in the partition can host one shard",
    GANG_ATOMIC: "members fit, but the gang cannot co-locate",
    SHARD_SPILL: "gang spilled its shard; cross-shard pass withheld it",
    NO_DELAY_GUARD: "fits the residual now; backfill withheld it",
    PREEMPTION_CAP: "displaceable incumbents excluded by the preemption cap",
    FAIRSHARE_DEFERRED: "deferred behind other tenants by fair share",
    FRAGMENTED: "capacity exists but no single node fits",
    PARTITION_FULL: "partition free capacity exhausted",
    UNKNOWN: "insufficient capacity",
}

#: interned ``Unschedulable: CODE: text`` strings — one object per
#: (code, detail), so a 43k-pod mark batch shares a handful of strings
#: exactly like the pre-ISSUE-15 single interned reason did
_REASON_MEMO: dict[tuple[str, str], str] = {}
_REASON_LOCK = threading.Lock()


def reason_string(code: str, detail: str = "") -> str:
    """The pod-facing reason for a code: ``Unschedulable: CODE: text``.

    ``detail`` (e.g. the partition name for NO_READY_VNODE) is folded
    into the interned key, preserving the old per-partition interning.
    """
    key = (code, detail)
    s = _REASON_MEMO.get(key)
    if s is None:
        text = REASON_TEXT.get(code, REASON_TEXT[UNKNOWN])
        if detail:
            text = f"{text} ({detail})"
        with _REASON_LOCK:
            s = _REASON_MEMO.setdefault(key, f"Unschedulable: {code}: {text}")
    return s


def code_of_reason(reason: str) -> str | None:
    """Parse the code back out of a pod reason string, or None when the
    reason is not an explain-formatted unschedulable verdict."""
    if not reason.startswith("Unschedulable: "):
        return None
    rest = reason[len("Unschedulable: "):]
    code = rest.split(":", 1)[0]
    return code if code in CODES else None


# --------------------------------------------------------------------------
# Vectorized attribution
# --------------------------------------------------------------------------


@dataclass
class UnplacedJob:
    """One unplaced pending job, captured from the solve's own batch
    rows — d/req/need come straight from the encoded columns, so the
    attribution judges exactly the model the solver judged."""

    j: int  #: tick job index (the scheduler's reordered pending order)
    partition: str
    d: np.ndarray  #: per-shard [cpu, mem, gpu] float demand
    need: int  #: shard count (gang size; 1 = single)
    req: int  #: required feature bits (uint32)
    shard: int = -1  #: routed shard id (-1 = monolithic tick)
    spilled: bool = False  #: reached the cross-shard reconcile pass


@dataclass
class ExplainInputs:
    """Everything attribution reads — all of it produced by the solve
    path anyway (the residual is the admission window's sibling; the
    capacity/feature columns are the encoder's)."""

    #: [N, 3] float residual free AFTER solve + backfill (+ reconcile)
    free: np.ndarray
    #: [N, 3] float total capacity
    capacity: np.ndarray
    #: [N] uint32 feature bitmasks
    features: np.ndarray
    #: partition name → member node positions on the global axis
    part_members: dict
    jobs: list[UnplacedJob] = field(default_factory=list)


@dataclass
class PolicyContext:
    """The policy-tick facts the FAIRSHARE_DEFERRED / PREEMPTION_CAP
    rungs read (None on policy-off ticks — those rungs never match)."""

    #: per pending job (reordered order): class rank
    ranks: list
    #: per pending job: raw spec priority (the pre-fair-share number)
    prios: list
    #: per pending job: partition name
    parts: list
    #: pending job indices that PLACED this tick (solver or backfill)
    placed: set
    fair_share: bool = True
    #: partition → min class rank among preemptible incumbents the
    #: bounded pool EXCLUDED this tick (policy.engine fills it)
    preempt_excluded: dict = field(default_factory=dict)


def _fairshare_floor(ctx: PolicyContext) -> dict[tuple[str, int], float]:
    """(partition, class rank) → min raw priority among PLACED jobs —
    the bar a FAIRSHARE_DEFERRED candidate must beat."""
    floor: dict[tuple[str, int], float] = {}
    for j in ctx.placed:
        key = (ctx.parts[j], ctx.ranks[j])
        p = float(ctx.prios[j])
        cur = floor.get(key)
        if cur is None or p < cur:
            floor[key] = p
    return floor


def attribute(
    inputs: ExplainInputs, policy_ctx: PolicyContext | None = None
) -> dict[int, str]:
    """Primary reason code per unplaced job index.

    Vectorized by demand SHAPE: jobs sharing (partition, demand, feature
    mask) — the common case under trace workloads — share one node-mask
    pass over the partition's member rows, so the cost is
    O(shapes × partition size + unplaced), not O(unplaced × nodes).
    """
    out: dict[int, str] = {}
    if not inputs.jobs:
        return out
    free, cap, feats = inputs.free, inputs.capacity, inputs.features
    groups: dict[tuple, list[UnplacedJob]] = {}
    for job in inputs.jobs:
        groups.setdefault(
            (job.partition, job.d.tobytes(), job.req), []
        ).append(job)
    fair_floor: dict[tuple[str, int], float] | None = None
    if policy_ctx is not None and policy_ctx.fair_share:
        fair_floor = _fairshare_floor(policy_ctx)
    #: partition → [cpu, mem, gpu] aggregate residual free (memoized —
    #: shapes within a partition share it)
    agg_free: dict[str, np.ndarray] = {}
    for (part, _dkey, req), jobs in sorted(
        groups.items(), key=lambda kv: (kv[0][0], kv[0][1], kv[0][2])
    ):
        m = inputs.part_members.get(part)
        if m is None or len(m) == 0:
            for job in jobs:
                out[job.j] = NO_FEASIBLE_NODE
            continue
        d = jobs[0].d
        feat_ok = (np.uint32(req) & ~feats[m]) == 0
        cap_count = int(((cap[m] >= d).all(axis=1) & feat_ok).sum())
        free_count = int(((free[m] >= d).all(axis=1) & feat_ok).sum())
        total_free = agg_free.get(part)
        if total_free is None:
            total_free = agg_free[part] = np.clip(
                free[m], 0.0, None
            ).sum(axis=0)
        for job in jobs:
            need = job.need
            if cap_count == 0:
                code = NO_FEASIBLE_NODE
            elif need > 1 and cap_count < need:
                code = GANG_ATOMIC
            elif free_count >= need:
                code = SHARD_SPILL if job.spilled else NO_DELAY_GUARD
            else:
                code = ""
                if policy_ctx is not None:
                    rank = policy_ctx.ranks[job.j]
                    excl = policy_ctx.preempt_excluded.get(part)
                    if excl is not None and rank > excl:
                        code = PREEMPTION_CAP
                    elif fair_floor is not None:
                        bar = fair_floor.get((part, rank))
                        if bar is not None and float(
                            policy_ctx.prios[job.j]
                        ) > bar:
                            code = FAIRSHARE_DEFERRED
                if not code:
                    code = (
                        FRAGMENTED
                        if bool((total_free >= d * need).all())
                        else PARTITION_FULL
                    )
            out[job.j] = code
    return out


# --------------------------------------------------------------------------
# Pressure ledger
# --------------------------------------------------------------------------


def build_ledger(rows: list[tuple[str, str, str, str, int]]) -> dict:
    """One tick's pressure ledger from per-pod attribution rows
    ``(code, partition, class, tenant, shard)``.

    The per-reason counts sum to the unplaced count BY CONSTRUCTION
    (one row per marked pod) — the acceptance invariant the explain
    tests pin. Cells are string-keyed (``code|partition|class|tenant``)
    so the ledger serializes into the flight record / scenario JSON
    without any schema machinery.
    """
    reasons: dict[str, int] = {}
    cells: dict[str, int] = {}
    shards: dict[int, dict[str, int]] = {}
    for code, part, cls, tenant, shard in rows:
        reasons[code] = reasons.get(code, 0) + 1
        key = f"{code}|{part}|{cls}|{tenant}"
        cells[key] = cells.get(key, 0) + 1
        if shard >= 0:
            sc = shards.setdefault(shard, {})
            sc[code] = sc.get(code, 0) + 1
    shard_top = {}
    for sid, counts in sorted(shards.items()):
        top = max(sorted(counts), key=lambda c: counts[c])
        shard_top[str(sid)] = {
            "top": top,
            "n": counts[top],
            "unplaced": sum(counts.values()),
        }
    return {
        "unplaced": len(rows),
        "reasons": dict(sorted(reasons.items())),
        "cells": dict(sorted(cells.items())),
        "shards": shard_top,
    }


def merge_ledgers(ledgers: list[dict], top_cells: int = 32) -> dict:
    """Run-level rollup of per-tick ledgers — the ``quality.wait_reasons``
    scorecard axis: job-ticks spent waiting, by reason (and the top
    reason × partition × class × tenant cells)."""
    reasons: dict[str, int] = {}
    cells: dict[str, int] = {}
    for led in ledgers:
        for code, n in led.get("reasons", {}).items():
            reasons[code] = reasons.get(code, 0) + n
        for key, n in led.get("cells", {}).items():
            cells[key] = cells.get(key, 0) + n
    top = sorted(cells.items(), key=lambda kv: (-kv[1], kv[0]))[:top_cells]
    return {
        "wait_reasons": dict(sorted(reasons.items())),
        "wait_reason_cells": dict(top),
    }


# --------------------------------------------------------------------------
# Decision trail (--explain <job>)
# --------------------------------------------------------------------------


class ExplainTrail:
    """One job's decision trail across the run.

    The scheduler (and the shard executor through it) appends one line
    per decision the TARGET pod flows through — routing, solve outcome,
    reconcile attempt, final reason, bind. All other pods cost nothing:
    every hook is guarded by one name compare.
    """

    def __init__(self, target: str):
        #: the sizecar pod name being traced
        self.target = target
        self.tick = 0  # stamped by the embedder (sim harness) per tick
        self.lines: list[str] = []

    def matches(self, name: str) -> bool:
        return name == self.target

    def add(self, stage: str, msg: str) -> None:
        self.lines.append(f"tick {self.tick}: [{stage}] {msg}")

    def render(self) -> str:
        header = f"decision trail for {self.target}"
        if not self.lines:
            return (
                f"{header}\n  (no decisions recorded — name the SIZECAR "
                "pod, e.g. <job>-sizecar, and check the job arrived)\n"
            )
        return header + "\n" + "\n".join(f"  {ln}" for ln in self.lines) + "\n"


# --------------------------------------------------------------------------
# /debug/schedz
# --------------------------------------------------------------------------


class SchedzPage:
    """The live scheduler-pressure zpage (``/debug/schedz``), fed one
    ledger per solve tick by every PlacementScheduler in the process —
    the tracez pattern (obs/tracing.py) applied to placement decisions."""

    def __init__(self, capacity: int = 64):
        self._ring: deque[tuple[int, dict]] = deque(maxlen=capacity)
        self._ticks = 0
        self._lock = threading.Lock()

    def publish(self, ledger: dict) -> None:
        with self._lock:
            self._ticks += 1
            self._ring.append((self._ticks, ledger))

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._ticks = 0

    def render(self) -> str:
        with self._lock:
            recent = list(self._ring)
        lines = [f"schedz — placement pressure, last {len(recent)} solve ticks", ""]
        if not recent:
            lines.append("(no solve ticks recorded yet)")
            return "\n".join(lines) + "\n"
        agg = merge_ledgers([led for _, led in recent])
        lines.append(f"{'reason':22s} {'job-ticks':>10s}")
        for code, n in sorted(
            agg["wait_reasons"].items(), key=lambda kv: (-kv[1], kv[0])
        ):
            lines.append(f"{code:22s} {n:10d}")
        lines.append("")
        lines.append("top cells (reason|partition|class|tenant):")
        for key, n in list(agg["wait_reason_cells"].items())[:12]:
            lines.append(f"  {key:48s} {n:8d}")
        lines.append("")
        lines.append("recent ticks:")
        for seq, led in recent[-8:]:
            reasons = " ".join(
                f"{c}={n}" for c, n in sorted(led.get("reasons", {}).items())
            )
            lines.append(f"  #{seq}: unplaced={led.get('unplaced', 0)} {reasons}")
            for sid, top in sorted(led.get("shards", {}).items()):
                lines.append(
                    f"      shard {sid}: top={top['top']} "
                    f"({top['n']}/{top['unplaced']})"
                )
        return "\n".join(lines) + "\n"


#: process-wide page, mounted by obs.bootstrap next to /debug/tracez
SCHEDZ = SchedzPage()
