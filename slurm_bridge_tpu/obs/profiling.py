"""Statistical stack profiler behind ``/debug/profilez``.

Reference parity: the virtual-kubelet binary exposes Go's pprof by
side-effect import (/root/reference/cmd/slurm-virtual-kubelet/app/options/
options.go:30 ``_ "net/http/pprof"``), so an operator can ask a live
process where it is spending time. The Python rebuild's counterpart is a
py-spy-style sampler over ``sys._current_frames()``: GET /debug/profilez
samples every thread's stack for a short window and returns collapsed
stacks (most-sampled first) as text — enough to spot a wedged tick or a
hot loop without attaching a debugger to the pod.

Sampling, not tracing: safe on a live bridge (no sys.settrace overhead —
the cost is ~duration/interval stack walks) and it sees ALL threads,
including the reconcile/pod-sync workers and the gRPC executor.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import Counter

#: GET handlers cannot carry query params through the metrics server's
#: prefix routes, so the window is env-tuned; 2 s catches anything hot.
DEFAULT_SECONDS = 2.0
DEFAULT_INTERVAL = 0.01


def sample_profile(
    duration_s: float | None = None, interval_s: float = DEFAULT_INTERVAL
) -> str:
    """Sample all thread stacks for ``duration_s``; collapsed-stack text."""
    if duration_s is None:
        try:
            duration_s = float(os.environ.get("SBT_PROFILE_SECONDS", ""))
        except ValueError:
            duration_s = DEFAULT_SECONDS
        if not duration_s or duration_s <= 0:
            duration_s = DEFAULT_SECONDS
    me = threading.get_ident()
    stacks: Counter[tuple[str, ...]] = Counter()
    samples = 0
    t_end = time.monotonic() + duration_s
    while time.monotonic() < t_end:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # the profiler sampling itself is noise
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(
                    f"{code.co_name} ({os.path.basename(code.co_filename)}"
                    f":{f.f_lineno})"
                )
                f = f.f_back
            stacks[tuple(reversed(stack))] += 1
        samples += 1
        time.sleep(interval_s)
    lines = [
        f"profilez — {samples} samples over {duration_s:.1f}s "
        f"across {len(stacks)} distinct stacks",
        "",
    ]
    for stack, n in stacks.most_common(40):
        lines.append(f"{n:6d}  {';'.join(stack)}")
    return "\n".join(lines) + "\n"
