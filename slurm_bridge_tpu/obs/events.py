"""Event recorder — the user-facing audit trail.

Reference parity: K8s Events with the reason taxonomy of
pkg/common/status.go:14-35 and cmd events/event.go:20-60 (the reference
leans on recorder.Eventf as its audit trail — SURVEY.md §5). Here events
are structured records kept in a ring buffer and logged; the kube layer
mirrors them onto objects so `describe` shows them.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import NamedTuple


class Reason:
    """Event reason taxonomy (kind-prefixed like the reference's NewReason
    helper, common/status.go:37-39)."""

    # job lifecycle
    JOB_CREATED = "SlurmBridgeJobCreated"
    JOB_SUBMITTED = "SlurmBridgeJobSubmitted"
    JOB_RUNNING = "SlurmBridgeJobRunning"
    JOB_SUCCEEDED = "SlurmBridgeJobSucceeded"
    JOB_FAILED = "SlurmBridgeJobFailed"
    JOB_CANCELLED = "SlurmBridgeJobCancelled"
    # placement
    PLACEMENT_OK = "PlacementSucceeded"
    PLACEMENT_FAILED = "PlacementFailed"
    # pods / virtual nodes
    POD_CREATED = "PodCreated"
    POD_FAILED = "PodFailed"
    POD_PENDING = "PodPendingRetry"
    NODE_READY = "VirtualNodeReady"
    NODE_GONE = "VirtualNodeGone"
    # results
    RESULT_FETCH_STARTED = "ResultFetchStarted"
    RESULT_FETCH_DONE = "ResultFetchSucceeded"
    RESULT_FETCH_FAILED = "ResultFetchFailed"


class Event(NamedTuple):
    """A NamedTuple, not a dataclass: the recorder mints ~100k of these
    per cold-start reconcile tick and C-level construction matters."""

    reason: str
    message: str
    kind: str = ""
    name: str = ""
    type: str = "Normal"  # Normal | Warning
    ts: float = 0.0


class EventRecorder:
    def __init__(self, *, capacity: int = 1024, logger: str = "sbt.events"):
        self._events: deque[Event] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._log = logging.getLogger(logger)
        self._sinks: list = []

    def add_sink(self, fn) -> None:
        """fn(Event) — e.g. the kube layer appending to object events."""
        self._sinks.append(fn)

    def event(self, obj, reason: str, message: str, *, warning: bool = False) -> Event:
        return self.emit(
            type(obj).__name__ if obj is not None else "",
            getattr(obj, "name", "") if obj is not None else "",
            reason,
            message,
            warning=warning,
        )

    def emit(
        self, kind: str, name: str, reason: str, message: str,
        *, warning: bool = False,
    ) -> Event:
        """The object-free form: columnar hot paths (bind, batched
        submit, sweep) record events without materializing a frozen view
        just to read its kind and name."""
        ev = Event(
            reason=reason,
            message=message,
            kind=kind,
            name=name,
            type="Warning" if warning else "Normal",
            ts=time.time(),
        )
        with self._lock:
            self._events.append(ev)
        # isEnabledFor before the log call: the simulator/benchmarks quiet
        # this logger and emit ~100k events per cold-start tick — skipping
        # the no-op logging machinery is a real win there
        level = logging.WARNING if warning else logging.INFO
        if self._log.isEnabledFor(level):
            self._log.log(
                level, "%s %s/%s: %s", ev.reason, ev.kind, ev.name, ev.message
            )
        for sink in self._sinks:
            sink(ev)
        return ev

    def emit_batch(
        self,
        kind: str,
        reason: str,
        pairs: list[tuple[str, str]],
        *,
        warning: bool = False,
    ) -> None:
        """Many events of one (kind, reason) in one pass — ONE lock
        acquisition, one logger-level probe, one timestamp (the batch is
        one logical commit; consumers key on reason/name, not ts). The
        columnar hot paths emit 45k+ events per cold tick; the per-event
        lock/log overhead was a visible slice of the bind phase."""
        if not pairs:
            return
        t = "Warning" if warning else "Normal"
        now = time.time()
        evs = [
            Event(reason=reason, message=msg, kind=kind, name=nm,
                  type=t, ts=now)
            for nm, msg in pairs
        ]
        with self._lock:
            self._events.extend(evs)
        level = logging.WARNING if warning else logging.INFO
        if self._log.isEnabledFor(level):
            for ev in evs:
                self._log.log(
                    level, "%s %s/%s: %s",
                    ev.reason, ev.kind, ev.name, ev.message,
                )
        for sink in self._sinks:
            for ev in evs:
                sink(ev)

    def events(self, *, name: str | None = None) -> list[Event]:
        with self._lock:
            evs = list(self._events)
        if name is not None:
            evs = [e for e in evs if e.name == name]
        return evs
