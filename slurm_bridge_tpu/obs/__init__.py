"""Observability: structured logging, metrics registry, event recorder,
distributed tracing (spans / samplers / exporters / tracez)."""

from slurm_bridge_tpu.obs.logging import setup_logging
from slurm_bridge_tpu.obs.metrics import Counter, Gauge, Histogram, MetricsRegistry, REGISTRY
from slurm_bridge_tpu.obs.events import Event, EventRecorder, Reason
from slurm_bridge_tpu.obs.tracing import (
    TRACER,
    InMemoryExporter,
    JsonFileExporter,
    LogExporter,
    Span,
    Tracer,
    setup_tracing,
    tracing_interceptor,
)
from slurm_bridge_tpu.obs.otlp import OtlpHttpExporter

__all__ = [
    "setup_logging",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "Event",
    "EventRecorder",
    "Reason",
    "TRACER",
    "Tracer",
    "Span",
    "LogExporter",
    "JsonFileExporter",
    "InMemoryExporter",
    "OtlpHttpExporter",
    "setup_tracing",
    "tracing_interceptor",
]
