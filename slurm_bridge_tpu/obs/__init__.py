"""Observability: structured logging, metrics registry, event recorder."""

from slurm_bridge_tpu.obs.logging import setup_logging
from slurm_bridge_tpu.obs.metrics import Counter, Gauge, Histogram, MetricsRegistry, REGISTRY
from slurm_bridge_tpu.obs.events import Event, EventRecorder, Reason

__all__ = [
    "setup_logging",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "Event",
    "EventRecorder",
    "Reason",
]
