"""Shared daemon observability bootstrap — flags + one-call startup.

Every daemon (agent main, bridge main) gets the same observability surface
the reference spreads across its binaries: a metrics server with
healthz/readyz probes (bridge-operator.go:57,100-107), tracing with
env-overridable sampling (SURVEY.md §5), and the /debug/tracez zpages view.
One helper holds the one correct version so the daemons cannot diverge.
"""

from __future__ import annotations

import logging

from slurm_bridge_tpu.obs.metrics import REGISTRY
from slurm_bridge_tpu.obs.tracing import TRACER, setup_tracing

log = logging.getLogger("sbt.obs")


def add_observability_flags(parser, *, metrics_port_default: int = 0) -> None:
    parser.add_argument(
        "--metrics-port", type=int, default=metrics_port_default,
        help="metrics/healthz/readyz/tracez port; 0 disables",
    )
    parser.add_argument(
        "--trace-sample", default=None,
        help="always|never|0-100 (default: $SBT_TRACE_SAMPLE or never)",
    )
    parser.add_argument(
        "--trace-exporter", default=None,
        help="log|jsonfile|memory (default: $SBT_TRACE_EXPORTER or none)",
    )


def start_observability(
    service: str,
    args,
    *,
    health_checks: dict | None = None,
    ready_checks: dict | None = None,
    node_name: str = "",
):
    """Configure tracing from flags/env and start the metrics server.

    Returns the HTTP server (caller shuts it down) or None when disabled.
    Flags left at None fall through to the SBT_TRACE_* env vars inside
    :func:`setup_tracing`; an explicitly empty value means "off"
    (sample "" → never, exporter "" → none), overriding the env.
    """
    setup_tracing(
        service,
        sample="never" if args.trace_sample == "" else args.trace_sample,
        exporter="" if args.trace_exporter == "" else (args.trace_exporter or None),
        node_name=node_name,
    )
    if not getattr(args, "metrics_port", 0):
        return None
    from slurm_bridge_tpu.fleet.runtime import render_fleetz
    from slurm_bridge_tpu.obs.explain import SCHEDZ
    from slurm_bridge_tpu.obs.profiling import sample_profile

    httpd = REGISTRY.serve(
        args.metrics_port,
        extra_routes={
            "/debug/tracez": lambda: ("text/plain", TRACER.render_tracez()),
            # py-spy-style stack sampling (obs/profiling.py) — the
            # reference's net/http/pprof side-effect import, rebuilt
            "/debug/profilez": lambda: ("text/plain", sample_profile()),
            # placement pressure (ISSUE 15): the live reason-code
            # ledger every PlacementScheduler publishes per solve tick
            "/debug/schedz": lambda: ("text/plain", SCHEDZ.render()),
            # fleet membership/ownership/sidecar health (ISSUE 17):
            # every live FleetRuntime in the process renders here
            "/debug/fleetz": lambda: ("text/plain", render_fleetz()),
        },
        health_checks=health_checks or {"ping": lambda: None},
        ready_checks=ready_checks or {},
    )
    log.info("%s: metrics/healthz/tracez/profilez/schedz/fleetz on :%d",
             service, args.metrics_port)
    return httpd
