"""slurm_bridge_tpu — a TPU-native Kubernetes↔Slurm bridge framework.

A ground-up rebuild of the capability set of chriskery/slurm-bridge-operator
(reference layer map: SURVEY.md §1) with the placement path re-founded on
JAX/XLA:

- ``core``         typed job/partition/node model + Slurm dialect parsers
                   (reference: apis/kubecluster.org/v1alpha1, pkg/slurm-agent/parse.go)
- ``wire``         the WorkloadManager gRPC contract
                   (reference: pkg/workload/workload.proto)
- ``agent``        Slurm CLI driver + gRPC server on the login node
                   (reference: pkg/slurm-agent, cmd/slurm-agent)
- ``solver``       the new thing: JAX/TPU batch placement solver
                   (auction/LP sweep under jit/shard_map; greedy parity baseline)
- ``bridge``       the SlurmBridgeJob reconciler ("operator")
                   (reference: pkg/slurm-bridge-operator)
- ``vnode``        virtual node: capacity advertiser, status translation, logs
                   (reference: pkg/slurm-virtual-kubelet)
- ``configurator`` partition watcher → virtual-node lifecycle
                   (reference: pkg/configurator)
- ``fetcher``      result fetcher (reference: cmd/result-fetcher)
- ``kube``         minimal in-process kube-like object store + watch machinery
- ``parallel``     device mesh / sharding helpers for the solver
- ``obs``          metrics, events, structured logging
"""

__version__ = "0.1.0"
