"""Descriptor-driven gRPC wiring — stubs and servicers without grpc_tools.

The image has the grpc runtime and protoc but not the grpc_python_plugin, so
instead of generated `*_grpc_pb2.py` stubs we derive everything from the
FileDescriptor at runtime: one table per service mapping method name →
(streaming kind, request class, response class), from which we build both
the client stub and the server's generic handler. This is less magic than
it sounds — it is exactly what the generated code does, minus the codegen.

Endpoint grammar mirrors the reference's dial sites: an endpoint ending in
``.sock`` dials/binds a unix-domain socket, anything else TCP
(pkg/slurm-virtual-kubelet/virtual-kubelet.go:112-120,
cmd/slurm-agent/slurm-agent.go:33-47).
"""

from __future__ import annotations

import inspect
import logging
import random
import time
from dataclasses import dataclass

import grpc
from google.protobuf import message_factory

from slurm_bridge_tpu.wire import workload_pb2 as pb


@dataclass(frozen=True)
class MethodSpec:
    name: str
    request_streaming: bool
    response_streaming: bool
    req_cls: type
    resp_cls: type

    @property
    def kind(self) -> str:
        return {
            (False, False): "unary_unary",
            (False, True): "unary_stream",
            (True, False): "stream_unary",
            (True, True): "stream_stream",
        }[(self.request_streaming, self.response_streaming)]


def service_methods(service_name: str) -> tuple[str, list[MethodSpec]]:
    """(full service name, method specs) for a service in workload.proto."""
    svc = pb.DESCRIPTOR.services_by_name[service_name]
    specs = [
        MethodSpec(
            name=m.name,
            request_streaming=m.client_streaming,
            response_streaming=m.server_streaming,
            req_cls=message_factory.GetMessageClass(m.input_type),
            resp_cls=message_factory.GetMessageClass(m.output_type),
        )
        for m in svc.methods
    ]
    return svc.full_name, specs


def normalize_endpoint(endpoint: str) -> str:
    """Reference semantics: *.sock → unix-domain socket target."""
    if endpoint.startswith(("unix:", "dns:", "ipv4:", "ipv6:")):
        return endpoint
    if endpoint.endswith(".sock"):
        return f"unix://{endpoint}" if endpoint.startswith("/") else f"unix:{endpoint}"
    return endpoint


def dial(endpoint: str) -> grpc.Channel:
    """Open an insecure channel (the reference dials insecure everywhere:
    SURVEY.md §5 'Distributed communication backend')."""
    return grpc.insecure_channel(normalize_endpoint(endpoint))


# --------------------------------------------------------------- retries

@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry for transient unary-call failures (PR-8).

    Exponential backoff with equal jitter, capped per-attempt delay, an
    overall deadline, and a closed set of retryable codes. The DEFAULT
    retries only UNAVAILABLE — the transport-flap shape (agent restart,
    wire blip) where the request almost certainly never reached the
    server. DEADLINE_EXCEEDED is transient too but NOT default-safe: the
    deadline can expire AFTER the server processed the call, so retrying
    a ledger-less SubmitJob would duplicate the Slurm job. Callers whose
    writes are idempotent — the bridge, whose every submit carries a
    ``submitter_id`` the agent's journal-backed ledger dedupes
    (``agent/server.py`` + ``agent/journal.py``) — opt in via
    ``RetryPolicy(codes=TRANSIENT_CODES)``. Everything else (NOT_FOUND,
    INVALID_ARGUMENT, INTERNAL…) is the server answering and surfaces
    immediately.
    """

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 1.0
    multiplier: float = 2.0
    #: give up once the NEXT sleep would cross this much elapsed time —
    #: the FALLBACK for methods without a ``method_budgets`` entry
    deadline_s: float = 8.0
    codes: tuple[str, ...] = ("UNAVAILABLE",)
    #: per-RPC budgets: method → (retry deadline s, per-attempt timeout
    #: s). One global deadline treats a 2 k-request SubmitJobs batch and
    #: a Partitions ping identically — and worse, an attempt with no RPC
    #: timeout can hang until the transport gives up, eating the WHOLE
    #: retry budget in one try (the ROADMAP durability leftover). The
    #: table sizes the deadline to the method's real cost and bounds
    #: each attempt so a slow attempt leaves room to retry; a caller's
    #: explicit ``timeout=`` always wins over the table's.
    method_budgets: tuple[tuple[str, float, float], ...] = ()

    def backoff_s(self, attempt: int, rng) -> float:
        """Delay before retry ``attempt`` (1-based): exponential, capped,
        equal-jitter (half fixed + half uniform — never collapses to 0,
        never synchronizes a thundering herd)."""
        raw = min(
            self.max_delay_s, self.base_delay_s * self.multiplier ** (attempt - 1)
        )
        return raw / 2.0 + rng.random() * raw / 2.0

    def _budget(self, method: str) -> tuple[float, float] | None:
        # memoized dict over the tuple: call_with_retries consults the
        # budget on EVERY unary RPC (tens of thousands per sim run) —
        # lazily built because the dataclass is frozen
        table = self.__dict__.get("_budget_map")
        if table is None:
            table = {name: (d, t) for name, d, t in self.method_budgets}
            object.__setattr__(self, "_budget_map", table)
        return table.get(method)

    def deadline_for(self, method: str) -> float:
        """The retry deadline this method's budget allows."""
        b = self._budget(method)
        return b[0] if b is not None else self.deadline_s

    def attempt_timeout_for(self, method: str, timeout):
        """The per-attempt RPC timeout: the caller's explicit value
        wins; otherwise the method's budgeted attempt timeout — but
        ONLY when this policy retries DEADLINE_EXCEEDED. Injecting a
        timeout under a policy that treats the resulting
        DEADLINE_EXCEEDED as fatal would convert a slow-but-healthy
        call that used to succeed into a zero-retry failure; callers on
        the default UNAVAILABLE-only policy keep unbounded attempts
        (None when the table has no entry either)."""
        if timeout is not None:
            return timeout
        if "DEADLINE_EXCEEDED" not in self.codes:
            return None
        b = self._budget(method)
        return b[1] if b is not None else None


#: both transient shapes — for callers whose writes are ledger-deduped
TRANSIENT_CODES = ("UNAVAILABLE", "DEADLINE_EXCEEDED")

#: the WorkloadManager surface's default budgets: heavyweight batched
#: RPCs (a 512-chunk SubmitJobs fans out across the agent's submit pool;
#: JobsInfo answers 45 k rows at the headline shape) get deadlines sized
#: to their cost, cheap inventory/control pings get tight ones — and
#: every entry bounds the per-attempt RPC so one hung call cannot eat
#: the whole retry budget (the attempt bound engages only for callers
#: that retry DEADLINE_EXCEEDED, i.e. ledger-deduped writers like the
#: bridge — see ``attempt_timeout_for``). Values are deliberately
#: generous (≥10× the measured sim-shape costs); the point is
#: proportionality, not tuning.
DEFAULT_METHOD_BUDGETS: tuple[tuple[str, float, float], ...] = (
    # (method, retry deadline s, per-attempt timeout s)
    ("SubmitJobs", 60.0, 30.0),
    ("JobsInfo", 45.0, 20.0),
    ("SubmitJob", 20.0, 10.0),
    ("Nodes", 20.0, 10.0),
    ("Partitions", 8.0, 5.0),
    ("Partition", 8.0, 5.0),
    ("JobInfo", 8.0, 5.0),
    ("JobState", 8.0, 5.0),
    ("CancelJob", 8.0, 5.0),
)

#: the default policy ServiceClient applies to every unary RPC
DEFAULT_RETRY = RetryPolicy(method_budgets=DEFAULT_METHOD_BUDGETS)


def _retries_counter():
    # lazy: wire must stay importable without dragging obs in at module
    # import (same posture as the tracing import in _traced_call)
    global _RETRIES_TOTAL
    if _RETRIES_TOTAL is None:
        from slurm_bridge_tpu.obs.metrics import REGISTRY

        _RETRIES_TOTAL = REGISTRY.counter(
            "sbt_rpc_retries_total",
            "unary RPC attempts retried after a transient status code",
        )
    return _RETRIES_TOTAL


_RETRIES_TOTAL = None


def _code_name(err: grpc.RpcError) -> str:
    code = getattr(err, "code", None)
    if not callable(code):
        return ""
    try:
        c = code()
    except Exception:
        return ""
    return getattr(c, "name", "")


def call_with_retries(
    fn,
    request,
    *,
    method: str,
    policy: RetryPolicy,
    timeout=None,
    sleep=time.sleep,
    clock=time.monotonic,
    rng=None,
    on_retry=None,
):
    """Run one unary call under the retry policy.

    ``sleep``/``clock``/``rng`` are injectable so the simulator retries
    on virtual time (no wall-clock sleeps) and tests are deterministic.
    ``on_retry(method, attempt, code)`` fires before each retry (the
    metric hook; RetryingClient also counts through it).
    """
    rng = rng if rng is not None else random
    start = clock()
    deadline_s = policy.deadline_for(method)
    timeout = policy.attempt_timeout_for(method, timeout)
    attempt = 1
    while True:
        try:
            return fn(request, timeout=timeout)
        except grpc.RpcError as err:
            code = _code_name(err)
            if code not in policy.codes or attempt >= policy.max_attempts:
                raise
            delay = policy.backoff_s(attempt, rng)
            if clock() - start + delay > deadline_s:
                raise
            _retries_counter().inc(method=method)
            if on_retry is not None:
                on_retry(method, attempt, code)
            sleep(delay)
            attempt += 1


class RetryingClient:
    """Bounded-retry wrapper over any WorkloadManager-shaped client —
    the duck-typed form the simulator stacks over its :class:`FaultyClient`
    (``ServiceClient`` applies the same policy natively to real channels).
    Only callable attributes are wrapped; ``close()`` passes through.
    """

    def __init__(
        self,
        inner,
        *,
        policy: RetryPolicy = DEFAULT_RETRY,
        sleep=time.sleep,
        clock=time.monotonic,
        seed: int | None = None,
    ):
        self._inner = inner
        self._policy = policy
        self._sleep = sleep
        self._clock = clock
        self._rng = random.Random(seed) if seed is not None else random
        #: retries performed, by method — the sim's determinism section
        #: reads this (the metric is process-global, runs would bleed)
        self.retries: dict[str, int] = {}

    def close(self) -> None:
        self._inner.close()

    def _count(self, method: str, attempt: int, code: str) -> None:
        self.retries[method] = self.retries.get(method, 0) + 1

    def __getattr__(self, name: str):
        inner_fn = getattr(self._inner, name)
        if not callable(inner_fn) or name.startswith("_"):
            return inner_fn

        def call(request, timeout=None):
            return call_with_retries(
                inner_fn,
                request,
                method=name,
                policy=self._policy,
                timeout=timeout,
                sleep=self._sleep,
                clock=self._clock,
                rng=self._rng,
                on_retry=self._count,
            )

        # memoize: __getattr__ only fires on cache misses afterwards —
        # the sim routes tens of thousands of calls per run through here
        setattr(self, name, call)
        return call


#: method name → ``fn(client_span, response)``, invoked while the
#: ``rpc.client.<Method>`` span is still OPEN (ISSUE 20 trace stitching):
#: the fleet runtime registers a PlaceShard hook that turns the response's
#: worker-side timing summary into synthetic child spans, so the flight
#: recorder's child-sum bookkeeping attributes the round-trip. Hook
#: failures are swallowed — stitching must never break an RPC.
_CLIENT_SPAN_HOOKS: dict = {}


def set_client_span_hook(method_name: str, hook) -> None:
    """Register (or, with ``hook=None``, clear) a per-method client-span
    response hook. Process-wide, last writer wins."""
    if hook is None:
        _CLIENT_SPAN_HOOKS.pop(method_name, None)
    else:
        _CLIENT_SPAN_HOOKS[method_name] = hook


def _traced_call(method_name: str, multicallable, unary: bool):
    """Wrap a multicallable with trace propagation: when the caller is
    inside an active span, a ``traceparent`` metadata entry rides the RPC
    (so the server-side interceptor parents its span into the caller's
    trace) and — for unary RPCs — a client-side ``rpc.client.<Method>``
    span records the round-trip. Outside a trace the wrapper is a
    pass-through: no metadata, no span, one attribute read of overhead."""
    from slurm_bridge_tpu.obs.tracing import TRACER, format_traceparent

    def call(request, timeout=None, metadata=None):
        parent = TRACER.current()
        if parent is None or not parent.sampled:
            # outside a trace — or inside one the sampler discarded (the
            # whole trace exports or none of it): true pass-through, no
            # span build, no metadata tuple, on e.g. 45k fallback RPCs
            return multicallable(request, timeout=timeout, metadata=metadata)
        if not unary:
            # streams outlive the call frame: propagate context only
            md = tuple(metadata or ()) + (
                ("traceparent", format_traceparent(parent)),
            )
            return multicallable(request, timeout=timeout, metadata=md)
        with TRACER.span(f"rpc.client.{method_name}") as span:
            # the server parents under the CLIENT span, not the tick span
            md = tuple(metadata or ()) + (
                ("traceparent", format_traceparent(span)),
            )
            response = multicallable(request, timeout=timeout, metadata=md)
            hook = _CLIENT_SPAN_HOOKS.get(method_name)
            if hook is not None:
                try:
                    hook(span, response)
                except Exception:
                    logging.getLogger("sbt.rpc").exception(
                        "client span hook for %s failed", method_name
                    )
            return response

    return call


def _retrying_call(method_name: str, traced, policy: RetryPolicy):
    """Retry wrapper OUTSIDE the traced call, so every attempt gets its
    own ``rpc.client.<Method>`` span inside an active trace."""

    def call(request, timeout=None, metadata=None):
        return call_with_retries(
            lambda req, timeout=None: traced(req, timeout=timeout, metadata=metadata),
            request,
            method=method_name,
            policy=policy,
            timeout=timeout,
        )

    return call


#: bulk RPCs with a raw-bytes client twin (``<Method>Bytes``): identity
#: response-deserializer, so the caller hands the buffer to
#: wire/coldec.py and decodes straight into columns — no pb2 response
#: object is ever built. Same wire method, same retry budget, same
#: ``rpc.client.<Method>`` span name.
BYTES_METHODS = ("JobsInfo", "Nodes", "SubmitJobs")


def _identity_bytes(raw: bytes) -> bytes:
    return raw


class ServiceClient:
    """Dynamic client stub: one callable attribute per RPC.

    Unary calls carry bounded retries for transient codes
    (UNAVAILABLE/DEADLINE_EXCEEDED — see :class:`RetryPolicy`); pass
    ``retry=None`` to fail fast instead. Streams are never retried (they
    outlive the call frame; the caller owns resumption).

    The bulk methods additionally expose raw-bytes twins
    (:data:`BYTES_METHODS`, e.g. ``client.JobsInfoBytes``) for the
    zero-object wire→column decode; ``coldec=False`` suppresses them and
    every consumer stays on the pb2 path.

    >>> client = ServiceClient(dial("localhost:9999"), "WorkloadManager")
    >>> client.SubmitJob(pb.SubmitJobRequest(script="...", partition="debug"))
    """

    def __init__(
        self,
        channel: grpc.Channel,
        service_name: str,
        *,
        retry: RetryPolicy | None = DEFAULT_RETRY,
        coldec: bool = True,
    ):
        self._channel = channel
        full_name, specs = service_methods(service_name)
        for spec in specs:
            factory = getattr(channel, spec.kind)
            multicallable = factory(
                f"/{full_name}/{spec.name}",
                request_serializer=spec.req_cls.SerializeToString,
                response_deserializer=spec.resp_cls.FromString,
            )
            unary = spec.kind == "unary_unary"
            call = _traced_call(spec.name, multicallable, unary=unary)
            if unary and retry is not None:
                call = _retrying_call(spec.name, call, retry)
            setattr(self, spec.name, call)
            if coldec and unary and spec.name in BYTES_METHODS:
                # request side is a passthrough too (ISSUE 18): the
                # provider's worker-pool pre-encode hands the twin raw
                # SubmitJobsRequest bytes; a pb2 message still
                # serializes exactly as before
                raw_mc = factory(
                    f"/{full_name}/{spec.name}",
                    request_serializer=_bytes_passthrough(
                        spec.req_cls.SerializeToString
                    ),
                    response_deserializer=_identity_bytes,
                )
                raw_call = _traced_call(spec.name, raw_mc, unary=True)
                if retry is not None:
                    raw_call = _retrying_call(spec.name, raw_call, retry)
                setattr(self, spec.name + "Bytes", raw_call)

    def close(self) -> None:
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def generic_handler(servicer, service_name: str) -> grpc.GenericRpcHandler:
    """Build the server-side handler table from a servicer object.

    The servicer implements methods named after the RPCs (missing ones
    return UNIMPLEMENTED — unlike the reference's JobState panic,
    api/slurm.go:48-51, an absent method degrades to a clean status).
    """
    full_name, specs = service_methods(service_name)
    handlers = {}
    for spec in specs:
        fn = getattr(servicer, spec.name, None)
        if fn is None:
            continue
        maker = getattr(grpc, f"{spec.kind}_rpc_method_handler")
        handlers[spec.name] = maker(
            fn,
            request_deserializer=spec.req_cls.FromString,
            response_serializer=_bytes_passthrough(
                spec.resp_cls.SerializeToString
            ),
        )
    return grpc.method_handlers_generic_handler(full_name, handlers)


def _bytes_passthrough(serialize):
    """Serializer accepting EITHER a message or pre-serialized wire
    bytes. Used on both halves of the bytes fast path: a servicer may
    hand back an already-assembled response buffer (ISSUE 14), and a
    Bytes-twin caller may hand in a pre-encoded request (the ISSUE 18
    worker-pool submit encode) — the wire is identical either way."""

    def ser(resp):
        return resp if isinstance(resp, bytes) else serialize(resp)

    return ser


def serve(
    servicers: dict[str, object],
    endpoint: str,
    *,
    max_workers: int = 16,
    interceptors: tuple = (),
) -> grpc.Server:
    """Start a server hosting {service_name: servicer} at endpoint.

    Returns the started server; caller owns shutdown. Binding ``host:0``
    rewrites the port into the returned server's ``bound_port`` attribute.
    """
    from concurrent import futures

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        interceptors=interceptors,
    )
    for name, servicer in servicers.items():
        server.add_generic_rpc_handlers((generic_handler(servicer, name),))
    target = normalize_endpoint(endpoint)
    port = server.add_insecure_port(target)
    server.bound_port = port  # type: ignore[attr-defined]
    server.start()
    return server
