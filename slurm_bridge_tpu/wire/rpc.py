"""Descriptor-driven gRPC wiring — stubs and servicers without grpc_tools.

The image has the grpc runtime and protoc but not the grpc_python_plugin, so
instead of generated `*_grpc_pb2.py` stubs we derive everything from the
FileDescriptor at runtime: one table per service mapping method name →
(streaming kind, request class, response class), from which we build both
the client stub and the server's generic handler. This is less magic than
it sounds — it is exactly what the generated code does, minus the codegen.

Endpoint grammar mirrors the reference's dial sites: an endpoint ending in
``.sock`` dials/binds a unix-domain socket, anything else TCP
(pkg/slurm-virtual-kubelet/virtual-kubelet.go:112-120,
cmd/slurm-agent/slurm-agent.go:33-47).
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass

import grpc
from google.protobuf import message_factory

from slurm_bridge_tpu.wire import workload_pb2 as pb


@dataclass(frozen=True)
class MethodSpec:
    name: str
    request_streaming: bool
    response_streaming: bool
    req_cls: type
    resp_cls: type

    @property
    def kind(self) -> str:
        return {
            (False, False): "unary_unary",
            (False, True): "unary_stream",
            (True, False): "stream_unary",
            (True, True): "stream_stream",
        }[(self.request_streaming, self.response_streaming)]


def service_methods(service_name: str) -> tuple[str, list[MethodSpec]]:
    """(full service name, method specs) for a service in workload.proto."""
    svc = pb.DESCRIPTOR.services_by_name[service_name]
    specs = [
        MethodSpec(
            name=m.name,
            request_streaming=m.client_streaming,
            response_streaming=m.server_streaming,
            req_cls=message_factory.GetMessageClass(m.input_type),
            resp_cls=message_factory.GetMessageClass(m.output_type),
        )
        for m in svc.methods
    ]
    return svc.full_name, specs


def normalize_endpoint(endpoint: str) -> str:
    """Reference semantics: *.sock → unix-domain socket target."""
    if endpoint.startswith(("unix:", "dns:", "ipv4:", "ipv6:")):
        return endpoint
    if endpoint.endswith(".sock"):
        return f"unix://{endpoint}" if endpoint.startswith("/") else f"unix:{endpoint}"
    return endpoint


def dial(endpoint: str) -> grpc.Channel:
    """Open an insecure channel (the reference dials insecure everywhere:
    SURVEY.md §5 'Distributed communication backend')."""
    return grpc.insecure_channel(normalize_endpoint(endpoint))


def _traced_call(method_name: str, multicallable, unary: bool):
    """Wrap a multicallable with trace propagation: when the caller is
    inside an active span, a ``traceparent`` metadata entry rides the RPC
    (so the server-side interceptor parents its span into the caller's
    trace) and — for unary RPCs — a client-side ``rpc.client.<Method>``
    span records the round-trip. Outside a trace the wrapper is a
    pass-through: no metadata, no span, one attribute read of overhead."""
    from slurm_bridge_tpu.obs.tracing import TRACER, format_traceparent

    def call(request, timeout=None, metadata=None):
        parent = TRACER.current()
        if parent is None or not parent.sampled:
            # outside a trace — or inside one the sampler discarded (the
            # whole trace exports or none of it): true pass-through, no
            # span build, no metadata tuple, on e.g. 45k fallback RPCs
            return multicallable(request, timeout=timeout, metadata=metadata)
        if not unary:
            # streams outlive the call frame: propagate context only
            md = tuple(metadata or ()) + (
                ("traceparent", format_traceparent(parent)),
            )
            return multicallable(request, timeout=timeout, metadata=md)
        with TRACER.span(f"rpc.client.{method_name}") as span:
            # the server parents under the CLIENT span, not the tick span
            md = tuple(metadata or ()) + (
                ("traceparent", format_traceparent(span)),
            )
            return multicallable(request, timeout=timeout, metadata=md)

    return call


class ServiceClient:
    """Dynamic client stub: one callable attribute per RPC.

    >>> client = ServiceClient(dial("localhost:9999"), "WorkloadManager")
    >>> client.SubmitJob(pb.SubmitJobRequest(script="...", partition="debug"))
    """

    def __init__(self, channel: grpc.Channel, service_name: str):
        self._channel = channel
        full_name, specs = service_methods(service_name)
        for spec in specs:
            factory = getattr(channel, spec.kind)
            multicallable = factory(
                f"/{full_name}/{spec.name}",
                request_serializer=spec.req_cls.SerializeToString,
                response_deserializer=spec.resp_cls.FromString,
            )
            setattr(
                self,
                spec.name,
                _traced_call(
                    spec.name, multicallable, unary=spec.kind == "unary_unary"
                ),
            )

    def close(self) -> None:
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def generic_handler(servicer, service_name: str) -> grpc.GenericRpcHandler:
    """Build the server-side handler table from a servicer object.

    The servicer implements methods named after the RPCs (missing ones
    return UNIMPLEMENTED — unlike the reference's JobState panic,
    api/slurm.go:48-51, an absent method degrades to a clean status).
    """
    full_name, specs = service_methods(service_name)
    handlers = {}
    for spec in specs:
        fn = getattr(servicer, spec.name, None)
        if fn is None:
            continue
        maker = getattr(grpc, f"{spec.kind}_rpc_method_handler")
        handlers[spec.name] = maker(
            fn,
            request_deserializer=spec.req_cls.FromString,
            response_serializer=spec.resp_cls.SerializeToString,
        )
    return grpc.method_handlers_generic_handler(full_name, handlers)


def serve(
    servicers: dict[str, object],
    endpoint: str,
    *,
    max_workers: int = 16,
    interceptors: tuple = (),
) -> grpc.Server:
    """Start a server hosting {service_name: servicer} at endpoint.

    Returns the started server; caller owns shutdown. Binding ``host:0``
    rewrites the port into the returned server's ``bound_port`` attribute.
    """
    from concurrent import futures

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        interceptors=interceptors,
    )
    for name, servicer in servicers.items():
        server.add_generic_rpc_handlers((generic_handler(servicer, name),))
    target = normalize_endpoint(endpoint)
    port = server.add_insecure_port(target)
    server.bound_port = port  # type: ignore[attr-defined]
    server.start()
    return server
