"""The WorkloadManager + PlacementSolver gRPC contract.

``workload_pb2.py`` is generated from ``workload.proto`` with plain protoc
(`protoc --python_out=. workload.proto` from this directory) and committed;
:mod:`rpc` derives stubs and handlers from its descriptors at runtime, so no
grpc_tools plugin is required.
"""

from slurm_bridge_tpu.wire import workload_pb2 as pb
from slurm_bridge_tpu.wire.rpc import (
    ServiceClient,
    dial,
    generic_handler,
    normalize_endpoint,
    serve,
    service_methods,
)

__all__ = [
    "pb",
    "ServiceClient",
    "dial",
    "generic_handler",
    "normalize_endpoint",
    "serve",
    "service_methods",
]
