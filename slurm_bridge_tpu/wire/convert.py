"""core dataclasses ↔ wire messages.

The reference's equivalents are the hand-written proto mappers in
pkg/slurm-agent/api/slurm.go:369-473; field-by-field equality of these
round-trips is part of the test surface (mirroring
pkg/slurm-agent/api/slurm_test.go:26-103).
"""

from __future__ import annotations

from datetime import datetime, timezone

from slurm_bridge_tpu.core.fastpath import frozen_new
from slurm_bridge_tpu.core.types import (
    UNLIMITED,
    JobDemand,
    JobInfo,
    JobStatus,
    JobStepInfo,
    NodeInfo,
    PartitionInfo,
)
from slurm_bridge_tpu.wire import workload_pb2 as pb
from slurm_bridge_tpu.wire.coldec import uvarint as _uvarint


def _ts(dt: datetime | None) -> int:
    if dt is None:
        return 0
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return int(dt.timestamp())


def _dt(ts: int) -> datetime | None:
    if ts <= 0:
        return None
    return datetime.fromtimestamp(ts, tz=timezone.utc).replace(tzinfo=None)


def demand_to_submit(demand: JobDemand, submitter_id: str = "") -> pb.SubmitJobRequest:
    return pb.SubmitJobRequest(
        nodelist=list(demand.nodelist),
        script=demand.script,
        partition=demand.partition,
        submitter_id=submitter_id,
        run_as_user=demand.run_as_user or 0,
        run_as_group=demand.run_as_group or 0,
        cpus_per_task=demand.cpus_per_task,
        ntasks=demand.ntasks,
        ntasks_per_node=demand.ntasks_per_node,
        nodes=demand.nodes,
        mem_per_cpu_mb=demand.mem_per_cpu_mb,
        array=demand.array,
        job_name=demand.job_name,
        working_dir=demand.working_dir,
        gres=demand.gres,
        licenses=demand.licenses,
        time_limit_s=demand.time_limit_s,
        priority=demand.priority,
    )


def fill_submit_request(
    m: pb.SubmitJobRequest, demand: JobDemand, submitter_id: str = ""
) -> None:
    """Write a demand straight into a wire ``SubmitJobRequest`` — the
    batched-submit fan-out path (45k requests per cold-start tick): a
    request constructed via kwargs and appended to the repeated field
    pays a full message COPY per entry; ``requests.add()`` + this fill
    does not. Field-for-field identical to :func:`demand_to_submit`
    (held together by a test)."""
    if demand.nodelist:
        m.nodelist.extend(demand.nodelist)
    m.script = demand.script
    m.partition = demand.partition
    m.submitter_id = submitter_id
    m.run_as_user = demand.run_as_user or 0
    m.run_as_group = demand.run_as_group or 0
    m.cpus_per_task = demand.cpus_per_task
    m.ntasks = demand.ntasks
    m.ntasks_per_node = demand.ntasks_per_node
    m.nodes = demand.nodes
    m.mem_per_cpu_mb = demand.mem_per_cpu_mb
    m.array = demand.array
    m.job_name = demand.job_name
    m.working_dir = demand.working_dir
    m.gres = demand.gres
    m.licenses = demand.licenses
    m.time_limit_s = demand.time_limit_s
    m.priority = demand.priority


#: precomputed proto3 tags for SubmitJobRequest (workload.proto:64-82):
#: tag = (field_number << 3) | wire_type, wire type 2 for strings, 0 for
#: the int64 varints. Fields 16+ need a 2-byte tag.
_T_SCRIPT = b"\x0a"          # 1, string
_T_PARTITION = b"\x12"       # 2, string
_T_SUBMITTER = b"\x1a"       # 3, string
_T_RUN_AS_USER = b"\x20"     # 4, int64
_T_RUN_AS_GROUP = b"\x28"    # 5, int64
_T_CPUS_PER_TASK = b"\x30"   # 6, int64
_T_NTASKS = b"\x38"          # 7, int64
_T_NTASKS_PER_NODE = b"\x40"  # 8, int64
_T_NODES = b"\x48"           # 9, int64
_T_MEM_PER_CPU = b"\x50"     # 10, int64
_T_ARRAY = b"\x5a"           # 11, string
_T_JOB_NAME = b"\x62"        # 12, string
_T_WORKING_DIR = b"\x6a"     # 13, string
_T_GRES = b"\x72"            # 14, string
_T_LICENSES = b"\x7a"        # 15, string
_T_TIME_LIMIT = b"\x80\x01"  # 16, int64
_T_PRIORITY = b"\x88\x01"    # 17, int64
_T_NODELIST = b"\x92\x01"    # 18, repeated string
#: SubmitJobsRequest.requests (workload.proto), field 1 length-delimited
_T_REQUESTS = b"\x0a"


def encode_submit_entry(
    script: str,
    partition: str,
    submitter_id: str,
    run_as_user: int,
    run_as_group: int,
    cpus_per_task: int,
    ntasks: int,
    ntasks_per_node: int,
    nodes: int,
    mem_per_cpu_mb: int,
    array: str,
    job_name: str,
    working_dir: str,
    gres: str,
    licenses: str,
    time_limit_s: int,
    priority: int,
    nodelist,
) -> bytes:
    """One serialized ``SubmitJobRequest`` message body built by hand —
    byte-identical to pb2 ``SerializeToString`` (held by the fuzz suite
    in tests/test_colpool_write.py): known fields emit in field-number
    order, proto3 default scalars (0 / "") are omitted, repeated string
    entries always emit (an explicit empty hostname still rides the
    wire). This is the column-driven twin of :func:`fill_submit_request`
    that the colpool write op runs in worker processes — no pb2 message
    graph is ever built off the main interpreter."""
    parts = []
    if script:
        b = script.encode("utf-8")
        parts += (_T_SCRIPT, _uvarint(len(b)), b)
    if partition:
        b = partition.encode("utf-8")
        parts += (_T_PARTITION, _uvarint(len(b)), b)
    if submitter_id:
        b = submitter_id.encode("utf-8")
        parts += (_T_SUBMITTER, _uvarint(len(b)), b)
    if run_as_user:
        parts += (_T_RUN_AS_USER, _uvarint(run_as_user))
    if run_as_group:
        parts += (_T_RUN_AS_GROUP, _uvarint(run_as_group))
    if cpus_per_task:
        parts += (_T_CPUS_PER_TASK, _uvarint(cpus_per_task))
    if ntasks:
        parts += (_T_NTASKS, _uvarint(ntasks))
    if ntasks_per_node:
        parts += (_T_NTASKS_PER_NODE, _uvarint(ntasks_per_node))
    if nodes:
        parts += (_T_NODES, _uvarint(nodes))
    if mem_per_cpu_mb:
        parts += (_T_MEM_PER_CPU, _uvarint(mem_per_cpu_mb))
    if array:
        b = array.encode("utf-8")
        parts += (_T_ARRAY, _uvarint(len(b)), b)
    if job_name:
        b = job_name.encode("utf-8")
        parts += (_T_JOB_NAME, _uvarint(len(b)), b)
    if working_dir:
        b = working_dir.encode("utf-8")
        parts += (_T_WORKING_DIR, _uvarint(len(b)), b)
    if gres:
        b = gres.encode("utf-8")
        parts += (_T_GRES, _uvarint(len(b)), b)
    if licenses:
        b = licenses.encode("utf-8")
        parts += (_T_LICENSES, _uvarint(len(b)), b)
    if time_limit_s:
        parts += (_T_TIME_LIMIT, _uvarint(time_limit_s))
    if priority:
        parts += (_T_PRIORITY, _uvarint(priority))
    for host in nodelist:
        b = host.encode("utf-8")
        parts += (_T_NODELIST, _uvarint(len(b)), b)
    return b"".join(parts)


def encode_submit_request(demand: JobDemand, submitter_id: str = "") -> bytes:
    """One demand as a serialized ``SubmitJobsRequest`` *entry* — the
    field-18-last wire bytes pb2 produces for ``requests.add()`` +
    :func:`fill_submit_request`, wrapped with the repeated-field tag.
    Concatenating these per-demand entries IS the serialized
    ``SubmitJobsRequest``."""
    body = encode_submit_entry(
        demand.script,
        demand.partition,
        submitter_id,
        demand.run_as_user or 0,
        demand.run_as_group or 0,
        demand.cpus_per_task,
        demand.ntasks,
        demand.ntasks_per_node,
        demand.nodes,
        demand.mem_per_cpu_mb,
        demand.array,
        demand.job_name,
        demand.working_dir,
        demand.gres,
        demand.licenses,
        demand.time_limit_s,
        demand.priority,
        demand.nodelist,
    )
    return _T_REQUESTS + _uvarint(len(body)) + body


def submit_to_demand(req: pb.SubmitJobRequest) -> JobDemand:
    return JobDemand(
        partition=req.partition,
        script=req.script,
        job_name=req.job_name,
        run_as_user=req.run_as_user or None,
        run_as_group=req.run_as_group or None,
        array=req.array,
        cpus_per_task=int(req.cpus_per_task) or 1,
        ntasks=int(req.ntasks) or 1,
        ntasks_per_node=int(req.ntasks_per_node),
        nodes=int(req.nodes) or 1,
        working_dir=req.working_dir,
        mem_per_cpu_mb=int(req.mem_per_cpu_mb),
        gres=req.gres,
        licenses=req.licenses,
        time_limit_s=int(req.time_limit_s),
        priority=int(req.priority),
        nodelist=tuple(req.nodelist),
    )


def job_info_to_proto(j: JobInfo) -> pb.JobInfo:
    return pb.JobInfo(
        id=j.id,
        user_id=j.user_id,
        name=j.name,
        exit_code=j.exit_code,
        status=int(j.state),
        submit_time=_ts(j.submit_time),
        start_time=_ts(j.start_time),
        run_time_s=j.run_time_s,
        time_limit_s=j.time_limit_s,
        working_dir=j.working_dir,
        std_out=j.std_out,
        std_err=j.std_err,
        partition=j.partition,
        node_list=j.node_list,
        batch_host=j.batch_host,
        num_nodes=j.num_nodes,
        array_id=j.array_id,
        reason=j.reason,
    )


#: enum-by-wire-value table: JobStatus(n) pays the Enum __call__ protocol
#: (~1 µs) on every decoded row; the dict probe is ~20× cheaper
_STATUS_BY_NUM = {int(s): s for s in JobStatus}


def job_info_from_proto(m: pb.JobInfo) -> JobInfo:
    # frozen_new: this decode runs once per live job per status-mirror
    # tick (45k rows at the headline shape); born-frozen construction
    # skips 18 guarded setattrs AND the 18-field commit-time freeze walk
    state = _STATUS_BY_NUM.get(m.status)
    if state is None:  # out-of-range wire value: keep the loud ValueError
        state = JobStatus(m.status)
    return frozen_new(
        JobInfo,
        id=int(m.id),
        user_id=m.user_id,
        name=m.name,
        exit_code=m.exit_code,
        state=state,
        submit_time=_dt(m.submit_time),
        start_time=_dt(m.start_time),
        run_time_s=int(m.run_time_s),
        time_limit_s=int(m.time_limit_s),
        working_dir=m.working_dir,
        std_out=m.std_out,
        std_err=m.std_err,
        partition=m.partition,
        node_list=m.node_list,
        batch_host=m.batch_host,
        num_nodes=int(m.num_nodes),
        array_id=m.array_id,
        reason=m.reason,
    )


def step_to_proto(s: JobStepInfo) -> pb.JobStepInfo:
    return pb.JobStepInfo(
        id=s.id,
        name=s.name,
        start_time=_ts(s.start_time),
        finish_time=_ts(s.finish_time),
        exit_code=s.exit_code,
        status=int(s.state),
    )


def step_from_proto(m: pb.JobStepInfo) -> JobStepInfo:
    return JobStepInfo(
        id=m.id,
        name=m.name,
        start_time=_dt(m.start_time),
        finish_time=_dt(m.finish_time),
        exit_code=int(m.exit_code),
        state=JobStatus(m.status),
    )


def node_to_proto(n: NodeInfo) -> pb.Node:
    return pb.Node(
        name=n.name,
        cpus=n.cpus,
        alloc_cpus=n.alloc_cpus,
        memory_mb=n.memory_mb,
        alloc_memory_mb=n.alloc_memory_mb,
        gpus=n.gpus,
        alloc_gpus=n.alloc_gpus,
        gpu_type=n.gpu_type,
        features=list(n.features),
        state=n.state,
    )


def node_from_proto(m: pb.Node) -> NodeInfo:
    return NodeInfo(
        name=m.name,
        cpus=int(m.cpus),
        alloc_cpus=int(m.alloc_cpus),
        memory_mb=int(m.memory_mb),
        alloc_memory_mb=int(m.alloc_memory_mb),
        gpus=int(m.gpus),
        alloc_gpus=int(m.alloc_gpus),
        gpu_type=m.gpu_type,
        features=tuple(m.features),
        # proto3 unset == "": an unstated node state means schedulable
        # (symmetric with partition_from_proto's `or "UP"`)
        state=m.state or "IDLE",
    )


def nodes_from_protos(msgs) -> list[NodeInfo]:
    """Batch node decode — one comprehension instead of a call per message
    at each use site; the first stage of the tick pipeline
    (docs/tick-pipeline.md) and what the tick benchmark times as "decode"."""
    return [node_from_proto(m) for m in msgs]


class NodesDecodeCache:
    """Content-keyed memo for repeated ``Nodes`` responses.

    A steady-state tick re-fetches an inventory that has not moved, and
    re-decoding 10k node protos costs ~120 ms per caller per tick. The
    cache keys on the serialized response bytes — pure content, so ANY
    field change (a drain, an allocation delta, a vanished node) misses
    and decodes fresh — and replays the previously decoded list.
    Single-slot by design: the access pattern is "same response as last
    tick" or "new cluster state", never a working set.

    On a hit the SAME list (and NodeInfo rows) is returned across ticks.
    That is safe — nothing in solver/ or bridge/ mutates NodeInfo — and
    deliberate: the encoder's identity cache keys on node-object
    identity, so a replayed list also skips the inventory re-encode.
    """

    __slots__ = ("_slot", "_bslot")

    def __init__(self):
        # one (resp ref, key, nodes) tuple, swapped atomically — concurrent
        # pool threads may decode the same response twice but never observe
        # a key paired with another response's rows
        self._slot: tuple[object, bytes, list[NodeInfo]] | None = None
        # the bytes-path twin (ISSUE 14): (bytes ref, decoded) — the raw
        # wire buffer IS the content key, so the hit check is one compare
        # (and one identity probe when the sim re-serves cached bytes)
        self._bslot: tuple[bytes, object] | None = None

    def decode_bytes(self, raw: bytes):
        """Decode a raw ``NodesResponse`` wire buffer via the vectorized
        coldec path, content-memoized on the buffer itself. Returns the
        full :class:`~slurm_bridge_tpu.wire.coldec.NodesDecoded` (the
        incremental mirror needs ``version``/``unchanged`` too); on a
        hit the SAME decoded object — and therefore the same identity-
        stable NodeInfo list — is replayed across ticks."""
        from slurm_bridge_tpu.wire import coldec

        slot = self._bslot
        if slot is not None and (slot[0] is raw or slot[0] == raw):
            if slot[0] is not raw:
                self._bslot = (raw, slot[1])
            return slot[1]
        decoded = coldec.decode_nodes(raw)
        if not decoded.unchanged:
            # tiny unchanged=true answers must not evict the full decode
            self._bslot = (raw, decoded)
            # counted HERE, not at the call sites: a memo replay is not
            # a decode, and the counter exists to read decode volume
            coldec.rows_counter().inc(len(decoded.nodes))
        return decoded

    def decode(self, resp) -> list[NodeInfo]:
        slot = self._slot
        if slot is not None and slot[0] is resp:
            # identity fast path (PR-11): an in-process agent (the sim
            # fake, a frozen stale_snapshot window) replays the SAME
            # response object while nothing changed — skip even the
            # O(nodes) serialize the content compare costs. Holding the
            # ref in the slot keeps the id from being recycled.
            return slot[2]
        key = resp.SerializeToString(deterministic=True)
        if slot is not None and slot[1] == key:
            self._slot = (resp, key, slot[2])
            return slot[2]
        nodes = nodes_from_protos(resp.nodes)
        self._slot = (resp, key, nodes)
        return nodes


def partitions_from_protos(msgs) -> list[PartitionInfo]:
    """Batch partition decode (see nodes_from_protos)."""
    return [partition_from_proto(m) for m in msgs]


class PartitionDecodeCache:
    """Identity-keyed memo for repeated ``Partition`` responses (PR-11).

    The sim agent (and a stale_snapshot window) replays the SAME response
    proto while partition membership is unchanged, so the caller can skip
    the O(members) node-tuple rebuild — and, because the decoded
    :class:`PartitionInfo` is also identity-stable, downstream memos
    (cluster-state reuse, shard sub-lists) get an O(1) "nothing moved"
    check. A fresh proto object (the real gRPC path builds one per call)
    decodes fresh — exactly the old behavior."""

    __slots__ = ("_slots",)

    def __init__(self):
        # name → (resp ref, decoded); the ref pin keeps ids stable
        self._slots: dict[str, tuple[object, PartitionInfo]] = {}

    def decode(self, resp) -> PartitionInfo:
        slot = self._slots.get(resp.name)
        if slot is not None and slot[0] is resp:
            return slot[1]
        part = partition_from_proto(resp)
        self._slots[resp.name] = (resp, part)
        return part


def partition_to_proto(p: PartitionInfo) -> pb.PartitionResponse:
    return pb.PartitionResponse(
        name=p.name,
        nodes=list(p.nodes),
        max_time_s=p.max_time_s,
        max_nodes=p.max_nodes,
        max_cpus_per_node=p.max_cpus_per_node,
        max_mem_per_node_mb=p.max_mem_per_node_mb,
        total_cpus=p.total_cpus,
        total_nodes=p.total_nodes,
        state=p.state,
    )


def partition_from_proto(m: pb.PartitionResponse) -> PartitionInfo:
    return PartitionInfo(
        name=m.name,
        nodes=tuple(m.nodes),
        max_time_s=int(m.max_time_s),
        max_nodes=int(m.max_nodes),
        max_cpus_per_node=int(m.max_cpus_per_node),
        max_mem_per_node_mb=int(m.max_mem_per_node_mb),
        total_cpus=int(m.total_cpus),
        total_nodes=int(m.total_nodes),
        state=m.state or "UP",
    )


def demand_to_place(d: JobDemand, *, job_id: str = "") -> pb.PlaceJob:
    """Lower a JobDemand into a PlaceJob for the PlacementSolver sidecar.

    PlaceJob quantities are PER-NODE: the sizecar sizing rule
    (solver/snapshot.py encode_jobs; pkg/slurm-bridge-operator/pod.go:143-162)
    spreads cpu evenly across ``nodes`` shards — sent as the EXACT
    fractional value (the wire fields are doubles) so a sidecar solve
    places identically to the in-process path; rounding up made a job
    whose cpus don't divide evenly by nodes unschedulable on an
    exactly-full cluster only when the sidecar was enabled (ADVICE r3).
    gres is a per-node quantity in Slurm and is not divided; the gres
    *type* rides along as a required feature the solver matches against
    node features.
    """
    from slurm_bridge_tpu.core.arrays import array_len

    arr = array_len(d.array)
    nshards = max(1, d.nodes)
    cpu = d.total_cpus(arr) / nshards
    mem_per_cpu = d.mem_per_cpu_mb or 1024
    gres_parts = d.gres.split(":") if d.gres else []
    gpus = 0
    features: list[str] = []
    if gres_parts and gres_parts[0] == "gpu":
        try:
            gpus = int(gres_parts[-1].split("(")[0]) * max(1, arr)
        except ValueError:
            gpus = 0
    # the gres TYPE is a feature constraint for ANY 3-part gres (tpu:v4:8
    # as much as gpu:a100:2) — mirroring _required_features
    # (solver/snapshot.py); only the count column is gpu-specific
    if len(gres_parts) == 3:
        features.append(gres_parts[1])
    return pb.PlaceJob(
        id=job_id,
        cpus=cpu,
        mem_mb=cpu * mem_per_cpu,
        gpus=gpus,
        partition=d.partition,
        req_features=features,
        nodes=nshards,
        priority=float(d.priority),
    )


def auction_config_to_proto(cfg) -> pb.SolverConfig:
    """AuctionConfig → SolverConfig so a bridge's tuned knobs ride each
    Place RPC instead of being silently replaced by the sidecar's
    launch-time defaults (ADVICE r3)."""
    return pb.SolverConfig(
        rounds=cfg.rounds,
        eta=cfg.eta,
        jitter=cfg.jitter,
        gang_salvage_rounds=cfg.gang_salvage_rounds,
        gang_first=cfg.gang_first,
        affinity_weight=cfg.affinity_weight,
    )


def auction_config_from_proto(msg: pb.SolverConfig, base=None):
    """SolverConfig → AuctionConfig by OVERLAYING the six wire fields onto
    ``base`` (the sidecar's launch-time config): knobs that don't ride the
    wire — candidates, dtype, use_pallas — keep the solver-side tuning
    instead of resetting to dataclass defaults."""
    import dataclasses

    from slurm_bridge_tpu.solver.auction import AuctionConfig

    return dataclasses.replace(
        base or AuctionConfig(),
        rounds=int(msg.rounds),
        eta=float(msg.eta),
        jitter=float(msg.jitter),
        gang_salvage_rounds=int(msg.gang_salvage_rounds),
        gang_first=bool(msg.gang_first),
        affinity_weight=float(msg.affinity_weight),
    )
