"""Vectorized protobuf wire→column decoder for the bulk RPCs (ISSUE 14).

The cold/storm tick's dominant residual cost is not parsing bytes — the
protobuf C runtime does that quickly — it is the Python object churn on
either side of it: one ``JobsInfoEntry``/``JobInfo`` proto materialized,
attribute-read field by field, and discarded per row, 457k rows deep per
mirror pass at the 500k×100k shape. This module removes the objects: the
raw response **bytes** of the three bulk messages are scanned with NumPy
(varint tables + a per-nesting-level field walk that loops over *field
slots* and vectorizes over *messages*) and scattered straight into the
column arrays the mirror, the Nodes decode cache and the submit commit
path already consume. No pb2 object is constructed on the bulk path.

Layout of one decode:

1. The **top level** is walked in plain Python with inlined varint
   reads (it is one field per repeated entry — a NumPy "loop" there
   would pay kernel-dispatch per entry).
2. Nested levels use :class:`_Fields`, whose iteration count is the
   *max field count per message* (≈18 for JobInfo), each iteration one
   set of vector ops over all sibling messages at that depth; varints
   decode per POSITION SET (:func:`_varint_at` — one full-width pass
   for the dominant 1-byte case, compressed tails for longer ones).
3. Scalar fields scatter into int64/uint64 columns (proto3 last-wins via
   ordered fancy assignment); string fields land as ``(start, len)``
   span pairs into the original buffer and materialize Python ``str``
   objects lazily — absent fields (proto3 default "") cost nothing.

**Schema safety.** The field-tag tables below are hand-written (that is
the point: they are the drift risk) and mechanically verified against
the live ``workload_pb2`` descriptor — at import by :func:`verify_tables`
(a mismatch disables the decoder so callers fall back to the pb2 path,
the "unknown schema version" fallback) and in CI by
``hack/regen_pb2_noprotoc.py --check`` (a schema edit that forgets this
decoder fails the hygiene job instead of silently misparsing).

**Failure posture.** Torn or truncated bytes, overrunning lengths,
oversized varints and group wire types raise :class:`DecodeError` —
never garbage columns. Unknown and out-of-order fields decode exactly as
the pb2 path would (skipped / last-wins); the fuzz suite in
``tests/test_coldec.py`` holds decoder ≡ pb2 over randomized protos.
"""

from __future__ import annotations

import numpy as np

from slurm_bridge_tpu.wire import workload_pb2 as pb

__all__ = [
    "DecodeError",
    "decode_jobs_info",
    "decode_nodes",
    "decode_submit_jobs",
    "verify_tables",
    "available",
    "JobsInfoChunk",
    "NodesDecoded",
    "SubmitResults",
    "uvarint",
    "read_uvarint",
]


class DecodeError(ValueError):
    """Malformed wire bytes — callers fall back to the pb2 decode (which
    will surface the same malformation through the protobuf runtime)."""


# ---- wire-type constants ----------------------------------------------

VARINT, I64, LEN, I32 = 0, 1, 2, 5

# ---- the hand field-tag tables (verified against the descriptor) ------
#
# name → (field number, wire type, repeated). Hand-written so a schema
# edit MUST touch this file; `verify_tables` + the hygiene gate make
# forgetting loud. Only messages reachable from the three bulk responses
# appear.

TABLES: dict[str, dict[str, tuple[int, int, bool]]] = {
    "JobsInfoResponse": {
        "jobs": (1, LEN, True),
        "version": (2, VARINT, False),
    },
    "JobsInfoEntry": {
        "job_id": (1, VARINT, False),
        "found": (2, VARINT, False),
        "info": (3, LEN, True),
    },
    "JobInfo": {
        "id": (1, VARINT, False),
        "user_id": (2, LEN, False),
        "name": (3, LEN, False),
        "exit_code": (4, LEN, False),
        "status": (5, VARINT, False),
        "submit_time": (6, VARINT, False),
        "start_time": (7, VARINT, False),
        "run_time_s": (8, VARINT, False),
        "time_limit_s": (9, VARINT, False),
        "working_dir": (10, LEN, False),
        "std_out": (11, LEN, False),
        "std_err": (12, LEN, False),
        "partition": (13, LEN, False),
        "node_list": (14, LEN, False),
        "batch_host": (15, LEN, False),
        "num_nodes": (16, VARINT, False),
        "array_id": (17, LEN, False),
        "reason": (18, LEN, False),
    },
    "NodesResponse": {
        "nodes": (1, LEN, True),
        "version": (2, VARINT, False),
        "unchanged": (3, VARINT, False),
    },
    "Node": {
        "name": (1, LEN, False),
        "cpus": (2, VARINT, False),
        "alloc_cpus": (3, VARINT, False),
        "memory_mb": (4, VARINT, False),
        "alloc_memory_mb": (5, VARINT, False),
        "gpus": (6, VARINT, False),
        "alloc_gpus": (7, VARINT, False),
        "gpu_type": (8, LEN, False),
        "features": (9, LEN, True),
        "state": (10, LEN, False),
    },
    "SubmitJobsResponse": {
        "results": (1, LEN, True),
    },
    "SubmitJobsEntry": {
        "job_id": (1, VARINT, False),
        "ok": (2, VARINT, False),
        "error_code": (3, LEN, False),
        "error": (4, LEN, False),
    },
}

#: proto field type → the wire type its scalar encoding uses (the subset
#: present in the bulk messages; everything else fails verify_tables)
_WIRE_OF_TYPE = {
    3: VARINT,   # int64
    5: VARINT,   # int32
    8: VARINT,   # bool
    9: LEN,      # string
    11: LEN,     # message
    14: VARINT,  # enum
}


def verify_tables() -> list[str]:
    """Diff :data:`TABLES` against the live descriptor; returns the list
    of mismatches (empty = in sync). The hygiene gate fails CI on any;
    at import a mismatch flips :func:`available` off so every caller
    falls back to the pb2 path instead of misparsing."""
    problems: list[str] = []
    pool = pb.DESCRIPTOR.message_types_by_name
    for msg_name, table in TABLES.items():
        desc = pool.get(msg_name)
        if desc is None:
            problems.append(f"{msg_name}: message absent from schema")
            continue
        by_name = {f.name: f for f in desc.fields}
        for fname, (num, wt, rep) in table.items():
            f = by_name.get(fname)
            if f is None:
                problems.append(f"{msg_name}.{fname}: absent from schema")
                continue
            if f.number != num:
                problems.append(
                    f"{msg_name}.{fname}: number {f.number} != table {num}"
                )
            want_wt = _WIRE_OF_TYPE.get(f.type)
            if want_wt is None:
                problems.append(
                    f"{msg_name}.{fname}: unsupported field type {f.type}"
                )
            elif want_wt != wt:
                problems.append(
                    f"{msg_name}.{fname}: wire type {want_wt} != table {wt}"
                )
            actual_rep = (
                f.is_repeated
                if hasattr(type(f), "is_repeated")
                else f.label == f.LABEL_REPEATED  # pragma: no cover
            )
            if actual_rep != rep:
                problems.append(f"{msg_name}.{fname}: repeated-ness drifted")
        for f in desc.fields:
            if f.name not in table:
                problems.append(
                    f"{msg_name}.{f.name}: field {f.number} missing from "
                    "coldec table — update wire/coldec.py with the schema"
                )
    return problems


_SCHEMA_OK: bool | None = None
_ROWS_TOTAL = None
_FALLBACK_TOTAL = None


def rows_counter():
    """``sbt_wire_coldec_rows_total`` — rows decoded straight from wire
    bytes into columns (lazy: wire stays importable without obs)."""
    global _ROWS_TOTAL
    if _ROWS_TOTAL is None:
        from slurm_bridge_tpu.obs.metrics import REGISTRY

        _ROWS_TOTAL = REGISTRY.counter(
            "sbt_wire_coldec_rows_total",
            "bulk-RPC rows decoded by the vectorized wire->column decoder",
        )
    return _ROWS_TOTAL


def fallback_counter():
    """``sbt_wire_coldec_fallback_total{method}`` — decodes that fell
    back to the pb2 path (schema drift, malformed bytes, agents without
    the bulk RPCs)."""
    global _FALLBACK_TOTAL
    if _FALLBACK_TOTAL is None:
        from slurm_bridge_tpu.obs.metrics import REGISTRY

        _FALLBACK_TOTAL = REGISTRY.counter(
            "sbt_wire_coldec_fallback_total",
            "bulk-RPC decodes that engaged the pb2 fallback path",
        )
    return _FALLBACK_TOTAL


def available() -> bool:
    """Whether the decoder's tables match the running schema (memoized).
    False = every consumer uses the pb2 path — the unknown-schema
    fallback of ISSUE 14 satellite 6."""
    global _SCHEMA_OK
    if _SCHEMA_OK is None:
        problems = verify_tables()
        if problems:  # pragma: no cover - requires a drifted schema
            import logging

            logging.getLogger("sbt.wire").warning(
                "coldec tables drifted from schema; pb2 fallback engaged: %s",
                "; ".join(problems),
            )
        _SCHEMA_OK = not problems
    return _SCHEMA_OK


# ---- scalar varint helpers (top-level walk + serializers) -------------


def read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    """(value, next position) for the varint at ``pos`` — plain-Python,
    used only on the top-level walk (one per repeated entry)."""
    result = 0
    shift = 0
    n = len(data)
    while True:
        if pos >= n:
            raise DecodeError("truncated varint")
        b = data[pos]
        result |= (b & 0x7F) << shift
        pos += 1
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift >= 70:
            raise DecodeError("varint over 10 bytes")


def uvarint(value: int) -> bytes:
    """Serialize one unsigned varint (the hand serializers' primitive)."""
    if value < 0:
        value &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


# ---- the NumPy wire scan ----------------------------------------------


def _varint_at(b: np.ndarray, pos: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Decode the varints starting at each position in ``pos``:
    ``(value uint64, length int64)``. A truncated or >10-byte varint
    reports length 0 (the caller raises). Vectorized over the position
    SET — per-byte tables over the whole buffer cost ~10 passes over
    every payload byte; this gathers only at real varint sites, pays the
    full-width ops ONCE (the dominant 1-byte case), and compresses to
    the continuing subset for longer varints."""
    n = b.size
    m = pos.size
    if not m:
        return np.empty(0, np.uint64), np.empty(0, np.int64)
    inb = pos < n
    clean = bool(inb.all())
    byte = b[pos if clean else np.minimum(pos, n - 1)]
    val = (byte & np.uint8(0x7F)).astype(np.uint64)
    cont = byte >= 0x80
    vlen = np.ones(m, np.int64)
    if not clean:
        vlen[~inb] = 0  # truncated at the very start
        cont &= inb
    if not cont.any():
        return val, vlen
    # slow tail: ONLY the continuing positions ride further iterations
    idx = np.nonzero(cont)[0]
    cur = pos[idx] + 1
    shift = np.uint64(7)
    for k in range(1, 10):
        inb = cur < n
        if not inb.all():
            vlen[idx[~inb]] = 0  # truncated mid-varint
            idx, cur = idx[inb], cur[inb]
            if not idx.size:
                return val, vlen
        byte = b[cur]
        val[idx] += (byte & np.uint8(0x7F)).astype(np.uint64) << shift
        more = byte >= 0x80
        done = ~more
        vlen[idx[done]] = k + 1
        idx, cur = idx[more], cur[more] + 1
        if not idx.size:
            return val, vlen
        shift += np.uint64(7)
    vlen[idx] = 0  # over 10 bytes: malformed
    return val, vlen


class _Fields:
    """All fields of M sibling messages with byte ranges
    ``[starts[i], ends[i])``, walked breadth-first: iteration k visits
    the k-th field of every message that still has one, so the loop
    count is the MAX field count per message (~18 for JobInfo) while
    every per-iteration op vectorizes over all M messages. Collected
    field records are ordered by occurrence rank — exactly what proto3
    last-wins scatter needs. Raises :class:`DecodeError` on torn
    varints, bogus lengths, or group wire types."""

    __slots__ = (
        "data", "m", "midx", "tag", "fno", "wt", "fval", "pstart", "plen",
    )

    def __init__(self, b: np.ndarray, data: bytes, starts, ends, m: int):
        self.data = data
        self.m = m
        n = b.size
        midx_p: list = []
        tag_p: list = []
        fval_p: list = []
        ps_p: list = []
        pl_p: list = []
        cur = starts.astype(np.int64, copy=True)
        end = ends.astype(np.int64, copy=False)
        mi = np.arange(cur.size, dtype=np.int64)
        while cur.size:
            live = cur < end
            if not live.all():
                cur, end, mi = cur[live], end[live], mi[live]
                if not cur.size:
                    break
            tag, tlen = _varint_at(b, cur)
            if bool((tlen == 0).any()):
                raise DecodeError("truncated field tag")
            tag = tag.astype(np.int64)
            if bool((tag < 8).any()):
                # field number 0 is invalid on the wire — pb2 rejects
                # it, so must we (the decoder≡pb2 contract)
                raise DecodeError("field number 0")
            wt = tag & 7
            vpos = cur + tlen
            is_len = wt == LEN
            need = (wt == VARINT) | is_len
            if need.all():
                vval, vvlen = _varint_at(b, vpos)
                if bool((vvlen == 0).any()):
                    raise DecodeError("truncated field value")
            else:
                vval = np.zeros(cur.size, np.uint64)
                vvlen = np.zeros(cur.size, np.int64)
                if need.any():
                    vv, vl = _varint_at(b, vpos[need])
                    if bool((vl == 0).any()):
                        raise DecodeError("truncated field value")
                    vval[need] = vv
                    vvlen[need] = vl
            plen = np.minimum(vval, np.uint64(n + 1)).astype(np.int64)
            pstart = vpos + vvlen
            # the common bulk layout is pure varint/len fields: next is
            # pstart (+payload for len) — one multiply instead of a
            # 4-deep where; rare wire types take the general form
            if need.all():
                nxt = pstart + plen * is_len
            else:
                nxt = np.where(
                    need, pstart + plen * is_len,
                    np.where(
                        wt == I32, vpos + 4,
                        np.where(wt == I64, vpos + 8, np.int64(n + 1)),
                    ),
                )
            if bool((nxt > end).any()):
                raise DecodeError(
                    "field overruns message bounds (torn bytes?)"
                )
            midx_p.append(mi)
            tag_p.append(tag)
            fval_p.append(vval)
            ps_p.append(pstart)
            pl_p.append(plen)
            cur = nxt
        if not midx_p:
            z = np.empty(0, np.int64)
            self.midx = self.tag = self.pstart = self.plen = z
            self.fval = np.empty(0, np.uint64)
            return
        if len(midx_p) == 1:
            self.midx, self.tag = midx_p[0], tag_p[0]
            self.fval, self.pstart, self.plen = fval_p[0], ps_p[0], pl_p[0]
        else:
            self.midx = np.concatenate(midx_p)
            self.tag = np.concatenate(tag_p)
            self.fval = np.concatenate(fval_p)
            self.pstart = np.concatenate(ps_p)
            self.plen = np.concatenate(pl_p)

    def varint_i64(self, field_no: int, default: int = 0) -> np.ndarray:
        """Signed-int64 column (proto int64/int32/enum/bool semantics)."""
        sel = self.tag == (field_no << 3 | VARINT)
        col = np.full(self.m, default, np.int64)
        col[self.midx[sel]] = self.fval[sel].astype(np.int64)
        return col

    def spans(self, field_no: int) -> tuple[np.ndarray, np.ndarray]:
        """(start, len) span columns for a string field; absent rows get
        start = -1 (materialize as "")."""
        sel = self.tag == (field_no << 3 | LEN)
        midx = self.midx[sel]
        start = np.full(self.m, -1, np.int64)
        length = np.zeros(self.m, np.int64)
        start[midx] = self.pstart[sel]
        length[midx] = self.plen[sel]
        return start, length

    def submessages(self, field_no: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(parent midx, payload starts, payload ends) of every
        occurrence of a repeated message field, occurrence-ordered."""
        sel = self.tag == (field_no << 3 | LEN)
        return self.midx[sel], self.pstart[sel], self.pstart[sel] + self.plen[sel]

    def strings(self, field_no: int) -> np.ndarray:
        """Materialized str column (absent → "") — eager form for the
        low-row-count messages (submit results, nodes)."""
        start, length = self.spans(field_no)
        return materialize_strings(self.data, start, length)


def materialize_strings(data: bytes, start: np.ndarray, length: np.ndarray) -> np.ndarray:
    """Object column of ``str`` from span pairs; only present, non-empty
    spans pay a decode (proto3 never serializes empty strings, so the
    common absent case is a fill)."""
    out = np.full(start.size, "", object)
    present = np.nonzero(start >= 0)[0]
    if present.size:
        try:
            for i in present.tolist():
                s = int(start[i])
                out[i] = data[s : s + int(length[i])].decode("utf-8")
        except UnicodeDecodeError as e:  # pb2 rejects it too
            raise DecodeError(f"invalid UTF-8 in string field: {e}") from None
    return out


def _walk_top(data: bytes) -> list[tuple[int, int, int, int]]:
    """Top-level fields as ``(field_no, wire_type, a, b)`` where a/b are
    (value, 0) for varints and (payload start, payload end) for
    length-delimited fields. Plain Python with inlined varint reads: the
    top level of a bulk response is one field per repeated entry, where
    a vectorized walk would pay NumPy dispatch per entry and a helper
    call per varint doubles the loop cost."""
    out: list[tuple[int, int, int, int]] = []
    append = out.append
    pos, n = 0, len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        if tag < 8:
            # field number 0: invalid on the wire, pb2 rejects it
            raise DecodeError("field number 0")
        if tag >= 0x80:
            tag &= 0x7F
            shift = 7
            while True:
                if pos >= n:
                    raise DecodeError("truncated varint")
                byte = data[pos]
                pos += 1
                tag |= (byte & 0x7F) << shift
                if byte < 0x80:
                    break
                shift += 7
                if shift >= 70:
                    raise DecodeError("varint over 10 bytes")
        wt = tag & 7
        if wt == LEN:
            if pos >= n:
                raise DecodeError("truncated varint")
            ln = data[pos]
            pos += 1
            if ln >= 0x80:
                ln &= 0x7F
                shift = 7
                while True:
                    if pos >= n:
                        raise DecodeError("truncated varint")
                    byte = data[pos]
                    pos += 1
                    ln |= (byte & 0x7F) << shift
                    if byte < 0x80:
                        break
                    shift += 7
                    if shift >= 70:
                        raise DecodeError("varint over 10 bytes")
            end = pos + ln
            if end > n:
                raise DecodeError("length-delimited field overruns buffer")
            append((tag >> 3, LEN, pos, end))
            pos = end
        elif wt == VARINT:
            v, pos = read_uvarint(data, pos)
            append((tag >> 3, VARINT, v, 0))
        elif wt == I64:
            pos += 8
        elif wt == I32:
            pos += 4
        else:
            raise DecodeError(f"unsupported wire type {wt} at top level")
        if pos > n:
            raise DecodeError("truncated field at top level")
    return out


def _i64(v: int) -> int:
    """uint64 wire value → signed int64 (proto int64/int32 semantics)."""
    return v - (1 << 64) if v >= (1 << 63) else v


# ---- JobsInfoResponse --------------------------------------------------

#: JobInfo string fields decoded lazily for tier-2 (column name →
#: field number) — matches InfoScratch._FULL_OBJ's column names
_INFO_STR_FIELDS = (
    ("user_id", 2), ("name", 3), ("workdir", 10), ("stdout", 11),
    ("stderr", 12), ("partition", 13), ("nodelist", 14),
    ("batch_host", 15), ("array_id", 17),
)


class JobsInfoChunk:
    """One decoded ``JobsInfoResponse``: per-row columns in exactly the
    accumulation order the pb2 path's :class:`InfoScratch` would produce
    (entry order; ``found=False``/info-less entries yield one UNKNOWN
    placeholder row, found entries one row per ``info`` message).

    Signal + numeric columns are dense arrays; the nine immutable string
    fields stay as spans into :attr:`data` and materialize only for rows
    the caller's diff flags (the tier-2 contract)."""

    __slots__ = (
        "data", "version", "rows", "jid",
        "id", "state", "start_ts", "limit", "submit_ts", "run_time",
        "num_nodes", "exit_code", "reason", "str_spans",
    )

    def __init__(self, data, version, rows, jid, cols, exit_code, reason, spans):
        self.data = data
        self.version = version
        self.rows = rows
        self.jid = jid
        self.id = cols["id"]
        self.state = cols["state"]
        self.start_ts = cols["start_ts"]
        self.limit = cols["limit"]
        self.submit_ts = cols["submit_ts"]
        self.run_time = cols["run_time"]
        self.num_nodes = cols["num_nodes"]
        self.exit_code = exit_code
        self.reason = reason
        #: col name → (start, len) spans for the tier-2 string fields
        self.str_spans = spans


def decode_jobs_info(data: bytes) -> JobsInfoChunk:
    """Decode one ``JobsInfoResponse`` wire buffer into columns."""
    version = 0
    entry_starts: list[int] = []
    entry_ends: list[int] = []
    for fno, wt, a, b in _walk_top(data):
        if fno == 1 and wt == LEN:
            entry_starts.append(a)
            entry_ends.append(b)
        elif fno == 2 and wt == VARINT:
            version = _i64(a)
    m = len(entry_starts)
    if m == 0:
        empty = np.empty(0, np.int64)
        return JobsInfoChunk(
            data, version, 0, empty,
            {k: empty for k in (
                "id", "state", "start_ts", "limit", "submit_ts",
                "run_time", "num_nodes",
            )},
            np.empty(0, object), np.empty(0, object),
            {k: (empty, empty) for k, _ in _INFO_STR_FIELDS},
        )
    b = np.frombuffer(data, np.uint8)
    ef = _Fields(
        b, data,
        np.asarray(entry_starts, np.int64), np.asarray(entry_ends, np.int64), m,
    )
    ejid = ef.varint_i64(1)
    efound = ef.varint_i64(2) != 0
    ipar, istart, iend = ef.submessages(3)
    # entry-major, occurrence-ordered info rows (stable sort keeps the
    # per-entry occurrence order _walk produced)
    order = np.argsort(ipar, kind="stable")
    ipar, istart, iend = ipar[order], istart[order], iend[order]
    icount = np.bincount(ipar, minlength=m)
    present = efound & (icount > 0)
    # row layout: present entries contribute their info rows, everything
    # else exactly one UNKNOWN placeholder — InfoScratch's accumulation
    per_entry = np.where(present, icount, 1)
    offsets = np.concatenate(([0], np.cumsum(per_entry)))
    rows = int(offsets[-1])
    # occurrence rank of each info row within its entry
    first_of = np.concatenate(([0], np.cumsum(icount)))
    rank = np.arange(ipar.size, dtype=np.int64) - first_of[ipar]
    keep = present[ipar]
    kpar, krank = ipar[keep], rank[keep]
    dest = offsets[kpar] + krank  # global row index of each kept info msg
    # decode JobInfo fields for ALL info messages (a malformed dropped
    # submessage must still error, as pb2's parse would), scatter kept
    jf = _Fields(b, data, istart, iend, int(ipar.size))
    from slurm_bridge_tpu.core.types import JobStatus

    unknown_state = int(JobStatus.UNKNOWN)
    # every row carries its entry's job id: forward-fill entry index
    steps = np.zeros(rows, np.int64)
    steps[offsets[:-1]] = 1
    entry_of_row = np.cumsum(steps) - 1
    jid_col = ejid[entry_of_row]
    cols = {}
    for cname, fno in (
        ("id", 1), ("state", 5), ("start_ts", 7), ("limit", 9),
        ("submit_ts", 6), ("run_time", 8), ("num_nodes", 16),
    ):
        full = jf.varint_i64(fno)
        col = np.zeros(rows, np.int64)
        col[dest] = full[keep]
        cols[cname] = col
    # UNKNOWN placeholder rows: id = entry job id, state = UNKNOWN
    unk_rows = offsets[:-1][~present]
    cols["id"][unk_rows] = ejid[~present]
    cols["state"][unk_rows] = unknown_state
    # signal strings (exit_code f4, reason f18) materialized eagerly —
    # the vector diff compares their VALUES; absent = "" costs a fill
    def scatter_str(fno: int) -> np.ndarray:
        s, ln = jf.spans(fno)
        start = np.full(rows, -1, np.int64)
        length = np.zeros(rows, np.int64)
        start[dest] = s[keep]
        length[dest] = ln[keep]
        return materialize_strings(data, start, length)

    exit_code = scatter_str(4)
    reason = scatter_str(18)
    spans = {}
    for cname, fno in _INFO_STR_FIELDS:
        s, ln = jf.spans(fno)
        start = np.full(rows, -1, np.int64)
        length = np.zeros(rows, np.int64)
        start[dest] = s[keep]
        length[dest] = ln[keep]
        spans[cname] = (start, length)
    return JobsInfoChunk(
        data, version, rows, jid_col, cols, exit_code, reason, spans
    )


# ---- NodesResponse -----------------------------------------------------


class NodesDecoded:
    """One decoded ``NodesResponse``."""

    __slots__ = ("version", "unchanged", "nodes")

    def __init__(self, version: int, unchanged: bool, nodes: list):
        self.version = version
        self.unchanged = unchanged
        #: list[NodeInfo] — field-for-field what ``nodes_from_protos``
        #: yields for the same bytes
        self.nodes = nodes


def decode_nodes(data: bytes) -> NodesDecoded:
    """Decode one ``NodesResponse`` buffer into the NodeInfo list the
    pb2 path produces (``node_from_proto`` semantics, including the
    ``state or "IDLE"`` default)."""
    from slurm_bridge_tpu.core.types import NodeInfo

    version = 0
    unchanged = False
    starts: list[int] = []
    ends: list[int] = []
    for fno, wt, a, b in _walk_top(data):
        if fno == 1 and wt == LEN:
            starts.append(a)
            ends.append(b)
        elif fno == 2 and wt == VARINT:
            version = _i64(a)
        elif fno == 3 and wt == VARINT:
            unchanged = a != 0
    m = len(starts)
    if m == 0:
        return NodesDecoded(version, unchanged, [])
    b = np.frombuffer(data, np.uint8)
    nf = _Fields(b, data, np.asarray(starts, np.int64), np.asarray(ends, np.int64), m)
    name = nf.strings(1)
    cpus = nf.varint_i64(2)
    alloc_cpus = nf.varint_i64(3)
    memory_mb = nf.varint_i64(4)
    alloc_memory_mb = nf.varint_i64(5)
    gpus = nf.varint_i64(6)
    alloc_gpus = nf.varint_i64(7)
    gpu_type = nf.strings(8)
    state = nf.strings(10)
    fpar, fs, fe = nf.submessages(9)  # repeated string: spans, parent-tagged
    feats: list = [()] * m
    if fpar.size:
        order = np.argsort(fpar, kind="stable")
        for k in order.tolist():
            p = int(fpar[k])
            s = int(fs[k])
            feats[p] = feats[p] + (data[s : int(fe[k])].decode("utf-8"),)
    nodes = []
    append = nodes.append
    new = NodeInfo.__new__
    for i in range(m):
        n = new(NodeInfo)
        n.__dict__.update(
            name=name[i],
            cpus=int(cpus[i]),
            alloc_cpus=int(alloc_cpus[i]),
            memory_mb=int(memory_mb[i]),
            alloc_memory_mb=int(alloc_memory_mb[i]),
            gpus=int(gpus[i]),
            alloc_gpus=int(alloc_gpus[i]),
            gpu_type=gpu_type[i],
            features=feats[i],
            state=state[i] or "IDLE",
        )
        append(n)
    return NodesDecoded(version, unchanged, nodes)


# ---- SubmitJobsResponse ------------------------------------------------


class SubmitResults:
    """One decoded ``SubmitJobsResponse``: parallel result columns."""

    __slots__ = ("n", "job_id", "ok", "error_code", "error")

    def __init__(self, n, job_id, ok, error_code, error):
        self.n = n
        self.job_id = job_id
        self.ok = ok
        self.error_code = error_code
        self.error = error

    @property
    def all_ok(self) -> bool:
        return bool(self.ok.all()) if self.n else True


def decode_submit_jobs(data: bytes) -> SubmitResults:
    starts: list[int] = []
    ends: list[int] = []
    for fno, wt, a, b in _walk_top(data):
        if fno == 1 and wt == LEN:
            starts.append(a)
            ends.append(b)
    m = len(starts)
    if m == 0:
        e = np.empty(0, np.int64)
        o = np.empty(0, object)
        return SubmitResults(0, e, np.empty(0, bool), o, o)
    b = np.frombuffer(data, np.uint8)
    rf = _Fields(b, data, np.asarray(starts, np.int64), np.asarray(ends, np.int64), m)
    return SubmitResults(
        m,
        rf.varint_i64(1),
        rf.varint_i64(2) != 0,
        rf.strings(3),
        rf.strings(4),
    )
