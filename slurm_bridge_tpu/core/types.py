"""Core typed model shared by every layer.

The message set mirrors the capability surface of the reference's CRD spec
(apis/kubecluster.org/v1alpha1/slurmbridgejob_types.go:39-94) and gRPC contract
(pkg/workload/workload.proto:64-308), re-expressed as plain dataclasses so the
solver can lower them into dense arrays without an ORM in the way.
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field
from datetime import datetime


#: Sentinel for Slurm's UNLIMITED/INFINITE values (parse.go:45-52 semantics):
#: we normalise them to this instead of raising, so matrix encoders can clamp.
UNLIMITED = -1


class JobStatus(enum.IntEnum):
    """Slurm job state, mirroring the reference's JobStatus enum
    (pkg/workload/workload.proto:241-250)."""

    COMPLETED = 0
    CANCELLED = 1
    FAILED = 2
    TIMEOUT = 3
    PENDING = 4
    RUNNING = 5
    UNKNOWN = 6

    @classmethod
    def from_slurm(cls, s: str) -> "JobStatus":
        """Map a Slurm state string (e.g. 'RUNNING', 'COMPLETED',
        'CANCELLED by 1000', 'NODE_FAIL') to a JobStatus."""
        head = s.strip().upper().split()[0] if s.strip() else ""
        head = head.rstrip("+")  # sacct suffixes e.g. CANCELLED+
        direct = {
            "COMPLETED": cls.COMPLETED,
            "CANCELLED": cls.CANCELLED,
            "FAILED": cls.FAILED,
            "TIMEOUT": cls.TIMEOUT,
            "PENDING": cls.PENDING,
            "RUNNING": cls.RUNNING,
            "COMPLETING": cls.RUNNING,
            "CONFIGURING": cls.PENDING,
            "SUSPENDED": cls.PENDING,
            "PREEMPTED": cls.CANCELLED,
            "NODE_FAIL": cls.FAILED,
            "BOOT_FAIL": cls.FAILED,
            "DEADLINE": cls.TIMEOUT,
            "OUT_OF_MEMORY": cls.FAILED,
        }
        return direct.get(head, cls.UNKNOWN)

    @property
    def is_terminal(self) -> bool:
        return self in (
            JobStatus.COMPLETED,
            JobStatus.CANCELLED,
            JobStatus.FAILED,
            JobStatus.TIMEOUT,
        )


@dataclass
class JobDemand:
    """What a job asks for — the union of the CR spec fields
    (slurmbridgejob_types.go:39-61) and SubmitJobRequest
    (workload.proto:64-82).

    ``mem_per_cpu_mb`` is in MiB, matching sbatch --mem-per-cpu default units.
    ``time_limit_s`` of ``UNLIMITED`` means no limit.
    """

    partition: str = ""
    script: str = ""
    job_name: str = ""
    run_as_user: int | None = None
    run_as_group: int | None = None
    array: str = ""
    cpus_per_task: int = 1
    ntasks: int = 1
    ntasks_per_node: int = 0
    nodes: int = 1
    working_dir: str = ""
    mem_per_cpu_mb: int = 0
    gres: str = ""
    licenses: str = ""
    time_limit_s: int = 0
    priority: int = 0
    #: solver-chosen hosts, forwarded as ``sbatch --nodelist`` (Slurm stays
    #: the final arbiter; an infeasible hint falls back to Slurm's choice)
    nodelist: tuple[str, ...] = ()

    def total_cpus(self, array_count: int = 1) -> int:
        """cpu = cpus_per_task × ntasks × array-len — the sizecar sizing rule
        (pkg/slurm-bridge-operator/pod.go:143-162, array multiply :153-156)."""
        return max(1, self.cpus_per_task) * max(1, self.ntasks) * max(1, array_count)

    def total_mem_mb(self, array_count: int = 1) -> int:
        return self.mem_per_cpu_mb * self.total_cpus(array_count)


@dataclass
class JobInfo:
    """Live job state — the 18-field JobInfo message
    (pkg/workload/workload.proto:253-292)."""

    id: int = 0
    user_id: str = ""
    name: str = ""
    exit_code: str = ""
    state: JobStatus = JobStatus.UNKNOWN
    submit_time: datetime | None = None
    start_time: datetime | None = None
    run_time_s: int = 0
    time_limit_s: int = 0
    working_dir: str = ""
    std_out: str = ""
    std_err: str = ""
    partition: str = ""
    node_list: str = ""
    batch_host: str = ""
    num_nodes: int = 0
    array_id: str = ""
    reason: str = ""

    def key(self) -> str:
        return f"{self.id}" if not self.array_id else self.array_id


@dataclass
class JobStepInfo:
    """One sacct step row (pkg/workload/workload.proto:295-308)."""

    id: str = ""
    name: str = ""
    start_time: datetime | None = None
    finish_time: datetime | None = None
    exit_code: int = 0
    state: JobStatus = JobStatus.UNKNOWN


@dataclass
class NodeInfo:
    """One Slurm node — capacity plus current allocation
    (pkg/workload/workload.proto:165-174; parse fields CPUTot/CPUAlloc/
    RealMemory/AllocMem per pkg/slurm-agent/parse.go:291-308)."""

    name: str = ""
    cpus: int = 0
    alloc_cpus: int = 0
    memory_mb: int = 0
    alloc_memory_mb: int = 0
    gpus: int = 0
    alloc_gpus: int = 0
    gpu_type: str = ""
    features: tuple[str, ...] = ()
    state: str = "IDLE"

    @property
    def free_cpus(self) -> int:
        return max(0, self.cpus - self.alloc_cpus)

    @property
    def free_memory_mb(self) -> int:
        return max(0, self.memory_mb - self.alloc_memory_mb)

    @property
    def free_gpus(self) -> int:
        return max(0, self.gpus - self.alloc_gpus)

    @property
    def schedulable(self) -> bool:
        # composite states join flags with '+' (IDLE+CLOUD, MIXED+CLOUD+POWERED_UP);
        # single-char suffix flags (*~#!%$@^-) decorate the base state
        state = self.state.upper().split("+")[0].rstrip("*~#!%$@^-")
        if any(
            bad in self.state.upper()
            for bad in ("DRAIN", "DOWN", "FAIL", "MAINT", "POWERED_DOWN", "POWERING_DOWN")
        ):
            return False
        return state in ("IDLE", "MIXED", "ALLOCATED", "ALLOC", "COMPLETING")


@dataclass
class PartitionInfo:
    """One Slurm partition — limits + member nodes
    (ResourcesResponse workload.proto:137-148; parseResources semantics with
    UNLIMITED→total fallbacks, pkg/slurm-agent/parse.go:113-190)."""

    name: str = ""
    nodes: tuple[str, ...] = ()
    max_time_s: int = UNLIMITED
    max_nodes: int = UNLIMITED
    max_cpus_per_node: int = UNLIMITED
    max_mem_per_node_mb: int = UNLIMITED
    total_cpus: int = 0
    total_nodes: int = 0
    state: str = "UP"
    features: tuple[str, ...] = ()


@dataclass
class PartitionResources:
    """Per-partition resource override config — the agent's YAML knobs
    (pkg/slurm-agent/api/slurm.go:54-78: auto_* flags, fixed values,
    additional_features)."""

    auto_nodes: bool = False
    auto_cpu_per_node: bool = False
    auto_mem_per_node: bool = False
    auto_wall_time: bool = False
    nodes: int = 0
    cpu_per_node: int = 0
    mem_per_node_mb: int = 0
    wall_time_s: int = 0
    additional_features: tuple[str, ...] = ()


@dataclass
class JobResult:
    """Where to put fetched job artifacts (types.go:6-10 JobResult{Volume})."""

    mount_path: str = ""


def asdict_shallow(obj) -> dict:
    """dataclasses.asdict without deep-copying nested values."""
    return {f.name: getattr(obj, f.name) for f in dataclasses.fields(obj)}
