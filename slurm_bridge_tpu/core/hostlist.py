"""Slurm hostlist expression expansion/compression.

Expands `node[1-4,7]`, `tpu-[001-003]`, `a1,b[2-3]c` style expressions into
concrete host names (and back). The reference leaned on `scontrol show nodes
a,b,c` with pre-expanded names (pkg/slurm-agent/slurm.go:355-365,
parse.go:278-289); we expand locally so a 10k-node partition does not need a
second round-trip.
"""

from __future__ import annotations

import re

#: Refuse to expand beyond this many hosts — a hostile `node[1-10**10]`
#: must not OOM the agent.
MAX_HOSTS = 1_000_000


def expand_hostlist(expr: str) -> list[str]:
    """Expand a Slurm hostlist expression into a list of host names."""
    out: list[str] = []
    for part in _split_top(expr):
        out.extend(_expand_one(part))
        if len(out) > MAX_HOSTS:
            raise ValueError(f"hostlist expands past {MAX_HOSTS} hosts")
    return out


def _split_top(expr: str) -> list[str]:
    """Split on commas that are not inside brackets."""
    parts, depth, cur = [], 0, []
    for ch in expr:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
            if depth < 0:
                raise ValueError(f"unbalanced ] in hostlist {expr!r}")
        if ch == "," and depth == 0:
            if cur:
                parts.append("".join(cur))
                cur = []
        else:
            cur.append(ch)
    if depth != 0:
        raise ValueError(f"unbalanced [ in hostlist {expr!r}")
    if cur:
        parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


_RANGE_RE = re.compile(r"^(\d+)-(\d+)$")


def _expand_one(part: str) -> list[str]:
    m = re.search(r"\[([^\]]*)\]", part)
    if not m:
        return [part]
    prefix, body, suffix = part[: m.start()], m.group(1), part[m.end() :]
    ids: list[str] = []
    for chunk in body.split(","):
        chunk = chunk.strip()
        rm = _RANGE_RE.match(chunk)
        if rm:
            lo_s, hi_s = rm.group(1), rm.group(2)
            width = len(lo_s) if lo_s.startswith("0") else 0
            lo, hi = int(lo_s), int(hi_s)
            if hi < lo:
                raise ValueError(f"inverted range in hostlist {part!r}")
            if hi - lo + 1 > MAX_HOSTS:
                raise ValueError(f"hostlist range {chunk!r} expands past {MAX_HOSTS} hosts")
            ids.extend(str(i).zfill(width) for i in range(lo, hi + 1))
        elif chunk.isdigit():
            ids.append(chunk)
        else:
            raise ValueError(f"bad hostlist range {chunk!r} in {part!r}")
    expanded = [f"{prefix}{i}{suffix}" for i in ids]
    # suffix may itself contain another bracket group (rare but legal);
    # cap the cross-product as it accumulates, not after materialising it
    if "[" in suffix:
        out: list[str] = []
        for e in expanded:
            out.extend(_expand_one(e))
            if len(out) > MAX_HOSTS:
                raise ValueError(f"hostlist expands past {MAX_HOSTS} hosts")
        return out
    return expanded


def compress_hostlist(hosts: list[str]) -> str:
    """Compress host names back into a compact `prefix[a-b,...]` expression.

    Groups by (prefix, numeric-suffix width); non-conforming names pass
    through verbatim.
    """
    groups: dict[tuple[str, int], list[int]] = {}
    passthrough: list[str] = []
    name_re = re.compile(r"^(.*?)(\d+)$")
    for h in hosts:
        m = name_re.match(h)
        if not m:
            passthrough.append(h)
            continue
        prefix, num = m.group(1), m.group(2)
        width = len(num) if num.startswith("0") else 0
        groups.setdefault((prefix, width), []).append(int(num))
    parts: list[str] = []
    for (prefix, width), nums in groups.items():
        nums = sorted(set(nums))
        ranges: list[str] = []
        start = prev = nums[0]
        for n in nums[1:] + [None]:  # type: ignore[list-item]
            if n is not None and n == prev + 1:
                prev = n
                continue
            lo = str(start).zfill(width)
            hi = str(prev).zfill(width)
            ranges.append(lo if start == prev else f"{lo}-{hi}")
            if n is not None:
                start = prev = n
        if len(ranges) == 1 and "-" not in ranges[0]:
            parts.append(f"{prefix}{ranges[0]}")
        else:
            parts.append(f"{prefix}[{','.join(ranges)}]")
    parts.extend(passthrough)
    return ",".join(parts)
