"""Parser for `sacct -p -n -j <id> -o start,end,exitcode,state,jobid,jobname`.

Reference parity: parseSacctResponse (pkg/slurm-agent/parse.go:214-253) reads
pipe-separated rows of 7 fields (6 + trailing empty from the final `|`) and
parseTime (:255-268) tolerates the `Unknown` sentinel.
"""

from __future__ import annotations

from slurm_bridge_tpu.core.timeparse import parse_slurm_time
from slurm_bridge_tpu.core.types import JobStatus, JobStepInfo

# sacct prints times as ISO-8601 without zone, e.g. 2023-10-10T10:00:00
_FIELDS = ("start", "end", "exitcode", "state", "jobid", "jobname")


def _parse_exit_code(v: str) -> int:
    # sacct renders "rc:signal"
    head = v.split(":", 1)[0].strip()
    try:
        return int(head)
    except ValueError:
        return 0


def parse_sacct_steps(text: str) -> list[JobStepInfo]:
    """Parse sacct's pipe-separated step rows into JobStepInfo records."""
    steps: list[JobStepInfo] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        cols = line.split("|")
        # trailing '|' yields an empty last column — the reference required
        # exactly 7 columns (parse.go:222-227); we accept 6 or 7.
        if cols and cols[-1] == "":
            cols = cols[:-1]
        if len(cols) != len(_FIELDS):
            raise ValueError(f"bad sacct row (want {len(_FIELDS)} cols): {line!r}")
        start, end, exitcode, state, jobid, jobname = cols
        steps.append(
            JobStepInfo(
                id=jobid.strip(),
                name=jobname.strip(),
                start_time=parse_slurm_time(start),
                finish_time=parse_slurm_time(end),
                exit_code=_parse_exit_code(exitcode),
                state=JobStatus.from_slurm(state),
            )
        )
    return steps
