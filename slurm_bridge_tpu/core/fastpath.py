"""Freeze-guard primitives + guard-bypassing dataclass constructors.

Lives in ``core`` (no bridge imports) so the wire decoders and the sim's
fake agent can use the fast constructors without pulling in the bridge
package; :mod:`bridge.freeze` builds its public API on top and re-exports
everything here.

Why this exists (PR-4): once a dataclass type has passed through
:func:`bridge.freeze.freeze`, its ``__init__`` pays a guarded
``__setattr__`` per field — measured 4× construction cost on the
18-field ``JobInfo`` — and the store's commit-time :func:`freeze` walks
every field of every fresh object. The cold-start paths build ~140k such
objects per tick at the 50k×10k headline shape. The helpers below
sidestep both costs without weakening the guard:

- :func:`fast_replace` / :func:`fast_new` build UNFROZEN instances
  straight into ``__dict__`` (no guarded ``__init__`` replay); the store
  freezes them on commit like any other fresh object;
- :func:`frozen_new` additionally marks the instance frozen at birth —
  legal ONLY for scalar-field dataclasses (strings/ints/enums/datetimes),
  where there is nothing recursive left for ``freeze`` to do. It patches
  the class guard first, so a born-frozen instance rejects mutation
  exactly like a store snapshot.
"""

from __future__ import annotations

import copy

#: instance-level marker: present and True on frozen instances
FROZEN_FLAG = "_sbt_frozen"
#: class-level marker: the guard has been installed on this type
PATCHED_FLAG = "_sbt_freezable"


class FrozenInstanceError(AttributeError):
    """Raised on any attempt to mutate a frozen store snapshot.

    Callers holding a snapshot from ``get``/``list`` must go through
    ``ObjectStore.mutate`` / ``get_for_update`` (or ``freeze.thaw``) to
    write.
    """


def _guarded_setattr(self, name, value):
    if self.__dict__.get(FROZEN_FLAG, False):
        raise FrozenInstanceError(
            f"{type(self).__name__} is a frozen store snapshot; use "
            "ObjectStore.mutate/get_for_update (or freeze.thaw) to modify"
        )
    object.__setattr__(self, name, value)


def _guarded_delattr(self, name):
    if self.__dict__.get(FROZEN_FLAG, False):
        raise FrozenInstanceError(
            f"{type(self).__name__} is a frozen store snapshot"
        )
    object.__delattr__(self, name)


def _thawing_deepcopy(self, memo):
    """deepcopy of a (possibly frozen) instance yields a thawed one."""
    cls = self.__class__
    new = cls.__new__(cls)
    memo[id(self)] = new
    for k, v in self.__dict__.items():
        if k == FROZEN_FLAG:
            continue
        object.__setattr__(new, k, copy.deepcopy(v, memo))
    return new


def enable_guard(cls: type) -> None:
    """Teach a dataclass type the frozen guard (idempotent, per-class)."""
    if cls.__dict__.get(PATCHED_FLAG, False):
        return
    cls.__setattr__ = _guarded_setattr
    cls.__delattr__ = _guarded_delattr
    cls.__deepcopy__ = _thawing_deepcopy
    setattr(cls, PATCHED_FLAG, True)


def fast_replace(obj, **changes):
    """``dataclasses.replace`` for the hot write paths (PR-4).

    A shallow replacement built straight into ``__dict__`` — no guarded
    ``__init__`` replay, no default re-evaluation — and UNFROZEN, so the
    store can take ownership (bump ``resource_version``, re-freeze) like
    any fresh replacement. Unchanged children are shared as-is: sharing a
    frozen child between versions is exactly the structural-sharing
    contract ``ObjectStore.replace_update`` already relies on.

    Caveat: ``__init__``/``__post_init__`` side effects are skipped, so
    only use it on plain field-bag dataclasses (everything in
    ``bridge/objects.py`` and ``core/types.py`` qualifies).
    """
    cls = obj.__class__
    new = cls.__new__(cls)
    d = new.__dict__
    d.update(obj.__dict__)
    d.pop(FROZEN_FLAG, None)
    d.update(changes)
    return new


def fast_new(cls, **fields):
    """Construct a dataclass instance straight into ``__dict__``,
    bypassing a (possibly freeze-guarded) ``__init__``. Callers MUST pass
    every field: defaults (and default factories) are not applied."""
    new = cls.__new__(cls)
    new.__dict__.update(fields)
    return new


def frozen_replace(obj, **changes):
    """:func:`fast_replace`, born frozen — commit-time ``freeze`` stops at
    one dict probe instead of re-walking every field.

    Contract (caller-audited, like :func:`frozen_new`): ``obj`` is
    already frozen, and every changed value is either a scalar or
    already-frozen (a ``FrozenDict``/``FrozenList``, a frozen instance).
    The write paths use this for the STATUS/SPEC children of replacement
    objects — never for ``meta``, which the store must mutate (resource
    version bump) at commit time."""
    cls = obj.__class__
    new = cls.__new__(cls)
    d = new.__dict__
    d.update(obj.__dict__)
    d.update(changes)
    d[FROZEN_FLAG] = True
    return new


def frozen_new(cls, **fields):
    """:func:`fast_new`, born frozen — for SCALAR-ONLY dataclasses.

    The mass-decoded rows (``JobInfo``, ``SubjobStatus``,
    ``ContainerStatus``) hold nothing but strings/ints/enums/datetimes,
    so commit-time ``freeze`` has no recursive work to do on them; the
    walk itself (one dispatch per field × 45k rows × 18 fields per
    mirror tick) was pure overhead. Marking them frozen at birth lets
    ``freeze`` short-circuit at one dict probe per row. The class guard
    is installed first, so these instances reject mutation exactly like
    store snapshots — do NOT use this for types with dict/list/dataclass
    fields (they would be shared un-frozen).
    """
    if not cls.__dict__.get(PATCHED_FLAG, False):
        enable_guard(cls)
    new = cls.__new__(cls)
    d = new.__dict__
    d.update(fields)
    d[FROZEN_FLAG] = True
    return new
