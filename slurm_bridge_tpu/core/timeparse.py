"""Shared sentinel-tolerant timestamp parsing for Slurm text output.

scontrol and sacct both render ISO-8601 local timestamps with a family of
null sentinels; this is the one place that knows the full sentinel set.
"""

from __future__ import annotations

from datetime import datetime

NULL_SENTINELS = {"", "(null)", "N/A", "n/a", "None", "NONE", "Unknown", "UNKNOWN"}


def parse_slurm_time(v: str) -> datetime | None:
    """Parse a Slurm timestamp (`2024-03-12T09:41:02`); sentinels → None."""
    s = v.strip()
    if s in NULL_SENTINELS:
        return None
    try:
        return datetime.fromisoformat(s)
    except ValueError:
        return None
