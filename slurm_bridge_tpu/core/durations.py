"""Slurm duration grammar.

Accepted forms (sbatch(1) --time):
  "minutes", "minutes:seconds", "hours:minutes:seconds",
  "days-hours", "days-hours:minutes", "days-hours:minutes:seconds"
plus the sentinels "UNLIMITED", "INFINITE", "NOT_SET", and "N/A".

Reference parity: pkg/slurm-agent/parse.go:38-109 (ParseDuration incl. the
`d-h:m:s` form and an UNLIMITED sentinel error). We normalise sentinels to
``UNLIMITED`` (-1) via :class:`UnlimitedError` carrying that value, because the
solver clamps them into matrix headroom rather than propagating errors.
"""

from __future__ import annotations

import re

from slurm_bridge_tpu.core.types import UNLIMITED

_SENTINELS = {"UNLIMITED", "INFINITE", "NOT_SET", "N/A", "NONE"}

_DAYS_RE = re.compile(
    r"^(?P<days>\d+)-(?P<hours>\d+)(?::(?P<mins>\d+))?(?::(?P<secs>\d+))?$"
)


class UnlimitedError(ValueError):
    """Raised for UNLIMITED/INFINITE inputs; carries the sentinel value."""

    def __init__(self, raw: str):
        super().__init__(f"duration is unlimited: {raw!r}")
        self.value = UNLIMITED


def parse_duration(raw: str, *, unlimited_ok: bool = True) -> int:
    """Parse a Slurm duration to whole seconds.

    With ``unlimited_ok`` (default) the UNLIMITED family returns the
    ``UNLIMITED`` sentinel (-1); otherwise :class:`UnlimitedError` is raised.
    """
    s = raw.strip()
    if not s:
        raise ValueError("empty duration")
    if s.upper() in _SENTINELS:
        if unlimited_ok:
            return UNLIMITED
        raise UnlimitedError(raw)

    m = _DAYS_RE.match(s)
    if m:
        days = int(m.group("days"))
        hours = int(m.group("hours"))
        mins = int(m.group("mins") or 0)
        secs = int(m.group("secs") or 0)
        return ((days * 24 + hours) * 60 + mins) * 60 + secs

    parts = s.split(":")
    if not all(p.isdigit() for p in parts):
        raise ValueError(f"bad duration: {raw!r}")
    if len(parts) == 1:  # minutes
        return int(parts[0]) * 60
    if len(parts) == 2:  # minutes:seconds
        return int(parts[0]) * 60 + int(parts[1])
    if len(parts) == 3:  # hours:minutes:seconds
        return (int(parts[0]) * 60 + int(parts[1])) * 60 + int(parts[2])
    raise ValueError(f"bad duration: {raw!r}")


def format_duration(seconds: int) -> str:
    """Render seconds in Slurm's canonical `[d-]hh:mm:ss` form."""
    if seconds == UNLIMITED:
        return "UNLIMITED"
    if seconds < 0:
        raise ValueError(f"negative duration: {seconds}")
    days, rem = divmod(seconds, 86400)
    hours, rem = divmod(rem, 3600)
    mins, secs = divmod(rem, 60)
    if days:
        return f"{days}-{hours:02d}:{mins:02d}:{secs:02d}"
    return f"{hours:02d}:{mins:02d}:{secs:02d}"
