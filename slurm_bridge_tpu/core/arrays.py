"""Slurm job-array spec grammar.

Forms (sbatch(1) --array): "0-31", "1,3,5,7", "1-7:2" (step), and a
"%N" max-simultaneous suffix, composable: "0-15%4", "1,3,9-12%2".

Reference parity: parseArrayLen (pkg/slurm-bridge-operator/parse.go:126-135)
only counted a plain "a-b" range; we implement the full grammar since the
array length multiplies placement demand (pod.go:153-156).
"""

from __future__ import annotations


def parse_array_spec(spec: str) -> list[int]:
    """Expand an --array spec into the sorted list of task ids."""
    s = spec.strip()
    if not s:
        return []
    # strip %N throttle suffix (applies to the whole spec)
    if "%" in s:
        s, _, throttle = s.rpartition("%")
        if not throttle.isdigit():
            raise ValueError(f"bad array throttle in {spec!r}")
    ids: set[int] = set()
    for chunk in s.split(","):
        chunk = chunk.strip()
        if not chunk:
            raise ValueError(f"bad array spec {spec!r}")
        step = 1
        if ":" in chunk:
            chunk, _, step_s = chunk.partition(":")
            if not step_s.isdigit() or int(step_s) < 1:
                raise ValueError(f"bad array step in {spec!r}")
            step = int(step_s)
        if "-" in chunk:
            lo_s, _, hi_s = chunk.partition("-")
            if not (lo_s.isdigit() and hi_s.isdigit()):
                raise ValueError(f"bad array range in {spec!r}")
            lo, hi = int(lo_s), int(hi_s)
            if hi < lo:
                raise ValueError(f"inverted array range in {spec!r}")
            ids.update(range(lo, hi + 1, step))
        else:
            if not chunk.isdigit():
                raise ValueError(f"bad array id in {spec!r}")
            ids.add(int(chunk))
    return sorted(ids)


def array_len(spec: str) -> int:
    """Number of array tasks; 1 for the empty spec (non-array job)."""
    if not spec.strip():
        return 1
    return max(1, len(parse_array_spec(spec)))
