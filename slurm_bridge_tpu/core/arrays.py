"""Slurm job-array spec grammar.

Forms (sbatch(1) --array): "0-31", "1,3,5,7", "1-7:2" (step), and a
"%N" max-simultaneous suffix, composable: "0-15%4", "1,3,9-12%2".

Reference parity: parseArrayLen (pkg/slurm-bridge-operator/parse.go:126-135)
only counted a plain "a-b" range; we implement the full grammar since the
array length multiplies placement demand (pod.go:153-156).
"""

from __future__ import annotations

from typing import Iterator

#: Upper bound on task ids — slurm.conf MaxArraySize's own ceiling (slurm
#: caps array indices at 4M; the common default is 1001). Without it,
#: "--array=0-99999999" from a user script would materialize a
#: hundred-million-element list in the control plane (found by hypothesis,
#: tests/test_properties.py).
MAX_ARRAY_SIZE = 4_000_001

#: Expansion sizes up to this are counted exactly (set union over chunks);
#: beyond it, multi-chunk counts fall back to the per-chunk arithmetic sum
#: — a conservative upper bound when chunks overlap, but no multi-million
#: element set ever exists in the sizing hot path.
_EXACT_COUNT_LIMIT = 1 << 16


def _iter_chunks(spec: str) -> Iterator[tuple[int, int, int]]:
    """Yield (lo, hi, step) per comma-chunk — the ONE implementation of
    the --array grammar; expansion and counting both consume it."""
    s = spec.strip()
    if not s:
        return
    # strip %N throttle suffix (applies to the whole spec)
    if "%" in s:
        s, _, throttle = s.rpartition("%")
        if not throttle.isdigit():
            raise ValueError(f"bad array throttle in {spec!r}")
    for chunk in s.split(","):
        chunk = chunk.strip()
        if not chunk:
            raise ValueError(f"bad array spec {spec!r}")
        step = 1
        if ":" in chunk:
            chunk, _, step_s = chunk.partition(":")
            if not step_s.isdigit() or int(step_s) < 1:
                raise ValueError(f"bad array step in {spec!r}")
            step = int(step_s)
        if "-" in chunk:
            lo_s, _, hi_s = chunk.partition("-")
            if not (lo_s.isdigit() and hi_s.isdigit()):
                raise ValueError(f"bad array range in {spec!r}")
            lo, hi = int(lo_s), int(hi_s)
            if hi < lo:
                raise ValueError(f"inverted array range in {spec!r}")
        else:
            if not chunk.isdigit():
                raise ValueError(f"bad array id in {spec!r}")
            lo = hi = int(chunk)
        if hi >= MAX_ARRAY_SIZE:
            raise ValueError(
                f"array range in {spec!r} exceeds MaxArraySize "
                f"({MAX_ARRAY_SIZE - 1})"
            )
        yield lo, hi, step


def parse_array_spec(spec: str) -> list[int]:
    """Expand an --array spec into the sorted list of task ids."""
    ids: set[int] = set()
    for lo, hi, step in _iter_chunks(spec):
        ids.update(range(lo, hi + 1, step))
    return sorted(ids)


def _merged_count(chunks: list[tuple[int, int, int]]) -> int:
    """Count the union without materializing: chunks sharing (step, phase)
    are interval-merged exactly; only cross-step overlap (rare: mixed
    ":N" steps hitting the same ids) can still overcount."""
    from collections import defaultdict

    groups: dict[tuple[int, int], list[tuple[int, int]]] = defaultdict(list)
    for lo, hi, step in chunks:
        groups[(step, lo % step)].append((lo, hi))
    total = 0
    for (step, _), ranges in groups.items():
        ranges.sort()
        cur_lo, cur_hi = ranges[0]
        for lo, hi in ranges[1:]:
            if lo <= cur_hi + step:  # touching progressions merge
                cur_hi = max(cur_hi, hi)
            else:
                total += (cur_hi - cur_lo) // step + 1
                cur_lo, cur_hi = lo, hi
        total += (cur_hi - cur_lo) // step + 1
    return total


def array_len(spec: str) -> int:
    """Number of array tasks; 1 for the empty spec (non-array job).

    Counted arithmetically — the sizecar sizing hot path never
    materializes task ids for large legal specs. Same-step overlapping
    chunks are interval-merged exactly at any size (ADVICE r3:
    "0-70000,0-70000" must not double demand); small multi-chunk specs are
    counted exactly via set union (collapsing even cross-step duplicates,
    matching :func:`parse_array_spec`); only large specs with duplicate
    ids across *different* steps keep a conservative upper bound."""
    chunks = list(_iter_chunks(spec))
    if not chunks:
        return 1
    if len(chunks) == 1:
        lo, hi, step = chunks[0]
        return max(1, (hi - lo) // step + 1)
    # gate the set-union path on the ARITHMETIC sum — it equals the number
    # of range inserts the union performs, so duplicated chunks can't push
    # materialization work past the cap (the merged total undercounts it)
    raw_sum = sum((hi - lo) // step + 1 for lo, hi, step in chunks)
    if raw_sum <= _EXACT_COUNT_LIMIT:
        ids: set[int] = set()
        for lo, hi, step in chunks:
            ids.update(range(lo, hi + 1, step))
        return max(1, len(ids))
    return max(1, _merged_count(chunks))
