"""Typed job/partition/node model and Slurm dialect parsers.

Parity surface (reference citations):
- job spec fields      apis/kubecluster.org/v1alpha1/slurmbridgejob_types.go:39-61
- job/sub-job status   apis/kubecluster.org/v1alpha1/slurmbridgejob_types.go:65-94
- duration grammar     pkg/slurm-agent/parse.go:38-109
- #SBATCH header scan  pkg/slurm-bridge-operator/parse.go:30-135
- scontrol/sacct/sinfo pkg/slurm-agent/slurm.go:263-447, parse.go:113-308
"""

from slurm_bridge_tpu.core.types import (
    JobStatus,
    JobDemand,
    JobInfo,
    JobStepInfo,
    NodeInfo,
    PartitionInfo,
    PartitionResources,
    JobResult,
    UNLIMITED,
)
from slurm_bridge_tpu.core.durations import parse_duration, format_duration, UnlimitedError
from slurm_bridge_tpu.core.arrays import parse_array_spec, array_len
from slurm_bridge_tpu.core.sbatch import extract_batch_resources, SbatchDirectives
from slurm_bridge_tpu.core.scontrol import (
    parse_scontrol_records,
    parse_job_info,
    parse_partition_info,
    parse_node_info,
)
from slurm_bridge_tpu.core.sacct import parse_sacct_steps

__all__ = [
    "JobStatus",
    "JobDemand",
    "JobInfo",
    "JobStepInfo",
    "NodeInfo",
    "PartitionInfo",
    "PartitionResources",
    "JobResult",
    "UNLIMITED",
    "parse_duration",
    "format_duration",
    "UnlimitedError",
    "parse_array_spec",
    "array_len",
    "extract_batch_resources",
    "SbatchDirectives",
    "parse_scontrol_records",
    "parse_job_info",
    "parse_partition_info",
    "parse_node_info",
    "parse_sacct_steps",
]
