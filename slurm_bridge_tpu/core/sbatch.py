"""#SBATCH header scanner.

Extracts resource directives from a batch script's header block so the bridge
can size a placement request before the script ever reaches Slurm.

Reference parity: extractBatchResourcesFromScript
(pkg/slurm-bridge-operator/parse.go:30-124) handled --time/-t, --nodes/-N,
--mem-per-cpu, --ntasks-per-node, --cpus-per-task/-c in both `=` and space
forms. We cover that set plus --ntasks/-n, --array/-a, --partition/-p,
--job-name/-J, --gres, --licenses/-L, --chdir/-D, since all of them feed the
solver's demand vector.
"""

from __future__ import annotations

import re
import shlex
from dataclasses import dataclass, field

from slurm_bridge_tpu.core.arrays import array_len
from slurm_bridge_tpu.core.durations import parse_duration
from slurm_bridge_tpu.core.types import JobDemand

_DIRECTIVE_RE = re.compile(r"^#SBATCH\s+(?P<body>.+?)\s*$")

# long-option → (field, converter); short flags alias into the same fields.
_LONG_OPTS = {
    "time": ("time_limit_s", parse_duration),
    "nodes": ("nodes", int),
    "mem-per-cpu": ("mem_per_cpu_mb", "mem"),
    "ntasks-per-node": ("ntasks_per_node", int),
    "cpus-per-task": ("cpus_per_task", int),
    "ntasks": ("ntasks", int),
    "array": ("array", str),
    "partition": ("partition", str),
    "job-name": ("job_name", str),
    "gres": ("gres", str),
    "licenses": ("licenses", str),
    "chdir": ("working_dir", str),
    "priority": ("priority", int),
}

_SHORT_OPTS = {
    "t": "time",
    "N": "nodes",
    "c": "cpus-per-task",
    "n": "ntasks",
    "a": "array",
    "p": "partition",
    "J": "job-name",
    "L": "licenses",
    "D": "chdir",
}

_MEM_RE = re.compile(r"^(?P<num>\d+)(?P<unit>[KkMmGgTt]?)B?$")


def parse_mem_mb(raw: str) -> int:
    """Parse sbatch memory values (default unit MiB; K/M/G/T suffixes)."""
    m = _MEM_RE.match(raw.strip())
    if not m:
        raise ValueError(f"bad memory value: {raw!r}")
    num = int(m.group("num"))
    unit = m.group("unit").upper() or "M"
    scale = {"K": 1 / 1024, "M": 1, "G": 1024, "T": 1024 * 1024}[unit]
    return int(num * scale)


@dataclass
class SbatchDirectives:
    """The parsed directive set plus anything we didn't recognise."""

    demand: JobDemand = field(default_factory=JobDemand)
    unknown: list[str] = field(default_factory=list)

    @property
    def array_count(self) -> int:
        return array_len(self.demand.array)


def _tokenize_directive(body: str) -> list[tuple[str, str | None]]:
    """Split one `#SBATCH` body into (option, value) pairs.

    Handles `--opt=v`, `--opt v`, `-x v`, `-xv`, quoted values
    (`--job-name="my job"`), and flag-only options. Trailing `# comments`
    are stripped, matching sbatch.
    """
    out: list[tuple[str, str | None]] = []
    try:
        toks = shlex.split(body, comments=True)
    except ValueError:  # unbalanced quotes: degrade to whitespace split
        toks = body.split()
    i = 0
    while i < len(toks):
        tok = toks[i]
        if tok.startswith("--"):
            name = tok[2:]
            if "=" in name:
                name, _, val = name.partition("=")
                out.append((name, val))
            elif i + 1 < len(toks) and not toks[i + 1].startswith("-"):
                out.append((name, toks[i + 1]))
                i += 1
            else:
                out.append((name, None))
        elif tok.startswith("-") and len(tok) > 1:
            short = tok[1]
            if len(tok) > 2:  # -c4 / -t10:00 attached form
                val = tok[2:]
                if val.startswith("="):
                    val = val[1:]
                out.append((_SHORT_OPTS.get(short, short), val))
            elif i + 1 < len(toks) and not toks[i + 1].startswith("-"):
                out.append((_SHORT_OPTS.get(short, short), toks[i + 1]))
                i += 1
            else:
                out.append((_SHORT_OPTS.get(short, short), None))
        i += 1
    return out


def extract_batch_resources(script: str) -> SbatchDirectives:
    """Scan a batch script's `#SBATCH` header block into a JobDemand.

    Scanning stops at the first non-blank, non-comment line after the shebang,
    matching sbatch's own semantics.
    """
    result = SbatchDirectives()
    demand = result.demand
    demand.script = script
    for lineno, line in enumerate(script.splitlines()):
        stripped = line.strip()
        if lineno == 0 and stripped.startswith("#!"):
            continue
        if not stripped:
            continue
        if not stripped.startswith("#"):
            break  # first command line: header block over
        m = _DIRECTIVE_RE.match(stripped)
        if not m:
            continue  # plain comment
        for name, val in _tokenize_directive(m.group("body")):
            spec = _LONG_OPTS.get(name)
            if spec is None:
                result.unknown.append(name if val is None else f"{name}={val}")
                continue
            field_name, conv = spec
            if val is None:
                result.unknown.append(name)
                continue
            try:
                if conv == "mem":
                    setattr(demand, field_name, parse_mem_mb(val))
                else:
                    setattr(demand, field_name, conv(val))
            except ValueError:
                result.unknown.append(f"{name}={val}")
    return result
