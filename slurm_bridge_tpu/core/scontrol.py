"""Parsers for `scontrol show {jobid,partition,nodes}` text output.

The reference fills structs by reflection over `slurm-agent:"Field"` tags
(pkg/slurm-agent/slurm.go:382-447) and parses partitions/nodes in
pkg/slurm-agent/parse.go:113-308. We parse the same key=value record grammar
into the core dataclasses, including the UNLIMITED→total fallbacks
(parse.go:113-190) and node CPUTot/CPUAlloc/RealMemory/AllocMem fields
(parse.go:291-308).
"""

from __future__ import annotations

import re
from datetime import datetime

from slurm_bridge_tpu.core.durations import parse_duration
from slurm_bridge_tpu.core.timeparse import NULL_SENTINELS, parse_slurm_time
from slurm_bridge_tpu.core.hostlist import expand_hostlist
from slurm_bridge_tpu.core.types import (
    UNLIMITED,
    JobInfo,
    JobStatus,
    NodeInfo,
    PartitionInfo,
)

_KEY_RE = re.compile(r"(?:^|\s)([A-Za-z][A-Za-z0-9_:/]*)=")
_NULLS = NULL_SENTINELS


def parse_scontrol_records(text: str) -> list[dict[str, str]]:
    """Split `scontrol show` output into records of key→value.

    Records are separated by blank lines. Within a record, values run from
    their `=` to the start of the next `key=` token, so values containing
    spaces (e.g. Reason) survive.
    """
    records: list[dict[str, str]] = []
    for block in re.split(r"\n\s*\n", text.strip()):
        block = block.strip()
        if not block or block.startswith("No jobs") or block.startswith("slurm_load"):
            continue
        flat = " ".join(line.strip() for line in block.splitlines())
        matches = list(_KEY_RE.finditer(flat))
        if not matches:
            continue
        rec: dict[str, str] = {}
        for i, m in enumerate(matches):
            key = m.group(1)
            end = matches[i + 1].start() if i + 1 < len(matches) else len(flat)
            rec[key] = flat[m.end() : end].strip()
        records.append(rec)
    return records


def _get(rec: dict[str, str], key: str, default: str = "") -> str:
    v = rec.get(key, default)
    return "" if v in _NULLS else v


_INT_RANGE_RE = re.compile(r"^(\d+)-(\d+)$")


def _int(rec: dict[str, str], key: str, default: int = 0) -> int:
    v = _get(rec, key)
    if not v:
        return default
    if v.upper() in ("UNLIMITED", "INFINITE"):
        return UNLIMITED
    # pending jobs render ranged counts, e.g. NumNodes=1-4: take the lower bound
    m = _INT_RANGE_RE.match(v)
    if m:
        return int(m.group(1))
    try:
        return int(float(v))
    except ValueError:
        return default


def _time(rec: dict[str, str], key: str) -> datetime | None:
    return parse_slurm_time(_get(rec, key))


def _dur(rec: dict[str, str], key: str) -> int:
    v = _get(rec, key)
    if not v:
        return 0
    try:
        return parse_duration(v)
    except ValueError:
        return 0


def parse_job_info(text: str) -> list[JobInfo]:
    """Parse `scontrol show jobid -dd <id>` output (one record per sub-job
    for arrays), mirroring jobInfoFromScontrolResponse slurm.go:382-447."""
    jobs: list[JobInfo] = []
    for rec in parse_scontrol_records(text):
        if "JobId" not in rec:
            continue
        array_job = _get(rec, "ArrayJobId")
        array_task = _get(rec, "ArrayTaskId")
        array_id = f"{array_job}_{array_task}" if array_job and array_task else ""
        # UserId renders as "name(uid)"
        user = _get(rec, "UserId")
        m = re.match(r"^([^()]+)\(", user)
        jobs.append(
            JobInfo(
                id=_int(rec, "JobId"),
                user_id=m.group(1) if m else user,
                name=_get(rec, "JobName") or _get(rec, "Name"),
                exit_code=_get(rec, "ExitCode"),
                state=JobStatus.from_slurm(_get(rec, "JobState")),
                submit_time=_time(rec, "SubmitTime"),
                start_time=_time(rec, "StartTime"),
                run_time_s=_dur(rec, "RunTime"),
                time_limit_s=(
                    UNLIMITED
                    if _get(rec, "TimeLimit").upper() == "UNLIMITED"
                    else _dur(rec, "TimeLimit")
                ),
                working_dir=_get(rec, "WorkDir"),
                std_out=_get(rec, "StdOut"),
                std_err=_get(rec, "StdErr"),
                partition=_get(rec, "Partition"),
                node_list=_get(rec, "NodeList"),
                batch_host=_get(rec, "BatchHost"),
                num_nodes=_int(rec, "NumNodes"),
                array_id=array_id,
                reason=_get(rec, "Reason"),
            )
        )
    return jobs


def parse_partition_info(text: str) -> list[PartitionInfo]:
    """Parse `scontrol show partition` output with the reference's
    UNLIMITED→total fallbacks (parse.go:113-190): an UNLIMITED MaxNodes
    falls back to TotalNodes, MaxCPUsPerNode to TotalCPUs/TotalNodes."""
    parts: list[PartitionInfo] = []
    for rec in parse_scontrol_records(text):
        if "PartitionName" not in rec:
            continue
        total_cpus = _int(rec, "TotalCPUs")
        total_nodes = _int(rec, "TotalNodes")
        max_nodes = _int(rec, "MaxNodes", UNLIMITED)
        if max_nodes == UNLIMITED and total_nodes > 0:
            max_nodes = total_nodes
        max_cpus = _int(rec, "MaxCPUsPerNode", UNLIMITED)
        if max_cpus == UNLIMITED and total_nodes > 0:
            max_cpus = total_cpus // total_nodes
        max_time_raw = _get(rec, "MaxTime")
        max_time = (
            UNLIMITED
            if max_time_raw.upper() in ("UNLIMITED", "INFINITE", "")
            else _dur(rec, "MaxTime")
        )
        nodes_expr = _get(rec, "Nodes")
        parts.append(
            PartitionInfo(
                name=_get(rec, "PartitionName"),
                nodes=tuple(expand_hostlist(nodes_expr)) if nodes_expr else (),
                max_time_s=max_time,
                max_nodes=max_nodes,
                max_cpus_per_node=max_cpus,
                max_mem_per_node_mb=_int(rec, "MaxMemPerNode", UNLIMITED),
                total_cpus=total_cpus,
                total_nodes=total_nodes,
                state=_get(rec, "State") or "UP",
            )
        )
    return parts


# Two GPU-count grammars coexist: Gres/GresUsed use colon form
# (`gpu:v100:4(S:0-1)`), AllocTRES/CfgTRES use equals form (`gres/gpu=4`,
# `gres/gpu:v100=4`).
_GRES_RE = re.compile(r"\bgpu(?::(?P<type>[^:,(=]+))?:(?P<count>\d+)")
_TRES_RE = re.compile(r"gres/gpu(?::(?P<type>[^:,=]+))?=(?P<count>\d+)")


def parse_gres_gpus(gres: str) -> tuple[int, str]:
    """Parse GPU counts from either Gres (`gpu:v100:4(S:0-1),lustre:1`) or
    TRES (`cpu=8,mem=32G,gres/gpu=4`) syntax → (4, 'v100')."""
    total, gpu_type = 0, ""
    pattern = _TRES_RE if "gres/gpu" in gres else _GRES_RE
    for m in pattern.finditer(gres):
        total += int(m.group("count"))
        if m.group("type"):
            gpu_type = m.group("type")
    return total, gpu_type


def parse_node_info(text: str) -> list[NodeInfo]:
    """Parse `scontrol show nodes` output (CPUTot/CPUAlloc/RealMemory/
    AllocMem per parse.go:291-308, plus Gres → gpus)."""
    nodes: list[NodeInfo] = []
    for rec in parse_scontrol_records(text):
        if "NodeName" not in rec:
            continue
        gpus, gpu_type = parse_gres_gpus(_get(rec, "Gres"))
        alloc_gpus, _ = parse_gres_gpus(_get(rec, "GresUsed") or _get(rec, "AllocTRES"))
        feats = _get(rec, "AvailableFeatures") or _get(rec, "Features")
        nodes.append(
            NodeInfo(
                name=_get(rec, "NodeName"),
                cpus=_int(rec, "CPUTot"),
                alloc_cpus=_int(rec, "CPUAlloc"),
                memory_mb=_int(rec, "RealMemory"),
                alloc_memory_mb=_int(rec, "AllocMem"),
                gpus=gpus,
                alloc_gpus=alloc_gpus,
                gpu_type=gpu_type,
                features=tuple(f for f in feats.split(",") if f) if feats else (),
                state=_get(rec, "State") or "IDLE",
            )
        )
    return nodes
