"""Agent-side YAML partition overrides.

Reference parity: the agent's per-partition resource config
(pkg/slurm-agent/api/slurm.go:54-78, loaded in cmd/slurm-agent/
slurm-agent.go:113-130): each partition can pin nodes/cpu/mem/walltime or
mark them ``auto_*`` to fall back to live queries, plus advertise
additional feature strings.

Schema::

    partition_name:
      auto_nodes: true            # or nodes: 4
      auto_cpu_per_node: false
      cpu_per_node: 32
      auto_mem_per_node: true
      auto_wall_time: true
      wall_time: "1-00:00:00"     # slurm duration grammar
      additional_features: [a100, ib]
"""

from __future__ import annotations

import yaml

from slurm_bridge_tpu.core.durations import parse_duration
from slurm_bridge_tpu.core.types import PartitionResources


def parse_partition_config(text: str) -> dict[str, PartitionResources]:
    raw = yaml.safe_load(text) or {}
    if not isinstance(raw, dict):
        raise ValueError("partition config must be a mapping")
    out: dict[str, PartitionResources] = {}
    for name, body in raw.items():
        body = body or {}
        if not isinstance(body, dict):
            raise ValueError(f"partition {name!r} config must be a mapping")
        wall = body.get("wall_time", 0)
        wall_s = parse_duration(str(wall)) if isinstance(wall, str) else int(wall)
        out[str(name)] = PartitionResources(
            auto_nodes=bool(body.get("auto_nodes", False)),
            auto_cpu_per_node=bool(body.get("auto_cpu_per_node", False)),
            auto_mem_per_node=bool(body.get("auto_mem_per_node", False)),
            auto_wall_time=bool(body.get("auto_wall_time", False)),
            nodes=int(body.get("nodes", 0)),
            cpu_per_node=int(body.get("cpu_per_node", 0)),
            mem_per_node_mb=int(body.get("mem_per_node", body.get("mem_per_node_mb", 0))),
            wall_time_s=wall_s,
            additional_features=tuple(body.get("additional_features", ()) or ()),
        )
    return out


def load_partition_config(path: str) -> dict[str, PartitionResources]:
    with open(path) as f:
        return parse_partition_config(f.read())
