"""The login-node agent: Slurm CLI driver + WorkloadManager gRPC server.

Reference parity: pkg/slurm-agent (CLI client slurm.go, gRPC server
api/slurm.go) and cmd/slurm-agent (main). The driver interface is pluggable
(the reference hints at this with its wlmName abstraction api/slurm.go:355):
anything implementing :class:`cli.WorkloadDriver` can back the server.
"""

from slurm_bridge_tpu.agent.cli import SlurmClient, SlurmError, WorkloadDriver
from slurm_bridge_tpu.agent.server import WorkloadServicer
from slurm_bridge_tpu.agent.config import load_partition_config

__all__ = [
    "SlurmClient",
    "SlurmError",
    "WorkloadDriver",
    "WorkloadServicer",
    "load_partition_config",
]
