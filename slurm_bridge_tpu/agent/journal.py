"""Agent job-state journal — WAL-backed durability for the login-node daemon.

The agent is the durability weak link when the control plane restarts
around it (JIRIAF's virtual-kubelet HPC integration, PAPERS.md
arxiv 2502.18596): the bridge's snapshot+WAL (PR-7) survives a bridge
crash, but the agent's in-memory submit ledger — the idempotency map
that keeps retried submissions from becoming resubmission storms — died
with the process, and a SIMULTANEOUS bridge+agent crash could double
submit. This module closes that hole with the same CRC-framed
record/replay machinery the bridge WAL uses (``utils/wal.py``):

- **Ledger records** (``{"op":"ledger","sid":...,"id":...}``) — one per
  submit-dedupe entry, appended durably (group-commit: the batched
  submit's thread-pool fan-out shares fsyncs) the moment the entry is
  made, BEFORE the response leaves the process. A crashed agent reloads
  the ledger and a bridge retry of an in-flight submit dedupes exactly
  as if nothing happened.
- **Job records** (``{"op":"job","id":...,"doc":{...}}``) — level-style
  puts of per-job state, later record wins. The real agent journals the
  submit-time document (id, name, partition, submitter — the reverse
  index that hands a restarted daemon its in-flight job set without a
  full queue scan; Slurm itself remains the job-state truth). The
  simulator's fake agent (``sim/agent.py``) journals every lifecycle
  transition — there the journal carries FULL job state, because
  ``SimCluster`` plays both the daemon and Slurm, and the ``agent_crash``
  fault rebuilds the whole cluster-side truth from replay.
- **Snapshot compaction** — past a record budget the caller checkpoints
  the full state (atomic tmp+rename via the same fsync seam) and the WAL
  truncates. Records and snapshots are stamped with a per-instance
  ``incarnation`` id, so a crash between snapshot install and WAL
  truncate can never replay a previous process's tail (identical to
  ``bridge/persist.py``'s contract); a restarted owner checkpoints first
  to rebase.
- **Replay tolerance** — a torn tail or checksum-corrupt record stops
  replay there with a warning; everything before it survives
  (``tests/test_agent_journal.py`` fuzzes exactly the
  ``tests/test_persist.py`` suite's shapes against this file format).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import uuid
from dataclasses import dataclass, field

from slurm_bridge_tpu.utils.wal import WalWriter, pack_record, read_wal

log = logging.getLogger("sbt.agent.journal")


@dataclass
class JournalState:
    """What :meth:`AgentJournal.load` recovered."""

    ledger: dict[str, int] = field(default_factory=dict)
    jobs: dict[int, dict] = field(default_factory=dict)
    #: incremental-sync cursor state (ISSUE 12 satellite d): the
    #: JobsInfo/Nodes signature+version maps the real agent persists so
    #: a restart does NOT force a full re-deliver to every cursor-
    #: holding caller. Shape: ``{"jobs_version": int, "jobs": {jid:
    #: [ver, sig_hash]}, "nodes": {key_hash: [ver, sig_hash]}}``.
    cursors: dict = field(default_factory=dict)
    #: None = clean; "torn" / "corrupt" = replay stopped at a defect
    #: (prior records kept — mirror of ``utils.wal.read_wal``)
    defect: str | None = None
    #: WAL records replayed (after the snapshot)
    replayed: int = 0


class AgentJournal:
    """Snapshot + WAL journal over ``(ledger, jobs)`` agent state.

    The journal does not own the state — callers append records as they
    mutate and hand the full state back for :meth:`checkpoint` when
    :attr:`needs_compaction` (the journal can't rebuild a snapshot from
    a truncated WAL alone). ``fsync=False`` is the simulator's mode
    (within-process durability, deterministic, no device flushes).
    """

    def __init__(
        self,
        path: str,
        *,
        fsync: bool = True,
        fsync_delay_s: float | None = None,
        compact_records: int = 10_000,
        compact_bytes: int = 4 << 20,
    ):
        self.path = path
        self.wal_path = path + ".wal"
        self.fsync = fsync
        self.fsync_delay_s = fsync_delay_s
        self.compact_records = compact_records
        self.compact_bytes = compact_bytes
        #: stamped into every record + snapshot; replay refuses to apply
        #: another incarnation's WAL tail over this one's snapshot
        self.incarnation = uuid.uuid4().hex
        self._wal = WalWriter(
            self.wal_path, fsync=fsync, fsync_delay_s=fsync_delay_s
        )
        # Orders appends against checkpoints: a record appended after a
        # checkpoint captured its state but before the WAL truncate would
        # be destroyed while covered by NOTHING — the exact durability
        # hole the journal exists to close. Appends hold the barrier only
        # around the buffered write (cheap); the fsync stays OUTSIDE it,
        # so group commit across the submit pool is untouched.
        self._barrier = threading.Lock()
        self.records = 0  # since last compaction
        self.records_total = 0
        self.snapshots_written = 0
        #: optional () → cursors dict, installed by the owner (the real
        #: agent's WorkloadServicer) and folded into every checkpoint so
        #: cursor records survive WAL truncation. None keeps the PR-8
        #: snapshot shape exactly (the sim journal never sets it).
        self.cursors_fn = None

    # ---- append paths ----

    def _append_all(self, payloads: list[dict]) -> None:
        with self._barrier:
            for payload in payloads:
                payload["inc"] = self.incarnation
                end = self._wal.append(pack_record(payload))
                self.records += 1
                self.records_total += 1
        # ONE durability barrier for the whole batch, outside the append
        # barrier: a concurrent checkpoint may truncate past ``end``, in
        # which case sync_to returns via the snapshot-covered check —
        # the records' content was captured by that checkpoint (callers
        # update their state maps BEFORE appending, and capture runs
        # under the barrier)
        self._wal.sync_to(end)

    def _append(self, payload: dict) -> None:
        self._append_all([payload])

    def record_ledger(self, submitter_id: str, job_id: int) -> None:
        """Durably note one submit-dedupe entry. Called BEFORE the submit
        response leaves the process — the write barrier that makes the
        ledger crash-consistent (group-commit keeps a batch submit's
        fan-out at ~1 fsync, not 1 per item). Delegates to
        :meth:`record_submit`, the single owner of the record shapes."""
        self.record_submit(submitter_id, job_id)

    def record_job(self, job_id: int, doc: dict) -> None:
        """Level-style put of one job's state; the latest record for an
        id wins on replay."""
        self._append({"op": "job", "id": int(job_id), "doc": doc})

    def record_submit(
        self, submitter_id: str, job_id: int, doc: dict | None = None
    ) -> None:
        """One submit = ledger entry + (optionally) its job doc behind a
        SINGLE durability barrier — a lone submit with nobody to share a
        group commit with would otherwise pay two device flushes."""
        payloads: list[dict] = []
        if submitter_id:
            payloads.append(
                {"op": "ledger", "sid": submitter_id, "id": int(job_id)}
            )
        if doc is not None:
            payloads.append({"op": "job", "id": int(job_id), "doc": doc})
        if payloads:
            self._append_all(payloads)

    def record_job_cursors(self, entries: list, watermark: int) -> None:
        """Durably note JobsInfo cursor movement: ``entries`` is
        ``[(job_id, version, sig_hash), ...]`` for the jobs whose
        mirror-visible signature changed this call, ``watermark`` the
        resulting jobs-state version. One record per call — the batch
        shares one durability barrier like a batched submit."""
        self._append({
            "op": "jcur",
            "v": int(watermark),
            "e": [[int(j), int(v), str(h)] for j, v, h in entries],
        })

    def record_nodes_cursor(
        self, key_hash: str, sig_hash: str, version: int
    ) -> None:
        """Durably note one Nodes cursor slot's movement (keyed by the
        requested-name-set hash — the raw name set would bloat records
        for zero recovery value)."""
        self._append({
            "op": "ncur",
            "k": str(key_hash),
            "h": str(sig_hash),
            "v": int(version),
        })

    @property
    def needs_compaction(self) -> bool:
        return (
            self.records > self.compact_records
            or self._wal.size > self.compact_bytes
        )

    @property
    def fsyncs(self) -> int:
        return self._wal.fsyncs

    # ---- snapshot + recovery ----

    def checkpoint(self, ledger: dict[str, int], jobs: dict[int, dict]) -> None:
        """Fold the full state into a fresh snapshot (atomic tmp+rename)
        and truncate the WAL. Also the rebase step after :meth:`load`: a
        restarted owner checkpoints first so its new-incarnation records
        never mix with the previous process's tail.

        Only safe when no appends can race (single-threaded owners — the
        sim, startup rebase). Concurrent writers use
        :meth:`checkpoint_with`, which captures state UNDER the append
        barrier."""
        self.checkpoint_with(lambda: (ledger, jobs))

    def checkpoint_with(self, state_fn) -> None:
        """Checkpoint with the state captured atomically: ``state_fn()``
        → ``(ledger, jobs)`` runs while the append barrier is held, so
        every record already appended is reflected in the captured state
        (callers update their maps BEFORE appending) and no record can
        land between capture and truncate — nothing is ever destroyed
        uncovered."""
        from slurm_bridge_tpu.utils.files import atomic_write

        with self._barrier:
            ledger, jobs = state_fn()
            payload = {
                "version": 1,
                "incarnation": self.incarnation,
                "ledger": ledger,
                "jobs": {str(k): v for k, v in jobs.items()},
            }
            if self.cursors_fn is not None:
                # the sync cursors ride every checkpoint, so truncating
                # the WAL can never lose them (satellite d)
                payload["cursors"] = self.cursors_fn()
            atomic_write(
                self.path,
                json.dumps(
                    payload,
                    separators=(",", ":"),
                ),
                # honor the journal's flush mode: the simulator's
                # fsync=False journal must stay device-flush-free on
                # checkpoints too (rename atomicity is kept either way)
                fsync=self.fsync,
            )
            self._wal.truncate()
            self.records = 0
            self.snapshots_written += 1
        log.debug(
            "agent journal: checkpointed %d ledger entries / %d jobs into %s",
            len(ledger), len(jobs), self.path,
        )

    def load(self) -> JournalState:
        """Snapshot + ordered WAL replay. Unknown ops are skipped with a
        warning (forward compatibility); a torn/corrupt tail stops replay
        there — state up to the defect survives."""
        state = JournalState()
        snap_inc = None
        if os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    data = json.load(f)
                snap_inc = data.get("incarnation")
                state.ledger = {
                    str(k): int(v) for k, v in data.get("ledger", {}).items()
                }
                state.jobs = {
                    int(k): v for k, v in data.get("jobs", {}).items()
                }
                cur = data.get("cursors")
                if isinstance(cur, dict):
                    state.cursors = cur
            except (OSError, ValueError, TypeError) as exc:
                log.warning(
                    "agent journal snapshot %s unreadable (%s); "
                    "starting from the WAL alone", self.path, exc,
                )
                state.ledger, state.jobs = {}, {}
        records, _, defect = read_wal(self.wal_path)
        state.defect = defect
        if defect is not None:
            log.warning(
                "agent journal %s has a %s tail; replaying the %d clean "
                "records before it", self.wal_path, defect, len(records),
            )
        for rec in records:
            if snap_inc is not None and rec.get("inc") not in (None, snap_inc):
                # another incarnation's leftover tail (crash between
                # snapshot install and WAL truncate): already folded in
                continue
            op = rec.get("op")
            if op == "ledger":
                state.ledger[str(rec.get("sid"))] = int(rec.get("id", 0))
            elif op == "job":
                state.jobs[int(rec.get("id", 0))] = rec.get("doc") or {}
            elif op == "jcur":
                cur = state.cursors
                cur["jobs_version"] = max(
                    int(cur.get("jobs_version") or 0), int(rec.get("v", 0))
                )
                jmap = cur.setdefault("jobs", {})
                for ent in rec.get("e") or []:
                    jmap[str(int(ent[0]))] = [int(ent[1]), str(ent[2])]
            elif op == "ncur":
                nmap = state.cursors.setdefault("nodes", {})
                nmap[str(rec.get("k"))] = [
                    int(rec.get("v", 0)), str(rec.get("h", "")),
                ]
            else:
                log.warning("agent journal record has unknown op %r; skipped", op)
                continue
            state.replayed += 1
        return state

    def close(self) -> None:
        self._wal.close()
