"""The WorkloadManager servicer — the agent's gRPC surface.

Reference parity: pkg/slurm-agent/api/slurm.go. Notable behaviors kept:
- submit dedupe keyed by submitter id, making SubmitJob idempotent across
  bridge restarts (:91-112) — upgraded here with an optional JSON state
  file so dedupe also survives *agent* restarts (the reference's map was
  in-memory only, called out in SURVEY.md §5);
- SubmitJobContainer synthesises a Singularity batch script (:475-567);
- TailFile is a bidi stream: FOLLOW starts the tail, READ_TO_END_AND_CLOSE
  drains and finishes (:240-295);
- Resources merges YAML per-partition overrides with live queries (:298-341);
- JobState is implemented (the reference panics: :48-51).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
import uuid

import grpc

from slurm_bridge_tpu.agent.cli import SlurmError, WorkloadDriver
from slurm_bridge_tpu.agent.tailer import TailReader, read_file_chunks
from slurm_bridge_tpu.core.types import UNLIMITED, JobStatus, PartitionResources
from slurm_bridge_tpu.wire import pb
from slurm_bridge_tpu.wire.convert import (
    job_info_to_proto,
    node_to_proto,
    partition_to_proto,
    step_to_proto,
    submit_to_demand,
)

log = logging.getLogger("sbt.agent")


def build_container_script(req: pb.SubmitJobContainerRequest) -> str:
    """Synthesise the sbatch script that runs a Singularity image.

    Functional equivalent of buildSLURMScript/buildRunCommand
    (api/slurm.go:475-567): #SBATCH headers from the job request, then one
    ``singularity run`` (or ``run --app``) line per requested app.
    """
    job = req.job
    c = req.container
    lines = ["#!/bin/sh"]
    if job.job_name:
        lines.append(f"#SBATCH --job-name={job.job_name}")
    if job.partition:
        lines.append(f"#SBATCH --partition={job.partition}")
    if job.nodes > 1:
        lines.append(f"#SBATCH --nodes={job.nodes}")
    if job.ntasks > 1:
        lines.append(f"#SBATCH --ntasks={job.ntasks}")
    if job.ntasks_per_node > 0:
        lines.append(f"#SBATCH --ntasks-per-node={job.ntasks_per_node}")
    if job.cpus_per_task > 1:
        lines.append(f"#SBATCH --cpus-per-task={job.cpus_per_task}")
    if job.mem_per_cpu_mb > 0:
        lines.append(f"#SBATCH --mem-per-cpu={job.mem_per_cpu_mb}")
    if job.array:
        lines.append(f"#SBATCH --array={job.array}")
    if job.working_dir:
        lines.append(f"#SBATCH --chdir={job.working_dir}")

    flags: list[str] = []
    if c.contain:
        flags.append("--contain")
    if c.fakeroot:
        flags.append("--fakeroot")
    if c.cleanenv:
        flags.append("--cleanenv")
    if c.no_home:
        flags.append("--no-home")
    if c.writable:
        flags.append("--writable")
    for bind in c.binds:
        flags.append(f"--bind {bind}")
    flag_str = (" " + " ".join(flags)) if flags else ""
    if c.apps:
        for app in c.apps:
            lines.append(f"singularity run{flag_str} --app {app} {c.image}")
    else:
        lines.append(f"singularity run{flag_str} {c.image}")
    return "\n".join(lines) + "\n"


class SubmitLedger:
    """Idempotency map submitter_id → job id, optionally persisted.

    The state file is the dedupe token that makes SubmitJob idempotent
    across AGENT restarts, so its durability matters. Two backends:

    - ``state_file`` (legacy, PR-7): the whole map rewritten through
      :func:`utils.files.atomic_write` on every put (tempfile + fsync +
      rename — a crash mid-write can never tear it).
    - ``journal`` (PR-8, preferred): an :class:`agent.journal.AgentJournal`
      — one CRC-framed WAL append per entry instead of an O(ledger)
      rewrite, group-commit fsync across the batched submit's thread-pool
      fan-out, snapshot compaction past the record budget. The journal
      entry lands BEFORE the submit response leaves the process, so a
      crash between Slurm accepting the job and the response reaching the
      bridge still dedupes the bridge's retry.

    Either way, a truncated/corrupt/wrong-shape file on load degrades to
    an empty ledger with a warning instead of killing the agent — losing
    dedupe history is recoverable (the bridge's resume tokens still
    prevent resubmission storms), a crash-looping agent is not.
    """

    #: journal-mode bound on the in-flight job index: the index exists
    #: to warm a restarted daemon's view of recent submissions, so the
    #: oldest entries age out by insertion order once past this
    MAX_JOB_DOCS = 10_000

    def __init__(self, state_file: str | None = None, journal=None,
                 preloaded=None):
        self._lock = threading.Lock()
        self._by_submitter: dict[str, int] = {}
        self._state_file = state_file
        self._journal = journal
        self._jobs: dict[int, dict] = {}
        legacy = self._load_legacy(state_file) if state_file else {}
        if journal is not None:
            # ``preloaded`` lets the owner hand in an already-replayed
            # JournalState (the servicer reads the cursors from the same
            # replay) — a restart then parses the snapshot and replays
            # the WAL exactly once
            state = preloaded if preloaded is not None else journal.load()
            # migration: an agent upgraded from --ledger to --journal
            # folds the legacy dedupe history into the first checkpoint —
            # journal entries win (they are newer); dropping the legacy
            # map here would reopen the double-submit hole for every
            # submission made before the upgrade
            self._by_submitter = {**legacy, **state.ledger}
            if legacy:
                log.info(
                    "folded %d legacy ledger entries from %s into the "
                    "journal", len(legacy), state_file,
                )
            self._jobs = dict(state.jobs)
            # rebase: fold the previous incarnation's snapshot+tail into
            # a fresh snapshot under THIS incarnation before appending
            journal.checkpoint(self._by_submitter, self._jobs)
        else:
            self._by_submitter = legacy

    @staticmethod
    def _load_legacy(state_file: str) -> dict[str, int]:
        if not os.path.exists(state_file):
            return {}
        try:
            with open(state_file) as f:
                raw = json.load(f)
            if not isinstance(raw, dict):
                raise ValueError(f"ledger is {type(raw).__name__}, not a map")
            return {str(k): int(v) for k, v in raw.items()}
        except (OSError, ValueError, TypeError, json.JSONDecodeError) as exc:
            log.warning(
                "could not load submit ledger %s (%s); starting empty",
                state_file, exc,
            )
            return {}

    def get(self, submitter_id: str) -> int | None:
        with self._lock:
            return self._by_submitter.get(submitter_id)

    def put(self, submitter_id: str, job_id: int, job_doc: dict | None = None) -> None:
        from slurm_bridge_tpu.utils.files import atomic_write

        with self._lock:
            self._by_submitter[submitter_id] = job_id
            if job_doc is not None and self._journal is not None:
                # the job index only exists in journal mode (nothing
                # reads it on the legacy path), bounded by insertion age
                self._jobs[job_id] = job_doc
                while len(self._jobs) > self.MAX_JOB_DOCS:
                    self._jobs.pop(next(iter(self._jobs)))
            if self._journal is None and self._state_file:
                try:
                    atomic_write(
                        self._state_file, json.dumps(self._by_submitter)
                    )
                except OSError:
                    log.warning("could not persist submit ledger")
        if self._journal is not None:
            # journal appends happen OUTSIDE the map lock: WalWriter
            # orders them itself, and group commit lets the batched
            # submit's pool threads share fsyncs instead of serializing
            # full-map rewrites under one lock. Compaction captures the
            # maps via checkpoint_with — under the journal's append
            # barrier — so a concurrent put's record can never land
            # between the capture and the WAL truncate (its map entry is
            # written before its record, hence inside any capture that
            # could truncate the record away).
            try:
                self._journal.record_submit(submitter_id, job_id, job_doc)
                if self._journal.needs_compaction:
                    self._journal.checkpoint_with(self._journal_state)
            except OSError:
                log.warning("could not journal submit ledger entry")

    def _journal_state(self):
        with self._lock:
            return dict(self._by_submitter), dict(self._jobs)


class WorkloadServicer:
    """Implements every WorkloadManager RPC against a WorkloadDriver."""

    wlm_name = "slurm"

    def __init__(
        self,
        driver: WorkloadDriver,
        *,
        partition_config: dict[str, PartitionResources] | None = None,
        ledger_file: str | None = None,
        journal_file: str | None = None,
        tail_poll_interval: float = 0.1,
        serve_bytes: bool = False,
    ):
        self.driver = driver
        #: serve JobsInfo responses as pre-assembled wire bytes (ISSUE
        #: 14): the response is concatenated from per-entry
        #: serializations instead of copy-assembling a JobsInfoResponse
        #: and serializing it again. Off by default — in-process callers
        #: (tests, embedders) expect message objects; ``sbt-agent``
        #: turns it on, and the wire is byte-compatible either way
        #: (generic_handler passes bytes through its response
        #: serializer untouched).
        self.serve_bytes = serve_bytes
        self.partition_config = partition_config or {}
        self.journal = None
        restored_cursors: dict = {}
        preloaded_state = None
        if journal_file:
            from slurm_bridge_tpu.agent.journal import AgentJournal

            self.journal = AgentJournal(journal_file)
            # ONE snapshot parse + WAL replay for the whole restart:
            # the sync cursors restore from it here (BEFORE the
            # ledger's rebase checkpoint truncates the WAL, satellite
            # d), and the same state is handed to the SubmitLedger
            preloaded_state = self.journal.load()
            restored_cursors = preloaded_state.cursors or {}
            self.journal.cursors_fn = self._cursor_state
        # ---- incremental-sync cursors (PR-11, journaled since ISSUE 12
        # satellite d) ----
        # The real agent must exec Slurm CLIs to know current state either
        # way; what the cursor saves is the RESPONSE — an unchanged job is
        # omitted, an unchanged inventory answers `unchanged=true` — so
        # the caller's decode/diff work is O(changes). Versions start at a
        # NANOSECOND wall-clock stamp so a restarted agent's version base
        # sits above any version a caller could hold from the previous
        # incarnation (the base grows ~1e9/s while bumps add +1 per
        # changed job — the clock outruns churn between restarts).
        # Journal-backed agents additionally PERSIST the signature/version
        # maps: a restarted agent whose jobs have not moved keeps their
        # old versions, so a caller's cursor still filters them — an
        # agent restart no longer forces a full re-deliver to every
        # caller. The restored base bumps PAST the persisted watermark,
        # never below it, so fresh changes always exceed stale cursors.
        self._sync_lock = threading.Lock()
        #: cursor-state bounds: a long-lived agent serving a job-cycling
        #: bridge must not accumulate signature entries forever. When the
        #: job maps outgrow the bound, the oldest-changed half is dropped
        #: (versions are monotonic ⇒ sort-by-version IS change order); a
        #: dropped id simply re-signs (and re-delivers once) on its next
        #: appearance. Name-set slots get a small hard cap with clear-all
        #: overflow (callers just resync once) — enforced on the
        #: journal-restore path below too, so repeated restarts cannot
        #: compound the maps past the bound.
        self._JOB_SIG_LIMIT = 500_000
        self._NODES_SYNC_LIMIT = 32
        jmap = restored_cursors.get("jobs") or {}
        self._job_sigs: dict[int, str] = {}
        self._job_versions: dict[int, int] = {}
        for j, ent in jmap.items():
            try:
                jid, ver, sig = int(j), int(ent[0]), str(ent[1])
            except (TypeError, ValueError, IndexError):
                continue
            self._job_versions[jid] = ver
            self._job_sigs[jid] = sig
        self._jobs_version = max(
            time.time_ns(),
            int(restored_cursors.get("jobs_version") or 0),
            max(self._job_versions.values(), default=0),
        )
        #: per requested-name-set: (sig hash, version, key hash)
        self._nodes_sync: dict[tuple, tuple[str, int, str]] = {}
        #: persisted Nodes cursor slots from the previous incarnation,
        #: keyed by name-set hash: (version, sig hash) — consulted on a
        #: slot's first request this incarnation, so an unchanged
        #: inventory keeps its version across the restart
        self._nodes_persisted: dict[str, tuple[int, str]] = {}
        for k, ent in (restored_cursors.get("nodes") or {}).items():
            try:
                self._nodes_persisted[str(k)] = (int(ent[0]), str(ent[1]))
            except (TypeError, ValueError, IndexError):
                continue
        if len(self._nodes_persisted) > self._NODES_SYNC_LIMIT:
            # keep the newest slots (versions are monotonic): older ones
            # just resync once, exactly like a cap overflow at runtime
            keep = sorted(
                self._nodes_persisted,
                key=lambda k: self._nodes_persisted[k][0],
            )[-self._NODES_SYNC_LIMIT:]
            self._nodes_persisted = {
                k: self._nodes_persisted[k] for k in keep
            }
        if len(self._job_versions) > self._JOB_SIG_LIMIT:
            keep_j = sorted(
                self._job_versions, key=self._job_versions.__getitem__
            )[-self._JOB_SIG_LIMIT:]
            keep_set = set(keep_j)
            self._job_versions = {
                j: v for j, v in self._job_versions.items() if j in keep_set
            }
            self._job_sigs = {
                j: s for j, s in self._job_sigs.items() if j in keep_set
            }
        self.ledger = SubmitLedger(
            ledger_file, journal=self.journal, preloaded=preloaded_state
        )
        self.uid = str(uuid.uuid4())
        self.tail_poll_interval = tail_poll_interval

    def _cursor_state(self) -> dict:
        """The journal-checkpoint view of the sync cursors (satellite
        d): jobs watermark + per-job (version, sig hash) + per-name-set
        Nodes slots — live slots over persisted ones (live is newer)."""
        with self._sync_lock:
            nodes = {
                kh: [ver, sh] for kh, (ver, sh) in self._nodes_persisted.items()
            }
            for _key, (sh, ver, kh) in self._nodes_sync.items():
                nodes[kh] = [ver, sh]
            return {
                "jobs_version": self._jobs_version,
                "jobs": {
                    str(j): [v, self._job_sigs.get(j, "")]
                    for j, v in self._job_versions.items()
                },
                "nodes": nodes,
            }

    @staticmethod
    def _job_doc(req: pb.SubmitJobRequest, job_id: int) -> dict:
        """The journaled submit-time document: the reverse index that
        hands a restarted agent its in-flight job set (Slurm remains the
        job-state truth — this is identity, not status)."""
        return {
            "name": req.job_name,
            "partition": req.partition,
            "submitter": req.submitter_id,
            "nodes": int(req.nodes),
        }

    # ---- submission ----

    def SubmitJob(self, request: pb.SubmitJobRequest, context) -> pb.SubmitJobResponse:
        if request.submitter_id:
            known = self.ledger.get(request.submitter_id)
            if known is not None:
                log.info("dedupe submit %s -> job %d", request.submitter_id, known)
                return pb.SubmitJobResponse(job_id=known)
        try:
            job_id = self.driver.submit(submit_to_demand(request))
        except SlurmError as e:
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        if request.submitter_id:
            self.ledger.put(
                request.submitter_id, job_id, self._job_doc(request, job_id)
            )
        log.info("submitted job %d (partition=%s)", job_id, request.partition)
        return pb.SubmitJobResponse(job_id=job_id)

    def SubmitJobs(self, request: pb.SubmitJobsRequest, context) -> pb.SubmitJobsResponse:
        """Batched SubmitJob (PR-4): one RPC round-trip for a provider's
        whole cold-start submit group. Per-item results — one rejected
        script comes back ok=false with the status code the unary form
        would have aborted with, and never fails its batch-mates.

        Like JobsInfo, each item still execs one sbatch, so the batch
        fans out across a small thread pool; ledger dedupe stays per item
        (the ledger is locked, and two items with the same submitter id
        in ONE batch are a caller bug the dedupe resolves benignly).
        """

        def one(req: pb.SubmitJobRequest) -> pb.SubmitJobsEntry:
            try:
                if req.submitter_id:
                    known = self.ledger.get(req.submitter_id)
                    if known is not None:
                        log.info(
                            "dedupe submit %s -> job %d", req.submitter_id, known
                        )
                        return pb.SubmitJobsEntry(job_id=known, ok=True)
                job_id = self.driver.submit(submit_to_demand(req))
            except SlurmError as e:
                return pb.SubmitJobsEntry(
                    ok=False, error_code="INTERNAL", error=str(e)
                )
            except Exception as e:  # noqa: BLE001 — item isolation is the
                # contract: ANY failure (a malformed request blowing up in
                # submit_to_demand, a driver bug) must fail its own entry,
                # never take 511 batch-mates down with the whole RPC
                log.exception("batch submit item failed")
                return pb.SubmitJobsEntry(
                    ok=False, error_code="INTERNAL", error=f"{type(e).__name__}: {e}"
                )
            if req.submitter_id:
                self.ledger.put(
                    req.submitter_id, job_id, self._job_doc(req, job_id)
                )
            log.info("submitted job %d (partition=%s)", job_id, req.partition)
            return pb.SubmitJobsEntry(job_id=job_id, ok=True)

        reqs = list(request.requests)
        if len(reqs) <= 1:
            return pb.SubmitJobsResponse(results=[one(r) for r in reqs])
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(8, len(reqs))) as pool:
            return pb.SubmitJobsResponse(results=list(pool.map(one, reqs)))

    def SubmitJobContainer(
        self, request: pb.SubmitJobContainerRequest, context
    ) -> pb.SubmitJobResponse:
        inner = pb.SubmitJobRequest()
        inner.CopyFrom(request.job)
        inner.script = build_container_script(request)
        return self.SubmitJob(inner, context)

    def CancelJob(self, request: pb.CancelJobRequest, context) -> pb.CancelJobResponse:
        try:
            self.driver.cancel(int(request.job_id))
        except SlurmError as e:
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        return pb.CancelJobResponse()

    # ---- queries ----

    def JobInfo(self, request: pb.JobInfoRequest, context) -> pb.JobInfoResponse:
        try:
            infos = self.driver.job_info(int(request.job_id))
        except SlurmError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return pb.JobInfoResponse(info=[job_info_to_proto(j) for j in infos])

    def JobsInfo(self, request: pb.JobsInfoRequest, context) -> pb.JobsInfoResponse:
        """Batched JobInfo (PR-3): one RPC round-trip for a provider's
        whole status-mirror pass. A job the driver no longer knows comes
        back found=false instead of aborting the batch — the other 49,999
        answers must not die with it.

        Each driver query still execs one Slurm CLI, so the batch fans
        out across a small thread pool — a serial loop would hold the RPC
        (and a gRPC worker thread) for exec-latency × batch-size, slower
        than the per-pod path it replaced.
        """

        def one(job_id: int) -> pb.JobsInfoEntry:
            try:
                infos = self.driver.job_info(job_id)
            except SlurmError:
                return pb.JobsInfoEntry(job_id=job_id, found=False)
            return pb.JobsInfoEntry(
                job_id=job_id,
                found=True,
                info=[job_info_to_proto(j) for j in infos],
            )

        ids = [int(j) for j in request.job_ids]
        if len(ids) <= 1:
            entries = [one(i) for i in ids]
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=min(8, len(ids))) as pool:
                entries = list(pool.map(one, ids))
        return self._jobs_cursor_filter(entries, request.since_version)

    @staticmethod
    def _entry_sig(entry: pb.JobsInfoEntry) -> str:
        """The mirror-visible signature of one job's entry: everything
        Slurm can change on a live job EXCEPT the always-ticking
        ``run_time_s`` (the mirror's own "not a change" rule). Hashed —
        the digest is what the journal persists (satellite d), and the
        field values are primitives so ``repr`` is stable across
        processes."""
        sig = tuple(
            (m.status, m.node_list, m.batch_host, m.reason, m.exit_code,
             m.start_time)
            for m in entry.info
        )
        return hashlib.blake2b(repr(sig).encode(), digest_size=12).hexdigest()

    def _jobs_cursor_filter(
        self, entries: list, since: int
    ) -> pb.JobsInfoResponse:
        """The JobsInfo cursor (PR-11): track each job's signature across
        calls, stamp a monotonically-growing version on every change, and
        — when the caller carries a cursor — omit entries that have not
        moved since it. found=false entries always ride along (an unknown
        id has no version). since=0 callers get the full pre-PR-11
        response, with the version field offering the cursor for next
        time. Signature movement is journaled (satellite d), so a
        restarted agent's unchanged jobs keep their versions and cursor-
        holding callers are not force-fed a full re-deliver."""
        moved: list[tuple[int, int, str]] = []
        with self._sync_lock:
            for entry in entries:
                if not entry.found:
                    continue
                jid = int(entry.job_id)
                sig = self._entry_sig(entry)
                if self._job_sigs.get(jid) != sig:
                    self._job_sigs[jid] = sig
                    self._jobs_version += 1
                    self._job_versions[jid] = self._jobs_version
                    moved.append((jid, self._jobs_version, sig))
            if len(self._job_sigs) > self._JOB_SIG_LIMIT:
                keep = sorted(
                    self._job_versions,
                    key=self._job_versions.__getitem__,
                )[len(self._job_versions) // 2 :]
                keep_set = set(keep)
                self._job_sigs = {
                    j: s for j, s in self._job_sigs.items() if j in keep_set
                }
                self._job_versions = {
                    j: v
                    for j, v in self._job_versions.items()
                    if j in keep_set
                }
            ver = self._jobs_version
            if since:
                entries = [
                    e
                    for e in entries
                    if not e.found
                    or self._job_versions.get(int(e.job_id), ver) > since
                ]
        if moved and self.journal is not None:
            # outside the sync lock, like the ledger's appends: the WAL
            # writer orders itself, group commit shares fsyncs
            try:
                self.journal.record_job_cursors(moved, ver)
                if self.journal.needs_compaction:
                    self.journal.checkpoint_with(self.ledger._journal_state)
            except OSError:
                log.warning("could not journal JobsInfo cursor movement")
        if self.serve_bytes:
            return self._assemble_jobs_bytes(entries, ver)
        resp = pb.JobsInfoResponse(jobs=entries)
        resp.version = ver
        return resp

    def _assemble_jobs_bytes(self, entries: list, ver: int) -> bytes:
        """Pre-serialized ``JobsInfoResponse`` wire bytes, assembled
        entry by entry: skips BOTH the per-entry message copy that
        ``JobsInfoResponse(jobs=entries)`` pays and the second full-tree
        serialization the response serializer would run. No caching —
        ``run_time_s`` ticks inside every live entry, so cached bytes
        would serve stale counters (the sim agent can splice because it
        owns the layout; real entries carry arbitrary multi-info
        shapes). Decodes identically to the message path — ``coldec``
        and ``FromString`` alike."""
        from slurm_bridge_tpu.wire.coldec import uvarint

        parts: list[bytes] = []
        for entry in entries:
            raw = entry.SerializeToString()
            parts.append(b"\x0a" + uvarint(len(raw)) + raw)
        return b"".join(parts) + b"\x10" + uvarint(ver)

    def JobSteps(self, request: pb.JobStepsRequest, context) -> pb.JobStepsResponse:
        try:
            steps = self.driver.job_steps(int(request.job_id))
        except SlurmError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return pb.JobStepsResponse(steps=[step_to_proto(s) for s in steps])

    def JobState(self, request: pb.JobStateRequest, context) -> pb.JobStateResponse:
        try:
            infos = self.driver.job_info(int(request.job_id))
        except SlurmError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        if not infos:
            return pb.JobStateResponse(status=int(JobStatus.UNKNOWN))
        return pb.JobStateResponse(status=int(infos[0].state))

    # ---- files ----

    def OpenFile(self, request: pb.OpenFileRequest, context):
        if not os.path.exists(request.path):
            context.abort(grpc.StatusCode.NOT_FOUND, f"no such file: {request.path}")
        try:
            for chunk in read_file_chunks(request.path):
                yield pb.Chunk(content=chunk)
        except OSError as e:
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    def TailFile(self, request_iterator, context):
        """Bidi tail: FOLLOW streams growth; READ_TO_END_AND_CLOSE drains."""
        first = next(request_iterator, None)
        if first is None or not first.path:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "no tail request")
        reader = TailReader(first.path, poll_interval=self.tail_poll_interval)
        if first.action == pb.READ_TO_END_AND_CLOSE:
            reader.stop()

        def watch_actions():
            for req in request_iterator:
                if req.action == pb.READ_TO_END_AND_CLOSE:
                    reader.stop()
                    return

        threading.Thread(target=watch_actions, daemon=True).start()
        while context.is_active():
            chunk = reader.read_chunk()
            if reader.finished:
                return
            if chunk:
                yield pb.Chunk(content=chunk)

    # ---- inventory ----

    def Resources(self, request: pb.ResourcesRequest, context) -> pb.ResourcesResponse:
        """Partition resources with YAML overrides over live queries
        (api/slurm.go:298-341)."""
        cfg = self.partition_config.get(request.partition, PartitionResources())
        need_auto = (
            cfg.auto_nodes
            or cfg.auto_cpu_per_node
            or cfg.auto_mem_per_node
            or cfg.auto_wall_time
            or not (cfg.nodes and cfg.cpu_per_node and cfg.mem_per_node_mb)
        )
        live = None
        if need_auto:
            try:
                live = self.driver.partition(request.partition)
            except SlurmError as e:
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))

        def pick(fixed: int, auto: bool, live_val: int) -> int:
            if fixed and not auto:
                return fixed
            return live_val

        resp = pb.ResourcesResponse(
            nodes=pick(cfg.nodes, cfg.auto_nodes, live.max_nodes if live else 0),
            cpu_per_node=pick(
                cfg.cpu_per_node, cfg.auto_cpu_per_node,
                live.max_cpus_per_node if live else 0,
            ),
            mem_per_node_mb=pick(
                cfg.mem_per_node_mb, cfg.auto_mem_per_node,
                live.max_mem_per_node_mb if live else 0,
            ),
            wall_time_s=pick(
                cfg.wall_time_s, cfg.auto_wall_time,
                live.max_time_s if live else UNLIMITED,
            ),
            features=list(cfg.additional_features),
        )
        return resp

    def Partitions(self, request: pb.PartitionsRequest, context) -> pb.PartitionsResponse:
        try:
            return pb.PartitionsResponse(partitions=self.driver.partitions())
        except SlurmError as e:
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    def Partition(self, request: pb.PartitionRequest, context) -> pb.PartitionResponse:
        try:
            return partition_to_proto(self.driver.partition(request.partition))
        except SlurmError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))

    def Nodes(self, request: pb.NodesRequest, context) -> pb.NodesResponse:
        try:
            nodes = self.driver.nodes(list(request.names))
        except SlurmError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        resp = pb.NodesResponse(nodes=[node_to_proto(n) for n in nodes])
        # the Nodes cursor (PR-11): signature per requested NAME SET (two
        # callers asking for different slices must not churn each other's
        # version), version bumped on content change. The scontrol exec
        # already happened — the cursor saves the wire + caller decode.
        # Journal-backed agents persist (version, sig hash) per slot
        # (satellite d): an unchanged inventory keeps its version across
        # a restart, so callers' cursors keep answering unchanged=true.
        key = tuple(request.names)
        sig = hashlib.blake2b(
            resp.SerializeToString(deterministic=True), digest_size=12
        ).hexdigest()
        journal_rec = None
        with self._sync_lock:
            ent = self._nodes_sync.get(key)
            if ent is None:
                key_hash = hashlib.blake2b(
                    "\x00".join(request.names).encode(), digest_size=12
                ).hexdigest()
                pers = self._nodes_persisted.get(key_hash)
                if pers is not None and pers[1] == sig:
                    # same content as the previous incarnation saw: the
                    # persisted version still names it — no re-deliver.
                    # The slot cap applies HERE too: restored slots must
                    # not grow the maps past the bound the cap exists
                    # for (callers past it just resync once), and an
                    # adopted persisted entry moves to the live map so
                    # checkpoints don't carry it twice forever.
                    if len(self._nodes_sync) >= self._NODES_SYNC_LIMIT:
                        self._nodes_sync.clear()
                        self._nodes_persisted.clear()
                    else:
                        self._nodes_persisted.pop(key_hash, None)
                        ent = (sig, pers[0], key_hash)
                        self._nodes_sync[key] = ent
            else:
                key_hash = ent[2]
            if ent is None or ent[0] != sig:
                # ns-stamped base for the same restart-monotonicity
                # argument as the jobs cursor (content changes bump +1,
                # the clock outruns them between restarts); a persisted
                # slot whose content moved while the agent was down
                # bumps PAST its persisted version, never below
                pers = self._nodes_persisted.get(key_hash)
                base = ent[1] if ent else max(
                    time.time_ns(), pers[0] if pers else 0
                )
                ver = base + 1
                if ent is None and len(self._nodes_sync) >= self._NODES_SYNC_LIMIT:
                    # each slot pins cursor state: cap hard, clear-all
                    # on overflow (callers just resync once)
                    self._nodes_sync.clear()
                    self._nodes_persisted.clear()
                self._nodes_sync[key] = (sig, ver, key_hash)
                journal_rec = (key_hash, sig, ver)
            else:
                ver = ent[1]
        if journal_rec is not None and self.journal is not None:
            try:
                self.journal.record_nodes_cursor(*journal_rec)
            except OSError:
                log.warning("could not journal Nodes cursor movement")
        if request.since_version and request.since_version == ver:
            return pb.NodesResponse(version=ver, unchanged=True)
        resp.version = ver
        return resp

    def WorkloadInfo(self, request: pb.WorkloadInfoRequest, context) -> pb.WorkloadInfoResponse:
        try:
            version = self.driver.version()
        except SlurmError:
            version = "unknown"
        return pb.WorkloadInfoResponse(name=self.wlm_name, version=version, uid=self.uid)

    def Healthz(self, request: pb.HealthzRequest, context) -> pb.HealthzResponse:
        # fleet version handshake: a skewed peer shows up as a
        # schema_version mismatch here instead of a mid-RPC decode error
        import os

        from slurm_bridge_tpu.fleet.columnar import healthz_response

        return healthz_response(
            "workload-manager",
            os.environ.get("SBT_INCARNATION", str(os.getpid())),
        )
