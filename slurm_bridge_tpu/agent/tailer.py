"""EOF-masking file tailer.

Reference parity: pkg/common/tail/reader.go:25-92 — a reader whose Read
blocks through EOF while the file may still grow, until the caller asks for
"read to end and close" (the TailFile READ_TO_END_AND_CLOSE action,
api/slurm.go:240-295). The reference vendors an inotify fork (pkg/tail) for
this; a poll at the same 100 ms cadence the RPC loop already used
(api/slurm.go:267-269) needs no native watcher and behaves identically at
the wire.
"""

from __future__ import annotations

import os
import threading
import time


class TailReader:
    """Follow a file as it grows.

    ``read_chunk`` returns b"" only transiently (no new data yet) — the
    stream is over when :attr:`finished` is True: either :meth:`stop` was
    called (drain-to-end semantics) and the tail is consumed, or the file
    vanished.
    """

    def __init__(self, path: str, *, poll_interval: float = 0.1, chunk_size: int = 4096):
        self.path = path
        self.poll_interval = poll_interval
        self.chunk_size = chunk_size
        self._offset = 0
        self._stopping = threading.Event()
        self._finished = False

    def stop(self) -> None:
        """Switch to drain mode: emit what remains, then finish."""
        self._stopping.set()

    @property
    def finished(self) -> bool:
        return self._finished

    def read_chunk(self, *, block: bool = True) -> bytes:
        """Next chunk of new data; waits up to one poll interval if none."""
        while True:
            if self._finished:
                return b""
            try:
                size = os.path.getsize(self.path)
            except OSError:
                # file vanished: stream over
                self._finished = True
                return b""
            if size < self._offset:
                # truncated (e.g. log rotation): restart from the top,
                # matching tail's reopen behaviour
                self._offset = 0
                size = os.path.getsize(self.path)
            if size > self._offset:
                with open(self.path, "rb") as f:
                    f.seek(self._offset)
                    data = f.read(self.chunk_size)
                self._offset += len(data)
                return data
            if self._stopping.is_set():
                self._finished = True
                return b""
            if not block:
                return b""
            time.sleep(self.poll_interval)

    def __iter__(self):
        while True:
            chunk = self.read_chunk()
            if self._finished:
                return
            if chunk:
                yield chunk


def read_file_chunks(path: str, *, chunk_size: int = 65536):
    """One-shot streaming read (the OpenFile RPC body)."""
    with open(path, "rb") as f:
        while True:
            data = f.read(chunk_size)
            if not data:
                return
            yield data
