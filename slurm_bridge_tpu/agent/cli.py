"""Slurm CLI driver — the only layer that talks to the workload manager.

Reference parity (pkg/slurm-agent/slurm.go):
- NewClient verifies all five binaries on PATH (:129-147);
- SBatch builds the flag list, pipes the script on stdin, and parses the
  ``--parsable`` job id (:167-229) — we fix the reference's duplicated
  ntasks-per-node flag (:216-221) by emitting each flag once;
- SJobInfo/SJobSteps/Resources/Partitions/Nodes/Version shell out to
  scontrol/sacct/sinfo and parse with the core parsers (:232-380).

Swapping this driver retargets the whole bridge at another WLM — the
``WorkloadDriver`` protocol is the seam.
"""

from __future__ import annotations

import shutil
import subprocess
from typing import IO, Iterable, Protocol

from slurm_bridge_tpu.core.sacct import parse_sacct_steps
from slurm_bridge_tpu.core.scontrol import (
    parse_job_info,
    parse_node_info,
    parse_partition_info,
)
from slurm_bridge_tpu.core.types import (
    JobDemand,
    JobInfo,
    JobStepInfo,
    NodeInfo,
    PartitionInfo,
)

REQUIRED_BINARIES = ("sbatch", "scancel", "scontrol", "sacct", "sinfo")


class SlurmError(RuntimeError):
    """A Slurm CLI invocation failed; carries the command and stderr."""

    def __init__(self, cmd: list[str], returncode: int, stderr: str):
        super().__init__(f"{' '.join(cmd)} failed (rc={returncode}): {stderr.strip()}")
        self.cmd = cmd
        self.returncode = returncode
        self.stderr = stderr


class WorkloadDriver(Protocol):
    """The pluggable WLM seam: what the gRPC server needs from a backend."""

    def submit(self, demand: JobDemand) -> int: ...
    def cancel(self, job_id: int) -> None: ...
    def job_info(self, job_id: int) -> list[JobInfo]: ...
    def job_steps(self, job_id: int) -> list[JobStepInfo]: ...
    def partitions(self) -> list[str]: ...
    def partition(self, name: str) -> PartitionInfo: ...
    def nodes(self, names: Iterable[str]) -> list[NodeInfo]: ...
    def version(self) -> str: ...


class SlurmClient:
    """CLI-backed driver (implements :class:`WorkloadDriver`)."""

    def __init__(self, *, check_binaries: bool = True):
        if check_binaries:
            missing = [b for b in REQUIRED_BINARIES if shutil.which(b) is None]
            if missing:
                raise SlurmError(
                    ["which", *missing], 127, f"missing slurm binaries: {missing}"
                )

    # ---- process plumbing ----

    def _run(self, cmd: list[str], *, stdin: str | None = None) -> str:
        proc = subprocess.run(
            cmd,
            input=stdin,
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            raise SlurmError(cmd, proc.returncode, proc.stderr)
        return proc.stdout

    # ---- submission ----

    @staticmethod
    def sbatch_args(demand: JobDemand) -> list[str]:
        """Flag list for sbatch; each option emitted at most once."""
        args = ["sbatch", "--parsable"]
        if demand.partition:
            args += ["--partition", demand.partition]
        if demand.run_as_user is not None:
            args += ["--uid", str(demand.run_as_user)]
        if demand.run_as_group is not None:
            args += ["--gid", str(demand.run_as_group)]
        if demand.array:
            args += ["--array", demand.array]
        if demand.cpus_per_task > 1:
            args += ["--cpus-per-task", str(demand.cpus_per_task)]
        if demand.ntasks > 1:
            args += ["--ntasks", str(demand.ntasks)]
        if demand.ntasks_per_node > 0:
            args += ["--ntasks-per-node", str(demand.ntasks_per_node)]
        if demand.nodes > 1:
            args += ["--nodes", str(demand.nodes)]
        if demand.mem_per_cpu_mb > 0:
            args += ["--mem-per-cpu", str(demand.mem_per_cpu_mb)]
        if demand.gres:
            args += ["--gres", demand.gres]
        if demand.licenses:
            args += ["--licenses", demand.licenses]
        if demand.job_name:
            args += ["--job-name", demand.job_name]
        if demand.working_dir:
            args += ["--chdir", demand.working_dir]
        if demand.time_limit_s > 0:
            mins = max(1, demand.time_limit_s // 60)
            args += ["--time", str(mins)]
        if demand.priority > 0:
            args += ["--priority", str(demand.priority)]
        if demand.nodelist:
            args += ["--nodelist", ",".join(demand.nodelist)]
        return args

    def submit(self, demand: JobDemand) -> int:
        if not demand.script.strip():
            raise SlurmError(["sbatch"], 1, "empty batch script")
        out = self._run(self.sbatch_args(demand), stdin=demand.script)
        # --parsable prints "jobid[;cluster]"
        head = out.strip().splitlines()[-1].split(";")[0]
        try:
            return int(head)
        except ValueError as e:
            raise SlurmError(["sbatch"], 0, f"unparsable sbatch output: {out!r}") from e

    def cancel(self, job_id: int) -> None:
        self._run(["scancel", str(job_id)])

    # ---- queries ----

    def job_info(self, job_id: int) -> list[JobInfo]:
        out = self._run(["scontrol", "show", "jobid", "-dd", str(job_id)])
        return parse_job_info(out)

    def job_steps(self, job_id: int) -> list[JobStepInfo]:
        out = self._run(
            [
                "sacct",
                "-p",
                "-n",
                "-j",
                str(job_id),
                "-o",
                "start,end,exitcode,state,jobid,jobname",
            ]
        )
        return parse_sacct_steps(out)

    def partitions(self) -> list[str]:
        out = self._run(["scontrol", "show", "partition"])
        return [p.name for p in parse_partition_info(out)]

    def partition(self, name: str) -> PartitionInfo:
        out = self._run(["scontrol", "show", "partition", name])
        parts = parse_partition_info(out)
        if not parts:
            raise SlurmError(["scontrol"], 0, f"no such partition: {name}")
        return parts[0]

    def all_partitions(self) -> list[PartitionInfo]:
        out = self._run(["scontrol", "show", "partition"])
        return parse_partition_info(out)

    def nodes(self, names: Iterable[str]) -> list[NodeInfo]:
        names = list(names)
        if not names:
            return []
        out = self._run(["scontrol", "show", "nodes", ",".join(names)])
        return parse_node_info(out)

    def version(self) -> str:
        return self._run(["sinfo", "-V"]).strip()
