"""sbt-agent — the login-node daemon.

Reference parity: cmd/slurm-agent/slurm-agent.go — serves the
WorkloadManager on both a unix socket and a TCP port, loads the YAML
partition config, handles signals.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from slurm_bridge_tpu.agent.cli import SlurmClient
from slurm_bridge_tpu.agent.config import load_partition_config
from slurm_bridge_tpu.agent.server import WorkloadServicer
from slurm_bridge_tpu.obs.bootstrap import add_observability_flags, start_observability
from slurm_bridge_tpu.obs.logging import setup_logging
from slurm_bridge_tpu.obs.tracing import tracing_interceptor
from slurm_bridge_tpu.wire import serve

DEFAULT_SOCKET = "/var/run/sbt/agent.sock"
DEFAULT_LISTEN = "0.0.0.0:9999"


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="slurm-bridge-tpu agent")
    parser.add_argument("--listen", default=DEFAULT_LISTEN, help="TCP host:port")
    parser.add_argument("--socket", default="", help="unix socket path (optional)")
    parser.add_argument("--config", default="", help="partition overrides YAML")
    parser.add_argument("--ledger", default="", help="submit-dedupe state file")
    parser.add_argument(
        "--journal", default="",
        help="agent job-state journal path (WAL-backed submit ledger + "
        "in-flight job index; supersedes --ledger when set)",
    )
    add_observability_flags(parser)
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)

    setup_logging(verbose=args.verbose)
    log = logging.getLogger("sbt.agent")

    partition_config = load_partition_config(args.config) if args.config else {}
    servicer = WorkloadServicer(
        SlurmClient(),
        partition_config=partition_config,
        ledger_file=args.ledger or None,
        journal_file=args.journal or None,
        # the wire agent serves JobsInfo as pre-assembled bytes (ISSUE
        # 14): byte-compatible on the wire, skips the response-message
        # copy+re-serialization per poll
        serve_bytes=True,
    )

    interceptors = (tracing_interceptor(),)
    servers = [serve({"WorkloadManager": servicer}, args.listen,
                     interceptors=interceptors)]
    log.info("serving WorkloadManager on %s", args.listen)
    if args.socket:
        servers.append(serve({"WorkloadManager": servicer}, args.socket,
                             interceptors=interceptors))
        log.info("serving WorkloadManager on %s", args.socket)

    httpd = start_observability(
        "sbt-agent", args,
        ready_checks={"slurm": lambda: servicer.driver.version()},
    )

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    log.info("shutting down")
    for s in servers:
        s.stop(grace=5).wait()
    if httpd is not None:
        httpd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
