"""Virtual-node (kubelet) configuration object + defaults + validation.

Reference parity: the `SlurmVirtualKubeletConfiguration` API object
(apis/kubecluster.org/v1alpha1/slurm_virtual_kubelet_types.go:11-73), its
defaults (slurm_virtual_kubelet_defaults.go:31-52 — port 10250, address
0.0.0.0, pods "10000", default TLS paths), relative-path resolution helpers
(slurm_virtual_kubelet_helpers.go:22-29), and the port-range validation
(pkg/slurm-virtual-kubelet/validation/validation.go:27-36). Loaded through
the strict-then-lenient codec like the reference's configfiles loader.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from slurm_bridge_tpu.utils.codec import (
    ConfigError,
    decode_yaml_config,
    resolve_relative_paths,
)

#: fields resolved against the config file's directory when relative
PATH_FIELDS = ("tls_cert_file", "tls_key_file", "static_config_path")


@dataclass(frozen=True)
class VirtualNodeConfiguration:
    """One virtual node's serving + sync knobs."""

    node_name: str = ""
    partition: str = ""
    endpoint: str = ""                  # agent endpoint (host:port or *.sock)
    address: str = "0.0.0.0"            # kubelet HTTP bind address
    port: int = 10250                   # kubelet HTTP port (logs API)
    metrics_port: int = 10255           # declared metrics port
    pods: int = 10000                   # advertised pod capacity
    sync_frequency_s: float = 60.0      # informer resync (options.go:105)
    startup_timeout_s: float = 0.0      # abort a hung boot (virtual-kubelet.go:267)
    tls_cert_file: str = "/var/lib/sbt/kubelet.crt"
    tls_key_file: str = "/var/lib/sbt/kubelet.key"
    static_config_path: str = ""
    labels: dict[str, str] = field(default_factory=dict)


def validate_vnode_config(cfg: VirtualNodeConfiguration) -> None:
    """Port-range + required-field checks (validation.go:27-36)."""
    errs = []
    for name, value in (("port", cfg.port), ("metrics_port", cfg.metrics_port)):
        if not 0 <= value <= 65535:  # 0 = disabled
            errs.append(f"{name} {value} outside 0-65535")
    if cfg.pods < 0:
        errs.append(f"pods capacity {cfg.pods} is negative")
    if cfg.sync_frequency_s <= 0:
        errs.append(f"sync_frequency_s {cfg.sync_frequency_s} must be positive")
    if errs:
        raise ConfigError("; ".join(errs))


def load_vnode_config(path: str) -> VirtualNodeConfiguration:
    """Read + decode + resolve paths + validate, the configfiles.go flow."""
    with open(path) as f:
        cfg = decode_yaml_config(f.read(), VirtualNodeConfiguration)
    cfg = resolve_relative_paths(cfg, os.path.dirname(os.path.abspath(path)), PATH_FIELDS)
    validate_vnode_config(cfg)
    return cfg
