"""sbt-bridge — the control-plane daemon.

Reference parity: cmd/bridge-operator/bridge-operator.go (manager main:
leader election :59-61, metrics server :57,73, healthz/readyz probes
:100-107, reconciler thread flag :62) plus the configurator daemon main
(cmd/configurator/configurator.go:53-114) — the rebuild runs the operator,
configurator, scheduler, and fetch worker in one process (SURVEY.md §7),
so one main serves them all.

    python -m slurm_bridge_tpu.bridge.main --endpoint host:9999 \
        [--scheduler auto|auction|greedy] [--metrics-port 8080] \
        [--leader-lock /var/run/sbt/bridge.lease] [--threads N]
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading

from slurm_bridge_tpu.bridge.leader import LeaderElector
from slurm_bridge_tpu.bridge.runtime import Bridge
from slurm_bridge_tpu.obs.bootstrap import add_observability_flags, start_observability
from slurm_bridge_tpu.obs.logging import setup_logging
from slurm_bridge_tpu.utils.codec import explicit_flags


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="slurm-bridge-tpu control plane")
    parser.add_argument("--endpoint", required=True, help="agent endpoint (host:port or *.sock)")
    parser.add_argument("--scheduler", default="auto",
                        choices=["auto", "auction", "greedy"])
    parser.add_argument("--scheduler-endpoint", default="",
                        help="PlacementSolver sidecar endpoint (host:port or "
                             "*.sock); empty = solve in-process (SURVEY §7: "
                             "the solver as a gRPC sidecar)")
    parser.add_argument("--preemption", action="store_true",
                        help="let higher-priority pending jobs displace "
                             "lower-priority submitted ones (auction only)")
    parser.add_argument("--policy", action="store_true",
                        help="enable the placement-policy engine: priority "
                             "classes, per-tenant fair share, bounded "
                             "preemption pool, backfill "
                             "(docs/scheduling-policy.md)")
    parser.add_argument("--shard", action="store_true",
                        help="enable sharded placement: partition/island "
                             "fan-out with per-shard encode+solve and "
                             "cross-shard gang reconciliation "
                             "(docs/sharding.md)")
    parser.add_argument("--shard-max-nodes", type=int, default=4096,
                        help="split partitions bigger than this across "
                             "shards (with --shard)")
    parser.add_argument("--shard-workers", type=int, default=2,
                        help="per-shard solve fan-out width (with --shard)")
    parser.add_argument("--policy-max-preemptions", type=int, default=64,
                        help="churn bound: incumbents displaceable per "
                             "scheduler tick (with --policy)")
    parser.add_argument("--no-incremental", action="store_true",
                        help="disable the event-driven incremental tick "
                             "(PR-11): cursor-scoped mirror sync, "
                             "dirty-set pending scan and warm-start "
                             "solve reuse — on by default, this flag "
                             "restores the full O(cluster) tick")
    parser.add_argument("--no-coldec", action="store_true",
                        help="disable the zero-object wire->column "
                             "decode of the bulk RPCs (ISSUE 14): on by "
                             "default, this flag keeps every response "
                             "on the pb2 object path")
    parser.add_argument("--no-mirror-frames", action="store_true",
                        help="disable the partitioned store commit "
                             "(ISSUE 19): worker-built commit frames "
                             "merged per writer partition — on by "
                             "default (engages only when the colpool "
                             "has workers), this flag keeps the serial "
                             "column scatter")
    parser.add_argument("--no-explain", action="store_true",
                        help="disable placement explainability (ISSUE "
                             "15): structured per-job reason codes, the "
                             "pressure ledger and /debug/schedz — on by "
                             "default, this flag restores the generic "
                             "'insufficient capacity' verdicts")
    parser.add_argument("--threads", type=int, default=2,
                        help="operator reconciler workers (--slurm-bridge-operator-threads)")
    parser.add_argument("--configurator-interval", type=float, default=30.0)
    parser.add_argument("--pod-sync-workers", type=int, default=10,
                        help="parallel pod converges per virtual-node sync "
                             "tick (the reference's --pod-sync-workers, "
                             "DefaultPodSyncWorkers=10)")
    parser.add_argument("--leader-lock", default="",
                        help="lease file enabling leader election; empty = no election")
    parser.add_argument("--leader-lease", default="",
                        help="coordination.k8s.io Lease name enabling leader "
                             "election across hosts (requires --kube-api); "
                             "takes precedence over --leader-lock")
    parser.add_argument("--state-file", default="",
                        help="durable store snapshot enabling restart resume "
                             "(the in-process stand-in for the K8s API's etcd)")
    parser.add_argument("--kubelet-port", type=int, default=-1,
                        help="kubelet-style HTTP logs API port (10250 in the "
                             "reference); -1 disables, an explicit 0 picks a "
                             "free port; a config-file port of 0 means disabled")
    parser.add_argument("--kubelet-config", default="",
                        help="virtual-node configuration YAML (ports, TLS, sync)")
    parser.add_argument("--kube-api", default="",
                        help="Kubernetes apiserver URL to watch SlurmBridgeJob "
                             "CRs on (e.g. https://10.0.0.1:443, or "
                             "'in-cluster' for the ServiceAccount env); "
                             "empty = no K8s edge")
    parser.add_argument("--kube-namespace", default="default")
    parser.add_argument("--kube-token-file", default="",
                        help="bearer-token file for --kube-api")
    parser.add_argument("--kube-ca-file", default="",
                        help="CA bundle for --kube-api TLS")
    add_observability_flags(parser, metrics_port_default=8080)
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)

    setup_logging(verbose=args.verbose)
    log = logging.getLogger("sbt.bridge.main")

    vncfg = None
    if args.kubelet_config:
        from slurm_bridge_tpu.bridge.vnconfig import load_vnode_config

        vncfg = load_vnode_config(args.kubelet_config)
    # Flag-over-file precedence (server.go:237-252): the file value applies
    # only when the flag was not actually passed. In the file, port 0 means
    # disabled; on the flag, an explicit 0 asks for an ephemeral port.
    passed = explicit_flags(parser, argv if argv is not None else sys.argv[1:])
    if "kubelet_port" in passed or vncfg is None:
        kubelet_port = args.kubelet_port
    else:
        kubelet_port = vncfg.port if vncfg.port > 0 else -1
    policy = None
    if args.policy:
        from slurm_bridge_tpu.policy import PlacementPolicy, PolicyConfig

        if not args.preemption:
            log.warning(
                "--policy without --preemption: classes, fair share and "
                "backfill apply, but the preemption pool is inactive — "
                "a higher class cannot displace running work (pass "
                "--preemption to enable it)"
            )
        policy = PlacementPolicy(
            PolicyConfig(max_preemptions_per_tick=args.policy_max_preemptions)
        )
    shard = None
    if args.shard:
        from slurm_bridge_tpu.shard import ShardConfig

        shard = ShardConfig(
            max_nodes_per_shard=args.shard_max_nodes,
            workers=args.shard_workers,
        )
    bridge = Bridge(
        args.endpoint,
        scheduler_backend=args.scheduler,
        solver_endpoint=args.scheduler_endpoint,
        preemption=args.preemption,
        policy=policy,
        shard=shard,
        incremental=not args.no_incremental,
        use_coldec=not args.no_coldec,
        mirror_frames=not args.no_mirror_frames,
        explain=not args.no_explain,
        state_file=args.state_file,
        configurator_interval=args.configurator_interval,
        operator_workers=args.threads,
        pod_sync_workers=args.pod_sync_workers,
        kubelet_port=None if kubelet_port < 0 else kubelet_port,
        kubelet_address=(vncfg.address if vncfg else "0.0.0.0"),
        kubelet_tls_cert=(vncfg.tls_cert_file if vncfg else ""),
        kubelet_tls_key=(vncfg.tls_key_file if vncfg else ""),
    )

    stop = threading.Event()
    ready = threading.Event()

    def check_ready() -> None:
        if not ready.is_set():
            raise RuntimeError("bridge components not started")

    httpd = start_observability(
        "sbt-bridge", args, ready_checks={"started": check_ready},
    )

    fatal: list[BaseException] = []

    kube_adapter = [None]
    kube_mirror = [None]

    def kube_config():
        from slurm_bridge_tpu.bridge.kubeapi import KubeConfig

        if args.kube_api == "in-cluster":
            return KubeConfig.in_cluster()
        token = ""
        if args.kube_token_file:
            with open(args.kube_token_file) as f:
                token = f.read().strip()
        return KubeConfig(
            base_url=args.kube_api,
            namespace=args.kube_namespace,
            token=token,
            ca_file=args.kube_ca_file,
        )

    def start_kube_adapter() -> None:
        if not args.kube_api:
            return
        from slurm_bridge_tpu.bridge.kubeapi import KubeApiAdapter, NodePodMirror

        cfg = kube_config()
        kube_adapter[0] = KubeApiAdapter(bridge, cfg).start()
        # kubectl visibility: one Node per partition + worker display pods;
        # advertise the vkhttp endpoint so the apiserver can proxy
        # `kubectl logs` to it (SBT_POD_IP = downward-API pod IP, like the
        # reference's VK_POD_IP env — configurator.go:188-293)
        kubelet_ep = None
        if bridge.kubelet_server is not None:
            import socket as _socket

            # precedence: downward-API env, then a CONCRETE configured bind
            # address (0.0.0.0 is not routable), then hostname resolution
            addr = os.environ.get("SBT_POD_IP", "")
            bind = getattr(bridge.kubelet_server, "address", "")
            if not addr and bind not in ("", "0.0.0.0", "::"):
                addr = bind
            if not addr:
                try:
                    addr = _socket.gethostbyname(_socket.gethostname())
                except OSError:
                    addr = "127.0.0.1"
            kubelet_ep = (addr, bridge.kubelet_server.port)
        kube_mirror[0] = NodePodMirror(
            bridge, cfg, kubelet_endpoint=kubelet_ep
        ).start()
        log.info("watching SlurmBridgeJob CRs on %s", cfg.base_url)

    def start_components() -> None:
        try:
            bridge.start()
            start_kube_adapter()
        except BaseException as exc:
            # Failing to start after winning the election must terminate the
            # daemon (as it would without election), not strand a zombie
            # that keeps renewing a lease it cannot serve.
            log.exception("bridge failed to start; exiting")
            fatal.append(exc)
            stop.set()
            return
        ready.set()
        log.info("bridge running against %s (scheduler=%s)", args.endpoint, args.scheduler)

    elector = None
    lost_lease: list[bool] = []

    def on_lost_leadership() -> None:
        # lost the lease ⇒ exit NON-ZERO (manager semantics) so an
        # on-failure supervisor restarts the replica as a standby; a
        # shutdown we initiated ourselves is not a loss
        if not stop.is_set():
            lost_lease.append(True)
            stop.set()

    if args.leader_lease:
        # the reference's actual primitive: a coordination.k8s.io Lease —
        # arbitrates replicas across hosts, not just one filesystem
        if not args.kube_api:
            parser.error("--leader-lease requires --kube-api")
        from slurm_bridge_tpu.bridge.leader import KubeLeaseElector

        elector = KubeLeaseElector(
            kube_config(),
            args.leader_lease,
            on_started=start_components,
            on_stopped=on_lost_leadership,
        ).start()
        log.info("waiting for leadership on Lease %s", args.leader_lease)
    elif args.leader_lock:
        elector = LeaderElector(
            args.leader_lock,
            on_started=start_components,
            on_stopped=on_lost_leadership,
        ).start()
        log.info("waiting for leadership on %s", args.leader_lock)
    else:
        start_components()

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    log.info("shutting down")
    ready.clear()
    if kube_mirror[0] is not None:
        kube_mirror[0].stop()
    if kube_adapter[0] is not None:
        kube_adapter[0].stop()
    bridge.stop()
    if elector is not None:
        elector.stop()
    if httpd is not None:
        httpd.shutdown()
    return 1 if (fatal or lost_lease) else 0


if __name__ == "__main__":
    sys.exit(main())
