"""sbt-bridge — the control-plane daemon.

Reference parity: cmd/bridge-operator/bridge-operator.go (manager main:
leader election :59-61, metrics server :57,73, healthz/readyz probes
:100-107, reconciler thread flag :62) plus the configurator daemon main
(cmd/configurator/configurator.go:53-114) — the rebuild runs the operator,
configurator, scheduler, and fetch worker in one process (SURVEY.md §7),
so one main serves them all.

    python -m slurm_bridge_tpu.bridge.main --endpoint host:9999 \
        [--scheduler auction|greedy] [--metrics-port 8080] \
        [--leader-lock /var/run/sbt/bridge.lease] [--threads N]
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from slurm_bridge_tpu.bridge.leader import LeaderElector
from slurm_bridge_tpu.bridge.runtime import Bridge
from slurm_bridge_tpu.obs.bootstrap import add_observability_flags, start_observability
from slurm_bridge_tpu.obs.logging import setup_logging


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="slurm-bridge-tpu control plane")
    parser.add_argument("--endpoint", required=True, help="agent endpoint (host:port or *.sock)")
    parser.add_argument("--scheduler", default="auction", choices=["auction", "greedy"])
    parser.add_argument("--threads", type=int, default=2,
                        help="operator reconciler workers (--slurm-bridge-operator-threads)")
    parser.add_argument("--configurator-interval", type=float, default=30.0)
    parser.add_argument("--leader-lock", default="",
                        help="lease file enabling leader election; empty = no election")
    add_observability_flags(parser, metrics_port_default=8080)
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)

    setup_logging(verbose=args.verbose)
    log = logging.getLogger("sbt.bridge.main")

    bridge = Bridge(
        args.endpoint,
        scheduler_backend=args.scheduler,
        configurator_interval=args.configurator_interval,
        operator_workers=args.threads,
    )

    stop = threading.Event()
    ready = threading.Event()

    def check_ready() -> None:
        if not ready.is_set():
            raise RuntimeError("bridge components not started")

    httpd = start_observability(
        "sbt-bridge", args, ready_checks={"started": check_ready},
    )

    def start_components() -> None:
        bridge.start()
        ready.set()
        log.info("bridge running against %s (scheduler=%s)", args.endpoint, args.scheduler)

    elector = None
    if args.leader_lock:
        elector = LeaderElector(
            args.leader_lock,
            on_started=start_components,
            on_stopped=stop.set,  # lost the lease ⇒ exit (manager semantics)
        ).start()
        log.info("waiting for leadership on %s", args.leader_lock)
    else:
        start_components()

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    log.info("shutting down")
    ready.clear()
    bridge.stop()
    if elector is not None:
        elector.stop()
    if httpd is not None:
        httpd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
