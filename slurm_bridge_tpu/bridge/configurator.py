"""Configurator — partition discovery → one virtual-node provider each.

Reference parity: pkg/configurator/configurator.go. A ticker (default 30s,
:94-118) lists partitions over the agent RPC, diffs them against the
providers currently registered (the reference diffs against nodes labeled
``type=slurm-agent-virtual-kubelet`` and creates/deletes one VK *pod* per
partition, :120-184; here each partition gets an in-process
:class:`VirtualNodeProvider` plus its sync ticker), and converges.
"""

from __future__ import annotations

import logging

from slurm_bridge_tpu.bridge.controller import Ticker
from slurm_bridge_tpu.bridge.store import ObjectStore
from slurm_bridge_tpu.bridge.vnode import VirtualNodeProvider
from slurm_bridge_tpu.obs.events import EventRecorder, Reason
from slurm_bridge_tpu.obs.tracing import TRACER, with_current_span
from slurm_bridge_tpu.wire import ServiceClient, pb

log = logging.getLogger("sbt.configurator")

DEFAULT_WATCH_INTERVAL_S = 30.0  # cmd/configurator/configurator.go:63


class Configurator:
    def __init__(
        self,
        store: ObjectStore,
        client: ServiceClient,
        *,
        agent_endpoint: str = "",
        events: EventRecorder | None = None,
        watch_interval: float = DEFAULT_WATCH_INTERVAL_S,
        node_sync_interval: float = 1.0,
        pod_sync_workers: int = 10,
        provider_inventory_ttl: float | None = None,
        provider_status_interval: float | None = None,
        incremental: bool = False,
        use_coldec: bool = True,
        mirror_frames: bool = True,
        inventory_listener=None,
    ):
        self.store = store
        self.client = client
        self.agent_endpoint = agent_endpoint
        self.events = events or EventRecorder()
        #: ``node_sync_interval <= 0`` disables the per-partition sync
        #: tickers entirely — the embedder (e.g. the sim harness, which
        #: must stay single-threaded for determinism) drives ``sync_now()``
        self.node_sync_interval = node_sync_interval
        self.pod_sync_workers = pod_sync_workers
        #: forwarded to each provider; ``None`` keeps the provider default
        #: (the sim sets 0 so no wall-clock cache window leaks in)
        self.provider_inventory_ttl = provider_inventory_ttl
        #: forwarded heartbeat interval; ``None`` keeps the provider
        #: default (the sim passes inf so steady ticks stay write-free
        #: regardless of how slow the box runs the tick)
        self.provider_status_interval = provider_status_interval
        #: event-driven incremental mirror (PR-11), forwarded per provider
        self.incremental = incremental
        #: zero-object wire->column decode (ISSUE 14), forwarded per
        #: provider; off = the pb2 bulk path byte-for-byte
        self.use_coldec = use_coldec
        #: partitioned commit frames (ISSUE 19), forwarded per provider;
        #: engages only when a colpool is active — off (or width 0) runs
        #: the serial column scatter byte-for-byte
        self.mirror_frames = mirror_frames
        #: per-provider inventory-change callback (ISSUE 15 /
        #: ROADMAP streaming-admission follow-up c): the scheduler's
        #: admission-window maintenance seam, forwarded to every
        #: provider this configurator spawns
        self.inventory_listener = inventory_listener
        self.providers: dict[str, VirtualNodeProvider] = {}
        self._tickers: dict[str, Ticker] = {}
        self._watch = Ticker(watch_interval, self.reconcile, name="configurator")

    def start(self) -> None:
        self.reconcile()
        self._watch.start()

    def stop(self) -> None:
        self._watch.stop()
        for t in self._tickers.values():
            t.stop()
        for p in self.providers.values():
            # shut the pod-sync pools: their threads are non-daemon and
            # would outlive a stopped Bridge (long-lived embedders/tests
            # cycling bridges would accumulate 10 idle threads per
            # partition per cycle). close(), NOT deregister() — a clean
            # stop (leader step-down, embedder cycling) must not delete
            # the VirtualNodes: the NodePodMirror propagates deletions to
            # the real apiserver and the nodes would flap across restarts
            # (ADVICE r5 #1); only _remove_partition deletes nodes.
            p.close()

    def reconcile(self) -> None:
        """Diff live partitions vs registered providers (:120-184)."""
        with TRACER.span("configurator.reconcile") as span:
            live = set(self.client.Partitions(pb.PartitionsRequest()).partitions)
            added = removed = 0
            for partition in sorted(live - self.providers.keys()):
                self._add_partition(partition)
                added += 1
            for partition in sorted(self.providers.keys() - live):
                self._remove_partition(partition)
                removed += 1
            span.count("partitions", len(live))
            if added:
                span.count("added", added)
            if removed:
                span.count("removed", removed)

    def sync_now(self) -> None:
        """Force one synchronous provider sync (tests/converge helpers).

        Partitions converge in parallel (PR-4): each provider sync can
        block on agent RPCs, and the forced-converge path used to pay the
        sum of all partitions' cold-start fan-outs serially. With
        ``pod_sync_workers == 1`` (the simulator's deterministic mode)
        the syncs stay serial in sorted-partition order.
        """
        with TRACER.span("configurator.sync_now") as span:
            providers = [self.providers[p] for p in sorted(self.providers)]
            span.count("providers", len(providers))
            if len(providers) <= 1 or self.pod_sync_workers == 1:
                for p in providers:
                    p.sync()
                return
            from concurrent.futures import ThreadPoolExecutor

            def sync_one(p, _parent=span):
                # pool workers start with an empty contextvar: seed the
                # sync_now span as parent so each provider's vnode.sync
                # span lands inside the tick trace
                with with_current_span(_parent):
                    p.sync()

            # transient pool: sync_now is the forced-converge path, not
            # the 250 ms ticker (each partition's ticker already runs in
            # its own thread in steady state) — churn here is irrelevant
            with ThreadPoolExecutor(
                max_workers=min(8, len(providers)),
                thread_name_prefix="partition-sync",
            ) as pool:
                list(pool.map(sync_one, providers))

    def _add_partition(self, partition: str) -> None:
        kwargs = {}
        if self.provider_inventory_ttl is not None:
            kwargs["inventory_ttl"] = self.provider_inventory_ttl
        if self.provider_status_interval is not None:
            kwargs["status_interval"] = self.provider_status_interval
        provider = VirtualNodeProvider(
            self.store,
            self.client,
            partition,
            agent_endpoint=self.agent_endpoint,
            events=self.events,
            sync_workers=self.pod_sync_workers,
            incremental=self.incremental,
            use_coldec=self.use_coldec,
            mirror_frames=self.mirror_frames,
            inventory_listener=self.inventory_listener,
            **kwargs,
        )
        provider.register()
        self.providers[partition] = provider
        if self.node_sync_interval > 0:
            ticker = Ticker(
                self.node_sync_interval, provider.sync, name=f"vnode-{partition}"
            )
            ticker.start()
            self._tickers[partition] = ticker
        log.info("partition %s: virtual node %s up", partition, provider.node_name)

    def _remove_partition(self, partition: str) -> None:
        ticker = self._tickers.pop(partition, None)
        if ticker:
            ticker.stop()
        provider = self.providers.pop(partition, None)
        if provider:
            provider.deregister()
            self.events.event(
                None, Reason.NODE_GONE, f"partition {partition} removed", warning=True
            )
        log.info("partition %s: virtual node removed", partition)
