"""Bridge API objects — the CRD surface re-expressed as dataclasses.

Reference parity: apis/kubecluster.org/v1alpha1/.
- ``BridgeJobSpec``  ↔ SlurmBridgeJobSpec   (slurmbridgejob_types.go:39-61)
- ``SubjobStatus``   ↔ SlurmSubjobStatus    (slurmbridgejob_types.go:65-85)
- ``BridgeJobStatus``↔ SlurmBridgeJobStatus (slurmbridgejob_types.go:87-94)
- ``validate_bridge_job`` ↔ ValidateV1alphaSlurmBridgeJob
  (slurmbridgejob_validation.go:8-26)
- pod roles sizecar/worker ↔ types.go:12-17

One deliberate redesign (SURVEY.md §7 "hard parts"): the reference smuggles
the agent's ``JobInfoResponse`` between virtual kubelet and operator as a
JSON string in ``pod.Status.Message`` (status.go:78-83 ↔
slurmbridgejob_controller.go:263). Here ``PodStatus.job_infos`` is a typed
field — same information flow, no stringly-typed bus.
"""

from __future__ import annotations

import itertools
import re
import uuid
from dataclasses import dataclass, field

from slurm_bridge_tpu.core.fastpath import frozen_new
from slurm_bridge_tpu.core.types import JobDemand, JobInfo, JobStatus

# RFC 1035 label: what K8s requires of resource names
# (slurmbridgejob_validation.go:12-18 uses apimachinery's IsDNS1035Label).
_DNS1035 = re.compile(r"^[a-z]([-a-z0-9]*[a-z0-9])?$")

_uid_counter = itertools.count(1)
#: one random prefix per process, a counter per object: same uniqueness
#: story as a per-object uuid4 (the prefix separates restarted bridges,
#: the counter separates objects) without paying an os.urandom syscall on
#: every Pod creation — 80 µs × 50k worker pods was real money (PR-3)
_uid_prefix = uuid.uuid4().hex[:12]


def new_uid() -> str:
    return f"{_uid_prefix}-{next(_uid_counter)}"


class ValidationError(ValueError):
    pass


@dataclass
class Meta:
    """Object metadata: identity, labels, ownership, optimistic-concurrency
    token. The ``owner`` field stands in for K8s owner references (cascade
    delete + watch routing, slurmbridgejob_controller.go:204)."""

    name: str = ""
    uid: str = field(default_factory=new_uid)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    owner: str = ""  # owning BridgeJob name, "" if unowned
    resource_version: int = 0
    deleted: bool = False  # deletion marker (graceful teardown)


# ---------------------------------------------------------------- BridgeJob


@dataclass
class BridgeJobSpec:
    """What the user asks for — field-for-field the CR spec
    (slurmbridgejob_types.go:39-61), with ``result_to`` standing in for the
    result volume (types.go:6-10)."""

    partition: str = ""
    sbatch_script: str = ""
    run_as_user: int | None = None
    run_as_group: int | None = None
    array: str = ""
    cpus_per_task: int = 0
    ntasks: int = 0
    ntasks_per_node: int = 0
    nodes: int = 0
    working_dir: str = ""
    mem_per_cpu_mb: int = 0
    gres: str = ""
    licenses: str = ""
    priority: int = 0
    result_to: str = ""  # local directory to fetch job output into


@dataclass
class SubjobStatus:
    """Status of one Slurm (sub-)job — SlurmSubjobStatus
    (slurmbridgejob_types.go:65-85)."""

    id: int = 0
    array_id: str = ""
    state: JobStatus = JobStatus.UNKNOWN
    exit_code: str = ""
    submit_time: str = ""
    start_time: str = ""
    run_time_s: int = 0
    std_out: str = ""
    std_err: str = ""
    reason: str = ""

    @classmethod
    def from_job_info(cls, info: JobInfo) -> "SubjobStatus":
        # frozen_new (every field explicit): rebuilt for every sub-job on
        # every CR status sync — 45k instances per sweep pass at the
        # headline shape — born frozen, so the commit walk skips them
        return frozen_new(
            cls,
            id=info.id,
            array_id=info.array_id,
            state=info.state,
            exit_code=info.exit_code,
            submit_time=info.submit_time.isoformat() if info.submit_time else "",
            start_time=info.start_time.isoformat() if info.start_time else "",
            run_time_s=info.run_time_s,
            std_out=info.std_out,
            std_err=info.std_err,
            reason=info.reason,
        )


class JobState:
    """CR-level lifecycle states (pkg/common/status.go:7-13)."""

    PENDING = "Pending"
    SUBMITTED = "Submitted"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"

    TERMINAL = (SUCCEEDED, FAILED)


class FetchState:
    """Result-fetch sub-state (SlurmBridgeJobStatus.FetchResult,
    slurmbridgejob_types.go:92 + controller :349-361)."""

    NONE = ""
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class BridgeJobStatus:
    state: str = JobState.PENDING
    reason: str = ""
    subjobs: dict[str, SubjobStatus] = field(default_factory=dict)
    fetch_result: str = FetchState.NONE
    cluster_endpoint: str = ""


@dataclass
class BridgeJob:
    meta: Meta
    spec: BridgeJobSpec
    status: BridgeJobStatus = field(default_factory=BridgeJobStatus)

    KIND = "BridgeJob"

    @property
    def name(self) -> str:
        return self.meta.name

    @property
    def finished(self) -> bool:
        return self.status.state in JobState.TERMINAL


def validate_bridge_job(job: BridgeJob) -> None:
    """Name must be DNS1035, partition and script required
    (slurmbridgejob_validation.go:8-26)."""
    validate_job_fields(job.meta.name, job.spec)


def validate_job_fields(name: str, spec: BridgeJobSpec) -> None:
    """The validation body over (name, spec) — validation is a pure
    function of exactly these two, which is what lets the columnar sweep
    validate from columns without materializing a view."""
    if not _DNS1035.match(name or ""):
        raise ValidationError(
            f"invalid job name {name!r}: must be a DNS-1035 label"
        )
    if len(name) > 63:
        raise ValidationError(f"job name {name!r} longer than 63 chars")
    if not spec.partition:
        raise ValidationError("spec.partition is required")
    if not spec.sbatch_script.strip():
        raise ValidationError("spec.sbatchScript is required")
    if spec.array:
        # reject malformed/oversized specs at ingress: raised deeper (the
        # sizing path) the ValueError would spin the reconcile-retry loop
        # forever instead of failing the job with a reason
        from slurm_bridge_tpu.core.arrays import array_len

        try:
            array_len(spec.array)
        except ValueError as exc:
            raise ValidationError(f"invalid spec.array: {exc}") from None


# ---------------------------------------------------------------- Pod


class PodRole:
    """Pod roles (types.go:12-17): the sizecar carries the placement
    request; workers are per-sub-job display pods; fetcher pods run the
    result collection."""

    SIZECAR = "sizecar"
    WORKER = "worker"
    FETCHER = "fetcher"


class PodPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"

    TERMINAL = (SUCCEEDED, FAILED)


@dataclass
class ContainerStatus:
    """Display status of one sub-job "container" on a worker pod
    (status.go:105-186)."""

    name: str = ""
    state: str = "waiting"  # waiting | running | terminated
    exit_code: int = 0
    reason: str = ""


@dataclass
class PodSpec:
    role: str = PodRole.SIZECAR
    partition: str = ""
    demand: JobDemand | None = None  # resolved resource request (sizecar)
    node_name: str = ""  # bound virtual node ("" = unscheduled)
    placement_hint: tuple[str, ...] = ()  # solver-chosen Slurm nodes


@dataclass
class PodStatus:
    phase: str = PodPhase.PENDING
    reason: str = ""
    job_ids: tuple[int, ...] = ()  # Slurm job ids owned by this pod
    job_infos: list[JobInfo] = field(default_factory=list)  # typed side-channel
    containers: list[ContainerStatus] = field(default_factory=list)


@dataclass
class Pod:
    meta: Meta
    spec: PodSpec
    status: PodStatus = field(default_factory=PodStatus)

    KIND = "Pod"

    @property
    def name(self) -> str:
        return self.meta.name


# ---------------------------------------------------------------- VirtualNode


@dataclass
class NodeCondition:
    type: str = "Ready"
    status: bool = True
    reason: str = ""


@dataclass
class VirtualNode:
    """One partition mirrored as a schedulable node (node.go:18-52): its
    capacity is the live partition inventory summed over member nodes
    (GetPartitionCapacity node.go:169-199 — with the reference's
    ``allogpu += AlloCpus`` bug fixed: alloc_gpus sums alloc_gpus)."""

    meta: Meta
    partition: str = ""
    capacity: dict[str, float] = field(default_factory=dict)
    allocatable: dict[str, float] = field(default_factory=dict)
    conditions: list[NodeCondition] = field(default_factory=list)
    heartbeat: float = 0.0
    agent_endpoint: str = ""

    KIND = "VirtualNode"

    @property
    def name(self) -> str:
        return self.meta.name

    @property
    def ready(self) -> bool:
        return any(c.type == "Ready" and c.status for c in self.conditions)


def partition_node_name(partition: str) -> str:
    """``slurm-partition-<p>`` (configurator.go:336)."""
    return f"slurm-partition-{partition}"


# ---------------------------------------------------------------- FetchJob


@dataclass
class FetchFile:
    remote_path: str = ""
    local_path: str = ""
    done: bool = False
    error: str = ""


@dataclass
class FetchJob:
    """The result-collection batch job (result.go:11-43): one file transfer
    per sub-job, backoff_limit 0 semantics — any failure fails the job."""

    meta: Meta
    files: list[FetchFile] = field(default_factory=list)
    agent_endpoint: str = ""
    state: str = FetchState.PENDING
    reason: str = ""

    KIND = "FetchJob"

    @property
    def name(self) -> str:
        return self.meta.name


# -------------------------------------------------------------- PolicyState


@dataclass
class PolicyState:
    """Durable scheduler-policy state — the fair-share service ledger.

    The policy engine's per-tenant accumulated dominant-share usage is
    the only scheduler state that is neither derivable from the cluster
    nor carried by a pod: losing it on restart resets every tenant's
    service to zero, so whichever tenant floods the queue first after a
    crash monopolizes the cluster until balance re-accumulates. One
    singleton object (``FAIRSHARE_NAME``) holds the ledger in the store,
    where the ordinary WAL persistence picks it up like any other kind
    (ROADMAP policy follow-up; regression: the crash_restart twin keeps
    Jain within tolerance — tests/test_policy.py).
    """

    meta: Meta
    #: tenant → accumulated dominant-share service (policy/fairshare.py)
    usage: dict[str, float] = field(default_factory=dict)
    #: bumped on every save — observability, not concurrency (the store
    #: rv is the concurrency token)
    generation: int = 0

    KIND = "PolicyState"
    FAIRSHARE_NAME = "fair-share"

    @property
    def name(self) -> str:
        return self.meta.name
