"""Durable store state — the etcd the standalone bridge doesn't have.

Reference parity (SURVEY.md §5 "Checkpoint/resume"): the reference keeps
its durable state in the K8s API server — CR status, and the jobid label
written at submit time, which is the resume token letting any restarted
component re-associate pods with running Slurm jobs. The standalone
bridge's ObjectStore is in-process, so without persistence a bridge
restart would orphan every running job. This module snapshots the store
to a JSON file (debounced write-behind, atomic rename) and reloads it on
start: a restarted bridge finds its pods, reads their ``job_ids``, and
the ordinary level-triggered sync re-converges against live Slurm state —
the same resume-by-label mechanism, one file instead of etcd.

Serialization is type-driven both ways: ``asdict`` + datetime/enum
encoding out, the config codec's dataclass decoder (tuples, nested
dataclasses, Optionals) back in.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import logging
import os
import threading
from datetime import datetime

from slurm_bridge_tpu.bridge.store import ObjectStore

log = logging.getLogger("sbt.persist")

_DT_KEY = "__dt__"


def _kind_registry() -> dict[str, type]:
    from slurm_bridge_tpu.bridge.objects import BridgeJob, FetchJob, Pod, VirtualNode

    return {cls.KIND: cls for cls in (BridgeJob, Pod, VirtualNode, FetchJob)}


def _encode(value):
    if isinstance(value, datetime):
        return {_DT_KEY: value.isoformat()}
    if isinstance(value, enum.Enum):
        return value.value
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {f.name: _encode(getattr(value, f.name)) for f in dataclasses.fields(value)}
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _encode(v) for k, v in value.items()}
    return value


def _decode(value, ftype):
    import types
    import typing

    origin = typing.get_origin(ftype)
    if isinstance(value, dict) and _DT_KEY in value:
        return datetime.fromisoformat(value[_DT_KEY])
    if isinstance(ftype, type) and issubclass(ftype, enum.Enum):
        return ftype(value)
    if dataclasses.is_dataclass(ftype):
        return _decode_dataclass(value, ftype)
    if origin in (list, tuple) and isinstance(value, list):
        args = typing.get_args(ftype)
        inner = args[0] if args else typing.Any
        seq = [_decode(v, inner) for v in value]
        return tuple(seq) if origin is tuple else seq
    if origin is dict and isinstance(value, dict):
        args = typing.get_args(ftype)
        vt = args[1] if len(args) == 2 else typing.Any
        return {k: _decode(v, vt) for k, v in value.items()}
    if origin in (typing.Union, types.UnionType):
        for arg in typing.get_args(ftype):
            if arg is type(None):
                if value is None:
                    return None
                continue
            try:
                return _decode(value, arg)
            except (TypeError, ValueError):
                continue
        return value
    return value


def _decode_dataclass(raw: dict, cls):
    import typing

    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in raw:
            kwargs[f.name] = _decode(raw[f.name], hints.get(f.name, typing.Any))
    return cls(**kwargs)


class StorePersistence:
    """Debounced write-behind snapshotting for an ObjectStore.

    Every store event schedules a flush ``debounce`` seconds out (coalescing
    bursts); ``close()`` flushes synchronously. Writes are atomic
    (tmp + rename), so a crash mid-write leaves the previous snapshot.
    """

    def __init__(self, store: ObjectStore, path: str, *, debounce: float = 0.2):
        self.store = store
        self.path = path
        self.debounce = debounce
        self._lock = threading.Lock()
        # Serializes whole snapshot writes: a timer-fired flush can race
        # close()'s synchronous flush (or the next timer when a flush
        # outlasts the debounce), and two writers interleaving on the same
        # ``.tmp`` could atomically install a corrupt snapshot.
        self._flush_lock = threading.Lock()
        self._timer: threading.Timer | None = None
        self._queue = store.watch(None)
        self._pump = threading.Thread(target=self._run, name="persist", daemon=True)
        self._stop = threading.Event()
        self._pump.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._queue.get(timeout=0.2)
            except Exception:
                continue
            with self._lock:
                if self._timer is None:
                    self._timer = threading.Timer(self.debounce, self.flush)
                    self._timer.daemon = True
                    self._timer.start()

    def flush(self) -> None:
        with self._lock:
            self._timer = None
        with self._flush_lock:
            registry = _kind_registry()
            docs = []
            for kind in registry:
                for obj in self.store.list(kind):
                    docs.append({"kind": kind, "object": _encode(obj)})
            tmp = f"{self.path}.tmp"
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                json.dump({"version": 1, "objects": docs}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            log.debug("persisted %d objects to %s", len(docs), self.path)

    def close(self) -> None:
        self._stop.set()
        self._pump.join(5.0)
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        self.flush()
        self.store.unwatch(self._queue)


def load_into(store: ObjectStore, path: str) -> int:
    """Restore a snapshot into an (empty) store; returns objects loaded.

    ``meta.resource_version`` restarts from the store's own counter — the
    optimistic-concurrency tokens only need to be consistent within one
    process lifetime (same as informer caches resyncing from scratch).
    """
    if not os.path.exists(path):
        return 0
    registry = _kind_registry()
    with open(path) as f:
        data = json.load(f)
    n = 0
    for doc in data.get("objects", []):
        cls = registry.get(doc.get("kind"))
        if cls is None:
            log.warning("snapshot has unknown kind %r; skipped", doc.get("kind"))
            continue
        try:
            obj = _decode_dataclass(doc["object"], cls)
            store.create(obj)
            n += 1
        except Exception:
            log.exception("failed to restore a %s object", doc.get("kind"))
    return n
