"""Durable store state — the etcd the standalone bridge doesn't have.

Reference parity (SURVEY.md §5 "Checkpoint/resume"): the reference keeps
its durable state in the K8s API server — CR status, and the jobid label
written at submit time, which is the resume token letting any restarted
component re-associate pods with running Slurm jobs. The standalone
bridge's ObjectStore is in-process, so without persistence a bridge
restart would orphan every running job.

Durability model (the PR-7 rework — the old module rewrote the ENTIRE
store as one JSON dump on any change):

- **Write-ahead log**: every flush appends only what moved since the
  last flush, read straight off the store's per-kind ``changes_since``
  dirty-sets. Records are length-prefixed and CRC32-checksummed
  (``<u32 len><u32 crc><json payload>``), so replay detects a torn tail
  (crash mid-append) or a corrupt record and keeps everything before it.
  Since PR-10 a flush writes ONE framed *batch envelope* (``op:
  "batch"``) carrying all its per-object records, zlib-deflated past a
  size floor (the length word's high bit marks compression) — the 50k
  cold tick's ~135k-record blob frames once and shrinks several-fold
  before the device fsync. Replay expands envelopes inline
  (:func:`iter_wal_records`); pre-batching WALs replay unchanged.
- **Snapshot compaction**: once the WAL grows past a byte/record budget
  (or on :meth:`StorePersistence.compact`), the full store is dumped to
  the snapshot file (atomic tmp+rename) and the WAL truncated. Each
  persistence instance stamps an ``incarnation`` id into its records and
  snapshots, so a crash BETWEEN snapshot install and WAL truncate can
  never replay a previous incarnation's records over the new snapshot.
- **Recovery** (:func:`load_into`): load the snapshot, then replay the
  WAL in order — ``put`` records upsert, ``del`` records delete; records
  already folded into the snapshot (same incarnation, rv ≤ snapshot rv)
  are skipped. A restarted bridge finds its pods, reads their
  ``job_ids``, and the ordinary level-triggered sync re-converges
  against live Slurm state — the same resume-by-label mechanism, one
  directory instead of etcd.
- **Columnar-aware serialization**: ``Pod``/``BridgeJob`` rows are
  dumped straight from the column tables (:mod:`bridge.columns` schema)
  without materializing frozen views, so a flush never fights the PR-6
  ``steady_views == 0`` discipline; and a flush with an empty dirty-set
  writes NOTHING — zero file I/O, zero views (`make bench-smoke`
  asserts both).

Serialization is type-driven both ways: ``asdict``-shaped encoding with
datetime/enum tagging out, the config codec's dataclass decoder (tuples,
nested dataclasses, Optionals) back in.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import logging
import os
import threading
import uuid
from datetime import datetime

from slurm_bridge_tpu.bridge.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)

# the CRC-framed record/replay machinery is shared with the agent's
# job-state journal (PR-8): utils/wal.py owns framing, torn/corrupt
# tolerant parsing, group-commit fsync and the disk-latency seam.
# pack_record/read_wal stay importable from here (the public surface
# tests and docs reference).
from slurm_bridge_tpu.utils.wal import (  # noqa: F401 - re-exported
    RECORD_HDR as _HDR,
    WalWriter,
    durable_fsync,
    frame_body,
    pack_record,
    read_wal,
)

log = logging.getLogger("sbt.persist")

_DT_KEY = "__dt__"


_KIND_REGISTRY: dict[str, type] | None = None


def _kind_registry() -> dict[str, type]:
    # memoized: the pump folds every store event through a registry
    # membership probe, so this sits on the watch fan-out path
    global _KIND_REGISTRY
    if _KIND_REGISTRY is None:
        from slurm_bridge_tpu.bridge.objects import (
            BridgeJob,
            FetchJob,
            Pod,
            PolicyState,
            VirtualNode,
        )

        _KIND_REGISTRY = {
            cls.KIND: cls
            for cls in (BridgeJob, Pod, VirtualNode, FetchJob, PolicyState)
        }
    return _KIND_REGISTRY


def _encode(value):
    if isinstance(value, datetime):
        return {_DT_KEY: value.isoformat()}
    if isinstance(value, enum.Enum):
        return value.value
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {f.name: _encode(getattr(value, f.name)) for f in dataclasses.fields(value)}
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _encode(v) for k, v in value.items()}
    return value


def _decode(value, ftype):
    import types
    import typing

    origin = typing.get_origin(ftype)
    if isinstance(value, dict) and _DT_KEY in value:
        return datetime.fromisoformat(value[_DT_KEY])
    if isinstance(ftype, type) and issubclass(ftype, enum.Enum):
        return ftype(value)
    if dataclasses.is_dataclass(ftype):
        return _decode_dataclass(value, ftype)
    if origin in (list, tuple) and isinstance(value, list):
        args = typing.get_args(ftype)
        inner = args[0] if args else typing.Any
        seq = [_decode(v, inner) for v in value]
        return tuple(seq) if origin is tuple else seq
    if origin is dict and isinstance(value, dict):
        args = typing.get_args(ftype)
        vt = args[1] if len(args) == 2 else typing.Any
        return {k: _decode(v, vt) for k, v in value.items()}
    if origin in (typing.Union, types.UnionType):
        for arg in typing.get_args(ftype):
            if arg is type(None):
                if value is None:
                    return None
                continue
            try:
                return _decode(value, arg)
            except (TypeError, ValueError):
                continue
        return value
    return value


# -- compiled decoders -------------------------------------------------
#
# Recovery at the headline shape replays ~100k objects, each fanning out
# into nested dataclasses/enums/unions. The generic ``_decode`` pays the
# full type-dispatch cascade (get_origin/is_dataclass/issubclass) for
# EVERY value, and ``typing.get_type_hints`` re-evaluated annotations per
# object — together they dominated the whole snapshot reload. Type hints
# are immutable per class, so each hint compiles ONCE into a closure;
# ``_decode`` stays as the semantics-defining fallback (the closures must
# decode exactly like it — the round-trip tests hold the two together).

_DECODERS: dict[object, object] = {}


def _decoder_for(ftype):
    try:
        cached = _DECODERS.get(ftype)
    except TypeError:  # unhashable hint: fall back to the generic path
        return lambda v, _t=ftype: _decode(v, _t)
    if cached is None:
        cached = _build_decoder(ftype)
        _DECODERS[ftype] = cached
    return cached


def _build_decoder(ftype):
    import types
    import typing

    origin = typing.get_origin(ftype)
    if ftype is datetime:
        def dec_dt(v):
            if isinstance(v, dict) and _DT_KEY in v:
                return datetime.fromisoformat(v[_DT_KEY])
            return v

        return dec_dt
    if isinstance(ftype, type) and issubclass(ftype, enum.Enum):
        return ftype
    if dataclasses.is_dataclass(ftype):
        return lambda v, _cls=ftype: _decode_dataclass(v, _cls)
    if origin in (list, tuple):
        args = typing.get_args(ftype)
        inner = _decoder_for(args[0] if args else typing.Any)
        as_tuple = origin is tuple

        def dec_seq(v, _inner=inner, _tuple=as_tuple):
            if not isinstance(v, list):
                return v
            seq = [_inner(x) for x in v]
            return tuple(seq) if _tuple else seq

        return dec_seq
    if origin is dict:
        args = typing.get_args(ftype)
        vt = _decoder_for(args[1] if len(args) == 2 else typing.Any)

        def dec_map(v, _vt=vt):
            if not isinstance(v, dict):
                return v
            return {k: _vt(x) for k, x in v.items()}

        return dec_map
    if origin in (typing.Union, types.UnionType):
        arms = [
            (arg, _decoder_for(arg))
            for arg in typing.get_args(ftype)
            if arg is not type(None)
        ]
        nullable = type(None) in typing.get_args(ftype)

        def dec_union(v, _arms=tuple(arms), _nullable=nullable):
            if v is None and _nullable:
                return None
            for _, dec in _arms:
                try:
                    return dec(v)
                except (TypeError, ValueError, KeyError):
                    continue
            return v

        return dec_union

    # plain/unknown type (str/int/float/Any/...): values pass through,
    # except the tagged-datetime sentinel the generic path honors for any
    # value shape
    def dec_plain(v):
        if isinstance(v, dict) and _DT_KEY in v:
            return datetime.fromisoformat(v[_DT_KEY])
        return v

    return dec_plain


#: per-class (field name, compiled decoder) pairs, built once
_FIELD_DECODERS: dict[type, tuple[tuple[str, object], ...]] = {}


def _decode_dataclass(raw: dict, cls):
    plan = _FIELD_DECODERS.get(cls)
    if plan is None:
        import typing

        hints = typing.get_type_hints(cls)
        plan = tuple(
            (f.name, _decoder_for(hints.get(f.name, typing.Any)))
            for f in dataclasses.fields(cls)
        )
        _FIELD_DECODERS[cls] = plan
    kwargs = {}
    for name, dec in plan:
        if name in raw:
            kwargs[name] = dec(raw[name])
    return cls(**kwargs)


# -------------------------------------------------- columnar row → doc

def _dt_doc(dt: datetime | None):
    return None if dt is None else {_DT_KEY: dt.isoformat()}


def _meta_doc(c, row: int) -> dict:
    return {
        "name": c.name[row],
        "uid": c.uid[row],
        "labels": _encode(c.labels[row]),
        "annotations": _encode(c.ann[row]),
        "owner": c.owner[row],
        "resource_version": int(c.rv[row]),
        "deleted": bool(c.deleted[row]),
    }


def _pod_row_doc(table, row: int) -> dict:
    """A Pod row as the snapshot/WAL document — field-for-field what
    ``_encode(table.view(row))`` would produce, built straight from
    columns so the flush materializes ZERO frozen views."""
    from slurm_bridge_tpu.bridge.columns import PHASE_STRS, heap_dt

    c = table.cols
    a = table.adapter
    h = a.infos
    istart, ilen = int(c.istart[row]), int(c.ilen[row])
    infos = []
    for i in range(istart, istart + ilen):
        infos.append({
            "id": int(h.id[i]),
            "user_id": h.user_id[i],
            "name": h.name[i],
            "exit_code": h.exit_code[i],
            "state": int(h.state[i]),
            "submit_time": _dt_doc(heap_dt(h, "submit", i)),
            "start_time": _dt_doc(heap_dt(h, "start", i)),
            "run_time_s": int(h.run_time[i]),
            "time_limit_s": int(h.limit[i]),
            "working_dir": h.workdir[i],
            "std_out": h.stdout[i],
            "std_err": h.stderr[i],
            "partition": h.partition[i],
            "node_list": h.nodelist[i],
            "batch_host": h.batch_host[i],
            "num_nodes": int(h.num_nodes[i]),
            "array_id": h.array_id[i],
            "reason": h.reason[i],
        })
    ch = a.containers
    cstart, clen = int(c.cstart[row]), int(c.clen[row])
    conts = [
        {
            "name": ch.cname[i],
            "state": ch.cstate[i],
            "exit_code": int(ch.cexit[i]),
            "reason": ch.creason[i],
        }
        for i in range(cstart, cstart + clen)
    ]
    return {
        "meta": _meta_doc(c, row),
        "spec": {
            "role": c.role[row],
            "partition": c.partition[row],
            "demand": _encode(c.demand[row]),
            "node_name": c.node[row],
            "placement_hint": _encode(c.hint[row]),
        },
        "status": {
            "phase": PHASE_STRS[c.phase[row]],
            "reason": c.reason[row],
            "job_ids": _encode(c.job_ids[row]),
            "job_infos": infos,
            "containers": conts,
        },
    }


def _job_row_doc(table, row: int) -> dict:
    """A BridgeJob row as the snapshot/WAL document (no views built)."""
    from slurm_bridge_tpu.bridge.columns import STATE_STRS

    c = table.cols
    h = table.adapter.subjobs
    start, n = int(c.sstart[row]), int(c.slen[row])
    keys = c.skeys[row] or ()
    subjobs = {}
    for k in range(n):
        i = start + k
        subjobs[keys[k]] = {
            "id": int(h.id[i]),
            "array_id": h.array_id[i],
            "state": int(h.state[i]),
            "exit_code": h.exit_code[i],
            "submit_time": h.submit[i],
            "start_time": h.start[i],
            "run_time_s": int(h.run_time[i]),
            "std_out": h.stdout[i],
            "std_err": h.stderr[i],
            "reason": h.reason[i],
        }
    return {
        "meta": _meta_doc(c, row),
        "spec": _encode(c.spec[row]),
        "status": {
            "state": STATE_STRS[c.state[row]],
            "reason": c.reason[row],
            "subjobs": subjobs,
            "fetch_result": c.fetch[row],
            "cluster_endpoint": c.endpoint[row],
        },
    }


def _row_doc_builder(kind: str):
    from slurm_bridge_tpu.bridge.objects import BridgeJob, Pod

    return {Pod.KIND: _pod_row_doc, BridgeJob.KIND: _job_row_doc}.get(kind)


class StorePersistence:
    """WAL-backed write-behind durability for an ObjectStore.

    Every store event schedules a flush ``debounce`` seconds out
    (coalescing bursts); a flush appends only the objects whose
    ``changes_since`` resource_version moved past the last flush — an
    idle store flushes NOTHING (no file write, no frozen views).
    ``close()`` flushes and compacts synchronously, leaving the snapshot
    file complete and the WAL empty.

    Embedders that need deterministic, single-threaded behavior (the sim
    harness) pass ``auto_flush=False`` and drive :meth:`flush` /
    :meth:`compact` themselves — no pump thread, no timers.
    """

    def __init__(
        self,
        store: ObjectStore,
        path: str,
        *,
        debounce: float = 0.2,
        auto_flush: bool = True,
        compact_bytes: int = 4 << 20,
        compact_records: int = 50_000,
        fsync: bool = True,
        fsync_delay_s: float | None = None,
        batch: bool = True,
        compress: bool = True,
        compress_floor: int = 4096,
    ):
        self.store = store
        self.path = path
        self.wal_path = path + ".wal"
        self.debounce = debounce
        self.compact_bytes = compact_bytes
        self.compact_records = compact_records
        self.fsync = fsync
        #: simulated device latency per fsync (None = the process-wide
        #: utils.wal seam) — the fsync-realism bench knob
        self.fsync_delay_s = fsync_delay_s
        #: record batching (PR-10, the ROADMAP durability leftover): one
        #: framed BATCH record per flush instead of one frame per object
        #: — the 50k cold tick's ~135k records become a handful of batch
        #: envelopes, dropping per-record header+parse overhead, and
        #: ``compress`` deflates any batch over ``compress_floor`` bytes
        #: (zlib level 1) so the one-blob flush a slow disk actually
        #: fsyncs is several times smaller. Replay-compatible both ways:
        #: a batch expands inline in :func:`load_into`, and un-batched
        #: records (pre-PR-10 WALs, ``batch=False`` writers) replay
        #: exactly as before.
        self.batch = batch
        self.compress = compress
        self.compress_floor = compress_floor
        self._wal = WalWriter(
            self.wal_path, fsync=fsync, fsync_delay_s=fsync_delay_s
        )
        #: stamped into every record + snapshot; replay refuses to apply
        #: another incarnation's WAL records over this one's snapshot
        self.incarnation = uuid.uuid4().hex
        #: flush watermark: the store rv everything ≤ is already durable
        self._last_rv = 0
        #: observability: record/byte/snapshot counters for gates + tests
        #: (``wal_records``/``wal_bytes`` reset at compaction; the
        #: ``*_total`` forms are cumulative for the instance's lifetime)
        self.wal_records = 0
        self.wal_records_total = 0
        self.snapshots_written = 0
        self.wal_bytes = self._wal.size
        #: batch envelopes appended + pre-compression byte volume — the
        #: on-disk wal_bytes vs wal_bytes_raw ratio is the compression win
        self.wal_batches = 0
        self.wal_bytes_raw = 0
        self._lock = threading.Lock()
        # Serializes whole flush/compact cycles: a timer-fired flush can
        # race close()'s synchronous flush, and two writers interleaving
        # on the same WAL tail (or the snapshot .tmp) would corrupt it.
        self._flush_lock = threading.Lock()
        self._timer: threading.Timer | None = None
        self._stop = threading.Event()
        #: delete tracking rides a dedicated watch, NOT the store's
        #: tombstone map: tombstones are capacity-bounded
        #: (ObjectStore.TOMBSTONE_LIMIT) and a delete burst bigger than
        #: the limit between two flushes would silently lose "del"
        #: records — replay would then resurrect the lost objects from
        #: their earlier "put" records. Watch events are exact and
        #: unbounded; names later recreated are skipped at emit time
        #: (their fresh "put" covers them).
        self._del_watch = store.watch(None)
        self._pending_dels: set[tuple[str, str]] = set()
        self._pump = None
        if auto_flush:
            self._pump = threading.Thread(target=self._run, name="persist", daemon=True)
            self._pump.start()

    def _run(self) -> None:
        # the delete watch doubles as the flush trigger — it already sees
        # every store event, and a second watch(None) would put one more
        # queue on the per-commit fan-out under the store lock
        while not self._stop.is_set():
            try:
                ev = self._del_watch.get(timeout=0.2)
            except Exception:
                continue
            self._fold_event(ev)
            with self._lock:
                if self._timer is None:
                    self._timer = threading.Timer(self.debounce, self.flush)
                    self._timer.daemon = True
                    self._timer.start()

    # ---- serialization ----

    def _fold_event(self, ev) -> None:
        """Fold one watch event into the pending-delete set (persisted
        kinds only). Called from both the pump thread and flush/compact
        drains, hence the lock."""
        if ev.type == "DELETED" and ev.kind in _kind_registry():
            with self._lock:
                self._pending_dels.add((ev.kind, ev.name))

    def _drain_deletes(self) -> None:
        """Fold everything still queued on the watch into the pending
        set (the pump consumes the same queue concurrently in auto-flush
        mode; either consumer folding an event is equivalent)."""
        while True:
            try:
                ev = self._del_watch.get_nowait()
            except Exception:
                break
            self._fold_event(ev)

    def _kind_docs(self, kind: str, names) -> list[tuple[str, dict]]:
        """``(name, doc)`` for the surviving names of one kind. Columnar
        kinds dump straight from rows (zero frozen views) under ONE lock
        acquisition for the whole batch — a 50k-name flush must not pay
        50k lock round-trips against live control loops; object kinds
        are low-churn (VirtualNode/FetchJob) and ride plain ``try_get``."""
        table = self.store.table(kind)
        if table is not None:
            builder = _row_doc_builder(kind)
            out = []
            with self.store.locked():
                row_of = table.row_of
                for name in names:
                    row = row_of.get(name)
                    if row is None:
                        continue  # deleted mid-scan; its del event is coming
                    out.append((
                        name,
                        builder(table, row)
                        if builder is not None
                        else _encode(table.view(row)),
                    ))
            return out
        docs = []
        for name in names:
            obj = self.store.try_get(kind, name)
            if obj is not None:
                docs.append((name, _encode(obj)))
        return docs

    # ---- the write paths ----

    def flush(self) -> int:
        """Append everything that changed since the last flush to the
        WAL; returns the number of records written (0 = nothing dirty —
        no file touched, no views built). Triggers compaction when the
        WAL outgrows its budget."""
        with self._lock:
            self._timer = None
        with self._flush_lock:
            return self._flush_locked()

    def _flush_locked(self) -> int:
        # deletes FIRST, watermark SECOND: every delete captured below
        # committed before ``current_rv()`` runs, so stamping its "del"
        # record with start_rv can never understate the delete's real rv
        # — an understated stamp would fall under the snapshot-rv skip
        # on replay and resurrect the object. Puts are safe the other
        # way around: anything committing while we scan lands above the
        # watermark and is re-emitted next flush (duplicates are
        # idempotent on replay; a gap would be data loss).
        self._drain_deletes()
        with self._lock:
            pending = sorted(self._pending_dels)
        start_rv = self.store.current_rv()
        items: list[dict] = []
        for kind in _kind_registry():
            # partitioned dirty-set (ISSUE 19): when frame commits have
            # recorded per-writer-partition dirty ranges for this kind,
            # read them directly (identical output, and the flush walks
            # each partition's own records). The O(1) no-change probe is
            # shared by both arms, so an idle flush stays zero-I/O.
            cs = (
                self.store.changes_since_partitioned
                if self.store.has_partitioned_dirty(kind)
                else self.store.changes_since
            )
            rv, changed, _ = cs(kind, self._last_rv)
            for name, doc in self._kind_docs(kind, changed):
                items.append({
                    "op": "put",
                    "kind": kind,
                    "name": name,
                    "rv": int(doc.get("meta", {}).get("resource_version", 0)),
                    "object": doc,
                })
        for kind, name in pending:
            if self.store.contains(kind, name):
                continue  # recreated since: its fresh "put" covers it
            # stamped with the flush watermark so the same-incarnation
            # snapshot-rv skip applies to deletes exactly like puts (a
            # crash between snapshot install and WAL truncate must not
            # replay this delete over a newer snapshot's recreation)
            items.append({
                "op": "del",
                "kind": kind,
                "name": name,
                "rv": start_rv,
            })
        n = len(items)
        if not items:
            with self._lock:
                self._pending_dels.difference_update(pending)
            self._last_rv = max(self._last_rv, start_rv)
            return 0
        if self.batch:
            # ONE framed envelope per flush; the incarnation stamp lives
            # on the envelope and covers every inner record on replay
            body = json.dumps(
                {
                    "op": "batch",
                    "inc": self.incarnation,
                    "count": n,
                    "records": items,
                },
                separators=(",", ":"),
            ).encode()
            self.wal_bytes_raw += len(body)
            blob = frame_body(
                body,
                compress=self.compress and len(body) >= self.compress_floor,
            )
            self.wal_batches += 1
        else:
            chunks = [
                pack_record({**it, "inc": self.incarnation}) for it in items
            ]
            blob = b"".join(chunks)
            self.wal_bytes_raw += len(blob)
        # one ordered append + one group-commit barrier for the whole
        # flush — concurrent flushers (debounce timer vs close()) share
        # a single device fsync through the WalWriter
        self._wal.append_durable(blob)
        # only the captured deletes are retired — ones folded while we
        # wrote ride to the next flush (a failed write retires nothing)
        with self._lock:
            self._pending_dels.difference_update(pending)
        self._last_rv = max(self._last_rv, start_rv)
        self.wal_records += n
        self.wal_records_total += n
        self.wal_bytes += len(blob)
        log.debug("WAL: appended %d records (%d bytes) to %s", n, len(blob), self.wal_path)
        if self.wal_bytes > self.compact_bytes or self.wal_records > self.compact_records:
            self._compact_locked()
        return n

    def compact(self) -> None:
        """Fold the WAL into a fresh full snapshot (atomic tmp+rename)
        and truncate the WAL. Also the rebase step after recovery: a
        restarted bridge compacts first so its new-incarnation records
        never mix with the previous process's tail."""
        with self._flush_lock:
            self._compact_locked()

    def _compact_locked(self) -> None:
        start_rv = self.store.current_rv()
        # deletions up to here are reflected in the snapshot itself —
        # the pending "del" set rides the truncated WAL into oblivion
        self._drain_deletes()
        with self._lock:
            self._pending_dels.clear()
        docs = []
        for kind in _kind_registry():
            table = self.store.table(kind)
            if table is not None:
                builder = _row_doc_builder(kind)
                with self.store.locked():
                    for name in sorted(table.row_of):
                        row = table.row_of[name]
                        doc = (
                            builder(table, row)
                            if builder is not None
                            else _encode(table.view(row))
                        )
                        docs.append({"kind": kind, "object": doc})
            else:
                for obj in self.store.list(kind):
                    docs.append({"kind": kind, "object": _encode(obj)})
        tmp = f"{self.path}.tmp"
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(
                {
                    "version": 2,
                    "rv": start_rv,
                    "incarnation": self.incarnation,
                    "objects": docs,
                },
                f,
            )
            f.flush()
            durable_fsync(f.fileno(), delay_s=self.fsync_delay_s)
        os.replace(tmp, self.path)
        # snapshot is durable; now the WAL prefix it folded in can go.
        # (A crash between the two replays an incarnation-matched WAL
        # whose rv ≤ snapshot rv records are skipped — no stale rewind.)
        self._wal.truncate()
        self._last_rv = max(self._last_rv, start_rv)
        self.wal_records = 0
        self.wal_bytes = 0
        self.snapshots_written += 1
        log.debug("compacted %d objects into %s", len(docs), self.path)

    def abandon(self) -> None:
        """Release resources WITHOUT flushing — the simulated-crash path
        (the whole point is that nothing gets a last-gasp write). Closes
        the WAL file handle and detaches the store watch; the instance
        must not be used afterwards."""
        if self._pump is not None:
            self._stop.set()
            self._pump.join(5.0)
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        self._wal.close()
        self.store.unwatch(self._del_watch)

    def close(self) -> None:
        if self._pump is not None:
            self._stop.set()
            self._pump.join(5.0)
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        with self._flush_lock:
            self._flush_locked()
            self._compact_locked()
            self._wal.close()
        self.store.unwatch(self._del_watch)


# ------------------------------------------------------------ recovery

def iter_wal_records(records):
    """Flatten batch envelopes (PR-10) into the plain per-object record
    stream replay has always consumed. Inner records inherit the
    envelope's incarnation stamp; non-batch records (pre-batching WALs,
    ``batch=False`` writers) pass through untouched — both formats
    replay through one loop."""
    for rec in records:
        if rec.get("op") == "batch":
            inc = rec.get("inc")
            for inner in rec.get("records", ()):
                if inc is not None and "inc" not in inner:
                    inner = {**inner, "inc": inc}
                yield inner
        else:
            yield rec


def _apply_put(store: ObjectStore, cls, doc: dict) -> bool:
    obj = _decode_dataclass(doc, cls)
    try:
        current = store.get(cls.KIND, obj.meta.name)
    except NotFound:
        try:
            store.create(obj, site="persist.replay")
            return True
        except AlreadyExists:
            return False
    obj.meta.resource_version = current.meta.resource_version
    try:
        store.update(obj, site="persist.replay")
        return True
    except (Conflict, NotFound):
        return False


def load_into(store: ObjectStore, path: str) -> int:
    """Restore snapshot + WAL into an (empty) store; returns the number
    of live objects restored.

    ``meta.resource_version`` restarts from the store's own counter — the
    optimistic-concurrency tokens only need to be consistent within one
    process lifetime (same as informer caches resyncing from scratch).
    WAL replay is level-triggered: ``put`` upserts, ``del`` deletes (the
    cascade mirrors what the live store already did); a torn tail or a
    checksum-corrupt record stops replay there with a warning — state up
    to the defect survives.
    """
    registry = _kind_registry()
    snap_rv = 0
    snap_inc = None
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
        snap_rv = int(data.get("rv", 0))
        snap_inc = data.get("incarnation")
        for doc in data.get("objects", []):
            cls = registry.get(doc.get("kind"))
            if cls is None:
                log.warning("snapshot has unknown kind %r; skipped", doc.get("kind"))
                continue
            try:
                store.create(_decode_dataclass(doc["object"], cls), site="persist.replay")
            except Exception:
                log.exception("failed to restore a %s object", doc.get("kind"))

    records, _, defect = read_wal(path + ".wal")
    if defect is not None:
        log.warning(
            "WAL %s.wal has a %s tail; replaying the %d clean records before it",
            path, defect, len(records),
        )
    for rec in iter_wal_records(records):
        if snap_inc is not None and rec.get("inc") not in (None, snap_inc):
            # another incarnation's leftover tail (crash between snapshot
            # install and WAL truncate): already folded into the snapshot
            continue
        if rec.get("inc") == snap_inc and int(rec.get("rv", 0)) <= snap_rv:
            # already folded into the snapshot — puts AND deletes (a
            # delete replayed over a later same-name recreation in the
            # snapshot would cascade-erase live state)
            continue
        cls = registry.get(rec.get("kind"))
        if cls is None:
            log.warning("WAL record has unknown kind %r; skipped", rec.get("kind"))
            continue
        try:
            if rec.get("op") == "del":
                try:
                    store.delete(cls.KIND, rec["name"])
                except NotFound:
                    pass
            else:
                _apply_put(store, cls, rec["object"])
        except Exception:
            log.exception("failed to replay a %s WAL record", rec.get("kind"))
    return sum(store.count(kind) for kind in registry)
