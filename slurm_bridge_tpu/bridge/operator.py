"""BridgeOperator — the BridgeJob reconciler.

Reference parity: pkg/slurm-bridge-operator/slurmbridgejob_controller.go.
The reconcile branches exactly as the reference's Reconcile (:104-159):
validate → if finished, converge the result-fetch job; else ensure the
sizecar pod, sync CR status from it, and maintain per-sub-job worker pods.

Sizecar sizing (pod.go:18-68): parse ``#SBATCH`` headers out of the script
(extractBatchResourcesFromScript, parse.go:30-124), let explicit spec
fields override them, default 1 node / 1 cpu / 1024 MB-per-cpu
(pod.go:91-95); cpu multiplies by ntasks × array length
(genResourceListForPod :143-162).
"""

from __future__ import annotations

import logging
from functools import lru_cache
import os
import queue
import time

import numpy as np

from slurm_bridge_tpu.bridge.columns import (
    CR_STATE_OF_PHASE,
    JOBSTATUS_BY_CODE,
    STATE_CODE,
    STATE_STRS,
    heap_iso,
    heap_iso_bulk,
)
from slurm_bridge_tpu.bridge.controller import Controller, Result
from slurm_bridge_tpu.bridge.freeze import (
    FrozenDict,
    FrozenList,
    fast_new,
    fast_replace,
    frozen_new,
    frozen_replace,
)
from slurm_bridge_tpu.bridge.objects import (
    BridgeJob,
    ContainerStatus,
    FetchFile,
    FetchJob,
    FetchState,
    JobState,
    Meta,
    Pod,
    PodPhase,
    PodRole,
    PodSpec,
    PodStatus,
    SubjobStatus,
    ValidationError,
    new_uid,
    validate_bridge_job,
    validate_job_fields,
)
from slurm_bridge_tpu.bridge.statusmap import (
    container_status_for,
    job_state_for_pod_phase,
)
from slurm_bridge_tpu.bridge.store import AlreadyExists, NotFound, ObjectStore
from slurm_bridge_tpu.core.arrays import array_len
from slurm_bridge_tpu.core.sbatch import extract_batch_resources
from slurm_bridge_tpu.core.types import JobDemand
from slurm_bridge_tpu.obs.events import EventRecorder, Reason
from slurm_bridge_tpu.obs.metrics import REGISTRY
from slurm_bridge_tpu.obs.tracing import TRACER, current_span
from slurm_bridge_tpu.parallel import colpool, writeops
from slurm_bridge_tpu.policy.classes import (
    CLASS_LABEL as _CLASS_LABEL,
    TENANT_LABEL as _TENANT_LABEL,
)

log = logging.getLogger("sbt.operator")

RESULT_REQUEUE_S = 30.0  # result-poll requeue (slurmbridgejob_controller.go:141)

_reconciles = REGISTRY.counter("sbt_operator_reconciles_total", "operator reconciles")
_sweeps = REGISTRY.counter(
    "sbt_operator_sweeps_total", "dirty-set batch sweeps (PR-4 cold-start path)"
)
_reconcile_seconds = REGISTRY.histogram(
    "sbt_operator_reconcile_seconds",
    "one single-key reconcile, or one whole dirty-set sweep pass",
)
_sweep_pool_rows = REGISTRY.counter(
    "sbt_operator_sweep_pool_rows_total",
    "sizecar creates whose demand/label resolution ran in colpool "
    "workers (ISSUE 18 write-side offload)",
)

#: sizecar creates per _OP_BUILD_ROWS frame: big enough that the frame
#: header/pack overhead amortizes, small enough that a 100k-create storm
#: still fans out across every worker
_BUILD_CHUNK = 2048

#: CR state transitions worth an event (UpdateSBJStatus's recorder calls)
_STATE_REASONS = {
    JobState.RUNNING: Reason.JOB_RUNNING,
    JobState.SUCCEEDED: Reason.JOB_SUCCEEDED,
    JobState.FAILED: Reason.JOB_FAILED,
}

#: shared empty job_infos for worker pods — immutable, so aliasing across
#: 45k creates per sweep is safe and skips a FrozenList build each
_EMPTY_FROZEN_LIST = FrozenList()
#: shared empty annotation map for born-frozen creates (immutable, so
#: sharing across pods is safe; writers always build replacement dicts)
_EMPTY_FROZEN_DICT = FrozenDict()

#: CR-state int8 codes the columnar sweep uses
_ST_RUNNING = STATE_CODE[JobState.RUNNING]
_ST_SUCCEEDED = STATE_CODE[JobState.SUCCEEDED]
_ST_FAILED = STATE_CODE[JobState.FAILED]
_POD_PHASE_PENDING = 0  # columns.PHASE_CODE[PodPhase.PENDING]
#: JobStatus display names by code (container reasons)
_STATUS_NAME = tuple(s.name for s in JOBSTATUS_BY_CODE)
_STATUS_NAME_ARR = np.empty(len(_STATUS_NAME), dtype=object)
_STATUS_NAME_ARR[:] = _STATUS_NAME

#: dirty sets at least this large AND covering ≥¼ of the stored CRs read
#: via two bulk list() dict builds instead of per-key try_get (3 locked
#: lookups × 45k owners is 135k lock round-trips on a cold-start sweep).
#: Module-level so the equivalence test can drop it and fuzz the bulk
#: branch too.
_BULK_SWEEP_THRESHOLD = 512


def sizecar_name(job_name: str) -> str:
    return f"{job_name}-sizecar"


def worker_name(job_name: str) -> str:
    return f"{job_name}-worker"


def fetch_job_name(job_name: str) -> str:
    return f"{job_name}-fetch"


@lru_cache(maxsize=512)
def _parsed_header(script: str):
    """Memoized #SBATCH header parse: a 500k-arrival storm submits the
    same handful of script bodies over and over, and re-parsing the
    headers per job was ~0.4 s per 100k sweeps (ISSUE 14)."""
    return extract_batch_resources(script).demand


def demand_for_job(job: BridgeJob) -> JobDemand:
    """Script #SBATCH headers, overridden by explicit spec fields, with
    the reference defaults (pod.go:18-95) — the object-path wrapper over
    :func:`demand_for_spec`."""
    return demand_for_spec(job.meta.name, job.spec)


def demand_for_spec(name: str, spec) -> JobDemand:
    """The demand build from (job name, spec) directly — what the
    columnar sweep calls with values gathered from the job table, so a
    100k-create storm never materializes the BridgeJob views the old
    per-create ``jt.view()`` path paid 2.5M ``_frozen_shell`` calls for
    (ISSUE 16). Born FROZEN via ``frozen_new`` — every field scalar, so
    commit-time freeze stops at one probe instead of a 19-field walk per
    pod (ISSUE 14; the storm creates one demand per arrival)."""
    hdr = _parsed_header(spec.sbatch_script)
    return frozen_new(
        JobDemand,
        partition=spec.partition or hdr.partition,
        script=spec.sbatch_script,
        job_name=name,
        run_as_user=spec.run_as_user,
        run_as_group=spec.run_as_group,
        array=spec.array or hdr.array,
        cpus_per_task=spec.cpus_per_task or hdr.cpus_per_task or 1,
        ntasks=spec.ntasks or hdr.ntasks or 1,
        ntasks_per_node=spec.ntasks_per_node or hdr.ntasks_per_node,
        nodes=spec.nodes or hdr.nodes or 1,
        working_dir=spec.working_dir or hdr.working_dir,
        mem_per_cpu_mb=spec.mem_per_cpu_mb or hdr.mem_per_cpu_mb or 1024,
        gres=spec.gres or hdr.gres,
        licenses=spec.licenses,
        time_limit_s=hdr.time_limit_s,
        priority=spec.priority,
        nodelist=(),
    )


class BridgeOperator:
    def __init__(
        self,
        store: ObjectStore,
        *,
        agent_endpoint: str = "",
        events: EventRecorder | None = None,
        workers: int = 1,
    ):
        self.store = store
        self.agent_endpoint = agent_endpoint
        self.events = events or EventRecorder()
        self.controller = Controller(
            name="bridge-operator", reconcile=self.reconcile, workers=workers
        )
        #: sweep-side validation cache: name -> the exact spec object that
        #: passed validation. Validation is a pure function of (name,
        #: spec), specs are immutable snapshots (any respec is a NEW
        #: object), and holding the reference pins the address so an `is`
        #: check can never alias a recycled id. The single-key oracle
        #: still validates from scratch every time.
        self._validated_specs: dict[str, object] = {}

    # ---- wiring ----

    def start(self) -> None:
        self.controller.start()
        self._watch_q = self.store.watch((BridgeJob.KIND, Pod.KIND, FetchJob.KIND))
        import threading

        threading.Thread(target=self._pump_events, daemon=True).start()

    def _pump_events(self) -> None:
        """Coalesce watch events into a dirty owner set and sweep it in
        batch (PR-4): a cold-start storm of 100k owned-object events
        collapses into a handful of sweep passes instead of 100k queued
        single reconciles. Keys the sweep cannot settle (validation
        failures, finished jobs, commit conflicts) go to the controller
        queue, whose single-key :meth:`reconcile` remains the correctness
        oracle — as does the whole dirty set if a sweep pass dies."""
        while True:
            ev = self._watch_q.get()
            if ev is None:
                return
            dirty: set[str] = set()
            self._collect_owner(ev, dirty)
            # drain whatever the storm has already queued — one sweep
            # per burst, not one reconcile per event
            while True:
                try:
                    ev = self._watch_q.get_nowait()
                except queue.Empty:
                    break
                if ev is None:
                    return
                self._collect_owner(ev, dirty)
            if not dirty:
                continue
            try:
                for key in self.sweep(dirty):
                    self.controller.enqueue(key)
            except Exception:
                log.exception(
                    "sweep of %d keys failed; requeueing singly", len(dirty)
                )
                for key in sorted(dirty):
                    self.controller.enqueue(key)

    def _collect_owner(self, ev, dirty: set[str]) -> None:
        """BridgeJobs reconcile as themselves; owned objects via their
        owner ref (SetupWithManager's Owns(&Pod{}),
        slurmbridgejob_controller.go:204). The conventional
        ``-sizecar``/``-worker``/``-fetch`` name suffix resolves the owner
        WITHOUT a store read — only unrecognized names pay the ``try_get``
        (a cold-start tick pumps 100k+ events through here)."""
        if ev.kind == BridgeJob.KIND:
            dirty.add(ev.name)
            return
        owner = self._owner_from_name(ev.name)
        if not owner:
            obj = self.store.try_get(ev.kind, ev.name)
            owner = obj.meta.owner if obj is not None else ""
        if owner:
            dirty.add(owner)

    def _owner_from_name(self, obj_name: str) -> str:
        for suffix in ("-sizecar", "-worker", "-fetch"):
            if obj_name.endswith(suffix):
                return obj_name[: -len(suffix)]
        return ""

    def stop(self) -> None:
        if hasattr(self, "_watch_q"):
            self.store.unwatch(self._watch_q)
            self._watch_q.put(None)  # unblock the pump thread
        self.controller.stop()

    def enqueue(self, job_name: str) -> None:
        self.controller.enqueue(job_name)

    # ---- the reconcile ----

    def reconcile(self, job_name: str) -> Result | None:
        t0 = time.perf_counter()
        # join an active SAMPLED trace only: a controller-thread reconcile
        # with no ambient span (production steady state) — or one inside a
        # trace the sampler discarded — pays one contextvar read, not a
        # span build per reconcile
        ambient = current_span()
        if ambient is not None and ambient.sampled:
            with TRACER.span("operator.reconcile", job=job_name):
                try:
                    return self._reconcile(job_name)
                finally:
                    _reconcile_seconds.observe(time.perf_counter() - t0)
        try:
            return self._reconcile(job_name)
        finally:
            _reconcile_seconds.observe(time.perf_counter() - t0)

    def _reconcile(self, job_name: str) -> Result | None:
        _reconciles.inc()
        job = self.store.try_get(BridgeJob.KIND, job_name)
        if job is None or job.meta.deleted:
            # drop the sweep's validation-cache pin here too — a deletion
            # settled by the single-key path must not leak the spec object
            self._validated_specs.pop(job_name, None)
            return None
        try:
            validate_bridge_job(job)
        except ValidationError as e:
            self._set_state(job_name, JobState.FAILED, reason=str(e))
            self.events.event(job, Reason.JOB_FAILED, str(e), warning=True)
            return None

        if job.finished:
            return self._reconcile_result(job)
        self._reconcile_sizecar(job)
        self._sync_status(job_name)
        self._reconcile_worker(job_name)
        return None

    # ---- the dirty-set batch sweep (PR-4 cold-start path) ----

    def sweep(self, names) -> list[str]:
        """Batch reconcile of a dirty owner set — the cold-start path.

        Semantically N single reconciles (the fuzzed equivalence test in
        tests/test_operator_sweep.py holds it to exactly that), but with
        batched store traffic: reads run against current snapshots, then
        ALL sizecar/worker creates land in one :meth:`~ObjectStore.
        create_batch` and ALL CR status replacements plus worker-pod
        writes land in one :meth:`~ObjectStore.update_batch` — two lock
        acquisitions per sweep where the single-key path paid ~5 per
        owner, 45k owners deep on a cold-start tick.

        Returns the keys the sweep deliberately does NOT settle —
        validation failures, finished jobs (the result-fetch path owns
        requeue timing), vanished sizecars, and commit conflicts. Callers
        route those to :meth:`reconcile`, the single-key correctness
        oracle and the fallback for everything unusual.
        """
        with TRACER.span("operator.sweep") as span:
            jt = self.store.table(BridgeJob.KIND)
            pt = self.store.table(Pod.KIND)
            if jt is not None and pt is not None:
                return self._sweep_cols(span, names, jt, pt)
            return self._sweep(span, names)

    def _sweep(self, span, names) -> list[str]:
        t0 = time.perf_counter()
        _sweeps.inc()
        slow: list[str] = []
        #: (pod to create, owning job when the create deserves an event)
        creates: list[tuple[Pod, BridgeJob | None]] = []
        cr_updates: list[tuple[BridgeJob, BridgeJob]] = []  # (before, after)
        worker_updates: list[Pod] = []
        ordered = sorted(set(names))
        if (
            len(ordered) >= _BULK_SWEEP_THRESHOLD
            and len(ordered) * 4 >= self.store.count(BridgeJob.KIND)
        ):
            # bulk reads: a cold-start sweep touches most of the store,
            # and 3 snapshot lookups × 45k owners is 135k lock round-trips
            # — two list() calls and dict probes replace them all. Gated
            # on the dirty set covering ≥¼ of the CRs, so a mid-size burst
            # against a huge steady-state store does NOT materialize the
            # whole store to answer a few hundred lookups.
            get_job = {
                o.meta.name: o for o in self.store.list(BridgeJob.KIND)
            }.get
            get_pod = {o.meta.name: o for o in self.store.list(Pod.KIND)}.get
        else:
            get_job = lambda n: self.store.try_get(BridgeJob.KIND, n)  # noqa: E731
            get_pod = lambda n: self.store.try_get(Pod.KIND, n)  # noqa: E731
        validated = self._validated_specs
        for name in ordered:
            job = get_job(name)
            if job is None or job.meta.deleted:
                validated.pop(name, None)
                continue
            if validated.get(name) is not job.spec:
                try:
                    validate_bridge_job(job)
                except ValidationError:
                    slow.append(name)
                    continue
                validated[name] = job.spec
            if job.finished:
                slow.append(name)
                continue
            sizecar = get_pod(sizecar_name(name))
            if sizecar is None:
                if job.status.subjobs:
                    # pod vanished but sub-jobs exist ⇒ Failed — the
                    # oracle owns the state write + warning event
                    slow.append(name)
                    continue
                sizecar = self._build_sizecar(job)
                creates.append((sizecar, job))
            after = self._cr_replacement(job, sizecar)
            if after is not None:
                cr_updates.append((job, after))
            eff = after if after is not None else job
            if not eff.status.subjobs:
                continue
            containers = FrozenList(
                container_status_for(info) for info in sizecar.status.job_infos
            )
            existing = get_pod(worker_name(name))
            if existing is None:
                creates.append(
                    (self._build_worker(job, sizecar, containers), None)
                )
            else:
                repl = self._worker_replacement(existing, sizecar, containers)
                if repl is not None:
                    worker_updates.append(repl)
        if creates:
            results = self.store.create_batch(
                [pod for pod, _ in creates], site="operator.sweep"
            )
            for (pod, job), res in zip(creates, results):
                # AlreadyExists loses the create race exactly like the
                # single path: silently (and without the event)
                if job is not None and not isinstance(res, Exception):
                    self.events.event(
                        job, Reason.POD_CREATED,
                        f"sizecar pod {pod.meta.name} created",
                    )
        updates = [after for _, after in cr_updates] + worker_updates
        if updates:
            results = self.store.update_batch(updates, site="operator.sweep")
            for (before, _), res in zip(cr_updates, results):
                if isinstance(res, Exception):
                    # racing writer: the oracle re-reads and retries
                    slow.append(before.meta.name)
                    continue
                if self._emit_state_events(before, res):
                    # just finished with a possible result request
                    slow.append(before.meta.name)
            for pod, res in zip(worker_updates, results[len(cr_updates):]):
                if isinstance(res, Exception):
                    slow.append(pod.meta.owner)
        span.count("owners", len(ordered))
        span.count("creates", len(creates))
        span.count("updates", len(updates))
        span.count("slow", len(set(slow)))
        _reconcile_seconds.observe(time.perf_counter() - t0)
        return sorted(set(slow))

    # ---- the columnar sweep (PR-6) ----

    def _worker_labels(self, partition: str) -> FrozenDict:
        """Interned per-partition worker labels — immutable, so aliasing
        across 45k creates per sweep is safe (content-equal to the
        oracle's per-pod dict)."""
        cache = getattr(self, "_worker_label_cache", None)
        if cache is None:
            cache = self._worker_label_cache = {}
        fd = cache.get(partition)
        if fd is None:
            fd = cache[partition] = FrozenDict(
                {"role": PodRole.WORKER, "partition": partition}
            )
        return fd

    def _start_sizecar_build(self, creates: list[tuple]):
        """Kick ``_OP_BUILD_ROWS`` for the sweep's sizecar creates —
        non-blocking (``colpool.start_frames``), so the header parse +
        override resolution runs in the workers while the caller
        finishes the locked capture. Returns the job handle, or ``None``
        when there is nothing to build or no pool (1-core box: the
        serial arm runs with zero overhead)."""
        if not creates:
            return None
        pool = colpool.active_pool()
        if pool is None:
            return None
        chunks = [
            creates[lo : lo + _BUILD_CHUNK]
            for lo in range(0, len(creates), _BUILD_CHUNK)
        ]
        return pool.start_frames(
            colpool._OP_BUILD_ROWS, chunks, writeops.pack_build_chunk
        )

    def _built_sizecar_rows(
        self, creates: list[tuple], frames: list[bytes]
    ) -> tuple[list, list]:
        """Reassemble the worker-resolved columns into the frozen
        demands + label dicts the create scatter writes — field-for-
        field what the serial ``demand_for_spec`` + label build
        produces (fuzz-pinned), with the parent supplying everything
        that never rode the wire (owner/job_name, run_as_user/group,
        licenses, priority, the label insertion order)."""
        sc_demand: list = []
        sc_labels: list = []
        i = 0
        for lo in range(0, len(creates), _BUILD_CHUNK):
            chunk = creates[lo : lo + _BUILD_CHUNK]
            cols = writeops.unpack_build_result(frames[i])
            i += 1
            for j, (o, s, jl) in enumerate(chunk):
                dem = frozen_new(
                    JobDemand,
                    partition=cols["partition"][j],
                    script=s.sbatch_script,
                    job_name=o,
                    run_as_user=s.run_as_user,
                    run_as_group=s.run_as_group,
                    array=cols["array"][j],
                    cpus_per_task=cols["cpus_per_task"][j],
                    ntasks=cols["ntasks"][j],
                    ntasks_per_node=cols["ntasks_per_node"][j],
                    nodes=cols["nodes"][j],
                    working_dir=cols["working_dir"][j],
                    mem_per_cpu_mb=cols["mem_per_cpu_mb"][j],
                    gres=cols["gres"][j],
                    licenses=s.licenses,
                    time_limit_s=cols["time_limit_s"][j],
                    priority=s.priority,
                    nodelist=(),
                )
                sc_demand.append(dem)
                labels = {
                    "role": PodRole.SIZECAR,
                    "partition": dem.partition,
                    "request-cpu": cols["request_cpu"][j],
                    "request-memory-mb": cols["request_mem"][j],
                }
                if jl:
                    for key in (_TENANT_LABEL, _CLASS_LABEL):
                        val = jl.get(key)
                        if val:
                            labels[key] = val
                sc_labels.append(FrozenDict(labels))
        return sc_demand, sc_labels

    def _sweep_cols(self, span, names, jt, pt) -> list[str]:
        """The sweep on columns, vectorized: one locked scan classifies
        every owner with NumPy column masks (the per-owner Python loop is
        gone — raw-field compares instead of 45k SubjobStatus/
        ContainerStatus builds + dict equality), captures the values for
        changed rows as gathered arrays (copies — heap indices would go
        stale if a concurrent writer compacts a heap), then commits land
        as batched row-writes. Owners with shapes the fast path doesn't
        model (multi-sub-job arrays) re-enter :meth:`_sweep`, the
        object-path oracle, at the end — so the two can never drift on
        the unusual cases either.
        """
        from slurm_bridge_tpu.bridge.colstore import object_array as oarr
        from slurm_bridge_tpu.bridge.colstore import object_full

        t0 = time.perf_counter()
        _sweeps.inc()
        slow: list[str] = []
        #: (owner name, job spec, job labels) — demand parse + pod-row
        #: build happen at commit time, outside the lock
        sizecar_creates: list[tuple] = []
        ordered = sorted(set(names))
        n = len(ordered)
        validated = self._validated_specs
        jc, pc = jt.cols, pt.cols
        h = pt.adapter.infos
        sh = jt.adapter.subjobs
        ch = pt.adapter.containers

        with self.store.locked():
            jrows = jt.rows_for(ordered)
            found = jrows >= 0
            jr = np.where(found, jrows, 0)
            alive = found & ~jc.deleted[jr]
            # validation gate (python: identity-cached per name)
            ok = np.zeros(n, bool)
            vget, vpop = validated.get, validated.pop
            spec_col = jc.spec
            for i in np.nonzero(alive)[0].tolist():
                name = ordered[i]
                spec = spec_col[jr[i]]
                if vget(name) is not spec:
                    try:
                        validate_job_fields(name, spec)
                    except ValidationError:
                        slow.append(name)
                        continue
                    validated[name] = spec
                ok[i] = True
            for i in np.nonzero(~alive)[0].tolist():
                vpop(ordered[i], None)
            state = jc.state[jr]
            terminal = (state == _ST_SUCCEEDED) | (state == _ST_FAILED)
            slow.extend(ordered[i] for i in np.nonzero(ok & terminal)[0].tolist())
            act0 = ok & ~terminal
            slen = jc.slen[jr].astype(np.int64)
            srows = pt.rows_for([sizecar_name(nm) for nm in ordered])
            has_s = srows >= 0
            sr = np.where(has_s, srows, 0)
            missing = act0 & ~has_s
            m_slow = missing & (slen > 0)
            slow.extend(ordered[i] for i in np.nonzero(m_slow)[0].tolist())
            m_create = missing & (slen == 0)
            # capture (owner, spec, job labels) only — the demand parse,
            # label build and pod materialization all run OUTSIDE the
            # lock, and the create lands as a row-write (no Pod objects,
            # no create_batch freeze-walk: ~100k ``jt.view`` shells per
            # cold sweep gone, ISSUE 16)
            for i in np.nonzero(m_create)[0].tolist():
                row = int(jr[i])
                sizecar_creates.append(
                    (ordered[i], spec_col[row], jc.labels[row])
                )
            # kick the worker-pool demand/label resolution NOW (ISSUE
            # 18): specs are immutable snapshots, so the fan-out threads
            # pack them safely while this thread still holds the lock —
            # the builds overlap the whole CR/worker capture below, and
            # the commit block collects (or falls back serially)
            build_job = self._start_sizecar_build(sizecar_creates)
            act = (act0 & has_s) | m_create
            pod_phase = np.where(has_s, pc.phase[sr], _POD_PHASE_PENDING)
            ilen = np.where(has_s, pc.ilen[sr], 0).astype(np.int64)
            pod_reason = np.where(has_s, pc.reason[sr], "")
            srow_node = np.where(has_s, pc.node[sr], "")
            fb = act & ((ilen > 1) | (slen > 1))
            obj_fallback = [ordered[i] for i in np.nonzero(fb)[0].tolist()]
            act &= ~fb
            new_state = CR_STATE_OF_PHASE[pod_phase]
            old_reason = jc.reason[jr]
            reason_changed = (
                act & (pod_reason != "") & (old_reason != pod_reason)
            )
            new_reason = np.where(reason_changed, pod_reason, old_reason)
            old_ep = jc.endpoint[jr]
            if self.agent_endpoint:
                ep_changed = act & (old_ep == "")
                new_ep = np.where(ep_changed, self.agent_endpoint, old_ep)
            else:
                ep_changed = np.zeros(n, bool)
                new_ep = old_ep
            one = act & (ilen == 1)
            ii = np.where(one, pc.istart[sr], 0)
            fresh = one & (slen == 0)
            both = one & (slen == 1)
            si = np.where(both, jc.sstart[jr], 0)
            neq = both & (
                (sh.id[si] != h.id[ii])
                | (sh.state[si] != h.state[ii])
                | (sh.run_time[si] != h.run_time[ii])
                | (sh.array_id[si] != h.array_id[ii])
                | (sh.exit_code[si] != h.exit_code[ii])
                | (sh.stdout[si] != h.stdout[ii])
                | (sh.stderr[si] != h.stderr[ii])
                | (sh.reason[si] != h.reason[ii])
            )
            sub_changed = fresh | neq
            # timestamp residual: the sub stores ISO strings, the info
            # heap datetime objects — rendered in bulk (heap_iso_bulk)
            # and compared only where every cheap field already matched
            res = np.nonzero(both & ~neq)[0]
            if res.size:
                svr, ivr = si[res], ii[res]
                ts_neq = (
                    sh.submit[svr] != heap_iso_bulk(h, "submit", ivr)
                ) | (sh.start[svr] != heap_iso_bulk(h, "start", ivr))
                sub_changed[res[ts_neq]] = True
            state_changed = act & (new_state != state)
            cr_mask = act & (
                sub_changed | state_changed | reason_changed | ep_changed
            )
            has_sub = act & (sub_changed | (slen > 0))

            # ---- CR update capture (value copies) ----
            cr_idx = np.nonzero(cr_mask)[0]
            cr_names = [ordered[i] for i in cr_idx.tolist()]
            cr_exp = jc.rv[jr[cr_idx]].astype(np.int64)
            cr_state_new = new_state[cr_idx].astype(np.int8)
            cr_reason_arr = new_reason[cr_idx]
            cr_ep_arr = new_ep[cr_idx]
            cr_before = state[cr_idx].astype(np.int64)
            cr_subflag = sub_changed[cr_idx]
            sub_of_cr = np.cumsum(cr_subflag) - 1  # cr pos -> sub pos
            sub_idx = cr_idx[cr_subflag]
            iiv = ii[sub_idx]
            sub_id = h.id[iiv].astype(np.int64)
            sub_aid = h.array_id[iiv]
            sub_state = h.state[iiv].astype(np.int8)
            sub_exit = h.exit_code[iiv]
            sub_rt = h.run_time[iiv].astype(np.int64)
            sub_out = h.stdout[iiv]
            sub_err = h.stderr[iiv]
            sub_rsn = h.reason[iiv]
            sub_submit = heap_iso_bulk(h, "submit", iiv)
            sub_start = heap_iso_bulk(h, "start", iiv)
            sub_keys = oarr([
                (a if a else str(int(b)),)
                for a, b in zip(sub_aid.tolist(), sub_id.tolist())
            ])

            # ---- worker capture ----
            hs_idx = np.nonzero(has_sub)[0]
            w_names = [worker_name(ordered[i]) for i in hs_idx.tolist()]
            wrows = pt.rows_for(w_names)
            w_has = wrows >= 0
            w1 = ilen[hs_idx] == 1  # a derivable container exists
            # derive container fields for every has-sub row with one info
            k = len(hs_idx)
            c_name = object_full(k, "")
            c_state = object_full(k, "")
            c_exit = np.zeros(k, np.int32)
            c_reason = object_full(k, "")
            d_idx = np.nonzero(w1)[0]
            if d_idx.size:
                div = ii[hs_idx[d_idx]]
                dst = h.state[div].astype(np.int64)
                dids = h.id[div]
                daid = h.array_id[div]
                decs = h.exit_code[div]
                c_name[d_idx] = [
                    f"job-{a if a else str(int(b))}"
                    for a, b in zip(daid.tolist(), dids.tolist())
                ]
                term = dst <= 3
                run = dst == 5
                cs = object_full(int(d_idx.size), "waiting")
                cs[term] = "terminated"
                cs[run] = "running"
                c_state[d_idx] = cs
                snames = _STATUS_NAME_ARR[dst]
                snames[run] = ""
                c_reason[d_idx] = snames
                ce = np.zeros(int(d_idx.size), np.int32)
                for t in np.nonzero(term)[0].tolist():
                    code = 0
                    ec = decs[t]
                    if ec:
                        try:
                            code = int(ec.split(":")[0])
                        except ValueError:
                            code = 0
                    if code == 0 and dst[t] in (1, 2, 3):  # the bad ends
                        code = 1
                    ce[t] = code
                c_exit[d_idx] = ce
            w_phase = pod_phase[hs_idx].astype(np.int8)
            # creates: no worker row yet
            wc_pos = np.nonzero(~w_has)[0]
            wc_names = [w_names[p] for p in wc_pos.tolist()]
            wc_owner = oarr([ordered[hs_idx[p]] for p in wc_pos.tolist()])
            wc_partition = oarr([
                spec_col[jr[hs_idx[p]]].partition for p in wc_pos.tolist()
            ])
            wc_node = srow_node[hs_idx[wc_pos]]
            wc_phase = w_phase[wc_pos]
            wc_hasc = w1[wc_pos]
            wc_cname = c_name[wc_pos]
            wc_cstate = c_state[wc_pos]
            wc_cexit = c_exit[wc_pos]
            wc_creason = c_reason[wc_pos]
            # updates: worker exists and stored container/phase differ
            we_pos = np.nonzero(w_has)[0]
            wr = wrows[we_pos]
            stored_n = pc.clen[wr].astype(np.int64)
            want1 = w1[we_pos]
            same_n = stored_n == want1.astype(np.int64)
            ci0 = np.where(stored_n == 1, pc.cstart[wr], 0)
            fields_same = (
                (ch.cname[ci0] == c_name[we_pos])
                & (ch.cstate[ci0] == c_state[we_pos])
                & (ch.cexit[ci0] == c_exit[we_pos])
                & (ch.creason[ci0] == c_reason[we_pos])
            )
            phase_same = pc.phase[wr] == w_phase[we_pos]
            skip = same_n & (~want1 | fields_same) & phase_same
            wu = we_pos[~skip]
            wu_names = [w_names[p] for p in wu.tolist()]
            wu_owner = [ordered[hs_idx[p]] for p in wu.tolist()]
            wu_exp = pc.rv[wrows[wu]].astype(np.int64)
            wu_phase = w_phase[wu]
            wu_hasc = w1[wu]
            wu_cname = c_name[wu]
            wu_cstate = c_state[wu]
            wu_cexit = c_exit[wu]
            wu_creason = c_reason[wu]

        # ---- commits: creates first, then updates (oracle order) ----
        if sizecar_creates:
            sc_owners = [o for o, _s, _l in sizecar_creates]
            sc_names = [sizecar_name(o) for o in sc_owners]
            with TRACER.span("operator.sweep.build") as bspan:
                bspan.count("pods", len(sizecar_creates))
                built = build_job.wait() if build_job is not None else None
                if built is not None:
                    sc_demand, sc_labels = self._built_sizecar_rows(
                        sizecar_creates, built
                    )
                    _sweep_pool_rows.inc(len(sizecar_creates))
                else:
                    # the serial oracle — also the fallback when the
                    # pool is off/broken or a build chunk failed (the
                    # real exception then surfaces here, in context)
                    sc_demand = [
                        demand_for_spec(o, s)
                        for o, s, _l in sizecar_creates
                    ]
                    sc_labels = []
                    for (_o, _s, jl), dem in zip(sizecar_creates, sc_demand):
                        arr = array_len(dem.array)
                        labels = {
                            "role": PodRole.SIZECAR,
                            "partition": dem.partition,
                            # resource-request labels (pod.go:164-187)
                            "request-cpu": str(dem.total_cpus(arr)),
                            "request-memory-mb": str(dem.total_mem_mb(arr)),
                        }
                        if jl:
                            # policy-bearing labels ride from the CR onto
                            # the sizecar (cf. _build_sizecar, the object
                            # oracle)
                            for key in (_TENANT_LABEL, _CLASS_LABEL):
                                val = jl.get(key)
                                if val:
                                    labels[key] = val
                        sc_labels.append(FrozenDict(labels))
            sc_owner_arr = oarr(sc_owners)
            sc_name_arr = oarr(sc_names)
            sc_label_arr = oarr(sc_labels)
            sc_demand_arr = oarr(sc_demand)
            sc_part_arr = oarr([d.partition for d in sc_demand])

            def sc_builder(rows, sel):
                m = len(sel)
                pc.name[rows] = sc_name_arr[sel]
                pc.uid[rows] = oarr([new_uid() for _ in range(m)])
                pc.labels[rows] = sc_label_arr[sel]
                pc.ann[rows] = object_full(m, _EMPTY_FROZEN_DICT)
                pc.owner[rows] = sc_owner_arr[sel]
                pc.deleted[rows] = False
                pc.role[rows] = object_full(m, PodRole.SIZECAR)
                pc.partition[rows] = sc_part_arr[sel]
                pc.demand[rows] = sc_demand_arr[sel]
                pc.node[rows] = object_full(m, "")
                pc.hint[rows] = object_full(m, ())
                pc.phase[rows] = _POD_PHASE_PENDING
                pc.reason[rows] = object_full(m, "")
                pc.job_ids[rows] = object_full(m, ())
                pc.njobs[rows] = 0
                pc.istart[rows] = 0
                pc.ilen[rows] = 0
                pc.cstart[rows] = 0
                pc.clen[rows] = 0

            results = self.store.create_rows(
                Pod.KIND, sc_names, sc_builder, site="operator.sweep"
            )
            self.events.emit_batch(
                BridgeJob.KIND,
                Reason.POD_CREATED,
                [
                    (owner, f"sizecar pod {nm} created")
                    for nm, owner, rc in zip(
                        sc_names, sc_owners, results.tolist()
                    )
                    if rc > 0
                ],
            )
        if wc_names:
            empty_fd = FrozenDict()
            wc_name_arr = oarr(wc_names)
            # per-partition label interning, vectorized: one
            # _worker_labels call per distinct partition, fanned out
            # through the unique-inverse instead of 90k dict probes
            wc_uparts, wc_inv = np.unique(wc_partition, return_inverse=True)
            wc_label_arr = oarr(
                [self._worker_labels(p) for p in wc_uparts.tolist()]
            )[wc_inv]

            def builder(rows, sel):
                m = len(sel)
                pc.name[rows] = wc_name_arr[sel]
                pc.uid[rows] = oarr([new_uid() for _ in range(m)])
                pc.labels[rows] = wc_label_arr[sel]
                pc.ann[rows] = object_full(m, empty_fd)
                pc.owner[rows] = wc_owner[sel]
                pc.deleted[rows] = False
                pc.role[rows] = object_full(m, PodRole.WORKER)
                pc.partition[rows] = wc_partition[sel]
                pc.demand[rows] = object_full(m, None)
                pc.node[rows] = wc_node[sel]
                pc.hint[rows] = object_full(m, ())
                pc.phase[rows] = wc_phase[sel]
                pc.reason[rows] = object_full(m, "")
                pc.job_ids[rows] = object_full(m, ())
                pc.njobs[rows] = 0
                pc.istart[rows] = 0
                pc.ilen[rows] = 0
                hasc = wc_hasc[sel]
                rows_c = rows[hasc]
                kk = int(rows_c.size)
                if kk:
                    start = ch.alloc(kk)
                    tgt = np.arange(start, start + kk, dtype=np.int64)
                    src = sel[hasc]
                    ch.cname[tgt] = wc_cname[src]
                    ch.cstate[tgt] = wc_cstate[src]
                    ch.cexit[tgt] = wc_cexit[src]
                    ch.creason[tgt] = wc_creason[src]
                    pc.cstart[rows_c] = tgt
                    pc.clen[rows_c] = 1
                rows_n = rows[~hasc]
                pc.cstart[rows_n] = 0
                pc.clen[rows_n] = 0

            self.store.create_rows(
                Pod.KIND, wc_names, builder, site="operator.sweep"
            )
        if cr_names:

            def cr_writer(rws, sel):
                jc.state[rws] = cr_state_new[sel]
                jc.reason[rws] = cr_reason_arr[sel]
                jc.endpoint[rws] = cr_ep_arr[sel]
                m = cr_subflag[sel]
                rows_sub = rws[m]
                if not rows_sub.size:
                    return
                sh.retire(int(jc.slen[rows_sub].sum()))
                kk = int(rows_sub.size)
                start = sh.alloc(kk)
                tgt = np.arange(start, start + kk, dtype=np.int64)
                src = sub_of_cr[sel[m]]
                sh.id[tgt] = sub_id[src]
                sh.array_id[tgt] = sub_aid[src]
                sh.state[tgt] = sub_state[src]
                sh.exit_code[tgt] = sub_exit[src]
                sh.submit[tgt] = sub_submit[src]
                sh.start[tgt] = sub_start[src]
                sh.run_time[tgt] = sub_rt[src]
                sh.stdout[tgt] = sub_out[src]
                sh.stderr[tgt] = sub_err[src]
                sh.reason[tgt] = sub_rsn[src]
                jc.sstart[rows_sub] = tgt
                jc.slen[rows_sub] = 1
                jc.skeys[rows_sub] = sub_keys[src]
                jt.adapter._maybe_compact_subjobs(jt)

            results = self.store.update_rows(
                BridgeJob.KIND, cr_names, cr_exp, cr_writer,
                site="operator.sweep",
            )
            before_l = cr_before.tolist()
            after_l = cr_state_new.tolist()
            ev_groups: dict[tuple[str, bool], list[tuple[str, str]]] = {}
            for p, rc in enumerate(results.tolist()):
                name = cr_names[p]
                if rc <= 0:
                    # racing writer / vanished: the oracle re-reads
                    slow.append(name)
                    continue
                before, after = before_l[p], after_l[p]
                if before == after:
                    continue
                r = _STATE_REASONS.get(STATE_STRS[after])
                if r:
                    ev_groups.setdefault((r, after == _ST_FAILED), []).append(
                        (name,
                         f"state {STATE_STRS[before]} -> {STATE_STRS[after]}")
                    )
                if after in (_ST_SUCCEEDED, _ST_FAILED):
                    slow.append(name)  # just finished: result pass
            for (r, warn), pairs in ev_groups.items():
                self.events.emit_batch(
                    BridgeJob.KIND, r, pairs, warning=warn
                )
        if wu_names:

            def w_writer(rws, sel):
                pc.phase[rws] = wu_phase[sel]
                hasc = wu_hasc[sel]
                ch.retire(int(pc.clen[rws].sum()))
                rows_c = rws[hasc]
                kk = int(rows_c.size)
                if kk:
                    start = ch.alloc(kk)
                    tgt = np.arange(start, start + kk, dtype=np.int64)
                    src = sel[hasc]
                    ch.cname[tgt] = wu_cname[src]
                    ch.cstate[tgt] = wu_cstate[src]
                    ch.cexit[tgt] = wu_cexit[src]
                    ch.creason[tgt] = wu_creason[src]
                    pc.cstart[rows_c] = tgt
                    pc.clen[rows_c] = 1
                rows_n = rws[~hasc]
                pc.cstart[rows_n] = 0
                pc.clen[rows_n] = 0
                pt.adapter._maybe_compact_containers(pt)

            results = self.store.update_rows(
                Pod.KIND, wu_names, wu_exp, w_writer, site="operator.sweep"
            )
            for owner, rc in zip(wu_owner, results.tolist()):
                if rc <= 0:
                    slow.append(owner)
        if obj_fallback:
            # shapes the fast path doesn't model take the object-path
            # sweep — the same oracle the fuzzed equivalence test pins
            slow.extend(self._sweep(span, obj_fallback))
        span.count("owners", len(ordered))
        span.count("creates", len(sizecar_creates) + len(wc_names))
        span.count("updates", len(cr_names) + len(wu_names))
        span.count("slow", len(set(slow)))
        _reconcile_seconds.observe(time.perf_counter() - t0)
        return sorted(set(slow))

    # ---- sizecar (ReconcileSizeCarPods, :296-319) ----

    def _build_sizecar(self, job: BridgeJob) -> Pod:
        demand = demand_for_job(job)
        arr = array_len(demand.array)
        labels = {
            "role": PodRole.SIZECAR,
            "partition": demand.partition,
            # resource-request labels (pod.go:164-187)
            "request-cpu": str(demand.total_cpus(arr)),
            "request-memory-mb": str(demand.total_mem_mb(arr)),
        }
        job_labels = job.meta.labels
        if job_labels:
            # policy-bearing labels ride from the CR onto the sizecar —
            # the scheduler's class/tenant resolution reads the POD
            # (policy/classes.py); jobs without them pay nothing
            for key in (_TENANT_LABEL, _CLASS_LABEL):
                val = job_labels.get(key)
                if val:
                    labels[key] = val
        # fast_new (every field explicit): one sizecar per arrival, 50k
        # deep on a cold-start tick, against freeze-guarded classes.
        # spec/status (and the demand, born frozen in demand_for_job) are
        # pre-frozen and the label/annotation dicts pre-wrapped (ISSUE
        # 14): commit-time freeze used to re-walk ~45 fields per pod —
        # demand's 19 included — which was a third of the 500k arrive
        # storm; now it probes meta's fields and stops.
        return fast_new(
            Pod,
            meta=fast_new(
                Meta,
                name=sizecar_name(job.meta.name),
                uid=new_uid(),
                labels=FrozenDict(labels),
                annotations=_EMPTY_FROZEN_DICT,
                owner=job.meta.name,
                resource_version=0,
                deleted=False,
            ),
            spec=frozen_new(
                PodSpec,
                role=PodRole.SIZECAR,
                partition=demand.partition,
                demand=demand,
                node_name="",
                placement_hint=(),
            ),
            status=frozen_new(
                PodStatus,
                phase=PodPhase.PENDING,
                reason="",
                job_ids=(),
                job_infos=_EMPTY_FROZEN_LIST,
                containers=_EMPTY_FROZEN_LIST,
            ),
        )

    def _reconcile_sizecar(self, job: BridgeJob) -> None:
        name = sizecar_name(job.meta.name)
        if self.store.try_get(Pod.KIND, name) is not None:
            return
        if job.status.subjobs:
            # pod vanished but sub-jobs exist ⇒ Failed (:296-303)
            self._set_state(
                job.meta.name, JobState.FAILED, reason="sizecar pod disappeared"
            )
            return
        pod = self._build_sizecar(job)
        try:
            self.store.create(pod, site="operator.reconcile")
        except AlreadyExists:
            return
        self.events.event(job, Reason.POD_CREATED, f"sizecar pod {name} created")

    # ---- status sync (UpdateSBJStatus, :246-294) ----

    def _cr_replacement(self, job: BridgeJob, pod: Pod) -> BridgeJob | None:
        """Replacement CR mirroring ``pod``'s state, sharing frozen
        spec/meta children — or None when nothing changed, so the
        no-change case (steady-state reconciles) costs zero copies and
        skips the write (no self-feeding watch loop). Shared by the
        single-key reconcile and the batch sweep so they can never
        drift."""
        state = job_state_for_pod_phase(pod.status.phase)
        subjobs = {
            info.key(): SubjobStatus.from_job_info(info)
            for info in pod.status.job_infos
        }
        pod_reason = pod.status.reason
        new_subjobs = job.status.subjobs
        if subjobs and job.status.subjobs != subjobs:
            new_subjobs = subjobs
        new_state = state
        # don't regress a terminal CR state on a stale pod read
        if job.status.state in JobState.TERMINAL:
            new_state = job.status.state
        new_reason = job.status.reason
        if pod_reason and job.status.reason != pod_reason:
            new_reason = pod_reason
        endpoint = job.status.cluster_endpoint
        if self.agent_endpoint and not endpoint:
            endpoint = self.agent_endpoint
        if (
            new_subjobs is job.status.subjobs
            and new_state == job.status.state
            and new_reason == job.status.reason
            and endpoint == job.status.cluster_endpoint
        ):
            return None
        if new_subjobs is not job.status.subjobs:
            # values are born-frozen SubjobStatus rows; wrapping here lets
            # the status be born frozen too (commit walk: one dict probe)
            new_subjobs = FrozenDict(new_subjobs)
        return fast_replace(
            job,
            meta=fast_replace(job.meta),
            status=frozen_replace(
                job.status,
                state=new_state,
                reason=new_reason,
                subjobs=new_subjobs,
                cluster_endpoint=endpoint,
            ),
        )

    def _emit_state_events(self, before: BridgeJob, after: BridgeJob) -> bool:
        """The recorder calls UpdateSBJStatus makes on a state transition.
        Returns True when the job just finished (needs a result pass)."""
        if before.status.state == after.status.state:
            return False
        r = _STATE_REASONS.get(after.status.state)
        if r:
            self.events.event(
                after, r, f"state {before.status.state} -> {after.status.state}",
                warning=after.status.state == JobState.FAILED,
            )
        return after.finished

    def _sync_status(self, job_name: str) -> None:
        pod = self.store.try_get(Pod.KIND, sizecar_name(job_name))
        if pod is None:
            return
        try:
            before = self.store.get(BridgeJob.KIND, job_name)
            after = self.store.replace_update(
                BridgeJob.KIND, job_name,
                lambda j: self._cr_replacement(j, pod),
                site="operator.status",
            )
        except NotFound:
            return
        # a just-finished job with a result request needs another pass
        if self._emit_state_events(before, after):
            self.controller.enqueue(job_name)

    # ---- worker pods (ReconcileWorkerPods, :365-451) ----

    def _build_worker(
        self, job: BridgeJob, sizecar: Pod | None, containers: FrozenList
    ) -> Pod:
        # fast_new/frozen_new (every field explicit): one worker pod per
        # job with sub-jobs — 45k per transition sweep at the headline
        # shape. spec/status are born frozen (their values are scalars or
        # frozen rows), so the create-commit walk stops at meta.
        return fast_new(
            Pod,
            meta=fast_new(
                Meta,
                name=worker_name(job.meta.name),
                uid=new_uid(),
                labels={"role": PodRole.WORKER, "partition": job.spec.partition},
                annotations={},
                owner=job.meta.name,
                resource_version=0,
                deleted=False,
            ),
            spec=frozen_new(
                PodSpec,
                role=PodRole.WORKER,
                partition=job.spec.partition,
                demand=None,
                node_name=sizecar.spec.node_name if sizecar else "",
                placement_hint=(),
            ),
            status=frozen_new(
                PodStatus,
                phase=sizecar.status.phase if sizecar else PodPhase.PENDING,
                reason="",
                job_ids=(),
                job_infos=_EMPTY_FROZEN_LIST,
                containers=containers,
            ),
        )

    @staticmethod
    def _worker_replacement(
        p: Pod, sizecar: Pod | None, containers: list[ContainerStatus]
    ) -> Pod | None:
        phase = sizecar.status.phase if sizecar else p.status.phase
        if p.status.containers == containers and p.status.phase == phase:
            return None
        return fast_replace(
            p,
            meta=fast_replace(p.meta),
            status=frozen_replace(
                p.status,
                containers=containers
                if isinstance(containers, FrozenList)
                else FrozenList(containers),
                phase=phase,
            ),
        )

    def _reconcile_worker(self, job_name: str) -> None:
        job = self.store.try_get(BridgeJob.KIND, job_name)
        if job is None or not job.status.subjobs:
            return
        sizecar = self.store.try_get(Pod.KIND, sizecar_name(job_name))
        containers = FrozenList(
            container_status_for(info)
            for info in (sizecar.status.job_infos if sizecar else ())
        )
        name = worker_name(job_name)
        existing = self.store.try_get(Pod.KIND, name)
        if existing is None:
            try:
                self.store.create(
                    self._build_worker(job, sizecar, containers),
                    site="operator.worker",
                )
            except AlreadyExists:
                pass
            return
        try:
            self.store.replace_update(
                Pod.KIND, name,
                lambda p: self._worker_replacement(p, sizecar, containers),
                site="operator.worker",
            )
        except NotFound:
            pass

    # ---- results (ReconcileSlurmBridgeJobResult, :321-361 + result.go) ----

    def _reconcile_result(self, job: BridgeJob) -> Result | None:
        # fetch for ANY terminal state: a failed job's stdout is exactly what
        # the user wants back (the reference keys only on "finished",
        # slurmbridgejob_controller.go:131-141)
        if not job.spec.result_to or job.status.state not in JobState.TERMINAL:
            return None
        if job.status.fetch_result in (FetchState.SUCCEEDED, FetchState.FAILED):
            return None
        name = fetch_job_name(job.meta.name)
        fetch = self.store.try_get(FetchJob.KIND, name)
        if fetch is None:
            files = [
                FetchFile(
                    remote_path=sub.std_out,
                    local_path=os.path.join(
                        job.spec.result_to, f"{job.meta.name}-{key}.out"
                    ),
                )
                for key, sub in sorted(job.status.subjobs.items())
                if sub.std_out
            ]
            if not files:
                self._set_fetch_state(job.meta.name, FetchState.FAILED,
                                      reason="no stdout paths to fetch")
                return None
            fetch = FetchJob(
                meta=Meta(name=name, owner=job.meta.name),
                files=files,
                agent_endpoint=self.agent_endpoint,
                state=FetchState.PENDING,
            )
            try:
                self.store.create(fetch, site="operator.fetch")
            except AlreadyExists:
                pass
            self._set_fetch_state(job.meta.name, FetchState.PENDING)
            self.events.event(job, Reason.RESULT_FETCH_STARTED,
                              f"fetching {len(files)} file(s)")
            return Result(requeue_after=RESULT_REQUEUE_S)
        # poll the fetch job's state (FetchResultStatus :349-361)
        if fetch.state in (FetchState.SUCCEEDED, FetchState.FAILED):
            self._set_fetch_state(job.meta.name, fetch.state, reason=fetch.reason)
            self.events.event(
                job,
                Reason.RESULT_FETCH_DONE
                if fetch.state == FetchState.SUCCEEDED
                else Reason.RESULT_FETCH_FAILED,
                fetch.reason or "result fetch finished",
                warning=fetch.state == FetchState.FAILED,
            )
            return None
        return Result(requeue_after=RESULT_REQUEUE_S)

    # ---- helpers ----

    def _set_state(self, job_name: str, state: str, *, reason: str = "") -> None:
        def record(job: BridgeJob):
            if job.status.state == state and job.status.reason == reason:
                return False
            job.status.state = state
            job.status.reason = reason

        try:
            self.store.mutate(
                BridgeJob.KIND, job_name, record, site="operator.state"
            )
        except NotFound:
            pass

    def _set_fetch_state(self, job_name: str, state: str, *, reason: str = "") -> None:
        def record(job: BridgeJob):
            if job.status.fetch_result == state:
                return False
            job.status.fetch_result = state
            if reason:
                job.status.reason = reason

        try:
            self.store.mutate(
                BridgeJob.KIND, job_name, record, site="operator.state"
            )
        except NotFound:
            pass
