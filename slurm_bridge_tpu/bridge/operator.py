"""BridgeOperator — the BridgeJob reconciler.

Reference parity: pkg/slurm-bridge-operator/slurmbridgejob_controller.go.
The reconcile branches exactly as the reference's Reconcile (:104-159):
validate → if finished, converge the result-fetch job; else ensure the
sizecar pod, sync CR status from it, and maintain per-sub-job worker pods.

Sizecar sizing (pod.go:18-68): parse ``#SBATCH`` headers out of the script
(extractBatchResourcesFromScript, parse.go:30-124), let explicit spec
fields override them, default 1 node / 1 cpu / 1024 MB-per-cpu
(pod.go:91-95); cpu multiplies by ntasks × array length
(genResourceListForPod :143-162).
"""

from __future__ import annotations

import dataclasses
import logging
import os

from slurm_bridge_tpu.bridge.controller import Controller, Result
from slurm_bridge_tpu.bridge.objects import (
    BridgeJob,
    ContainerStatus,
    FetchFile,
    FetchJob,
    FetchState,
    JobState,
    Meta,
    Pod,
    PodPhase,
    PodRole,
    PodSpec,
    PodStatus,
    SubjobStatus,
    ValidationError,
    validate_bridge_job,
)
from slurm_bridge_tpu.bridge.statusmap import (
    container_status_for,
    job_state_for_pod_phase,
)
from slurm_bridge_tpu.bridge.store import AlreadyExists, NotFound, ObjectStore
from slurm_bridge_tpu.core.arrays import array_len
from slurm_bridge_tpu.core.sbatch import extract_batch_resources
from slurm_bridge_tpu.core.types import JobDemand
from slurm_bridge_tpu.obs.events import EventRecorder, Reason
from slurm_bridge_tpu.obs.metrics import REGISTRY

log = logging.getLogger("sbt.operator")

RESULT_REQUEUE_S = 30.0  # result-poll requeue (slurmbridgejob_controller.go:141)

_reconciles = REGISTRY.counter("sbt_operator_reconciles_total", "operator reconciles")


def sizecar_name(job_name: str) -> str:
    return f"{job_name}-sizecar"


def worker_name(job_name: str) -> str:
    return f"{job_name}-worker"


def fetch_job_name(job_name: str) -> str:
    return f"{job_name}-fetch"


def demand_for_job(job: BridgeJob) -> JobDemand:
    """Script #SBATCH headers, overridden by explicit spec fields, with the
    reference defaults (pod.go:18-95)."""
    hdr = extract_batch_resources(job.spec.sbatch_script).demand
    spec = job.spec
    return JobDemand(
        partition=spec.partition or hdr.partition,
        script=spec.sbatch_script,
        job_name=job.meta.name,
        run_as_user=spec.run_as_user,
        run_as_group=spec.run_as_group,
        array=spec.array or hdr.array,
        cpus_per_task=spec.cpus_per_task or hdr.cpus_per_task or 1,
        ntasks=spec.ntasks or hdr.ntasks or 1,
        ntasks_per_node=spec.ntasks_per_node or hdr.ntasks_per_node,
        nodes=spec.nodes or hdr.nodes or 1,
        working_dir=spec.working_dir or hdr.working_dir,
        mem_per_cpu_mb=spec.mem_per_cpu_mb or hdr.mem_per_cpu_mb or 1024,
        gres=spec.gres or hdr.gres,
        licenses=spec.licenses,
        time_limit_s=hdr.time_limit_s,
        priority=spec.priority,
    )


class BridgeOperator:
    def __init__(
        self,
        store: ObjectStore,
        *,
        agent_endpoint: str = "",
        events: EventRecorder | None = None,
        workers: int = 1,
    ):
        self.store = store
        self.agent_endpoint = agent_endpoint
        self.events = events or EventRecorder()
        self.controller = Controller(
            name="bridge-operator", reconcile=self.reconcile, workers=workers
        )

    # ---- wiring ----

    def start(self) -> None:
        self.controller.start()
        self._watch_q = self.store.watch((BridgeJob.KIND, Pod.KIND, FetchJob.KIND))
        import threading

        threading.Thread(target=self._pump_events, daemon=True).start()

    def _pump_events(self) -> None:
        """Map watch events to reconcile keys: BridgeJobs directly, owned
        objects via their owner ref (SetupWithManager's Owns(&Pod{}),
        slurmbridgejob_controller.go:204)."""
        while True:
            ev = self._watch_q.get()
            if ev is None:
                return
            if ev.kind == BridgeJob.KIND:
                self.controller.enqueue(ev.name)
            else:
                obj = self.store.try_get(ev.kind, ev.name)
                owner = obj.meta.owner if obj is not None else self._owner_from_name(ev.name)
                if owner:
                    self.controller.enqueue(owner)

    def _owner_from_name(self, obj_name: str) -> str:
        for suffix in ("-sizecar", "-worker", "-fetch"):
            if obj_name.endswith(suffix):
                return obj_name[: -len(suffix)]
        return ""

    def stop(self) -> None:
        if hasattr(self, "_watch_q"):
            self.store.unwatch(self._watch_q)
            self._watch_q.put(None)  # unblock the pump thread
        self.controller.stop()

    def enqueue(self, job_name: str) -> None:
        self.controller.enqueue(job_name)

    # ---- the reconcile ----

    def reconcile(self, job_name: str) -> Result | None:
        _reconciles.inc()
        job = self.store.try_get(BridgeJob.KIND, job_name)
        if job is None or job.meta.deleted:
            return None
        try:
            validate_bridge_job(job)
        except ValidationError as e:
            self._set_state(job_name, JobState.FAILED, reason=str(e))
            self.events.event(job, Reason.JOB_FAILED, str(e), warning=True)
            return None

        if job.finished:
            return self._reconcile_result(job)
        self._reconcile_sizecar(job)
        self._sync_status(job_name)
        self._reconcile_worker(job_name)
        return None

    # ---- sizecar (ReconcileSizeCarPods, :296-319) ----

    def _reconcile_sizecar(self, job: BridgeJob) -> None:
        name = sizecar_name(job.meta.name)
        if self.store.try_get(Pod.KIND, name) is not None:
            return
        if job.status.subjobs:
            # pod vanished but sub-jobs exist ⇒ Failed (:296-303)
            self._set_state(
                job.meta.name, JobState.FAILED, reason="sizecar pod disappeared"
            )
            return
        demand = demand_for_job(job)
        arr = array_len(demand.array)
        pod = Pod(
            meta=Meta(
                name=name,
                owner=job.meta.name,
                labels={
                    "role": PodRole.SIZECAR,
                    "partition": demand.partition,
                    # resource-request labels (pod.go:164-187)
                    "request-cpu": str(demand.total_cpus(arr)),
                    "request-memory-mb": str(demand.total_mem_mb(arr)),
                },
            ),
            spec=PodSpec(
                role=PodRole.SIZECAR, partition=demand.partition, demand=demand
            ),
            status=PodStatus(phase=PodPhase.PENDING),
        )
        try:
            self.store.create(pod)
        except AlreadyExists:
            return
        self.events.event(job, Reason.POD_CREATED, f"sizecar pod {name} created")

    # ---- status sync (UpdateSBJStatus, :246-294) ----

    def _sync_status(self, job_name: str) -> None:
        pod = self.store.try_get(Pod.KIND, sizecar_name(job_name))
        if pod is None:
            return
        state = job_state_for_pod_phase(pod.status.phase)
        subjobs = {
            info.key(): SubjobStatus.from_job_info(info)
            for info in pod.status.job_infos
        }
        pod_reason = pod.status.reason

        def build(job: BridgeJob):
            """Replacement CR sharing frozen spec/meta children — the
            no-change case (steady-state reconciles) costs zero copies and
            skips the write (no self-feeding watch loop)."""
            new_subjobs = job.status.subjobs
            if subjobs and job.status.subjobs != subjobs:
                new_subjobs = subjobs
            new_state = state
            # don't regress a terminal CR state on a stale pod read
            if job.status.state in JobState.TERMINAL:
                new_state = job.status.state
            new_reason = job.status.reason
            if pod_reason and job.status.reason != pod_reason:
                new_reason = pod_reason
            endpoint = job.status.cluster_endpoint
            if self.agent_endpoint and not endpoint:
                endpoint = self.agent_endpoint
            if (
                new_subjobs is job.status.subjobs
                and new_state == job.status.state
                and new_reason == job.status.reason
                and endpoint == job.status.cluster_endpoint
            ):
                return None
            return BridgeJob(
                meta=dataclasses.replace(job.meta),
                spec=job.spec,
                status=dataclasses.replace(
                    job.status,
                    state=new_state,
                    reason=new_reason,
                    subjobs=new_subjobs,
                    cluster_endpoint=endpoint,
                ),
            )

        try:
            before = self.store.get(BridgeJob.KIND, job_name)
            after = self.store.replace_update(BridgeJob.KIND, job_name, build)
        except NotFound:
            return
        if before.status.state != after.status.state:
            reason_map = {
                JobState.RUNNING: Reason.JOB_RUNNING,
                JobState.SUCCEEDED: Reason.JOB_SUCCEEDED,
                JobState.FAILED: Reason.JOB_FAILED,
            }
            r = reason_map.get(after.status.state)
            if r:
                self.events.event(
                    after, r, f"state {before.status.state} -> {after.status.state}",
                    warning=after.status.state == JobState.FAILED,
                )
            # a just-finished job with a result request needs another pass
            if after.finished:
                self.controller.enqueue(job_name)

    # ---- worker pods (ReconcileWorkerPods, :365-451) ----

    def _reconcile_worker(self, job_name: str) -> None:
        job = self.store.try_get(BridgeJob.KIND, job_name)
        if job is None or not job.status.subjobs:
            return
        sizecar = self.store.try_get(Pod.KIND, sizecar_name(job_name))
        containers = [
            container_status_for(info)
            for info in (sizecar.status.job_infos if sizecar else [])
        ]
        name = worker_name(job_name)
        existing = self.store.try_get(Pod.KIND, name)
        if existing is None:
            pod = Pod(
                meta=Meta(
                    name=name,
                    owner=job_name,
                    labels={"role": PodRole.WORKER, "partition": job.spec.partition},
                ),
                spec=PodSpec(
                    role=PodRole.WORKER,
                    partition=job.spec.partition,
                    node_name=sizecar.spec.node_name if sizecar else "",
                ),
                status=PodStatus(
                    phase=sizecar.status.phase if sizecar else PodPhase.PENDING,
                    containers=containers,
                ),
            )
            try:
                self.store.create(pod)
            except AlreadyExists:
                pass
            return

        def build(p: Pod):
            phase = sizecar.status.phase if sizecar else p.status.phase
            if p.status.containers == containers and p.status.phase == phase:
                return None
            return Pod(
                meta=dataclasses.replace(p.meta),
                spec=p.spec,
                status=dataclasses.replace(
                    p.status, containers=containers, phase=phase
                ),
            )

        try:
            self.store.replace_update(Pod.KIND, name, build)
        except NotFound:
            pass

    # ---- results (ReconcileSlurmBridgeJobResult, :321-361 + result.go) ----

    def _reconcile_result(self, job: BridgeJob) -> Result | None:
        # fetch for ANY terminal state: a failed job's stdout is exactly what
        # the user wants back (the reference keys only on "finished",
        # slurmbridgejob_controller.go:131-141)
        if not job.spec.result_to or job.status.state not in JobState.TERMINAL:
            return None
        if job.status.fetch_result in (FetchState.SUCCEEDED, FetchState.FAILED):
            return None
        name = fetch_job_name(job.meta.name)
        fetch = self.store.try_get(FetchJob.KIND, name)
        if fetch is None:
            files = [
                FetchFile(
                    remote_path=sub.std_out,
                    local_path=os.path.join(
                        job.spec.result_to, f"{job.meta.name}-{key}.out"
                    ),
                )
                for key, sub in sorted(job.status.subjobs.items())
                if sub.std_out
            ]
            if not files:
                self._set_fetch_state(job.meta.name, FetchState.FAILED,
                                      reason="no stdout paths to fetch")
                return None
            fetch = FetchJob(
                meta=Meta(name=name, owner=job.meta.name),
                files=files,
                agent_endpoint=self.agent_endpoint,
                state=FetchState.PENDING,
            )
            try:
                self.store.create(fetch)
            except AlreadyExists:
                pass
            self._set_fetch_state(job.meta.name, FetchState.PENDING)
            self.events.event(job, Reason.RESULT_FETCH_STARTED,
                              f"fetching {len(files)} file(s)")
            return Result(requeue_after=RESULT_REQUEUE_S)
        # poll the fetch job's state (FetchResultStatus :349-361)
        if fetch.state in (FetchState.SUCCEEDED, FetchState.FAILED):
            self._set_fetch_state(job.meta.name, fetch.state, reason=fetch.reason)
            self.events.event(
                job,
                Reason.RESULT_FETCH_DONE
                if fetch.state == FetchState.SUCCEEDED
                else Reason.RESULT_FETCH_FAILED,
                fetch.reason or "result fetch finished",
                warning=fetch.state == FetchState.FAILED,
            )
            return None
        return Result(requeue_after=RESULT_REQUEUE_S)

    # ---- helpers ----

    def _set_state(self, job_name: str, state: str, *, reason: str = "") -> None:
        def record(job: BridgeJob):
            if job.status.state == state and job.status.reason == reason:
                return False
            job.status.state = state
            job.status.reason = reason

        try:
            self.store.mutate(BridgeJob.KIND, job_name, record)
        except NotFound:
            pass

    def _set_fetch_state(self, job_name: str, state: str, *, reason: str = "") -> None:
        def record(job: BridgeJob):
            if job.status.fetch_result == state:
                return False
            job.status.fetch_result = state
            if reason:
                job.status.reason = reason

        try:
            self.store.mutate(BridgeJob.KIND, job_name, record)
        except NotFound:
            pass
