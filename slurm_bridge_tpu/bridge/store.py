"""In-process object store — the API-server seam of the control plane.

The reference's controllers converge on the K8s API server: optimistic
concurrency via resourceVersion, label-selector lists, watches feeding
level-triggered reconcilers, owner references for cascade behavior.
This store reproduces exactly that contract in-process so every bridge
component keeps the reference's architecture (SURVEY.md §3 call stacks)
while the framework runs standalone. Swapping this for a real kube client
retargets the bridge at an actual cluster — the interface is the seam.

Objects are stored by (kind, name). Writers must pass the object they last
read; a stale ``meta.resource_version`` raises :class:`Conflict`, same as
a 409 from the API server (controllers retry via requeue).
"""

from __future__ import annotations

import copy
import queue
import threading
from dataclasses import dataclass


class NotFound(KeyError):
    pass


class Conflict(RuntimeError):
    pass


class AlreadyExists(RuntimeError):
    pass


@dataclass(frozen=True)
class StoreEvent:
    """ADDED | MODIFIED | DELETED, like a watch event."""

    type: str
    kind: str
    name: str


class ObjectStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._objects: dict[tuple[str, str], object] = {}
        self._rv = 0
        self._watchers: list[tuple[queue.Queue, tuple[str, ...] | None]] = []

    # ---- plumbing ----

    def _key(self, obj) -> tuple[str, str]:
        return (obj.KIND, obj.meta.name)

    def _notify(self, etype: str, kind: str, name: str) -> None:
        for q, kinds in list(self._watchers):
            if kinds is None or kind in kinds:
                q.put(StoreEvent(etype, kind, name))

    def watch(self, kinds: tuple[str, ...] | None = None) -> queue.Queue:
        """A queue of StoreEvents for the given kinds (None = all).

        New watchers receive synthetic ADDED events for existing objects so
        level-triggered consumers converge from any start time.
        """
        q: queue.Queue = queue.Queue()
        with self._lock:
            for (kind, name) in self._objects:
                if kinds is None or kind in kinds:
                    q.put(StoreEvent("ADDED", kind, name))
            self._watchers.append((q, kinds))
        return q

    def unwatch(self, q: queue.Queue) -> None:
        with self._lock:
            self._watchers = [(w, k) for (w, k) in self._watchers if w is not q]

    # ---- CRUD ----

    def create(self, obj) -> object:
        with self._lock:
            key = self._key(obj)
            if key in self._objects:
                raise AlreadyExists(f"{key[0]}/{key[1]} already exists")
            self._rv += 1
            obj.meta.resource_version = self._rv
            stored = copy.deepcopy(obj)
            self._objects[key] = stored
            self._notify("ADDED", *key)
        return copy.deepcopy(stored)

    def get(self, kind: str, name: str) -> object:
        with self._lock:
            try:
                return copy.deepcopy(self._objects[(kind, name)])
            except KeyError:
                raise NotFound(f"{kind}/{name}") from None

    def try_get(self, kind: str, name: str):
        try:
            return self.get(kind, name)
        except NotFound:
            return None

    def update(self, obj) -> object:
        """Replace; raises Conflict if the caller's copy is stale."""
        with self._lock:
            key = self._key(obj)
            current = self._objects.get(key)
            if current is None:
                raise NotFound(f"{key[0]}/{key[1]}")
            if current.meta.resource_version != obj.meta.resource_version:
                raise Conflict(
                    f"{key[0]}/{key[1]}: stale resource_version "
                    f"{obj.meta.resource_version} != {current.meta.resource_version}"
                )
            self._rv += 1
            obj.meta.resource_version = self._rv
            stored = copy.deepcopy(obj)
            self._objects[key] = stored
            self._notify("MODIFIED", *key)
        return copy.deepcopy(stored)

    def delete(self, kind: str, name: str) -> None:
        """Delete an object and cascade to objects it owns (owner refs)."""
        with self._lock:
            if (kind, name) not in self._objects:
                raise NotFound(f"{kind}/{name}")
            del self._objects[(kind, name)]
            self._notify("DELETED", kind, name)
            owned = [
                k
                for k, o in self._objects.items()
                if getattr(o.meta, "owner", "") == name
            ]
            for okind, oname in owned:
                del self._objects[(okind, oname)]
                self._notify("DELETED", okind, oname)

    def list(self, kind: str, *, labels: dict[str, str] | None = None) -> list:
        with self._lock:
            out = []
            for (k, _), obj in self._objects.items():
                if k != kind:
                    continue
                if labels and any(
                    obj.meta.labels.get(lk) != lv for lk, lv in labels.items()
                ):
                    continue
                out.append(copy.deepcopy(obj))
        out.sort(key=lambda o: o.meta.name)
        return out

    def owned_by(self, kind: str, owner: str) -> list:
        with self._lock:
            return [
                copy.deepcopy(o)
                for (k, _), o in self._objects.items()
                if k == kind and o.meta.owner == owner
            ]

    # ---- convenience used by reconcilers ----

    def mutate(self, kind: str, name: str, fn, *, retries: int = 8):
        """Read-modify-write with conflict retry; fn mutates in place and
        may return False to skip the write."""
        for _ in range(retries):
            obj = self.get(kind, name)
            if fn(obj) is False:
                return obj
            try:
                return self.update(obj)
            except Conflict:
                continue
        raise Conflict(f"{kind}/{name}: too many conflicts")
