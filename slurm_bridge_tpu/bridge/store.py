"""In-process object store — the API-server seam of the control plane.

Columnar hot state (PR-6): the high-churn kinds (``Pod``, ``BridgeJob``)
are stored as column-oriented tables (:mod:`bridge.colstore` machinery,
:mod:`bridge.columns` schemas) instead of frozen object graphs. Every
caller keeps this class's contract — ``get``/``list`` still hand out
immutable frozen snapshots (materialized lazily, cached per resource
version), writers still pay optimistic concurrency, watches/indexes/
``changes_since``/commit attribution behave identically — but the hot
write paths (:meth:`update_rows`, :meth:`create_rows`) commit straight
to rows, so a cold-start tick's ~135k commits build zero frozen objects
for anything nothing reads.

The reference's controllers converge on the K8s API server: optimistic
concurrency via resourceVersion, label-selector lists, watches feeding
level-triggered reconcilers, owner references for cascade behavior.
This store reproduces exactly that contract in-process so every bridge
component keeps the reference's architecture (SURVEY.md §3 call stacks)
while the framework runs standalone. Swapping this for a real kube client
retargets the bridge at an actual cluster — the interface is the seam.

Read path (the PR-3 rework): reads hand out **immutable copy-on-read
snapshots** — the stored object itself, frozen once at write time
(:mod:`bridge.freeze`) — instead of deep-copying on every ``get``/``list``.
Mutating a snapshot raises :class:`freeze.FrozenInstanceError`; writers go
through :meth:`mutate` / :meth:`get_for_update`, which hand them a private
thawed copy. At the 100k-object headline shape this removes the dominant
cost of the reconcile tick (BASELINE.md PR-2: 14.3 s of store deep-copies
per tick).

Write path: writers pass fresh objects (``update``/``create`` take
ownership and freeze the argument in place); :meth:`update_batch` applies
many optimistic-concurrency writes under ONE lock acquisition — the
scheduler's bind loop rides it.

Indexes: a secondary index on ``(kind, spec.node_name)`` serves each
virtual-node provider exactly its own pods (:meth:`list_by_node`), and a
per-kind monotonic dirty-set keyed by ``resource_version``
(:meth:`changes_since`) lets level-triggered consumers scan only what
changed since their last pass.

Objects are stored by (kind, name). Writers must pass the object they last
read; a stale ``meta.resource_version`` raises :class:`Conflict`, same as
a 409 from the API server (controllers retry via requeue).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
import weakref
from typing import NamedTuple

import numpy as np

from slurm_bridge_tpu.bridge import columns as _columns
from slurm_bridge_tpu.bridge.colstore import ROWS_GAUGE
from slurm_bridge_tpu.bridge.freeze import (
    FrozenInstanceError,
    freeze,
    thaw,
)
from slurm_bridge_tpu.obs.metrics import REGISTRY, Histogram
from slurm_bridge_tpu.obs.tracing import TRACER, current_span

__all__ = [
    "AlreadyExists",
    "Conflict",
    "FrozenInstanceError",
    "NotFound",
    "ObjectStore",
    "StoreEvent",
]

_list_seconds = REGISTRY.histogram(
    "sbt_store_list_seconds",
    "store list/list_by_node wall time per call (copy-on-read path)",
    buckets=Histogram.FAST_BUCKETS,
)

_frames_applied = REGISTRY.counter(
    "sbt_store_frames_applied_total",
    "rows committed through the partitioned frame-merge path "
    "(ObjectStore.apply_frames)",
)
_frame_fallback = REGISTRY.counter(
    "sbt_store_frame_fallback_total",
    "commit-frame payload fallbacks: rows whose frame was missing or "
    "malformed and were re-materialized on the serial span arm",
)


def frame_fallback_counter():
    """The frame-fallback counter, for the consumers (vnode) that count
    per-chunk serial re-runs without importing the metrics registry."""
    return _frame_fallback


class _CommitsCollector:
    """``sbt_store_commits_total{kind,site}`` — a scrape-time collector.

    The source of truth is each live store's ``commit_counts`` dict,
    incremented inline under the store lock (a plain dict add — no metric
    lock, no label-tuple sort on the 135k-commits-per-tick path); this
    object only SUMS those dicts when /metrics renders. Counts of
    garbage-collected stores are folded into ``_retired`` so the exposed
    counter stays monotonic for the life of the process.
    """

    name = "sbt_store_commits_total"
    help = "store create/update commits by object kind and callsite"

    def __init__(self):
        self._stores: weakref.WeakSet = weakref.WeakSet()
        self._retired: dict[tuple[str, str], int] = {}
        # RLock, not Lock: allocations inside totals() can trigger cyclic
        # GC, which may run a dead store's finalize (_retire) SYNCHRONOUSLY
        # on this same thread — with a plain lock that self-deadlocks the
        # /metrics scrape
        self._lock = threading.RLock()

    def track(self, store: "ObjectStore") -> None:
        with self._lock:
            self._stores.add(store)
        weakref.finalize(store, self._retire, store.commit_counts)

    def _retire(self, counts: dict) -> None:
        with self._lock:
            for key, n in counts.items():
                self._retired[key] = self._retired.get(key, 0) + n

    def totals(self) -> dict[tuple[str, str], int]:
        with self._lock:
            stores = list(self._stores)
            agg = dict(self._retired)
        for store in stores:
            for key, n in store.commit_counts_snapshot().items():
                agg[key] = agg.get(key, 0) + n
        return agg

    def collect(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for (kind, site), n in sorted(self.totals().items()):
            out.append(f'{self.name}{{kind="{kind}",site="{site}"}} {n}')
        return out


_COMMITS = _CommitsCollector()
REGISTRY.register(_COMMITS)
REGISTRY.register(ROWS_GAUGE)


class NotFound(KeyError):
    pass


class Conflict(RuntimeError):
    pass


class AlreadyExists(RuntimeError):
    pass


class StoreEvent(NamedTuple):
    """ADDED | MODIFIED | DELETED, like a watch event.

    A NamedTuple, not a dataclass: construction is C-level, and _notify
    builds one per watcher per commit — 135k+ per cold-start tick."""

    type: str
    kind: str
    name: str


def _node_of(obj) -> str | None:
    """The secondary-index key: ``spec.node_name`` where present.

    Reads ``spec.__dict__`` directly instead of ``getattr`` with a
    default: specs are plain (non-slots) dataclasses, and the swallowed
    AttributeError on every BridgeJob commit (whose spec has no
    ``node_name``) was ~2 µs × two calls × 45k commits per cold-start
    sweep."""
    spec = obj.__dict__.get("spec")
    if spec is None:
        return None
    node = spec.__dict__.get("node_name")
    return node if isinstance(node, str) else None


class ObjectStore:
    def __init__(self, *, columnar: tuple[str, ...] | None = None):
        """``columnar`` selects which kinds live in column tables;
        defaults to :data:`bridge.columns.DEFAULT_COLUMNAR`. Pass ``()``
        for the pure frozen-object store (the equivalence oracle)."""
        self._lock = threading.RLock()
        kinds = _columns.DEFAULT_COLUMNAR if columnar is None else tuple(columnar)
        #: kind -> KindTable for the columnar kinds
        self._tables = {k: _columns.make_table(k) for k in kinds}
        #: ``(kind, site) -> commits`` — the per-kind × per-callsite
        #: attribution ledger behind ``sbt_store_commits_total`` and the
        #: flight recorder's commit breakdown. Incremented inline by the
        #: commit paths (a dict add under the already-held store lock);
        #: writers name their callsite via the ``site=`` kwarg, anything
        #: that doesn't lands under "other".
        self.commit_counts: dict[tuple[str, str], int] = {}
        _COMMITS.track(self)
        #: kind -> name -> frozen stored object
        self._by_kind: dict[str, dict[str, object]] = {}
        #: kind -> node_name -> set of names bound there (Pods, mostly)
        self._by_node: dict[str, dict[str, set[str]]] = {}
        #: name-sorted cache per kind / per (kind, node); None = stale.
        #: Updates keep membership, so only create/delete invalidate.
        self._sorted_names: dict[str, list[str] | None] = {}
        self._node_sorted: dict[tuple[str, str], list[str] | None] = {}
        #: monotonic dirty-set: kind -> name -> rv of last create/update,
        #: and the tombstone side: kind -> name -> rv at delete
        self._changed: dict[str, dict[str, int]] = {}
        self._tombstones: dict[str, dict[str, int]] = {}
        #: partitioned dirty-set (ISSUE 19): kind -> writer partition id
        #: -> name -> rv, populated by :meth:`apply_frames` when the
        #: caller names its partition. Keyed lazily — a store that never
        #: sees a partitioned commit carries no extra state, and
        #: ``changes_since`` unions these with the catch-all ``_changed``
        #: so every existing consumer stays correct; the WAL flush reads
        #: the partitions directly via :meth:`changes_since_partitioned`.
        self._dirty_parts: dict[str, dict[int, dict[str, int]]] = {}
        #: per-kind high-water mark: the global rv of the kind's LAST
        #: change or delete. ``changes_since`` answers "nothing moved"
        #: in O(1) off this — the incremental tick (PR-11) probes the
        #: Pod dirty-set several times per tick, and enumerating a 50k-
        #: name dict per probe was most of a steady tick's residual cost
        self._kind_rv: dict[str, int] = {}
        self._rv = 0
        #: SimpleQueue, not Queue: put() is C-implemented and lock-free
        #: on the GIL — _notify runs under the store lock for EVERY
        #: commit, and a cold-start tick delivers 100k+ events per
        #: watcher (Queue.put's mutex+notify was ~5 µs each there).
        #: The tuple snapshot exists so _notify iterates without building
        #: a defensive list copy per commit.
        self._watchers: list[tuple[queue.SimpleQueue, tuple[str, ...] | None]] = []
        self._watchers_snapshot: tuple = ()

    # ---- plumbing ----

    def _key(self, obj) -> tuple[str, str]:
        return (obj.KIND, obj.meta.name)

    def _notify(self, etype: str, kind: str, name: str) -> None:
        for q, kinds in self._watchers_snapshot:
            if kinds is None or kind in kinds:
                q.put(StoreEvent(etype, kind, name))

    def watch(self, kinds: tuple[str, ...] | None = None) -> queue.SimpleQueue:
        """A queue of StoreEvents for the given kinds (None = all).

        New watchers receive synthetic ADDED events for existing objects so
        level-triggered consumers converge from any start time.
        """
        q: queue.SimpleQueue = queue.SimpleQueue()
        with self._lock:
            for kind, objs in self._by_kind.items():
                if kinds is None or kind in kinds:
                    for name in objs:
                        q.put(StoreEvent("ADDED", kind, name))
            for kind, table in self._tables.items():
                if kinds is None or kind in kinds:
                    for name in table.row_of:
                        q.put(StoreEvent("ADDED", kind, name))
            self._watchers.append((q, kinds))
            self._watchers_snapshot = tuple(self._watchers)
        return q

    def unwatch(self, q: queue.SimpleQueue) -> None:
        with self._lock:
            self._watchers = [(w, k) for (w, k) in self._watchers if w is not q]
            self._watchers_snapshot = tuple(self._watchers)

    # ---- index maintenance (call with the lock held) ----

    def _index_add(self, kind: str, name: str, obj) -> None:
        self._index_add_node(kind, name, _node_of(obj))

    def _index_add_node(self, kind: str, name: str, node) -> None:
        if node is not None:
            self._by_node.setdefault(kind, {}).setdefault(node, set()).add(name)
            self._node_sorted[(kind, node)] = None

    def _index_remove(self, kind: str, name: str, obj) -> None:
        self._index_remove_node(kind, name, _node_of(obj))

    def _index_remove_node(self, kind: str, name: str, node) -> None:
        if node is None:
            return
        bucket = self._by_node.get(kind, {}).get(node)
        if bucket is not None:
            bucket.discard(name)
            if not bucket:
                del self._by_node[kind][node]
            self._node_sorted[(kind, node)] = None

    def _index_move(self, kind: str, name: str, old, new) -> None:
        old_node, new_node = _node_of(old), _node_of(new)
        if old_node == new_node:
            return
        self._index_remove(kind, name, old)
        self._index_add(kind, name, new)

    def _record_change(self, kind: str, name: str) -> None:
        self._changed.setdefault(kind, {})[name] = self._rv
        self._kind_rv[kind] = self._rv
        tombs = self._tombstones.get(kind)
        if tombs is not None:
            tombs.pop(name, None)

    # ---- commit attribution ----

    def commit_counts_snapshot(self) -> dict[tuple[str, str], int]:
        """A copy of the commit ledger (small: one entry per kind × site)."""
        with self._lock:
            return dict(self.commit_counts)

    def commits_total(self) -> int:
        with self._lock:
            return sum(self.commit_counts.values())

    def current_rv(self) -> int:
        """The store's global resource-version counter — the watermark
        write-behind consumers (WAL persistence) flush up to."""
        with self._lock:
            return self._rv

    def contains(self, kind: str, name: str) -> bool:
        """Existence probe that builds NO view for columnar kinds (a
        ``try_get`` would materialize one just to throw it away)."""
        with self._lock:
            table = self._tables.get(kind)
            if table is not None:
                return name in table.row_of
            return name in self._by_kind.get(kind, {})

    @staticmethod
    def _span_commits(kind: str, site: str, n: int) -> None:
        """Attribute ``n`` commits to the active sampled span, if any —
        the per-phase spans end up carrying exactly the commits their
        phase caused. One contextvar read when tracing is off."""
        span = current_span()
        if span is not None and span.sampled:
            span.count(f"commits.{kind}.{site}", n)

    #: tombstones kept per kind; beyond this the oldest are compacted away
    #: so a long-running bridge's delete churn doesn't grow memory (and
    #: the changes_since scan) forever. A consumer further than this many
    #: deletions behind misses some tombstones — every in-repo consumer
    #: self-heals (the scheduler's cancel scan drops names whose try_get
    #: misses), same contract as a K8s watch falling off the event horizon.
    TOMBSTONE_LIMIT = 10_000

    def _record_delete(self, kind: str, name: str) -> None:
        self._changed.get(kind, {}).pop(name, None)
        parts = self._dirty_parts.get(kind)
        if parts:
            for pdirty in parts.values():
                pdirty.pop(name, None)
        self._kind_rv[kind] = self._rv
        tombs = self._tombstones.setdefault(kind, {})
        tombs[name] = self._rv
        # compact with 25% slack so the sort amortizes over many deletes
        if len(tombs) > self.TOMBSTONE_LIMIT * 5 // 4:
            for old in sorted(tombs, key=tombs.__getitem__)[
                : len(tombs) - self.TOMBSTONE_LIMIT
            ]:
                del tombs[old]

    # ---- CRUD ----

    def create(self, obj, *, site: str = "other") -> object:
        """Insert ``obj``; the store takes ownership and freezes it in
        place. The returned object IS the stored (frozen) snapshot."""
        with self._lock:
            stored = self._commit_create(obj, site)
        self._span_commits(obj.KIND, site, 1)
        return stored

    def _commit_create(self, obj, site: str = "other") -> object:
        """One insert; caller holds the lock."""
        kind, name = key = self._key(obj)
        table = self._tables.get(kind)
        if table is not None:
            if name in table.row_of:
                raise AlreadyExists(f"{key[0]}/{key[1]} already exists")
            self._rv += 1
            obj.meta.resource_version = self._rv
            freeze(obj)
            row = table.insert(name, obj)
            self._index_add_node(kind, name, table.adapter.node_value(table, row))
        else:
            objs = self._by_kind.setdefault(kind, {})
            if name in objs:
                raise AlreadyExists(f"{key[0]}/{key[1]} already exists")
            self._rv += 1
            obj.meta.resource_version = self._rv
            freeze(obj)
            objs[name] = obj
            self._index_add(kind, name, obj)
        self._sorted_names[kind] = None
        self._record_change(kind, name)
        ckey = (kind, site)
        self.commit_counts[ckey] = self.commit_counts.get(ckey, 0) + 1
        self._notify("ADDED", kind, name)
        return obj

    def create_batch(self, objs: list, *, site: str = "other") -> list:
        """Insert many objects under ONE lock acquisition (the operator
        sweep's sizecar/worker-pod creates — a cold-start tick used to pay
        45k separate lock round-trips here).

        Returns one entry per input, in order: the stored (frozen) object
        on success, or the :class:`AlreadyExists` instance that create
        raised. A failed create never aborts the batch — each object
        stands alone, exactly as if inserted via :meth:`create`.
        """
        out: list = []
        span = current_span()
        committed: dict[str, int] | None = (
            {} if span is not None and span.sampled else None
        )
        with self._lock:
            for obj in objs:
                try:
                    out.append(self._commit_create(obj, site))
                except AlreadyExists as exc:
                    out.append(exc)
                    continue
                if committed is not None:
                    committed[obj.KIND] = committed.get(obj.KIND, 0) + 1
        if committed:
            for kind, n in committed.items():
                span.count(f"commits.{kind}.{site}", n)
        return out

    def get(self, kind: str, name: str) -> object:
        """The current frozen snapshot — shared, zero-copy. To modify,
        use :meth:`mutate` or :meth:`get_for_update`. For columnar kinds
        the snapshot is a lazily-materialized view, cached per resource
        version, so repeated reads share one object exactly like the
        object-backed kinds."""
        with self._lock:
            table = self._tables.get(kind)
            if table is not None:
                row = table.row_of.get(name)
                if row is None:
                    raise NotFound(f"{kind}/{name}")
                return table.view(row)
            try:
                return self._by_kind[kind][name]
            except KeyError:
                raise NotFound(f"{kind}/{name}") from None

    def try_get(self, kind: str, name: str):
        try:
            return self.get(kind, name)
        except NotFound:
            return None

    def count(self, kind: str) -> int:
        """Number of stored objects of ``kind`` — O(1), one lock. Lets
        bulk-read consumers (the operator sweep) decide between per-key
        lookups and a full list() by dirty-set FRACTION, not just size."""
        with self._lock:
            table = self._tables.get(kind)
            if table is not None:
                return len(table.row_of)
            return len(self._by_kind.get(kind, {}))

    def get_for_update(self, kind: str, name: str) -> object:
        """A private, mutable deep copy for read-modify-write callers
        (pass it back through :meth:`update`)."""
        return thaw(self.get(kind, name))

    def update(self, obj, *, site: str = "other") -> object:
        """Replace; raises Conflict if the caller's copy is stale.

        Takes ownership of ``obj`` (freezes it in place) — callers keep
        reading it but can no longer mutate it."""
        with self._lock:
            stored = self._commit_update(obj, site)
        self._span_commits(obj.KIND, site, 1)
        return stored

    def _commit_update(self, obj, site: str = "other") -> object:
        """One optimistic write; caller holds the lock."""
        kind, name = self._key(obj)
        table = self._tables.get(kind)
        if table is not None:
            row = table.row_of.get(name)
            if row is None:
                raise NotFound(f"{kind}/{name}")
            current_rv = int(table.cols.rv[row])
            if current_rv != obj.meta.resource_version:
                raise Conflict(
                    f"{kind}/{name}: stale resource_version "
                    f"{obj.meta.resource_version} != {current_rv}"
                )
            old_node = table.adapter.node_value(table, row)
            self._rv += 1
            obj.meta.resource_version = self._rv
            freeze(obj)
            table.replace(row, obj)
            new_node = table.adapter.node_value(table, row)
            if old_node != new_node:
                self._index_remove_node(kind, name, old_node)
                self._index_add_node(kind, name, new_node)
        else:
            objs = self._by_kind.get(kind, {})
            current = objs.get(name)
            if current is None:
                raise NotFound(f"{kind}/{name}")
            if current.meta.resource_version != obj.meta.resource_version:
                raise Conflict(
                    f"{kind}/{name}: stale resource_version "
                    f"{obj.meta.resource_version} != {current.meta.resource_version}"
                )
            self._rv += 1
            obj.meta.resource_version = self._rv
            freeze(obj)
            objs[name] = obj
            self._index_move(kind, name, current, obj)
        self._record_change(kind, name)
        ckey = (kind, site)
        self.commit_counts[ckey] = self.commit_counts.get(ckey, 0) + 1
        self._notify("MODIFIED", kind, name)
        return obj

    def update_batch(self, objs: list, *, site: str = "other") -> list:
        """Apply many optimistic-concurrency writes under ONE lock
        acquisition (the scheduler's bind path).

        Returns one entry per input, in order: the stored (frozen) object
        on success, or the :class:`Conflict`/:class:`NotFound` instance
        that write raised. A failed write never aborts the batch — each
        object stands alone, exactly as if written via :meth:`update`.
        """
        out: list = []
        span = current_span()
        committed: dict[str, int] | None = (
            {} if span is not None and span.sampled else None
        )
        with self._lock:
            for obj in objs:
                try:
                    out.append(self._commit_update(obj, site))
                except (Conflict, NotFound) as exc:
                    out.append(exc)
                    continue
                if committed is not None:
                    committed[obj.KIND] = committed.get(obj.KIND, 0) + 1
        if committed:
            for kind, n in committed.items():
                span.count(f"commits.{kind}.{site}", n)
        return out

    def delete(self, kind: str, name: str) -> None:
        """Delete an object and cascade transitively through owner refs:
        children, grandchildren, and so on all go (K8s garbage-collector
        semantics — one level was not enough, a BridgeJob→Pod→owned-object
        chain leaked the leaves)."""
        with self._lock:
            table = self._tables.get(kind)
            exists = (
                name in table.row_of
                if table is not None
                else name in self._by_kind.get(kind, {})
            )
            if not exists:
                raise NotFound(f"{kind}/{name}")
            self._delete_one(kind, name)
            frontier = {name}
            while frontier:
                owned = sorted(
                    itertools.chain(
                        (
                            (k, n)
                            for k, kobjs in self._by_kind.items()
                            for n, o in kobjs.items()
                            if getattr(o.meta, "owner", "") in frontier
                        ),
                        *(
                            t.names_owned_by(frontier)
                            for t in self._tables.values()
                        ),
                    )
                )
                frontier = set()
                for okind, oname in owned:
                    self._delete_one(okind, oname)
                    frontier.add(oname)

    def _delete_one(self, kind: str, name: str) -> None:
        table = self._tables.get(kind)
        if table is not None:
            row = table.row_of[name]
            node = table.adapter.node_value(table, row)
            table.release(name)
            self._index_remove_node(kind, name, node)
        else:
            obj = self._by_kind[kind].pop(name)
            self._index_remove(kind, name, obj)
        self._sorted_names[kind] = None
        self._rv += 1
        self._record_delete(kind, name)
        self._notify("DELETED", kind, name)

    # ---- reads over many objects ----

    def _names(self, kind: str) -> list[str]:
        names = self._sorted_names.get(kind)
        if names is None:
            table = self._tables.get(kind)
            source = table.row_of if table is not None else self._by_kind.get(kind, {})
            names = sorted(source)
            self._sorted_names[kind] = names
        return names

    def list(self, kind: str, *, labels: dict[str, str] | None = None) -> list:
        """Name-sorted frozen snapshots of every object of ``kind``."""
        t0 = time.perf_counter()
        with self._lock:
            table = self._tables.get(kind)
            if table is not None:
                row_of = table.row_of
                out = [table.view(row_of[n]) for n in self._names(kind)]
            else:
                objs = self._by_kind.get(kind, {})
                out = [objs[n] for n in self._names(kind)]
        if labels:
            out = [
                o
                for o in out
                if all(o.meta.labels.get(lk) == lv for lk, lv in labels.items())
            ]
        _list_seconds.observe(time.perf_counter() - t0)
        return out

    def list_by_node(self, kind: str, node_name: str) -> list:
        """Name-sorted frozen snapshots of the objects whose
        ``spec.node_name`` equals ``node_name`` — the secondary index that
        lets each virtual-node provider list only ITS pods instead of
        copying the whole store every sync tick."""
        t0 = time.perf_counter()
        with self._lock:
            bucket = self._by_node.get(kind, {}).get(node_name)
            if not bucket:
                _list_seconds.observe(time.perf_counter() - t0)
                return []
            names = self._node_sorted.get((kind, node_name))
            if names is None:
                names = sorted(bucket)
                self._node_sorted[(kind, node_name)] = names
            table = self._tables.get(kind)
            if table is not None:
                row_of = table.row_of
                out = [table.view(row_of[n]) for n in names]
            else:
                objs = self._by_kind.get(kind, {})
                out = [objs[n] for n in names]
        _list_seconds.observe(time.perf_counter() - t0)
        return out

    def owned_by(self, kind: str, owner: str) -> list:
        """Name-sorted (same order as :meth:`list` — reconcilers iterating
        owned sets must be deterministic) frozen snapshots."""
        with self._lock:
            table = self._tables.get(kind)
            if table is not None:
                owner_col = table.cols.owner
                names = sorted(
                    n for n, r in table.row_of.items() if owner_col[r] == owner
                )
                return [table.view(table.row_of[n]) for n in names]
            return sorted(
                (
                    o
                    for o in self._by_kind.get(kind, {}).values()
                    if o.meta.owner == owner
                ),
                key=lambda o: o.meta.name,
            )

    def changes_since(
        self, kind: str, since_rv: int
    ) -> tuple[int, list[str], list[str]]:
        """The per-kind monotonic dirty-set: ``(rv, changed, deleted)``.

        ``changed``/``deleted`` are the name-sorted sets of objects
        created-or-updated / deleted after ``since_rv``; feed the returned
        ``rv`` back in on the next call. ``since_rv=0`` returns everything
        (and every tombstone still remembered), so consumers converge from
        any start point — the watch contract, poll-shaped.
        """
        with self._lock:
            rv = self._rv
            if self._kind_rv.get(kind, 0) <= since_rv:
                # O(1) idle probe: the kind's last change/delete is at or
                # before the caller's cursor — nothing to enumerate
                return rv, [], []
            parts = self._dirty_parts.get(kind)
            if parts:
                names = {
                    n
                    for n, r in self._changed.get(kind, {}).items()
                    if r > since_rv
                }
                for pdirty in parts.values():
                    names.update(
                        n for n, r in pdirty.items() if r > since_rv
                    )
                changed = sorted(names)
            else:
                changed = sorted(
                    n
                    for n, r in self._changed.get(kind, {}).items()
                    if r > since_rv
                )
            deleted = sorted(
                n
                for n, r in self._tombstones.get(kind, {}).items()
                if r > since_rv
            )
        return rv, changed, deleted

    def has_partitioned_dirty(self, kind: str) -> bool:
        """True when ``kind`` has any per-partition dirty records — the
        WAL flush switches to :meth:`changes_since_partitioned` then."""
        with self._lock:
            parts = self._dirty_parts.get(kind)
            return bool(parts) and any(parts.values())

    def changes_since_partitioned(
        self, kind: str, since_rv: int
    ) -> tuple[int, list[str], list[str]]:
        """:meth:`changes_since`, reading the per-partition dirty dicts
        directly (partition-id order) plus the catch-all set — identical
        output by construction, but the flush walks each writer
        partition's own records instead of one global per-kind dict."""
        with self._lock:
            rv = self._rv
            if self._kind_rv.get(kind, 0) <= since_rv:
                return rv, [], []
            names = {
                n
                for n, r in self._changed.get(kind, {}).items()
                if r > since_rv
            }
            for pid in sorted(self._dirty_parts.get(kind, {})):
                names.update(
                    n
                    for n, r in self._dirty_parts[kind][pid].items()
                    if r > since_rv
                )
            deleted = sorted(
                n
                for n, r in self._tombstones.get(kind, {}).items()
                if r > since_rv
            )
        return rv, sorted(names), deleted

    # ---- columnar row access (the PR-6 hot paths) ----

    def table(self, kind: str):
        """The :class:`~bridge.colstore.KindTable` backing ``kind``, or
        None when the kind is object-backed. Consumers that read columns
        directly must hold :meth:`locked` while touching them."""
        return self._tables.get(kind)

    def locked(self):
        """The store lock, for column readers: ``with store.locked():``."""
        return self._lock

    def rows_by_node(self, kind: str, node_name: str) -> tuple[list[str], np.ndarray]:
        """``(names, rows)`` of the node-index bucket, name-sorted — the
        column-level sibling of :meth:`list_by_node` (no views built)."""
        table = self._tables[kind]
        with self._lock:
            bucket = self._by_node.get(kind, {}).get(node_name)
            if not bucket:
                return [], np.empty(0, np.int64)
            names = self._node_sorted.get((kind, node_name))
            if names is None:
                names = sorted(bucket)
                self._node_sorted[(kind, node_name)] = names
            return names, table.rows_for(names)

    def update_rows(
        self,
        kind: str,
        names: list[str],
        expected_rv,
        writer,
        *,
        site: str = "other",
        node_to=None,
    ) -> np.ndarray:
        """Batch optimistic row-commit for a columnar kind.

        Resolves ``names`` → rows under ONE lock acquisition, drops
        entries that vanished (NotFound) or whose row rv moved past
        ``expected_rv`` (Conflict; pass None to skip the check), then
        calls ``writer(rows, sel)`` once — ``rows`` are the surviving row
        indices, ``sel`` their positions in ``names`` — to scatter column
        values. The store does everything :meth:`update_batch` would per
        object: sequential resource versions in caller order, dirty-set
        records, MODIFIED watch events, node-index moves (via
        ``node_to``, an array of new node keys aligned with ``names`` —
        writers must NOT touch the node column themselves), commit
        attribution. View caches invalidate by construction (the rv
        moves past the cached one).

        Returns an int64 array aligned with ``names``: the new rv on
        success, 0 for NotFound, -1 for Conflict.
        """
        table = self._tables[kind]
        n = len(names)
        out = np.zeros(n, np.int64)
        with self._lock:
            rows = table.rows_for(names)
            found = rows >= 0
            ok = found.copy()
            if expected_rv is not None and n:
                cur = table.cols.rv[np.where(found, rows, 0)]
                ok &= cur == np.asarray(expected_rv, np.int64)
            out[found & ~ok] = -1
            sel = np.nonzero(ok)[0]
            if not sel.size:
                return out
            okrows = rows[sel]
            writer(okrows, sel)
            if node_to is not None:
                node_col = table.cols.col(table.adapter.node_col)
                for pos, row in zip(sel.tolist(), okrows.tolist()):
                    old = node_col[row]
                    new = node_to[pos]
                    if old != new:
                        name = names[pos]
                        self._index_remove_node(
                            kind, name, old if isinstance(old, str) else None
                        )
                        self._index_add_node(
                            kind, name, new if isinstance(new, str) else None
                        )
                        node_col[row] = new
            base = self._rv
            new_rvs = base + 1 + np.arange(sel.size, dtype=np.int64)
            table.cols.rv[okrows] = new_rvs
            self._rv = int(base + sel.size)
            out[sel] = new_rvs
            changed = self._changed.setdefault(kind, {})
            tombs = self._tombstones.get(kind)
            names_sel = (
                list(names)
                if sel.size == n
                else [names[p] for p in sel.tolist()]
            )
            changed.update(zip(names_sel, new_rvs.tolist()))
            self._kind_rv[kind] = self._rv
            if tombs:
                for name in names_sel:
                    tombs.pop(name, None)
            # per-queue event order matches the per-name loop (queues are
            # independent); hoisting the watcher filter halves the tail
            for q, kinds in self._watchers_snapshot:
                if kinds is None or kind in kinds:
                    put = q.put
                    for name in names_sel:
                        put(StoreEvent("MODIFIED", kind, name))
            table.rows_written += int(sel.size)
            ckey = (kind, site)
            self.commit_counts[ckey] = self.commit_counts.get(ckey, 0) + int(sel.size)
        self._span_commits(kind, site, int(sel.size))
        return out

    def apply_frames(
        self,
        kind: str,
        parts: list,
        *,
        site: str = "other",
        partition: int | None = None,
    ) -> list[np.ndarray]:
        """The partitioned commit merge (ISSUE 19): scatter pre-built
        writer partitions under ONE short lock, in the deterministic
        order ``parts`` arrives in.

        ``parts`` is a list of ``(names, expected_rv, writer)`` tuples —
        each the per-partition slice of what one :meth:`update_rows` call
        would have committed, with the column values already staged
        outside the lock (a worker-built commit frame, typically). The
        merge applies each part with :meth:`update_rows`'s exact
        bookkeeping — optimistic rv check, sequential resource versions
        in caller order, dirty-set records, MODIFIED watch events, commit
        attribution — all main-thread, so the result is byte-identical to
        the serial column scatter by construction. (Node-index moves are
        not supported here: the status-commit writers never move a pod's
        node; callers that need ``node_to`` use :meth:`update_rows`.)

        ``partition`` names the writer partition whose dirty dict the
        changed names land in; None records into the global per-kind set
        exactly as :meth:`update_rows` does. Returns one rv-result array
        per part, aligned with that part's ``names`` (new rv / 0 NotFound
        / -1 Conflict).

        The merge runs inside a ``store.apply`` child span so the flight
        record attributes it; the commit-site attribution itself lands on
        the CALLER's span, matching :meth:`update_rows`'s posture.
        """
        table = self._tables[kind]
        outs: list[np.ndarray] = []
        total = 0
        with TRACER.span("store.apply") as span:
            with self._lock:
                if partition is None:
                    dirty = self._changed.setdefault(kind, {})
                else:
                    dirty = self._dirty_parts.setdefault(
                        kind, {}
                    ).setdefault(int(partition), {})
                tombs = self._tombstones.get(kind)
                for names, expected_rv, writer in parts:
                    n = len(names)
                    out = np.zeros(n, np.int64)
                    outs.append(out)
                    rows = table.rows_for(names)
                    found = rows >= 0
                    ok = found.copy()
                    if expected_rv is not None and n:
                        cur = table.cols.rv[np.where(found, rows, 0)]
                        ok &= cur == np.asarray(expected_rv, np.int64)
                    out[found & ~ok] = -1
                    sel = np.nonzero(ok)[0]
                    if not sel.size:
                        continue
                    okrows = rows[sel]
                    writer(okrows, sel)
                    base = self._rv
                    new_rvs = base + 1 + np.arange(sel.size, dtype=np.int64)
                    table.cols.rv[okrows] = new_rvs
                    self._rv = int(base + sel.size)
                    out[sel] = new_rvs
                    names_sel = (
                        list(names)
                        if sel.size == n
                        else [names[p] for p in sel.tolist()]
                    )
                    dirty.update(zip(names_sel, new_rvs.tolist()))
                    self._kind_rv[kind] = self._rv
                    if tombs:
                        for name in names_sel:
                            tombs.pop(name, None)
                    for q, kinds in self._watchers_snapshot:
                        if kinds is None or kind in kinds:
                            put = q.put
                            for name in names_sel:
                                put(StoreEvent("MODIFIED", kind, name))
                    table.rows_written += int(sel.size)
                    total += int(sel.size)
                ckey = (kind, site)
                self.commit_counts[ckey] = (
                    self.commit_counts.get(ckey, 0) + total
                )
            span.count("parts", len(parts))
            span.count("rows", total)
        if total:
            _frames_applied.inc(total)
        self._span_commits(kind, site, total)
        return outs

    def create_rows(
        self, kind: str, names: list[str], builder, *, site: str = "other"
    ) -> np.ndarray:
        """Batch row-insert for a columnar kind (:meth:`create_batch`'s
        row-level sibling). Names already present are skipped
        (AlreadyExists semantics, 0 in the result); ``builder(rows,
        sel)`` must fill EVERY schema column for the fresh rows
        (segments via the adapter's heaps) except ``rv``, which the
        store assigns. Returns new rv per name (0 = already existed)."""
        table = self._tables[kind]
        n = len(names)
        out = np.zeros(n, np.int64)
        with self._lock:
            row_of = table.row_of
            sel_list: list[int] = []
            fresh: list[str] = []
            seen: set[str] = set()
            for i, name in enumerate(names):
                if name in row_of or name in seen:
                    continue
                seen.add(name)
                sel_list.append(i)
                fresh.append(name)
            if not sel_list:
                return out
            sel = np.asarray(sel_list, np.int64)
            rows = table.alloc_bulk(fresh)
            row_list = rows.tolist()
            builder(rows, sel)
            base = self._rv
            new_rvs = base + 1 + np.arange(sel.size, dtype=np.int64)
            table.cols.rv[rows] = new_rvs
            self._rv = int(base + sel.size)
            out[sel] = new_rvs
            self._sorted_names[kind] = None
            changed = self._changed.setdefault(kind, {})
            tombs = self._tombstones.get(kind)
            adapter = table.adapter
            names_sel = [names[p] for p in sel_list]
            for name, row in zip(names_sel, row_list):
                self._index_add_node(kind, name, adapter.node_value(table, row))
            changed.update(zip(names_sel, new_rvs.tolist()))
            self._kind_rv[kind] = self._rv
            if tombs:
                for name in names_sel:
                    tombs.pop(name, None)
            for q, kinds in self._watchers_snapshot:
                if kinds is None or kind in kinds:
                    put = q.put
                    for name in names_sel:
                        put(StoreEvent("ADDED", kind, name))
            table.rows_written += int(sel.size)
            ckey = (kind, site)
            self.commit_counts[ckey] = self.commit_counts.get(ckey, 0) + int(sel.size)
        self._span_commits(kind, site, int(sel.size))
        return out

    def view_builds_total(self) -> int:
        """Frozen views materialized across columnar kinds — the
        view-materialization pressure diagnostic (``decoded_views_total``
        in the sim headline)."""
        return sum(t.view_builds for t in self._tables.values())

    def rows_written_total(self) -> int:
        """Commits that went through the columnar row path."""
        return sum(t.rows_written for t in self._tables.values())

    # ---- convenience used by reconcilers ----

    def mutate(self, kind: str, name: str, fn, *, retries: int = 8,
               site: str = "other"):
        """Read-modify-write with conflict retry; fn mutates a private
        thawed copy in place and may return False to skip the write."""
        for _ in range(retries):
            snapshot = self.get(kind, name)
            obj = thaw(snapshot)
            if fn(obj) is False:
                return snapshot
            try:
                return self.update(obj, site=site)
            except Conflict:
                continue
        raise Conflict(f"{kind}/{name}: too many conflicts")

    def replace_update(self, kind: str, name: str, build, *, retries: int = 8,
                       site: str = "other"):
        """Optimistic write without the deep copy: ``build(snapshot)``
        returns a REPLACEMENT object (``dataclasses.replace``-style,
        structurally sharing the snapshot's frozen sub-objects) or None to
        skip the write. The hot write paths (status mirror, bind) ride
        this instead of :meth:`mutate` — no thaw, no deepcopy, unchanged
        children shared between versions."""
        for _ in range(retries):
            snapshot = self.get(kind, name)
            obj = build(snapshot)
            if obj is None:
                return snapshot
            try:
                return self.update(obj, site=site)
            except Conflict:
                continue
        raise Conflict(f"{kind}/{name}: too many conflicts")
