"""Recursive freezing for store-held objects — the copy-on-read seam.

The :class:`ObjectStore` used to ``copy.deepcopy`` every object on every
``get``/``list``; at the paper's headline shape that is ~100k deep copies
per provider per tick and the single largest cost in the reconcile loop
(BASELINE.md PR-2: store phase 14.3 s of a 121.6 s tick). The rework
instead freezes each object ONCE when it is written and hands out the
stored reference on every read:

- reads share references — zero copies, safe because a frozen object
  rejects mutation loudly (:class:`FrozenInstanceError`) instead of
  silently corrupting the store;
- writers get a private thawed copy via :func:`thaw` (``copy.deepcopy``
  — the freeze types unfreeze themselves on deepcopy), mutate it, and
  hand ownership back to the store, which freezes it in place;
- frozen sub-objects (an unchanged ``spec.demand``, a labels dict) can be
  structurally shared between versions by writers that build replacement
  objects with :func:`dataclasses.replace` — immutability makes the
  sharing safe.

Freezing is type-driven and class-patching: the first time a dataclass
type passes through :func:`freeze`, its ``__setattr__`` gains the frozen
guard and its ``__deepcopy__`` the thaw-on-copy behavior (idempotent, a
dict lookup per setattr otherwise). Plain ``dict``/``list`` fields are
wrapped in :class:`FrozenDict`/:class:`FrozenList`, which compare equal
to their plain counterparts and deep-copy back to them.
"""

from __future__ import annotations

import copy
import dataclasses

from slurm_bridge_tpu.core.fastpath import (  # noqa: F401  (re-exported)
    FROZEN_FLAG,
    FrozenInstanceError,
    enable_guard as _enable,
    fast_new,
    fast_replace,
    frozen_new,
    frozen_replace,
)


def _blocked(self, *a, **k):
    raise FrozenInstanceError(
        f"{type(self).__name__} belongs to a frozen store snapshot"
    )


class FrozenDict(dict):
    """A dict that rejects mutation; deep-copies back to a plain dict."""

    __setitem__ = __delitem__ = _blocked
    pop = popitem = clear = update = setdefault = _blocked
    __ior__ = _blocked

    def __deepcopy__(self, memo):
        return {
            copy.deepcopy(k, memo): copy.deepcopy(v, memo)
            for k, v in self.items()
        }

    def __reduce_ex__(self, protocol):  # pickle as a plain dict
        return (dict, (dict(self),))


class FrozenList(list):
    """A list that rejects mutation; deep-copies back to a plain list."""

    __setitem__ = __delitem__ = _blocked
    append = extend = insert = remove = pop = clear = _blocked
    sort = reverse = __iadd__ = __imul__ = _blocked

    def __deepcopy__(self, memo):
        return [copy.deepcopy(v, memo) for v in self]

    def __reduce_ex__(self, protocol):  # pickle as a plain list
        return (list, (list(self),))


def is_frozen(obj) -> bool:
    d = getattr(obj, "__dict__", None)
    return bool(d) and d.get(FROZEN_FLAG, False)


#: per-type dispatch cache — freeze() runs once per field of every store
#: write, so the classification (scalar / dataclass / container) must be
#: one dict lookup, not an is_dataclass()+fields() walk each time
_K_SCALAR, _K_DATACLASS, _K_DICT, _K_LIST, _K_TUPLE = range(5)
_kind_of: dict[type, int] = {}
_field_names: dict[type, tuple[str, ...]] = {}


def _classify(t: type) -> int:
    if t is dict:
        k = _K_DICT
    elif t is list:
        k = _K_LIST
    elif t is tuple:
        k = _K_TUPLE
    elif dataclasses.is_dataclass(t):
        _enable(t)
        _field_names[t] = tuple(f.name for f in dataclasses.fields(t))
        k = _K_DATACLASS
    else:
        # scalars, enums, datetimes, FrozenDict/FrozenList (already
        # frozen), frozen dataclasses: nothing to do, ever
        k = _K_SCALAR
    _kind_of[t] = k
    return k


def freeze(obj):
    """Deep-freeze a dataclass graph in place (the store takes ownership).

    Returns the same object. Dict/list fields are replaced by their
    frozen wrappers; nested dataclasses are frozen recursively. Already-
    frozen sub-objects short-circuit, so re-freezing a replacement object
    that structurally shares frozen children is cheap.
    """
    t = obj.__class__
    k = _kind_of.get(t)
    if k is None:
        k = _classify(t)
    if k == _K_SCALAR:
        return obj
    if k == _K_DATACLASS:
        d = obj.__dict__
        if d.get(FROZEN_FLAG, False):
            return obj
        for name in _field_names[t]:
            fv = d.get(name)
            nv = freeze(fv)
            if nv is not fv:
                d[name] = nv
        d[FROZEN_FLAG] = True
        return obj
    if k == _K_DICT:
        return FrozenDict((key, freeze(v)) for key, v in obj.items())
    if k == _K_LIST:
        return FrozenList(freeze(v) for v in obj)
    items = [freeze(v) for v in obj]  # tuple
    if any(a is not b for a, b in zip(items, obj)):
        return tuple(items)
    return obj


def thaw(obj):
    """A private, fully-mutable deep copy of a (frozen) object graph."""
    return copy.deepcopy(obj)
